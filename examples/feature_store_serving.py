"""Feature-store read path: serve windowed features at high QPS while
the job ingests — the r19 native serving fast path, end to end.

A session cluster runs a windowed aggregation job; client threads issue
batched point lookups through three read surfaces:

1. ``cluster.lookup_batch_packed`` — the NATIVE FAST PATH: the whole
   key batch probes the GIL-free hot-row table in ONE C call and hit
   results stay packed until read (zero dicts built for keys you never
   touch — serialize straight from the packed form in a real frontend);
2. ``cluster.lookup_batch`` — the same results, eagerly materialized;
3. ``QueryableStateClient`` — the client wrapper, which routes through
   the cluster's serving plane when one exists.

Run: JAX_PLATFORMS=cpu python examples/feature_store_serving.py
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

KEYS = 1024


def build_pipeline(sink):
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.core.config import Configuration
    from flink_tpu.datastream.environment import (
        StreamExecutionEnvironment,
    )
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 4096,
        "parallelism.default": 4,
        "latency.fire-deadline-ms": 25,
        "serving.replica": True,              # boundary-published snapshots
        "serving.replica.publish-interval-ms": 25,
    }))
    (env.add_source(
        DataGenSource(total_records=150_000, num_keys=KEYS,
                      events_per_second_of_eventtime=50_000, seed=11),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key")
        .window(TumblingEventTimeWindows.of(60_000))
        .sum("value").sink_to(sink))
    return env


def main():
    from flink_tpu.cluster.queryable_state import QueryableStateClient
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.tenancy.session_cluster import SessionCluster

    operator = "window_agg(SumAggregate)"
    cluster = SessionCluster(quantum_records=8192)
    cluster.submit(build_pipeline(CollectSink()), "features")
    client = QueryableStateClient(cluster)
    stats = {"packed": 0, "dict": 0, "client": 0}
    stop = threading.Event()

    def reader():
        rng = np.random.default_rng(7)
        while not stop.is_set():
            keys = rng.integers(0, KEYS, 256).tolist()
            try:
                packed = cluster.lookup_batch_packed(
                    "features", operator, keys)
                # only the keys you READ pay dict materialization
                _ = packed[0]
                stats["packed"] += len(packed)
                stats["dict"] += len(cluster.lookup_batch(
                    "features", operator, keys[:16]))
                stats["client"] += len(client.get_state_batch_packed(
                    "features", operator, keys[:16]))
            except (RuntimeError, TimeoutError):
                return  # job finished: the plane reports not-serving
            # request interarrival: an unthrottled spin loop would
            # starve the ingest scheduler on a small box
            time.sleep(0.002)

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    cluster.run(timeout_s=300)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    m = cluster.serving.metrics()
    print(f"served lookups: packed={stats['packed']} "
          f"dict={stats['dict']} client={stats['client']}")
    print(f"hot-row hit rate: {m['hot_row_hit_rate']:.3f} "
          f"(native tables: {int(m.get('hot_row_native_tables', 0))}) "
          f"p99 {m['lookup_p99_ms']:.2f} ms")


if __name__ == "__main__":
    main()
