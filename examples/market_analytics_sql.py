"""A tour of the streaming-SQL surface on one market-data scenario:

1. a JSON-encoded Kafka topic read through ``'format' = 'json'``
2. a rolling average via an OVER window (ROWS BETWEEN ... PRECEDING)
3. a V-shape dip-recovery detector via MATCH_RECOGNIZE
4. an event-time temporal join against versioned FX rates
5. a lookup (dimension) join for symbol metadata
6. a plain GROUP BY written to an upsert Kafka table
   (PRIMARY KEY ... NOT ENFORCED -> SinkUpsertMaterializer)

Run: python examples/market_analytics_sql.py
"""

try:
    import _bootstrap  # noqa: F401  (repo-root sys.path when run by file path)
except ImportError:  # exec'd / repo already importable
    pass
import json

import numpy as np

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.kafka import FakeBroker, KafkaSource
from flink_tpu.connectors.lookup import TableLookupFunction
from flink_tpu.core.records import ROWKIND_DELETE, ROWKIND_FIELD, RecordBatch
from flink_tpu.table.environment import StreamTableEnvironment


def seed_topics(broker):
    rng = np.random.default_rng(7)
    n = 4000
    sym = rng.integers(0, 4, n).astype(np.int64)
    base = np.asarray([100.0, 50.0, 10.0, 250.0])[sym]
    price = np.round(base + np.cumsum(rng.normal(0, 0.5, n)) % 7 - 3, 2)
    ts = np.arange(n, dtype=np.int64) * 250  # 4 ticks/s
    broker.create_topic("ticks", 2)
    for p in range(2):
        m = sym % 2 == p
        recs = [json.dumps({"sym": int(s), "price": float(v),
                            "ts": int(t)}).encode()
                for s, v, t in zip(sym[m], price[m], ts[m])]
        broker.append_raw("ticks", p, recs, timestamps=ts[m])
    # versioned FX rates (the temporal join's right side)
    broker.create_topic("fx", 1)
    fx_ts = np.asarray([0, 300_000, 600_000], dtype=np.int64)
    broker.append("fx", 0, RecordBatch.from_pydict(
        {"ccy": np.asarray([1, 1, 1], dtype=np.int64),
         "rate": np.asarray([1.00, 1.05, 0.97]),
         "fts": fx_ts}, timestamps=fx_ts))


def main():
    broker = FakeBroker.get("default")
    seed_topics(broker)
    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 512}))
    tenv = StreamTableEnvironment(env)

    tenv.execute_sql("""
        CREATE TABLE ticks (sym BIGINT, price DOUBLE, ts BIGINT,
                            WATERMARK FOR ts AS ts)
        WITH ('connector' = 'kafka', 'topic' = 'ticks',
              'format' = 'json')
    """)

    print("== rolling 20-tick average (OVER window) ==")
    rows = tenv.execute_sql("""
        SELECT sym, ts, price,
               AVG(price) OVER (PARTITION BY sym ORDER BY ts
                   ROWS BETWEEN 19 PRECEDING AND CURRENT ROW) AS avg20
        FROM ticks
    """).collect()
    print(f"  {len(rows)} rows; sample: {rows[len(rows) // 2]}")

    print("== dip-recovery patterns (MATCH_RECOGNIZE) ==")
    matches = tenv.execute_sql("""
        SELECT sym, start_p, bottom_p, end_p FROM ticks
        MATCH_RECOGNIZE (
          PARTITION BY sym ORDER BY ts
          MEASURES FIRST(A.price) AS start_p,
                   LAST(DOWN.price) AS bottom_p,
                   LAST(UP.price) AS end_p
          AFTER MATCH SKIP PAST LAST ROW
          PATTERN (A DOWN{2,} UP{2,})
          WITHIN INTERVAL '30' SECONDS
          DEFINE DOWN AS DOWN.price < A.price,
                 UP AS UP.price > DOWN.price
        ) AS m
    """).collect()
    print(f"  {len(matches)} V-shapes; first: "
          f"{matches[0] if matches else None}")

    print("== event-time temporal join against versioned FX ==")
    tenv.execute_sql("""
        CREATE TABLE fx (ccy BIGINT, rate DOUBLE, fts BIGINT,
                         WATERMARK FOR fts AS fts)
        WITH ('connector' = 'kafka', 'topic' = 'fx')
    """)
    tenv.execute_sql("""
        CREATE VIEW priced AS
        SELECT sym, price, ts, 1 AS ccy FROM ticks
    """)
    conv = tenv.execute_sql("""
        SELECT o.sym, o.price * r.rate AS usd, o.ts
        FROM priced AS o
        JOIN fx FOR SYSTEM_TIME AS OF o.ts AS r ON o.ccy = r.ccy
    """).collect()
    print(f"  {len(conv)} converted rows; the rate flips at ts 300k/600k")

    print("== lookup join for symbol metadata ==")
    tenv.create_lookup_table("symbols", TableLookupFunction(
        [{"sym": 0, "name": "ACME"}, {"sym": 1, "name": "GLOBEX"},
         {"sym": 2, "name": "INITECH"}, {"sym": 3, "name": "HOOLI"}],
        key_column="sym"), ["sym", "name"])
    named = tenv.execute_sql("""
        SELECT t.price, s.name FROM ticks AS t
        JOIN symbols FOR SYSTEM_TIME AS OF t.ts AS s ON t.sym = s.sym
    """).collect()
    print(f"  {len(named)} enriched rows; sample: {named[0]}")

    print("== plain GROUP BY into an upsert Kafka table ==")
    tenv.execute_sql("""
        CREATE TABLE tick_counts (sym BIGINT, n BIGINT,
                                  PRIMARY KEY (sym) NOT ENFORCED)
        WITH ('connector' = 'kafka', 'topic' = 'tick_counts')
    """)
    tenv.execute_sql(
        "INSERT INTO tick_counts "
        "SELECT sym, COUNT(*) AS n FROM ticks GROUP BY sym")
    src = KafkaSource("tick_counts")
    src.open(0, 1)
    current = {}
    while True:
        b = src.poll_batch(10_000)
        if b is None:
            break
        for r in b.to_rows():
            if r.get(ROWKIND_FIELD) == ROWKIND_DELETE:
                current.pop(r["sym"], None)
            else:
                current[r["sym"]] = r["n"]
    print(f"  compacted topic view: {dict(sorted(current.items()))}")


if __name__ == "__main__":
    main()
