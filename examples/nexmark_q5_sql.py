"""Nexmark Q5 (hot items) in SQL, end to end: windowed GROUP BY over the
bid stream, Top-N per window via ROW_NUMBER, INSERT INTO a sink table.

Run: python examples/nexmark_q5_sql.py
"""

try:
    import _bootstrap  # noqa: F401  (repo-root sys.path when run by file path)
except ImportError:  # exec'd / repo already importable
    pass
from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.benchmarks.nexmark import BidSource
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.table.environment import StreamTableEnvironment


def main():
    env = StreamExecutionEnvironment(Configuration(
        {"execution.micro-batch.size": 1 << 14}))
    t_env = StreamTableEnvironment(env)

    bids = env.from_source(
        BidSource(total_records=200_000, num_auctions=1000,
                  events_per_second_of_eventtime=20_000),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
    t_env.create_temporary_view("bid", bids,
                                columns=["auction", "bidder", "price"],
                                time_field="__ts__")

    sink = CollectSink()
    t_env.create_sink_table("hot_items", sink,
                            columns=["auction", "bids", "window_end"])
    t_env.execute_sql("""
        INSERT INTO hot_items
        SELECT auction, bids, window_end FROM (
          SELECT auction, window_end, bids, ROW_NUMBER() OVER (
            PARTITION BY window_end ORDER BY bids DESC) AS rn
          FROM (
            SELECT auction, window_end, COUNT(*) AS bids
            FROM TABLE(HOP(TABLE bid, DESCRIPTOR(__ts__),
                           INTERVAL '2' SECOND, INTERVAL '10' SECOND))
            GROUP BY auction, window_start, window_end
          )
        ) WHERE rn <= 3
    """)
    rows = sink.result().to_rows()
    by_window = {}
    for r in rows:
        by_window.setdefault(r["window_end"], []).append(
            (r["auction"], r["bids"]))
    for wend in sorted(by_window)[:5]:
        top = sorted(by_window[wend], key=lambda x: -x[1])
        print(f"window_end={wend}: top3={top}")
    assert rows and all(len(v) <= 3 for v in by_window.values())
    print(f"ok: {len(by_window)} windows, <=3 hot items each")


if __name__ == "__main__":
    main()
