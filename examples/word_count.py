"""WordCount over a socket — the baseline config of BASELINE.json row 1
(reference example: flink-examples-streaming WindowWordCount: socket source,
keyBy word, 5 s tumbling window, count).

Usage: python examples/word_count.py [--self-feed]
With --self-feed the script starts a local line server and pumps sample text
through it, so the whole flow (socket -> flat_map split -> key_by ->
tumbling window count -> print) runs end to end with no external setup.
"""

try:
    import _bootstrap  # noqa: F401  (repo-root sys.path when run by file path)
except ImportError:  # exec'd / repo already importable
    pass
import argparse
import socket
import threading
import time

import numpy as np

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import SocketSource
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

SAMPLE = """to be or not to be that is the question
whether tis nobler in the mind to suffer
the slings and arrows of outrageous fortune
or to take arms against a sea of troubles
"""


def start_feeder(port: int, lines, delay_s: float = 0.05):
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", port))
    server.listen(1)

    def run():
        conn, _ = server.accept()
        with conn:
            for line in lines:
                conn.sendall((line + "\n").encode())
                time.sleep(delay_s)
        server.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def split_words(batch):
    """Vectorized-enough line -> words expansion."""
    lines = batch["line"]
    ts = batch.timestamps
    words, word_ts = [], []
    for line, t in zip(lines, ts):
        for w in line.split():
            words.append(w)
            word_ts.append(t)
    from flink_tpu.core.records import RecordBatch

    if not words:
        return []
    return [RecordBatch.from_pydict(
        {"word": np.array(words, dtype=object)},
        timestamps=np.array(word_ts, dtype=np.int64))]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=19099)
    ap.add_argument("--window-ms", type=int, default=5000)
    ap.add_argument("--self-feed", action="store_true")
    args = ap.parse_args()

    if args.self_feed:
        start_feeder(args.port, SAMPLE.strip().splitlines() * 3)
        time.sleep(0.2)

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 4096,
        "execution.micro-batch.timeout-ms": 10,
    }))
    sink = CollectSink()
    (
        env.add_source(
            SocketSource(args.host, args.port),
            WatermarkStrategy.for_monotonous_timestamps())
        .flat_map(split_words, name="split")
        .key_by("word")
        .window(TumblingEventTimeWindows.of(args.window_ms))
        .count()
        .sink_to(sink)
    )
    result = env.execute("socket-word-count")
    rows = sorted(sink.rows(), key=lambda r: -r["count"])
    print(f"\n== word counts over {args.window_ms} ms tumbling windows ==")
    for r in rows[:10]:
        print(f"  {r['word']!r:<12} window_start={r['window_start']} "
              f"count={r['count']}")
    total = sum(r["count"] for r in rows)
    print(f"total words counted: {total}")
    print(result.metrics["records_emitted_by_sources"], "source records,",
          f"{result.metrics['runtime_s']:.2f}s")


if __name__ == "__main__":
    main()
