"""Make the repo importable when an example is run by path from any cwd
(``python examples/foo.py``): Python puts examples/ on sys.path, not the
repo root. Imported for its side effect: ``import _bootstrap``."""

import os
import sys

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)
