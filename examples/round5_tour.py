"""Tour of the round-5 surface: DataStream V2, async keyed state, the
bucketed exactly-once filesystem warehouse, and State TTL.

Run: python examples/round5_tour.py
(Works with or without the TPU tunnel — the execution path probes the
backend and falls back to CPU.)
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

from flink_tpu import Configuration
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.datastream.v2 import (
    ExecutionEnvironment,
    OneInputStreamProcessFunction,
)
from flink_tpu.state.keyed_state import ReducingStateDescriptor


class RunningTotals(OneInputStreamProcessFunction):
    """V2 process function using ASYNC keyed state: the adds and the
    read coalesce into batched kernels; the future's callback emits."""

    def open(self, ctx):
        self.desc = ReducingStateDescriptor("total", np.add, np.float64,
                                            0.0)

    def process_batch(self, batch, out, ctx):
        st = ctx.async_state(self.desc)
        keys = batch[KEY_ID_FIELD]
        st.add(keys, np.asarray(batch["value"]))

        def emit(totals, b=batch):
            out.collect(b.with_column("running_total", totals))

        st.get(keys).then(emit)


def main() -> None:
    print("== DataStream V2 + async keyed state ==")
    env = ExecutionEnvironment.get_instance(Configuration({
        "execution.micro-batch.size": 8192}))
    sink = CollectSink()
    (env.from_source(DataGenSource(total_records=100_000, num_keys=100,
                                   events_per_second_of_eventtime=50_000),
                     name="orders")
        .key_by("key")
        .process(RunningTotals())
        .to_sink(sink))
    env.execute("v2-running-totals")
    b = sink.result()
    print(f"  {len(b)} rows; max running total "
          f"{float(np.asarray(b['running_total']).max()):.1f}")

    print("== bucketed exactly-once warehouse (SQL) ==")
    from flink_tpu.connectors.filesystem import read_committed_rows
    from flink_tpu.connectors.kafka import FakeBroker
    from flink_tpu.datastream.environment import (
        StreamExecutionEnvironment,
    )
    from flink_tpu.table.environment import StreamTableEnvironment

    warehouse = tempfile.mkdtemp(prefix="flink-tpu-warehouse-")
    broker = FakeBroker.get("default")
    broker.create_topic("trades", 1)
    rng = np.random.default_rng(1)
    n = 20_000
    ts = np.arange(n, dtype=np.int64) * 2
    broker.append("trades", 0, RecordBatch.from_pydict(
        {"sym": rng.integers(0, 8, n), "px": rng.random(n),
         "ts": ts}, timestamps=ts))

    env1 = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 2048,
        # State TTL: idle GROUP BY accumulators expire after 10 min
        "table.exec.state.ttl": 600_000}))
    tenv = StreamTableEnvironment(env1)
    tenv.execute_sql(
        "CREATE TABLE trades (sym BIGINT, px DOUBLE, ts BIGINT, "
        "WATERMARK FOR ts AS ts) "
        "WITH ('connector'='kafka', 'topic'='trades')")
    tenv.execute_sql(
        "CREATE TABLE warehouse (sym BIGINT, window_end BIGINT, "
        "vwap DOUBLE) "
        f"WITH ('connector'='filesystem', 'path'='{warehouse}', "
        "'format'='json', 'sink.bucket-by'='sym')")
    tenv.execute_sql("""
        INSERT INTO warehouse
        SELECT sym, window_end, AVG(px) AS vwap
        FROM TABLE(TUMBLE(TABLE trades, DESCRIPTOR(ts),
                          INTERVAL '5' SECOND))
        GROUP BY sym, window_start, window_end
    """)
    buckets = sorted(os.listdir(warehouse))
    rows = read_committed_rows(warehouse)
    print(f"  {len(rows)} committed rows across buckets {buckets}")

    print("== reading the warehouse back through SQL ==")
    env2 = StreamExecutionEnvironment(Configuration({}))
    tenv2 = StreamTableEnvironment(env2)
    tenv2.execute_sql(
        "CREATE TABLE warehouse (sym BIGINT, window_end BIGINT, "
        "vwap DOUBLE) "
        f"WITH ('connector'='filesystem', 'path'='{warehouse}', "
        "'format'='json')")
    got = tenv2.execute_sql(
        "SELECT sym, COUNT(*) AS windows FROM warehouse GROUP BY sym"
    ).collect()
    print(f"  per-symbol window counts: "
          f"{ {r['sym']: r['windows'] for r in got} }")
    print("done.")


if __name__ == "__main__":
    main()
