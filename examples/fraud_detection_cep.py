"""CEP fraud detection — the reference docs' canonical pattern, extended.

A run of small test-charges NOT followed by a normal purchase, then a big
withdrawal right after — with a negative guard: no verification event may
occur in between (the fraudster never completes 2FA).

Run: python examples/fraud_detection_cep.py
"""

try:
    import _bootstrap  # noqa: F401  (repo-root sys.path when run by file path)
except ImportError:  # exec'd / repo already importable
    pass
import numpy as np

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.cep.operator import CEP
from flink_tpu.cep.pattern import AfterMatchSkipStrategy, Pattern


def main():
    env = StreamExecutionEnvironment(Configuration(
        {"execution.micro-batch.size": 64}))

    tx = []
    # account 1: classic fraud — probes, no verification, big grab
    for i, (kind, amount) in enumerate(
            [("charge", 0.5), ("charge", 0.8), ("withdraw", 900.0)]):
        tx.append({"account": 1, "kind": kind, "amount": amount,
                   "t": i * 1000})
    # account 2: same shape but the user verified in between -> not fraud
    for i, (kind, amount) in enumerate(
            [("charge", 0.6), ("verify", 0.0), ("withdraw", 800.0)]):
        tx.append({"account": 2, "kind": kind, "amount": amount,
                   "t": i * 1000})
    # watermark pusher
    tx.append({"account": 99, "kind": "noop", "amount": 0.0, "t": 60_000})

    # SKIP_PAST_LAST_EVENT: one alert per fraud episode (NO_SKIP would
    # emit every probe-subset combination)
    pattern = (
        Pattern.begin("probe",
                      skip=AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)
        .where(lambda b: (np.asarray(b["kind"]) == "charge")
               & (np.asarray(b["amount"]) < 1.0))
        .one_or_more()
        .not_followed_by("verified")
        .where(lambda b: np.asarray(b["kind"]) == "verify")
        .followed_by("grab")
        .where(lambda b: (np.asarray(b["kind"]) == "withdraw")
               & (np.asarray(b["amount"]) > 500.0))
        .within(10_000)
    )

    alerts = CEP.pattern(
        env.from_collection(tx, timestamp_field="t").key_by("account"),
        pattern,
    ).select(lambda key, match, events: {
        "account": key,
        "probes": len(events["probe"]),
        "amount": events["grab"][0]["amount"],
    })
    rows = alerts.execute_and_collect().to_rows()
    for r in rows:
        print(f"FRAUD account={r['account']} probes={r['probes']} "
              f"amount={r['amount']}")
    assert [r["account"] for r in rows] == [1], rows
    print("ok: only the unverified account alerted")


if __name__ == "__main__":
    main()
