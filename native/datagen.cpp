// Native synthetic-stream generator: the data-loader half of the benchmark
// harness. The reference generates its Nexmark-style load in JVM code
// (reference: flink-examples / the TableEnvironment datagen connector,
// flink-table/flink-table-runtime DataGeneratorSource analog); here the
// generator is one C pass so the measured path spends its single host core
// on the engine, not on producing the input.
//
// Determinism contract: bid i is a pure function of its global index
// (splitmix64), so checkpoint replay and strided multi-subtask splits
// produce identical streams (see flink_tpu/benchmarks/nexmark.py).

#include <cstdint>
#include <cmath>

namespace {

// Bit-exact mirror of flink_tpu.connectors.sources._splitmix64(idx, salt):
// z = idx + salt*PHI; then one splitmix64 finalization round. The native
// and numpy generators MUST produce identical streams — a checkpoint taken
// with one must replay identically under the other.
inline uint64_t splitmix64_salted(uint64_t idx, uint64_t salt) {
  uint64_t z = idx + salt * 0x9E3779B97F4A7C15ull;
  z += 0x9E3779B97F4A7C15ull;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// Generates n bids for global indices idx[i] = first + i * stride.
// Field derivation mirrors BidSource.poll_batch (one hash per record,
// fields sliced from its 64 bits): hot flag 10 bits, auction uniform 22,
// bidder 16, price 16 (Pareto a=3 by inverse transform).
void ngen_bids(int64_t n, int64_t first, int64_t stride, int64_t seed,
               int64_t num_auctions, int64_t num_bidders,
               int64_t hot_ratio_1024, int64_t rate,
               int64_t* out_auction, int64_t* out_bidder,
               float* out_price, int64_t* out_ts) {
  int64_t hot_span = num_auctions / 100;
  if (hot_span < 1) hot_span = 1;
  double inv22 = 1.0 / (double)(1 << 22);
  double inv16 = 1.0 / (double)(1 << 16);
  for (int64_t i = 0; i < n; i++) {
    int64_t idx = first + i * stride;
    uint64_t u = splitmix64_salted((uint64_t)idx, (uint64_t)seed);
    bool hot = (int64_t)(u & 0x3FF) < hot_ratio_1024;
    double ua = (double)((u >> 10) & 0x3FFFFF) * inv22;
    out_auction[i] = (int64_t)(ua * (double)(hot ? hot_span : num_auctions));
    out_bidder[i] = (int64_t)(((u >> 32) & 0xFFFF) * num_bidders) >> 16;
    double up = (double)(u >> 48) * inv16;
    if (up < 1e-12) up = 1e-12;
    // ::pow, not cbrt(1/x): must round identically to np.power(u, -1/3)
    out_price[i] = (float)((::pow(up, -1.0 / 3.0) - 1.0) * 100.0 + 1.0);
    out_ts[i] = (idx * 1000) / rate;
  }
}

}  // extern "C"
