// Batch (key, namespace) -> slot hash index for the TPU slot-table state
// backend. This is the native half of the keyed-state hot path: the role the
// reference delegates to RocksDB/ForSt via JNI (batch point lookups backing
// StateExecutor.executeBatchRequests) is played here by an open-addressing
// table that maps 128-bit (key_id, namespace) pairs to dense device slot ids
// in one C call per micro-batch. No LSM is needed — persistence comes from
// logical snapshots of the slot arrays (see flink_tpu/state/slot_table.py).
//
// Design: linear-probing buckets sized 2x slot capacity (load <= 0.5),
// slot-id free list, slot 0 reserved as the identity slot, growth by
// doubling with full rebuild (bounded amortized cost, mirrors the device
// array growth in Python).
//
// Exposed as a plain C ABI for ctypes; all batch arguments are raw pointers
// into NumPy buffers.

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

struct SlotMap {
  int64_t capacity;      // slot array capacity (includes reserved slot 0)
  int64_t max_capacity;  // growth bound
  int64_t used;          // live entries
  int64_t bucket_count;  // power of two, >= 2*capacity
  int32_t* buckets;      // slot id, -1 empty (deletion is backward-shift,
                         // so no tombstones ever exist)
  int64_t* slot_key;     // [capacity]
  int64_t* slot_ns;      // [capacity]
  uint8_t* slot_used;    // [capacity]
  int32_t* free_stack;   // [capacity]
  int64_t free_top;      // stack size
};

inline uint64_t mix_hash(uint64_t k, uint64_t n) {
  uint64_t x = k ^ (n * 0x9E3779B97F4A7C15ull);
  x ^= x >> 30;
  x *= 0xBF58476D1CE4E5B9ull;
  x ^= x >> 27;
  x *= 0x94D049BB133111EBull;
  x ^= x >> 31;
  return x;
}

void build_buckets(SlotMap* m) {
  int64_t want = m->capacity * 2;
  int64_t bc = 64;
  while (bc < want) bc <<= 1;
  m->bucket_count = bc;
  free(m->buckets);
  m->buckets = (int32_t*)malloc(sizeof(int32_t) * bc);
  for (int64_t i = 0; i < bc; i++) m->buckets[i] = -1;
  uint64_t mask = (uint64_t)bc - 1;
  for (int64_t s = 1; s < m->capacity; s++) {
    if (!m->slot_used[s]) continue;
    uint64_t h = mix_hash((uint64_t)m->slot_key[s], (uint64_t)m->slot_ns[s]);
    uint64_t i = h & mask;
    while (m->buckets[i] >= 0) i = (i + 1) & mask;
    m->buckets[i] = (int32_t)s;
  }
}

// returns 0 on success, -1 if at max capacity
int grow(SlotMap* m) {
  if (m->capacity >= m->max_capacity) return -1;
  int64_t old_cap = m->capacity;
  int64_t new_cap = old_cap * 2;
  if (new_cap > m->max_capacity) new_cap = m->max_capacity;
  m->slot_key = (int64_t*)realloc(m->slot_key, sizeof(int64_t) * new_cap);
  m->slot_ns = (int64_t*)realloc(m->slot_ns, sizeof(int64_t) * new_cap);
  m->slot_used = (uint8_t*)realloc(m->slot_used, sizeof(uint8_t) * new_cap);
  m->free_stack = (int32_t*)realloc(m->free_stack, sizeof(int32_t) * new_cap);
  memset(m->slot_used + old_cap, 0, (size_t)(new_cap - old_cap));
  for (int64_t s = new_cap - 1; s >= old_cap; s--)
    m->free_stack[m->free_top++] = (int32_t)s;
  m->capacity = new_cap;
  build_buckets(m);
  return 0;
}

}  // namespace

extern "C" {

void* sm_create(int64_t initial_capacity, int64_t max_capacity) {
  if (initial_capacity < 1024) initial_capacity = 1024;
  if (max_capacity < initial_capacity) max_capacity = initial_capacity;
  SlotMap* m = (SlotMap*)calloc(1, sizeof(SlotMap));
  m->capacity = initial_capacity;
  m->max_capacity = max_capacity;
  m->slot_key = (int64_t*)calloc(initial_capacity, sizeof(int64_t));
  m->slot_ns = (int64_t*)calloc(initial_capacity, sizeof(int64_t));
  m->slot_used = (uint8_t*)calloc(initial_capacity, 1);
  m->free_stack = (int32_t*)malloc(sizeof(int32_t) * initial_capacity);
  m->free_top = 0;
  for (int64_t s = initial_capacity - 1; s >= 1; s--)
    m->free_stack[m->free_top++] = (int32_t)s;
  m->buckets = nullptr;
  build_buckets(m);
  return m;
}

void sm_destroy(void* h) {
  SlotMap* m = (SlotMap*)h;
  free(m->buckets);
  free(m->slot_key);
  free(m->slot_ns);
  free(m->slot_used);
  free(m->free_stack);
  free(m);
}

int64_t sm_capacity(void* h) { return ((SlotMap*)h)->capacity; }
int64_t sm_used(void* h) { return ((SlotMap*)h)->used; }
const int64_t* sm_slot_keys(void* h) { return ((SlotMap*)h)->slot_key; }
const int64_t* sm_slot_namespaces(void* h) { return ((SlotMap*)h)->slot_ns; }
const uint8_t* sm_slot_used(void* h) { return ((SlotMap*)h)->slot_used; }

// Batch lookup-or-insert. Duplicates within the batch are fine (first
// occurrence inserts, later ones find). out_is_new[i]=1 iff record i
// performed the insert. Returns:
//   >=0 : number of grows that occurred (caller must re-wrap slot arrays)
//   -1  : table full at max_capacity
int32_t sm_lookup_or_insert(void* h, int64_t n, const int64_t* keys,
                            const int64_t* nss, int32_t* out_slots,
                            uint8_t* out_is_new) {
  SlotMap* m = (SlotMap*)h;
  int32_t grows = 0;
  // Chunked software prefetch: the table spans far more than L2, so the
  // bucket probe and the slot_key/slot_ns verify are each a likely cache
  // miss. Hash a chunk up front, prefetch every home bucket line, then
  // peek the (now warm) buckets to prefetch the slot rows. Inserts during
  // processing only make earlier hints stale — hints are never required
  // for correctness.
  constexpr int64_t CHUNK = 256;
  uint64_t hashes[CHUNK];
  for (int64_t base = 0; base < n; base += CHUNK) {
    int64_t end = base + CHUNK < n ? base + CHUNK : n;
    uint64_t pmask = (uint64_t)m->bucket_count - 1;
    for (int64_t r = base; r < end; r++) {
      uint64_t hh = mix_hash((uint64_t)keys[r], (uint64_t)nss[r]);
      hashes[r - base] = hh;
      __builtin_prefetch(&m->buckets[hh & pmask], 0, 1);
    }
    for (int64_t r = base; r < end; r++) {
      int32_t b = m->buckets[hashes[r - base] & pmask];
      if (b >= 0) {
        __builtin_prefetch(&m->slot_key[b], 0, 1);
        __builtin_prefetch(&m->slot_ns[b], 0, 1);
      }
    }
  for (int64_t r = base; r < end; r++) {
    int64_t k = keys[r], ns = nss[r];
    uint64_t mask = (uint64_t)m->bucket_count - 1;
    uint64_t i = hashes[r - base] & mask;
    for (;;) {
      int32_t b = m->buckets[i];
      if (b == -1) {
        // miss -> insert
        if (m->free_top == 0) {
          if (grow(m) != 0) return -1;
          grows++;
          // re-probe against rebuilt buckets
          mask = (uint64_t)m->bucket_count - 1;
          i = mix_hash((uint64_t)k, (uint64_t)ns) & mask;
          continue;
        }
        int32_t slot = m->free_stack[--m->free_top];
        m->buckets[i] = slot;
        m->slot_key[slot] = k;
        m->slot_ns[slot] = ns;
        m->slot_used[slot] = 1;
        m->used++;
        out_slots[r] = slot;
        if (out_is_new) out_is_new[r] = 1;
        break;
      } else if (m->slot_key[b] == k && m->slot_ns[b] == ns) {
        out_slots[r] = b;
        if (out_is_new) out_is_new[r] = 0;
        break;
      }
      i = (i + 1) & mask;
    }
  }
  }
  return grows;
}

// Read-only batch probe: out_slots[i] = slot id, or -1 if the pair is not
// present. Never inserts — this is the queryable-state point-lookup path
// (the role of the reference's QueryableStateClient -> KvStateServer
// lookups against the live backend).
void sm_lookup(void* h, int64_t n, const int64_t* keys, const int64_t* nss,
               int32_t* out_slots) {
  SlotMap* m = (SlotMap*)h;
  uint64_t mask = (uint64_t)m->bucket_count - 1;
  constexpr int64_t CHUNK = 256;
  uint64_t hashes[CHUNK];
  for (int64_t base = 0; base < n; base += CHUNK) {
    int64_t end = base + CHUNK < n ? base + CHUNK : n;
    for (int64_t r = base; r < end; r++) {
      uint64_t hh = mix_hash((uint64_t)keys[r], (uint64_t)nss[r]);
      hashes[r - base] = hh;
      __builtin_prefetch(&m->buckets[hh & mask], 0, 1);
    }
    for (int64_t r = base; r < end; r++) {
      int32_t b = m->buckets[hashes[r - base] & mask];
      if (b >= 0) {
        __builtin_prefetch(&m->slot_key[b], 0, 1);
        __builtin_prefetch(&m->slot_ns[b], 0, 1);
      }
    }
    for (int64_t r = base; r < end; r++) {
      int64_t k = keys[r], ns = nss[r];
      uint64_t i = hashes[r - base] & mask;
      out_slots[r] = -1;
      for (;;) {
        int32_t b = m->buckets[i];
        if (b == -1) break;
        if (m->slot_key[b] == k && m->slot_ns[b] == ns) {
          out_slots[r] = b;
          break;
        }
        i = (i + 1) & mask;
      }
    }
  }
}

// Verify folded slot hints against the table's own metadata: out[i] is
// hints[i] iff the table currently maps (keys[i], nss[i]) at exactly
// that slot, else -1 (caller falls back to the hash probe there). A
// passing verification can never name a wrong row — this IS the
// table's content. One direct-indexed pass; no hashing.
void sm_verify(void* h, int64_t n, const int64_t* keys, const int64_t* nss,
               const int32_t* hints, int32_t* out_slots) {
  SlotMap* m = (SlotMap*)h;
  constexpr int64_t CHUNK = 256;
  for (int64_t base = 0; base < n; base += CHUNK) {
    int64_t end = base + CHUNK < n ? base + CHUNK : n;
    for (int64_t r = base; r < end; r++) {
      int32_t s = hints[r];
      if (s >= 0 && s < m->capacity) {
        __builtin_prefetch(&m->slot_used[s], 0, 1);
        __builtin_prefetch(&m->slot_key[s], 0, 1);
        __builtin_prefetch(&m->slot_ns[s], 0, 1);
      }
    }
    for (int64_t r = base; r < end; r++) {
      int32_t s = hints[r];
      out_slots[r] = (s >= 0 && s < m->capacity && m->slot_used[s] &&
                      m->slot_key[s] == keys[r] && m->slot_ns[s] == nss[r])
                         ? s
                         : -1;
    }
  }
}

// Erase pairs; writes freed slot ids to out_slots (only for pairs that were
// present). Returns the number actually erased. Deletion is backward-shift
// (Knuth 6.4 algorithm R): no tombstones, so probe chains stay short under
// the insert/erase churn of session windows and slice expiry.
int64_t sm_erase(void* h, int64_t n, const int64_t* keys, const int64_t* nss,
                 int32_t* out_slots) {
  SlotMap* m = (SlotMap*)h;
  int64_t erased = 0;
  uint64_t mask = (uint64_t)m->bucket_count - 1;
  constexpr int64_t CHUNK = 256;
  uint64_t hashes[CHUNK];
  for (int64_t base = 0; base < n; base += CHUNK) {
    int64_t end = base + CHUNK < n ? base + CHUNK : n;
    // chunked prefetch (same discipline as the probe paths): session
    // fires erase tens of thousands of scattered pairs per watermark,
    // each probe a likely miss. Erases inside the chunk only stale the
    // hints — correctness never depends on them.
    for (int64_t r = base; r < end; r++) {
      uint64_t hh = mix_hash((uint64_t)keys[r], (uint64_t)nss[r]);
      hashes[r - base] = hh;
      __builtin_prefetch(&m->buckets[hh & mask], 0, 1);
    }
    for (int64_t r = base; r < end; r++) {
      int32_t b = m->buckets[hashes[r - base] & mask];
      if (b >= 0) {
        __builtin_prefetch(&m->slot_key[b], 0, 1);
        __builtin_prefetch(&m->slot_ns[b], 0, 1);
      }
    }
  for (int64_t r = base; r < end; r++) {
    int64_t k = keys[r], ns = nss[r];
    uint64_t i = hashes[r - base] & mask;
    for (;;) {
      int32_t b = m->buckets[i];
      if (b == -1) break;  // not present
      if (m->slot_key[b] == k && m->slot_ns[b] == ns) {
        m->slot_used[b] = 0;
        m->free_stack[m->free_top++] = b;
        m->used--;
        out_slots[erased++] = b;
        // backward-shift: compact the probe chain following i
        uint64_t hole = i;
        uint64_t j = (i + 1) & mask;
        while (m->buckets[j] != -1) {
          int32_t c = m->buckets[j];
          uint64_t home =
              mix_hash((uint64_t)m->slot_key[c], (uint64_t)m->slot_ns[c]) &
              mask;
          // move c into the hole if its home position does not lie
          // (cyclically) strictly after the hole
          uint64_t dist_home = (j - home) & mask;
          uint64_t dist_hole = (j - hole) & mask;
          if (dist_home >= dist_hole) {
            m->buckets[hole] = c;
            hole = j;
          }
          j = (j + 1) & mask;
        }
        m->buckets[hole] = -1;
        break;
      }
      i = (i + 1) & mask;
    }
  }
  }
  return erased;
}

// Fused pane-table ingest, pass A — ONE sweep over the micro-batch doing
// what previously took five numpy passes plus a separate native probe:
//   - slice end per record from its timestamp (aligned windows, floor-mod
//     so pre-epoch timestamps match numpy's np.remainder semantics):
//       se = ts - floormod(ts - offset, width) + width
//   - key -> dense column via the same probe as sm_lookup_or_insert
//     (namespace fixed at 0: a pane-table column is keyed by key only)
//   - distinct slice ends tracked first-seen through a small open hash
// Outputs: out_cols[n] (i32 column ids), out_is_new[n], out_sinv[n]
// (i32 index into out_uniq), out_uniq[maxu] (i64 distinct slice ends,
// first-seen order), *out_k (distinct count), *out_max_col.
// Returns grows (>=0), -1 table full, -2 more than maxu distinct slice
// ends (caller falls back to the unfused path).
int32_t sm_pane_ingest(void* h, int64_t n, const int64_t* keys,
                       const int64_t* ts, int64_t offset, int64_t width,
                       int64_t maxu, int32_t* out_cols, uint8_t* out_is_new,
                       int32_t* out_sinv, int64_t* out_uniq, int64_t* out_k,
                       int64_t* out_max_col) {
  SlotMap* m = (SlotMap*)h;
  int32_t grows = 0;
  // distinct-slice-end scratch hash (tiny: slices per batch is a handful)
  uint64_t nb = 64;
  while (nb < (uint64_t)maxu * 2) nb <<= 1;
  int64_t* se_key = (int64_t*)malloc(sizeof(int64_t) * nb);
  int32_t* se_idx = (int32_t*)malloc(sizeof(int32_t) * nb);
  memset(se_idx, 0xff, sizeof(int32_t) * nb);
  int64_t k_count = 0;
  int64_t max_col = 0;
  constexpr int64_t CHUNK = 256;
  uint64_t hashes[CHUNK];
  for (int64_t base = 0; base < n; base += CHUNK) {
    int64_t end = base + CHUNK < n ? base + CHUNK : n;
    uint64_t pmask = (uint64_t)m->bucket_count - 1;
    for (int64_t r = base; r < end; r++) {
      uint64_t hh = mix_hash((uint64_t)keys[r], 0);
      hashes[r - base] = hh;
      __builtin_prefetch(&m->buckets[hh & pmask], 0, 1);
    }
    for (int64_t r = base; r < end; r++) {
      int32_t b = m->buckets[hashes[r - base] & pmask];
      if (b >= 0) __builtin_prefetch(&m->slot_key[b], 0, 1);
    }
    for (int64_t r = base; r < end; r++) {
      // slice end (floor-mod)
      int64_t x = ts[r] - offset;
      int64_t rem = x % width;
      if (rem < 0) rem += width;
      int64_t se = ts[r] - rem + width;
      uint64_t sb = mix_hash((uint64_t)se, 0) & (nb - 1);
      for (;;) {
        if (se_idx[sb] < 0) {
          if (k_count >= maxu) {
            free(se_key);
            free(se_idx);
            return -2;
          }
          se_key[sb] = se;
          se_idx[sb] = (int32_t)k_count;
          out_uniq[k_count++] = se;
          break;
        }
        if (se_key[sb] == se) break;
        sb = (sb + 1) & (nb - 1);
      }
      out_sinv[r] = se_idx[sb];
      // key -> column (lookup-or-insert, ns = 0)
      int64_t k = keys[r];
      uint64_t mask = (uint64_t)m->bucket_count - 1;
      uint64_t i = hashes[r - base] & mask;
      for (;;) {
        int32_t b = m->buckets[i];
        if (b == -1) {
          if (m->free_top == 0) {
            if (grow(m) != 0) {
              free(se_key);
              free(se_idx);
              return -1;
            }
            grows++;
            mask = (uint64_t)m->bucket_count - 1;
            i = mix_hash((uint64_t)k, 0) & mask;
            continue;
          }
          int32_t slot = m->free_stack[--m->free_top];
          m->buckets[i] = slot;
          m->slot_key[slot] = k;
          m->slot_ns[slot] = 0;
          m->slot_used[slot] = 1;
          m->used++;
          out_cols[r] = slot;
          out_is_new[r] = 1;
          if (slot > max_col) max_col = slot;
          break;
        } else if (m->slot_key[b] == k && m->slot_ns[b] == 0) {
          out_cols[r] = b;
          out_is_new[r] = 0;
          if (b > max_col) max_col = b;
          break;
        }
        i = (i + 1) & mask;
      }
    }
  }
  free(se_key);
  free(se_idx);
  *out_k = k_count;
  *out_max_col = max_col;
  return grows;
}

// Fused pane-table ingest, pass B: the flat i32 scatter index from the
// pass-A columns + the ring rows Python allocated for the distinct slice
// ends (row allocation may grow device arrays, so it stays in Python).
void sm_flat_fuse(int64_t n, const int32_t* cols, const int32_t* sinv,
                  const int64_t* rowmap, int64_t capacity,
                  int32_t* out_flat) {
  for (int64_t i = 0; i < n; i++) {
    out_flat[i] = (int32_t)(rowmap[sinv[i]] * capacity + (int64_t)cols[i]);
  }
}

// Assign a dense row id per DISTINCT key (first-seen order) — the O(n)
// replacement for np.unique(..., return_inverse=True) on the per-fire
// hot path. out_keys needs n int64s (only the first K are written),
// out_row_of needs n int32s. Returns K, the number of distinct keys;
// the caller allocates the [K, n_slices] fire matrix right-sized and
// scatters with one vectorized numpy assignment.
int64_t sm_group_rows(const int64_t* keys, int64_t n, int64_t* out_keys,
                      int32_t* out_row_of) {
  if (n == 0) return 0;
  uint64_t nb = 1;
  while (nb < (uint64_t)n * 2) nb <<= 1;
  int64_t* tbl_key = (int64_t*)malloc(sizeof(int64_t) * nb);
  int32_t* tbl_row = (int32_t*)malloc(sizeof(int32_t) * nb);
  memset(tbl_row, 0xff, sizeof(int32_t) * nb);  // -1 = empty
  int64_t rows = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t k = keys[i];
    uint64_t b = mix_hash((uint64_t)k, 0) & (nb - 1);
    for (;;) {
      if (tbl_row[b] < 0) {
        tbl_key[b] = k;
        tbl_row[b] = (int32_t)rows;
        out_keys[rows++] = k;
        break;
      }
      if (tbl_key[b] == k) break;
      b = (b + 1) & (nb - 1);
    }
    out_row_of[i] = tbl_row[b];
  }
  free(tbl_key);
  free(tbl_row);
  return rows;
}

}  // extern "C"
