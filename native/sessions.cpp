// Native session-metadata plane: ONE C sweep per batch for the host half
// of session windows (sessionize -> absorb -> slot-fold -> pop).
//
// This is the metadata sibling of native/slotmap.cpp: where the slotmap
// plays the RocksDB/ForSt batch-lookup role for the *state* plane, this
// table owns the *merge metadata* (reference: MergingWindowSet) — per-key
// live session intervals, the session-id allocator's fast path, and the
// lazy fire-candidate heap. The Python plane
// (flink_tpu/windowing/session_meta.py) remains the bit-identical
// fallback; flink_tpu/windowing/session_native.py is the ctypes wrapper.
//
// Layout:
//   - singles store: open-addressing hash key -> row over parallel
//     columns (key, start, end, sid, dslot, used). ``dslot`` FOLDS the
//     session's device-plane slot into the metadata row — engines verify
//     it against the state table's metadata views instead of re-probing
//     the state hash per batch (stale folds are harmless: verification
//     fails and the caller falls back to the probe).
//   - multi-key membership set: keys holding >= 2 live sessions live in
//     Python interval lists (exact reference semantics); this set only
//     answers "is this key multi?" during the sweep.
//   - fire chunks: columnar (ends, keys, sids) candidate chunks with
//     cached [lo, hi] end bounds — the watermark cut pops whole chunks
//     and splits only straddlers, exactly mirroring the Python plane's
//     chunk discipline (bit-identical pop order).
//
// All scalar run state (next_sid, max_fired_watermark) stays in Python —
// the sweep takes them as arguments, so there is exactly one source of
// truth and snapshots never consult this object.
//
// Exposed as a plain C ABI for ctypes; batch arguments are raw pointers
// into NumPy buffers.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

inline uint64_t mix_hash(uint64_t k) {
  uint64_t x = k ^ 0x9E3779B97F4A7C15ull;
  x ^= x >> 30;
  x *= 0xBF58476D1CE4E5B9ull;
  x ^= x >> 27;
  x *= 0x94D049BB133111EBull;
  x ^= x >> 31;
  return x;
}

constexpr int64_t kMinPendingEmpty = (int64_t)1 << 62;
constexpr int64_t kNegInf = -((int64_t)1 << 62);

struct Chunk {
  std::vector<int64_t> ends, keys, sids;
  // the session's metadata row at push time (-1 unknown): lets the pop
  // validate by direct row access instead of a hash probe — a stale
  // row (freed/reused since the push) falls back to the probe
  std::vector<int32_t> rows;
  int64_t lo = 0, hi = 0;
};

struct SessionSet {
  // ------------------------------------------------------- singles store
  int64_t capacity = 0;      // row capacity (row 0 is a normal row here)
  int64_t max_capacity = 0;
  int64_t used = 0;
  int64_t bucket_count = 0;
  int32_t* buckets = nullptr;  // row id, -1 empty (backward-shift erase)
  int64_t* row_key = nullptr;
  int64_t* row_start = nullptr;
  int64_t* row_end = nullptr;
  int64_t* row_sid = nullptr;
  int32_t* row_dslot = nullptr;  // folded device slot, -1 unknown
  uint8_t* row_used = nullptr;
  int32_t* free_stack = nullptr;
  int64_t free_top = 0;
  // --------------------------------------------------- multi-key set
  int64_t multi_count = 0;
  uint64_t multi_buckets = 0;  // power of two
  int64_t* multi_key = nullptr;
  uint8_t* multi_used = nullptr;
  // --------------------------------------------------- fire candidates
  std::vector<Chunk*> chunks;
  int64_t min_pending = kMinPendingEmpty;
  // --------------------------------------------------------- pop scratch
  std::vector<int64_t> pk, ps, pe, psid;
  std::vector<int32_t> pslot;
  std::vector<int64_t> rk, rsid, re;
  // ------------------------------------------------------ sort scratch
  std::vector<uint64_t> sv0, sv1;
  std::vector<int64_t> si0, si1;
  std::vector<int64_t> fa_e, fa_k, fa_s, fb_e, fb_k, fb_s;
  std::vector<int32_t> fa_r, fb_r;
};

// ------------------------------------------------------------- row hash

void build_buckets(SessionSet* m) {
  int64_t want = m->capacity * 2;
  int64_t bc = 64;
  while (bc < want) bc <<= 1;
  m->bucket_count = bc;
  free(m->buckets);
  m->buckets = (int32_t*)malloc(sizeof(int32_t) * bc);
  for (int64_t i = 0; i < bc; i++) m->buckets[i] = -1;
  uint64_t mask = (uint64_t)bc - 1;
  for (int64_t r = 0; r < m->capacity; r++) {
    if (!m->row_used[r]) continue;
    uint64_t i = mix_hash((uint64_t)m->row_key[r]) & mask;
    while (m->buckets[i] >= 0) i = (i + 1) & mask;
    m->buckets[i] = (int32_t)r;
  }
}

int grow(SessionSet* m) {
  if (m->capacity >= m->max_capacity) return -1;
  int64_t old_cap = m->capacity;
  int64_t new_cap = old_cap * 2;
  if (new_cap > m->max_capacity) new_cap = m->max_capacity;
  m->row_key = (int64_t*)realloc(m->row_key, sizeof(int64_t) * new_cap);
  m->row_start = (int64_t*)realloc(m->row_start, sizeof(int64_t) * new_cap);
  m->row_end = (int64_t*)realloc(m->row_end, sizeof(int64_t) * new_cap);
  m->row_sid = (int64_t*)realloc(m->row_sid, sizeof(int64_t) * new_cap);
  m->row_dslot = (int32_t*)realloc(m->row_dslot, sizeof(int32_t) * new_cap);
  m->row_used = (uint8_t*)realloc(m->row_used, new_cap);
  m->free_stack = (int32_t*)realloc(m->free_stack,
                                    sizeof(int32_t) * new_cap);
  memset(m->row_used + old_cap, 0, (size_t)(new_cap - old_cap));
  for (int64_t r = new_cap - 1; r >= old_cap; r--)
    m->free_stack[m->free_top++] = (int32_t)r;
  m->capacity = new_cap;
  build_buckets(m);
  return 0;
}

inline int32_t find_row(const SessionSet* m, int64_t key) {
  uint64_t mask = (uint64_t)m->bucket_count - 1;
  uint64_t i = mix_hash((uint64_t)key) & mask;
  for (;;) {
    int32_t b = m->buckets[i];
    if (b == -1) return -1;
    if (m->row_key[b] == key) return b;
    i = (i + 1) & mask;
  }
}

// returns the row, or -1 when the table is full at max capacity
inline int32_t insert_row(SessionSet* m, int64_t key) {
  uint64_t mask = (uint64_t)m->bucket_count - 1;
  uint64_t i = mix_hash((uint64_t)key) & mask;
  for (;;) {
    int32_t b = m->buckets[i];
    if (b == -1) {
      if (m->free_top == 0) {
        if (grow(m) != 0) return -1;
        mask = (uint64_t)m->bucket_count - 1;
        i = mix_hash((uint64_t)key) & mask;
        continue;
      }
      int32_t row = m->free_stack[--m->free_top];
      m->buckets[i] = row;
      m->row_key[row] = key;
      m->row_used[row] = 1;
      m->row_dslot[row] = -1;
      m->used++;
      return row;
    }
    if (m->row_key[b] == key) return b;
    i = (i + 1) & mask;
  }
}

// backward-shift erase (Knuth 6.4 R) — no tombstones under the heavy
// insert/erase churn of session fires
void erase_row(SessionSet* m, int32_t row) {
  uint64_t mask = (uint64_t)m->bucket_count - 1;
  uint64_t i = mix_hash((uint64_t)m->row_key[row]) & mask;
  while (m->buckets[i] != row) i = (i + 1) & mask;
  m->row_used[row] = 0;
  m->free_stack[m->free_top++] = row;
  m->used--;
  uint64_t hole = i;
  uint64_t j = (i + 1) & mask;
  while (m->buckets[j] != -1) {
    int32_t c = m->buckets[j];
    uint64_t home = mix_hash((uint64_t)m->row_key[c]) & mask;
    uint64_t dist_home = (j - home) & mask;
    uint64_t dist_hole = (j - hole) & mask;
    if (dist_home >= dist_hole) {
      m->buckets[hole] = c;
      hole = j;
    }
    j = (j + 1) & mask;
  }
  m->buckets[hole] = -1;
}

// --------------------------------------------------------- multi-key set

void multi_rebuild(SessionSet* m, uint64_t nb) {
  int64_t* ok = m->multi_key;
  uint8_t* ou = m->multi_used;
  uint64_t onb = m->multi_buckets;
  m->multi_key = (int64_t*)malloc(sizeof(int64_t) * nb);
  m->multi_used = (uint8_t*)calloc(nb, 1);
  m->multi_buckets = nb;
  if (ok) {
    for (uint64_t i = 0; i < onb; i++) {
      if (!ou[i]) continue;
      uint64_t j = mix_hash((uint64_t)ok[i]) & (nb - 1);
      while (m->multi_used[j]) j = (j + 1) & (nb - 1);
      m->multi_key[j] = ok[i];
      m->multi_used[j] = 1;
    }
  }
  free(ok);
  free(ou);
}

inline bool multi_contains(const SessionSet* m, int64_t key) {
  if (m->multi_count == 0) return false;
  uint64_t mask = m->multi_buckets - 1;
  uint64_t i = mix_hash((uint64_t)key) & mask;
  while (m->multi_used[i]) {
    if (m->multi_key[i] == key) return true;
    i = (i + 1) & mask;
  }
  return false;
}

// ------------------------------------------------------------ fire chunks

void push_chunk(SessionSet* m, const int64_t* ends, const int64_t* keys,
                const int64_t* sids, const int32_t* rows, int64_t n) {
  if (n == 0) return;
  Chunk* c = new Chunk();
  c->ends.assign(ends, ends + n);
  c->keys.assign(keys, keys + n);
  c->sids.assign(sids, sids + n);
  if (rows != nullptr) {
    c->rows.assign(rows, rows + n);
  } else {
    c->rows.assign(n, -1);
  }
  int64_t lo = ends[0], hi = ends[0];
  for (int64_t i = 1; i < n; i++) {
    if (ends[i] < lo) lo = ends[i];
    if (ends[i] > hi) hi = ends[i];
  }
  c->lo = lo;
  c->hi = hi;
  m->chunks.push_back(c);
  if (lo < m->min_pending) m->min_pending = lo;
}

// ------------------------------------------------- stable radix argsort

// LSD radix argsort over biased-unsigned 64-bit values; stable, so it
// reproduces numpy's kind="stable" permutation exactly. vals is
// clobbered; idx receives the order.
void radix_argsort(SessionSet* m, std::vector<uint64_t>& vals,
                   std::vector<int64_t>& idx, int64_t n) {
  m->sv1.resize(n);
  m->si1.resize(n);
  uint64_t maxv = 0;
  for (int64_t i = 0; i < n; i++)
    if (vals[i] > maxv) maxv = vals[i];
  static thread_local std::vector<int64_t> count;
  count.resize(1 << 16);
  uint64_t* a = vals.data();
  uint64_t* b = m->sv1.data();
  int64_t* ia = idx.data();
  int64_t* ib = m->si1.data();
  for (int pass = 0; pass < 4; pass++) {
    int shift = pass * 16;
    if (pass > 0 && (maxv >> shift) == 0) break;  // higher digits all 0
    std::fill(count.begin(), count.end(), 0);
    for (int64_t i = 0; i < n; i++) count[(a[i] >> shift) & 0xffff]++;
    if (count[(a[0] >> shift) & 0xffff] == n) continue;  // constant digit
    int64_t total = 0;
    for (int64_t d = 0; d < (1 << 16); d++) {
      int64_t c = count[d];
      count[d] = total;
      total += c;
    }
    for (int64_t i = 0; i < n; i++) {
      int64_t pos = count[(a[i] >> shift) & 0xffff]++;
      b[pos] = a[i];
      ib[pos] = ia[i];
    }
    std::swap(a, b);
    std::swap(ia, ib);
  }
  if (ia != idx.data()) {
    memcpy(idx.data(), ia, sizeof(int64_t) * n);
  }
}

// stable (key, ts) argsort — identical permutation to the Python
// plane's packed argsort / lexsort (both stable over the same ordering)
void sort_order(SessionSet* m, const int64_t* keys, const int64_t* ts,
                int64_t n, int64_t* order) {
  int64_t tmin = ts[0], tmax = ts[0], kmin = keys[0], kmax = keys[0];
  for (int64_t i = 1; i < n; i++) {
    if (ts[i] < tmin) tmin = ts[i];
    if (ts[i] > tmax) tmax = ts[i];
    if (keys[i] < kmin) kmin = keys[i];
    if (keys[i] > kmax) kmax = keys[i];
  }
  uint64_t span = (uint64_t)(tmax - tmin);
  int shift = 1;
  while (shift < 64 && (span >> shift) != 0) shift++;
  bool packable = shift <= 62 && kmin >= 0 &&
                  ((uint64_t)kmax >> (62 - shift)) == 0;
  if (packable) {
    m->sv0.resize(n);
    m->si0.resize(n);
    for (int64_t i = 0; i < n; i++) {
      m->sv0[i] = ((uint64_t)keys[i] << shift) | (uint64_t)(ts[i] - tmin);
      m->si0[i] = i;
    }
    radix_argsort(m, m->sv0, m->si0, n);
    memcpy(order, m->si0.data(), sizeof(int64_t) * n);
  } else {
    for (int64_t i = 0; i < n; i++) order[i] = i;
    std::stable_sort(order, order + n, [&](int64_t x, int64_t y) {
      if (keys[x] != keys[y]) return keys[x] < keys[y];
      return ts[x] < ts[y];
    });
  }
}

}  // namespace

extern "C" {

void* sx_create(int64_t initial_capacity, int64_t max_capacity) {
  if (initial_capacity < 1024) initial_capacity = 1024;
  if (max_capacity < initial_capacity) max_capacity = initial_capacity;
  SessionSet* m = new SessionSet();
  m->capacity = initial_capacity;
  m->max_capacity = max_capacity;
  m->row_key = (int64_t*)calloc(initial_capacity, sizeof(int64_t));
  m->row_start = (int64_t*)calloc(initial_capacity, sizeof(int64_t));
  m->row_end = (int64_t*)calloc(initial_capacity, sizeof(int64_t));
  m->row_sid = (int64_t*)calloc(initial_capacity, sizeof(int64_t));
  m->row_dslot = (int32_t*)malloc(sizeof(int32_t) * initial_capacity);
  for (int64_t i = 0; i < initial_capacity; i++) m->row_dslot[i] = -1;
  m->row_used = (uint8_t*)calloc(initial_capacity, 1);
  m->free_stack = (int32_t*)malloc(sizeof(int32_t) * initial_capacity);
  m->free_top = 0;
  for (int64_t r = initial_capacity - 1; r >= 0; r--)
    m->free_stack[m->free_top++] = (int32_t)r;
  build_buckets(m);
  multi_rebuild(m, 64);
  return m;
}

void sx_destroy(void* h) {
  SessionSet* m = (SessionSet*)h;
  free(m->buckets);
  free(m->row_key);
  free(m->row_start);
  free(m->row_end);
  free(m->row_sid);
  free(m->row_dslot);
  free(m->row_used);
  free(m->free_stack);
  free(m->multi_key);
  free(m->multi_used);
  for (Chunk* c : m->chunks) delete c;
  delete m;
}

int64_t sx_capacity(void* h) { return ((SessionSet*)h)->capacity; }
int64_t sx_used(void* h) { return ((SessionSet*)h)->used; }
const int64_t* sx_keys(void* h) { return ((SessionSet*)h)->row_key; }
int64_t* sx_starts(void* h) { return ((SessionSet*)h)->row_start; }
int64_t* sx_ends(void* h) { return ((SessionSet*)h)->row_end; }
int64_t* sx_sids(void* h) { return ((SessionSet*)h)->row_sid; }
int32_t* sx_dslots(void* h) { return ((SessionSet*)h)->row_dslot; }
const uint8_t* sx_used_mask(void* h) { return ((SessionSet*)h)->row_used; }

void sx_lookup(void* h, int64_t n, const int64_t* keys, int32_t* out_rows) {
  SessionSet* m = (SessionSet*)h;
  for (int64_t i = 0; i < n; i++) out_rows[i] = find_row(m, keys[i]);
}

// lookup-or-insert; new rows get dslot=-1 and zeroed interval columns
// (the Python caller writes start/end/sid through the views). Returns
// the number of grows (>0: caller re-wraps views), or -1 when full.
int32_t sx_insert(void* h, int64_t n, const int64_t* keys,
                  int32_t* out_rows) {
  SessionSet* m = (SessionSet*)h;
  int64_t cap0 = m->capacity;
  for (int64_t i = 0; i < n; i++) {
    int32_t r = insert_row(m, keys[i]);
    if (r < 0) return -1;
    out_rows[i] = r;
  }
  int32_t grows = 0;
  for (int64_t c = cap0; c < m->capacity; c *= 2) grows++;
  return grows;
}

void sx_erase_rows(void* h, int64_t n, const int32_t* rows) {
  SessionSet* m = (SessionSet*)h;
  for (int64_t i = 0; i < n; i++) {
    if (rows[i] >= 0 && m->row_used[rows[i]]) erase_row(m, rows[i]);
  }
}

// Scalar forms for the Python slow path (_merge_session walks one
// session at a time): plain int in / int out, no pointer marshalling —
// the array forms cost more in ctypes casts than in hashing at a
// batch of one.
int32_t sx_lookup1(void* h, int64_t key) {
  return find_row((SessionSet*)h, key);
}

int32_t sx_insert1(void* h, int64_t key) {
  return insert_row((SessionSet*)h, key);  // -1 when full at max cap
}

void sx_erase1(void* h, int32_t row) {
  SessionSet* m = (SessionSet*)h;
  if (row >= 0 && m->row_used[row]) erase_row(m, row);
}

void sx_multi_add(void* h, int64_t key) {
  SessionSet* m = (SessionSet*)h;
  if (multi_contains(m, key)) return;
  if ((uint64_t)(m->multi_count + 1) * 2 >= m->multi_buckets)
    multi_rebuild(m, m->multi_buckets * 2);
  uint64_t mask = m->multi_buckets - 1;
  uint64_t i = mix_hash((uint64_t)key) & mask;
  while (m->multi_used[i]) i = (i + 1) & mask;
  m->multi_key[i] = key;
  m->multi_used[i] = 1;
  m->multi_count++;
}

void sx_multi_remove(void* h, int64_t key) {
  SessionSet* m = (SessionSet*)h;
  if (m->multi_count == 0) return;
  uint64_t mask = m->multi_buckets - 1;
  uint64_t i = mix_hash((uint64_t)key) & mask;
  while (m->multi_used[i]) {
    if (m->multi_key[i] == key) {
      m->multi_used[i] = 0;
      m->multi_count--;
      // backward-shift compaction of the probe chain
      uint64_t hole = i;
      uint64_t j = (i + 1) & mask;
      while (m->multi_used[j]) {
        uint64_t home = mix_hash((uint64_t)m->multi_key[j]) & mask;
        uint64_t dist_home = (j - home) & mask;
        uint64_t dist_hole = (j - hole) & mask;
        if (dist_home >= dist_hole) {
          m->multi_key[hole] = m->multi_key[j];
          m->multi_used[hole] = 1;
          m->multi_used[j] = 0;
          hole = j;
        }
        j = (j + 1) & mask;
      }
      return;
    }
    i = (i + 1) & mask;
  }
}

int64_t sx_multi_count(void* h) { return ((SessionSet*)h)->multi_count; }

// Batched probe-and-set of the folded device slot: rows whose stored
// sid still matches take the new slot (a session that merged or fired
// between resolve and fold simply keeps its fold unset).
void sx_fold(void* h, int64_t n, const int64_t* keys, const int64_t* sids,
             const int32_t* slots) {
  SessionSet* m = (SessionSet*)h;
  for (int64_t i = 0; i < n; i++) {
    int32_t r = find_row(m, keys[i]);
    if (r >= 0 && m->row_sid[r] == sids[i]) m->row_dslot[r] = slots[i];
  }
}

// Row-addressed fold: the caller holds the sessions' metadata rows
// from this batch's sweep; the sid guard drops any row the slow path
// re-purposed between sweep and fold. One direct-indexed pass.
void sx_fold_rows(void* h, int64_t n, const int32_t* rows,
                  const int64_t* sids, const int32_t* slots) {
  SessionSet* m = (SessionSet*)h;
  constexpr int64_t CHUNK = 256;
  for (int64_t base = 0; base < n; base += CHUNK) {
    int64_t end = base + CHUNK < n ? base + CHUNK : n;
    for (int64_t i = base; i < end; i++) {
      if (rows[i] >= 0) __builtin_prefetch(&m->row_sid[rows[i]], 0, 1);
    }
    for (int64_t i = base; i < end; i++) {
      int32_t r = rows[i];
      if (r >= 0 && m->row_sid[r] == sids[i]) m->row_dslot[r] = slots[i];
    }
  }
}

void sx_push_chunk(void* h, int64_t n, const int64_t* ends,
                   const int64_t* keys, const int64_t* sids) {
  // Python-side pushes (slow-path buffer drains, restore) carry no row
  // knowledge — those candidates validate via the hash probe
  push_chunk((SessionSet*)h, ends, keys, sids, nullptr, n);
}

int64_t sx_min_pending(void* h) { return ((SessionSet*)h)->min_pending; }

// The fused absorb sweep — ONE pass over the batch columns doing what
// the Python plane does in ~a dozen vectorized numpy passes:
//
//   1. stable (key, ts) argsort (radix when the span packs, mirroring
//      the Python packed-argsort condition — the permutation is
//      identical either way);
//   2. sessionize: gap scan over the sorted stream -> batch-local
//      sessions with (key, min_ts, max_ts + gap);
//   3. classify + apply per session, ascending:
//        FRESH    sole local session, key unknown, not stale: insert a
//                 store row, allocate sid (contiguous block from
//                 ``next_sid``, matching the Python fast path), queue
//                 its fire candidate;
//        EXTENDED sole local session overlapping the key's stored
//                 single: min/max-extend in place, expose the stored
//                 sid AND the folded device slot, queue a fire
//                 candidate iff the end changed;
//        STALE    fresh but already behind the fired watermark
//                 (sid = -1, never stored);
//        SLOW     everything multi-flavored or disjoint-second — the
//                 Python caller runs the exact reference-shaped merge.
//
// Fire candidates land as two chunks (FRESH then EXTENDED) in exactly
// the Python plane's push order, so pop order stays bit-identical.
// Returns the session count m, or -1 when the store hit max capacity.
int64_t sx_absorb(void* h, int64_t n, const int64_t* keys, const int64_t* ts,
                  int64_t gap, int64_t lateness, int64_t max_fired_wm,
                  int64_t next_sid, int64_t* order, int64_t* rec_to_sess,
                  int64_t* sess_key, int64_t* sess_start, int64_t* sess_end,
                  int64_t* sess_sid, int32_t* sess_slot, int32_t* sess_row,
                  uint8_t* sess_flag, int64_t* out_n_fast) {
  SessionSet* m = (SessionSet*)h;
  *out_n_fast = 0;
  if (n == 0) return 0;
  sort_order(m, keys, ts, n, order);
  // sessionize the sorted stream
  int64_t ms = 0;
  int64_t prev_key = 0, prev_ts = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t k = keys[order[i]];
    int64_t t = ts[order[i]];
    if (i == 0 || k != prev_key || t - prev_ts > gap) {
      sess_key[ms] = k;
      sess_start[ms] = t;
      ms++;
    }
    sess_end[ms - 1] = t + gap;
    rec_to_sess[i] = ms - 1;
    prev_key = k;
    prev_ts = t;
  }
  const bool have_wm = max_fired_wm > kNegInf / 2;
  m->fa_e.clear(); m->fa_k.clear(); m->fa_s.clear(); m->fa_r.clear();
  m->fb_e.clear(); m->fb_k.clear(); m->fb_s.clear(); m->fb_r.clear();
  int64_t n_fast = 0;
  // chunked software prefetch (the slotmap discipline): the store spans
  // far more than L2 at high cardinality, so the bucket probe and the
  // row verify are each a likely miss. Hash a chunk of session keys up
  // front, prefetch their home buckets, then peek the (warm) buckets to
  // prefetch the row columns. Inserts during processing only make
  // hints stale — never wrong.
  constexpr int64_t CHUNK = 256;
  uint64_t hashes[CHUNK];
  for (int64_t base = 0; base < ms; base += CHUNK) {
    int64_t endj = base + CHUNK < ms ? base + CHUNK : ms;
    uint64_t pmask = (uint64_t)m->bucket_count - 1;
    for (int64_t j = base; j < endj; j++) {
      uint64_t hh = mix_hash((uint64_t)sess_key[j]);
      hashes[j - base] = hh;
      __builtin_prefetch(&m->buckets[hh & pmask], 0, 1);
    }
    int64_t miss_guess = 0;
    for (int64_t j = base; j < endj; j++) {
      int32_t b = m->buckets[hashes[j - base] & pmask];
      if (b >= 0) {
        __builtin_prefetch(&m->row_key[b], 0, 1);
        __builtin_prefetch(&m->row_end[b], 0, 1);
      } else if (m->free_top > miss_guess) {
        // empty home bucket -> this key likely INSERTS; the free
        // stack is LIFO, so the miss_guess-th miss of this chunk will
        // take free_stack[top-1-miss_guess] — prefetch its row
        // columns for write (a wrong guess only wastes the hint)
        int32_t r = m->free_stack[m->free_top - 1 - miss_guess];
        miss_guess++;
        __builtin_prefetch(&m->row_key[r], 1, 1);
        __builtin_prefetch(&m->row_start[r], 1, 1);
        __builtin_prefetch(&m->row_end[r], 1, 1);
        __builtin_prefetch(&m->row_sid[r], 1, 1);
        __builtin_prefetch(&m->row_dslot[r], 1, 1);
      }
    }
  for (int64_t j = base; j < endj; j++) {
    int64_t k = sess_key[j];
    bool first = (j == 0) || sess_key[j - 1] != k;
    bool only = first && (j == ms - 1 || sess_key[j + 1] != k);
    sess_slot[j] = -1;
    sess_row[j] = -1;
    if (only) {
      int32_t row = find_row(m, k);
      if (row >= 0) {
        int64_t ex_s = m->row_start[row], ex_e = m->row_end[row];
        if (sess_start[j] <= ex_e && ex_s <= sess_end[j]) {
          // overlap-extend the stored single in place
          int64_t ns_ = ex_s < sess_start[j] ? ex_s : sess_start[j];
          int64_t ne_ = ex_e > sess_end[j] ? ex_e : sess_end[j];
          bool changed = ne_ != ex_e;
          m->row_start[row] = ns_;
          m->row_end[row] = ne_;
          sess_sid[j] = m->row_sid[row];
          sess_slot[j] = m->row_dslot[row];
          sess_row[j] = row;
          sess_flag[j] = 1;  // EXTENDED
          if (changed) {
            m->fb_e.push_back(ne_);
            m->fb_k.push_back(k);
            m->fb_s.push_back(m->row_sid[row]);
            m->fb_r.push_back(row);
          }
          continue;
        }
        sess_flag[j] = 2;  // SLOW: disjoint second session of the key
        sess_sid[j] = 0;
        continue;
      }
      if (!multi_contains(m, k)) {
        if (have_wm && sess_end[j] - 1 + lateness <= max_fired_wm) {
          sess_flag[j] = 3;  // STALE on arrival (never stored)
          sess_sid[j] = -1;
          continue;
        }
        int64_t sid = next_sid + n_fast;
        n_fast++;
        int32_t r = insert_row(m, k);
        if (r < 0) return -1;
        m->row_start[r] = sess_start[j];
        m->row_end[r] = sess_end[j];
        m->row_sid[r] = sid;
        m->row_dslot[r] = -1;
        sess_sid[j] = sid;
        sess_row[j] = r;
        sess_flag[j] = 0;  // FRESH
        m->fa_e.push_back(sess_end[j]);
        m->fa_k.push_back(k);
        m->fa_s.push_back(sid);
        m->fa_r.push_back(r);
        continue;
      }
    }
    sess_flag[j] = 2;  // SLOW: the Python merge path fills the sid
    sess_sid[j] = 0;
  }
  }
  // fire-candidate chunks in the Python plane's push order: the FRESH
  // block first, then the EXTENDED block
  push_chunk(m, m->fa_e.data(), m->fa_k.data(), m->fa_s.data(),
             m->fa_r.data(), (int64_t)m->fa_e.size());
  push_chunk(m, m->fb_e.data(), m->fb_k.data(), m->fb_s.data(),
             m->fb_r.data(), (int64_t)m->fb_e.size());
  *out_n_fast = n_fast;
  return ms;
}

// The chunk-bounded watermark cut + validate + remove, in one sweep:
// wholly-due chunks pop whole, wholly-pending chunks are untouched,
// straddlers split once. Due candidates stable-sort by end (the heap
// pop order), validate against the singles store (sid AND end must
// match — merged/extended sessions left stale candidates behind), and
// the fired rows leave the store with their (key, start, end, sid,
// folded slot) columns staged for fetch. Candidates whose key is not
// in the singles store at all are returned as the REST set for the
// Python multi-interval walk. Returns the fired-singles count.
int64_t sx_pop(void* h, int64_t watermark, int64_t* out_rest) {
  SessionSet* m = (SessionSet*)h;
  m->pk.clear(); m->ps.clear(); m->pe.clear(); m->psid.clear();
  m->pslot.clear();
  m->rk.clear(); m->rsid.clear(); m->re.clear();
  *out_rest = 0;
  std::vector<Chunk*> kept;
  static thread_local std::vector<int64_t> due_e, due_k, due_s;
  static thread_local std::vector<int32_t> due_r;
  due_e.clear(); due_k.clear(); due_s.clear(); due_r.clear();
  int64_t minp = kMinPendingEmpty;
  for (Chunk* c : m->chunks) {
    int64_t nc = (int64_t)c->ends.size();
    if (c->hi - 1 <= watermark) {  // wholly due
      due_e.insert(due_e.end(), c->ends.begin(), c->ends.end());
      due_k.insert(due_k.end(), c->keys.begin(), c->keys.end());
      due_s.insert(due_s.end(), c->sids.begin(), c->sids.end());
      due_r.insert(due_r.end(), c->rows.begin(), c->rows.end());
      delete c;
    } else if (c->lo - 1 > watermark) {  // wholly pending: untouched
      kept.push_back(c);
      if (c->lo < minp) minp = c->lo;
    } else {  // straddler: split once
      Chunk* k2 = new Chunk();
      int64_t lo = 0, hi = 0;
      bool any = false;
      for (int64_t i = 0; i < nc; i++) {
        if (c->ends[i] - 1 <= watermark) {
          due_e.push_back(c->ends[i]);
          due_k.push_back(c->keys[i]);
          due_s.push_back(c->sids[i]);
          due_r.push_back(c->rows[i]);
        } else {
          k2->ends.push_back(c->ends[i]);
          k2->keys.push_back(c->keys[i]);
          k2->sids.push_back(c->sids[i]);
          k2->rows.push_back(c->rows[i]);
          if (!any) {
            lo = hi = c->ends[i];
            any = true;
          } else {
            if (c->ends[i] < lo) lo = c->ends[i];
            if (c->ends[i] > hi) hi = c->ends[i];
          }
        }
      }
      delete c;
      k2->lo = lo;
      k2->hi = hi;
      kept.push_back(k2);
      if (lo < minp) minp = lo;
    }
  }
  m->chunks = kept;
  m->min_pending = minp;
  int64_t nd = (int64_t)due_e.size();
  if (nd == 0) return 0;
  // stable argsort by end; min-biased so the radix skips the dead
  // upper digit passes (watermark pops see a narrow end range)
  int64_t emin = due_e[0];
  for (int64_t i = 1; i < nd; i++)
    if (due_e[i] < emin) emin = due_e[i];
  m->sv0.resize(nd);
  m->si0.resize(nd);
  for (int64_t i = 0; i < nd; i++) {
    m->sv0[i] = (uint64_t)(due_e[i] - emin);
    m->si0[i] = i;
  }
  radix_argsort(m, m->sv0, m->si0, nd);
  // validate by DIRECT ROW ACCESS first: most candidates carry their
  // metadata row from push time; a candidate whose row still holds its
  // (key, sid) is decided — fire or drop — with zero hashing. Only
  // candidates whose row was freed/reused since (session fired or
  // merged) or that were pushed rowless (slow path, restore) pay the
  // probe, and those are prefetched a chunk ahead.
  constexpr int64_t CHUNK = 256;
  for (int64_t base = 0; base < nd; base += CHUNK) {
    int64_t endx = base + CHUNK < nd ? base + CHUNK : nd;
    for (int64_t x = base; x < endx; x++) {
      int32_t r = due_r[m->si0[x]];
      if (r >= 0 && r < m->capacity) {
        __builtin_prefetch(&m->row_key[r], 0, 1);
        __builtin_prefetch(&m->row_sid[r], 0, 1);
        __builtin_prefetch(&m->row_used[r], 0, 1);
      }
    }
  for (int64_t x = base; x < endx; x++) {
    int64_t i = m->si0[x];
    int64_t k = due_k[i], sid = due_s[i], e = due_e[i];
    int32_t row = due_r[i];
    if (row >= 0 && row < m->capacity && m->row_used[row] &&
        m->row_key[row] == k && m->row_sid[row] == sid) {
      // the candidate's own row is live with the same (key, sid):
      // this IS the session — validate its end in place
    } else {
      row = find_row(m, k);
      if (row < 0) {
        m->rk.push_back(k);
        m->rsid.push_back(sid);
        m->re.push_back(e);
        continue;
      }
    }
    if (m->row_sid[row] == sid && m->row_end[row] == e) {
      m->pk.push_back(k);
      m->ps.push_back(m->row_start[row]);
      m->pe.push_back(e);
      m->psid.push_back(sid);
      m->pslot.push_back(m->row_dslot[row]);
      erase_row(m, row);
    }
    // else: stale candidate of a merged/extended session — dropped
  }
  }
  *out_rest = (int64_t)m->rk.size();
  return (int64_t)m->pk.size();
}

void sx_pop_fetch(void* h, int64_t* keys, int64_t* starts, int64_t* ends,
                  int64_t* sids, int32_t* slots) {
  SessionSet* m = (SessionSet*)h;
  int64_t n = (int64_t)m->pk.size();
  memcpy(keys, m->pk.data(), sizeof(int64_t) * n);
  memcpy(starts, m->ps.data(), sizeof(int64_t) * n);
  memcpy(ends, m->pe.data(), sizeof(int64_t) * n);
  memcpy(sids, m->psid.data(), sizeof(int64_t) * n);
  memcpy(slots, m->pslot.data(), sizeof(int32_t) * n);
}

void sx_pop_fetch_rest(void* h, int64_t* keys, int64_t* sids,
                       int64_t* ends) {
  SessionSet* m = (SessionSet*)h;
  int64_t n = (int64_t)m->rk.size();
  memcpy(keys, m->rk.data(), sizeof(int64_t) * n);
  memcpy(sids, m->rsid.data(), sizeof(int64_t) * n);
  memcpy(ends, m->re.data(), sizeof(int64_t) * n);
}

// ------------------------------------------------------------------------
// Stateless host-prep sweeps (no store handle): the shard-grouping and
// record-routing passes of the engines' per-batch flow, each replacing
// half a dozen numpy passes over batch-sized arrays with one C pass.
// ------------------------------------------------------------------------

namespace {

// key -> owning shard: EXACTLY flink_tpu.state.keygroups —
// fold 64->32, murmur fmix32, % max_parallelism, then the reference's
// group->subtask formula (remapped into the local key-group range when
// the engine owns a sub-range of the global group space).
inline int64_t shard_of_key(int64_t key, int64_t maxp, int64_t P,
                            int64_t kg_first, int64_t kg_last) {
  uint32_t h = (uint32_t)(uint64_t)(key ^ (key >> 32));
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  int64_t g = (int64_t)(h % (uint32_t)maxp);
  if (kg_first >= 0) {
    // a group outside the engine's range is a misroute: report -1
    // (callers fail loudly). An unchecked (g - kg_first) * P / span
    // would TRUNCATE toward zero where Python floors — group
    // kg_first-1 would silently land on shard 0 instead of erroring.
    if (g < kg_first || g > kg_last) return -1;
    return ((g - kg_first) * P) / (kg_last - kg_first + 1);
  }
  return (g * P) / maxp;
}

}  // namespace

// Per-session shard assignment + stable counting sort of the LIVE
// sessions (sid >= 0) by shard, gathering the resolve columns in one
// pass. out_shard is the full per-session shard column (record routing
// reads it); the *_sorted outputs are the live sessions grouped by
// shard, within-shard session order preserved. Returns the live count.
int64_t sx_shard_group(int64_t m, const int64_t* sess_key,
                       const int64_t* sess_sid, const uint8_t* fresh,
                       const int32_t* slot_hint, const int32_t* meta_row,
                       int64_t P, int64_t maxp, int64_t kg_first,
                       int64_t kg_last, int64_t* out_shard,
                       int64_t* out_counts, int64_t* out_sorted_idx,
                       int64_t* key_sorted, int64_t* sid_sorted,
                       uint8_t* fresh_sorted, int32_t* hint_sorted,
                       int32_t* row_sorted) {
  for (int64_t p = 0; p < P; p++) out_counts[p] = 0;
  for (int64_t j = 0; j < m; j++) {
    int64_t s = shard_of_key(sess_key[j], maxp, P, kg_first, kg_last);
    // a key whose group falls outside the engine's key-group range is
    // a ROUTING BUG upstream — fail loudly (the numpy path raised from
    // bincount/index), never index out_counts out of bounds
    if (s < 0 || s >= P) return -1;
    out_shard[j] = s;
    if (sess_sid[j] >= 0) out_counts[s]++;
  }
  // exclusive prefix -> write cursors
  static thread_local std::vector<int64_t> cursor;
  cursor.resize(P);
  int64_t total = 0;
  for (int64_t p = 0; p < P; p++) {
    cursor[p] = total;
    total += out_counts[p];
  }
  for (int64_t j = 0; j < m; j++) {
    if (sess_sid[j] < 0) continue;
    int64_t pos = cursor[out_shard[j]]++;
    out_sorted_idx[pos] = j;
    key_sorted[pos] = sess_key[j];
    sid_sorted[pos] = sess_sid[j];
    fresh_sorted[pos] = fresh[j];
    hint_sorted[pos] = slot_hint[j];
    row_sorted[pos] = meta_row[j];
  }
  return total;
}

// Per-shard record counts in one pass (the batch-split working-set
// bound pays this EVERY batch): returns the max count over shards.
int64_t sx_rec_shard_max(int64_t n, const int64_t* keys, int64_t P,
                         int64_t maxp, int64_t kg_first, int64_t kg_last) {
  static thread_local std::vector<int64_t> counts;
  counts.resize(P);
  std::fill(counts.begin(), counts.end(), 0);
  for (int64_t i = 0; i < n; i++) {
    int64_t s = shard_of_key(keys[i], maxp, P, kg_first, kg_last);
    if (s < 0 || s >= P) return -1;  // misrouted key: fail loudly
    counts[s]++;
  }
  int64_t mx = 0;
  for (int64_t p = 0; p < P; p++)
    if (counts[p] > mx) mx = counts[p];
  return mx;
}

// Record routing: scatter each record's session slot and shard through
// the sort order — rec[order[i]] = per_session[rec_to_sess[i]] — with
// the resolved slots arriving as (sorted_idx, slot_sorted) pairs from
// the per-shard resolve. One pass in C for what took a slot scatter
// plus two gather+scatter round trips in numpy.
void sx_route(int64_t n, int64_t m, const int64_t* order,
              const int64_t* rec_to_sess, int64_t n_live,
              const int64_t* sorted_idx, const int32_t* slot_sorted,
              const int64_t* sess_shard, int32_t* out_rec_slots,
              int64_t* out_rec_shards) {
  static thread_local std::vector<int32_t> slot_of;
  slot_of.resize(m);
  std::fill(slot_of.begin(), slot_of.end(), 0);
  for (int64_t i = 0; i < n_live; i++)
    slot_of[sorted_idx[i]] = slot_sorted[i];
  for (int64_t i = 0; i < n; i++) {
    int64_t j = rec_to_sess[i];
    int64_t dst = order[i];
    out_rec_slots[dst] = slot_of[j];
    out_rec_shards[dst] = sess_shard[j];
  }
}

}  // extern "C"
