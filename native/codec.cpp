// Columnar RecordBatch wire codec — the native record (de)serializer of the
// data plane. Role parity: the reference compiles its hot coders to native
// code (flink-python/pyflink/fn_execution/coder_impl_fast.pyx) and
// compresses shuffle/state buffers with lz4/snappy JNI (root pom.xml:168);
// SURVEY.md §2.10 items 5 and 7.
//
// Design: one C call encodes a whole columnar batch payload (concatenated
// raw column buffers) into a self-contained block:
//
//   u32 magic 'FTRB' | u16 version | u16 flags | u64 raw_len | u64 enc_len
//   | u32 crc32(raw) | enc bytes
//
// flags bit0: payload is LZ-compressed (greedy byte-level LZ with a 64Ki
// hash table — FastLZ-class ratio/speed, no external deps). Encoding falls
// back to stored form when compression does not help. The CRC is over the
// raw payload, so corruption in transit OR a bad decompression both fail
// loudly. Column metadata (names/dtypes/offsets) travels in a small
// Python-built header next to this block: the *bulk bytes* take the native
// path, the few dozen metadata bytes do not need C++.
//
// No object (de)serialization happens here — unlike pickle, a hostile
// frame can at worst fail the CRC, not execute code.

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// ---------------------------------------------------------------- crc32
// Table built by a static initializer: runs before any thread can call
// into the library (a lazy flag would be a data race under the GIL-free
// ctypes calls of concurrent shuffle threads).
struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const CrcTable crc_tbl;

uint32_t crc32(const uint8_t* p, uint64_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (uint64_t i = 0; i < n; i++)
    c = crc_tbl.t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------- LZ codec
// Greedy LZ77, byte-oriented. Token stream:
//   literal run:  0x00..0x1F -> (ctrl+1) literal bytes follow
//   match:        ctrl >= 0x20: len3 = ctrl >> 5 (1..7), offs_hi = ctrl & 0x1F
//                 if len3 == 7 an extension byte adds to the length
//                 next byte: offs_lo; offset = (offs_hi << 8 | offs_lo) + 1
//                 match length = len3 + 2 (+ext)
// Max offset 8192, min match 3 — the FastLZ level-1 scheme.

constexpr uint32_t KMAX_OFFSET = 8191;

uint64_t lz_compress(const uint8_t* in, uint64_t n, uint8_t* out,
                     uint64_t out_cap) {
  if (n < 16) return 0;  // not worth it
  uint32_t htab[1 << 16];
  memset(htab, 0, sizeof(htab));
  uint64_t ip = 0, op = 0;
  uint64_t lit_start = 0;

  auto hash3 = [&](uint64_t i) -> uint32_t {
    uint32_t v;
    memcpy(&v, in + i, 4);
    return (v * 2654435761u) >> 16;
  };
  auto flush_lits = [&](uint64_t end) -> bool {
    uint64_t len = end - lit_start;
    while (len > 0) {
      uint64_t run = len > 32 ? 32 : len;
      if (op + 1 + run > out_cap) return false;
      out[op++] = (uint8_t)(run - 1);
      memcpy(out + op, in + lit_start, run);
      op += run;
      lit_start += run;
      len -= run;
    }
    return true;
  };

  while (ip + 4 < n) {
    uint32_t h = hash3(ip);
    uint64_t ref = htab[h];
    htab[h] = (uint32_t)ip;
    uint64_t dist = ip - ref;
    if (ref < ip && dist <= KMAX_OFFSET &&
        in[ref] == in[ip] && in[ref + 1] == in[ip + 1] &&
        in[ref + 2] == in[ip + 2]) {
      // extend
      uint64_t len = 3;
      uint64_t max_len = n - ip;
      while (len < max_len && in[ref + len] == in[ip + len]) len++;
      if (!flush_lits(ip)) return 0;
      uint64_t remaining = len;
      uint64_t offs = dist - 1;
      while (remaining >= 3) {
        uint64_t take = remaining;
        uint64_t l3 = take - 2;
        if (l3 >= 7) {
          uint64_t ext = l3 - 7;
          if (ext > 255) { ext = 255; take = 7 + 2 + 255; }
          if (op + 3 > out_cap) return 0;
          out[op++] = (uint8_t)(0xE0 | (offs >> 8));
          out[op++] = (uint8_t)ext;
          out[op++] = (uint8_t)(offs & 0xFF);
        } else {
          if (op + 2 > out_cap) return 0;
          out[op++] = (uint8_t)((l3 << 5) | (offs >> 8));
          out[op++] = (uint8_t)(offs & 0xFF);
        }
        remaining -= take;
        if (remaining > 0 && remaining < 3) {
          // tail too short for a match token — emit as literals
          break;
        }
      }
      ip += len - remaining;
      lit_start = ip;
      // re-seed hashes inside the match sparsely (every 8th) for speed
      for (uint64_t j = ip > 8 ? ip - 8 : 0; j + 4 < ip; j += 2)
        htab[hash3(j)] = (uint32_t)j;
    } else {
      ip++;
    }
  }
  if (!flush_lits(n)) return 0;
  return op;
}

int lz_decompress(const uint8_t* in, uint64_t n, uint8_t* out,
                  uint64_t raw_len) {
  uint64_t ip = 0, op = 0;
  while (ip < n) {
    uint8_t ctrl = in[ip++];
    if (ctrl < 0x20) {
      uint64_t run = (uint64_t)ctrl + 1;
      if (ip + run > n || op + run > raw_len) return -1;
      memcpy(out + op, in + ip, run);
      ip += run;
      op += run;
    } else {
      uint64_t l3 = ctrl >> 5;
      uint64_t len = l3 + 2;
      if (l3 == 7) {
        if (ip >= n) return -1;
        len += in[ip++];
      }
      if (ip >= n) return -1;
      uint64_t offs = (((uint64_t)(ctrl & 0x1F)) << 8 | in[ip++]) + 1;
      if (offs > op || op + len > raw_len) return -1;
      // overlapping copy must run forward byte-wise
      const uint8_t* src = out + op - offs;
      uint8_t* dst = out + op;
      for (uint64_t i = 0; i < len; i++) dst[i] = src[i];
      op += len;
    }
  }
  return op == raw_len ? 0 : -1;
}

constexpr uint32_t MAGIC = 0x42525446u;  // 'FTRB' little-endian
constexpr uint16_t VERSION = 1;
constexpr uint64_t HEADER = 4 + 2 + 2 + 8 + 8 + 4;

void put_header(uint8_t* f, uint16_t flags, uint64_t raw_len,
                uint64_t enc_len, uint32_t crc) {
  memcpy(f, &MAGIC, 4);
  memcpy(f + 4, &VERSION, 2);
  memcpy(f + 6, &flags, 2);
  memcpy(f + 8, &raw_len, 8);
  memcpy(f + 16, &enc_len, 8);
  memcpy(f + 24, &crc, 4);
}

}  // namespace

extern "C" {

// Encode a raw payload into a framed block. Returns a malloc'd frame via
// out/out_len (caller frees with codec_free), or -1 on allocation failure.
// compress=0 forces stored form.
int codec_encode(const uint8_t* raw, uint64_t raw_len, int compress,
                 uint8_t** out, uint64_t* out_len) {
  uint32_t crc = crc32(raw, raw_len);
  uint8_t* frame = nullptr;
  if (compress && raw_len >= 64) {
    uint64_t cap = raw_len - raw_len / 16;  // only keep wins >= ~6%
    uint8_t* tmp = (uint8_t*)malloc(cap ? cap : 1);
    if (!tmp) return -1;
    uint64_t enc = lz_compress(raw, raw_len, tmp, cap);
    if (enc > 0 && enc < raw_len) {
      frame = (uint8_t*)malloc(HEADER + enc);
      if (!frame) { free(tmp); return -1; }
      put_header(frame, 1, raw_len, enc, crc);
      memcpy(frame + HEADER, tmp, enc);
      free(tmp);
      *out = frame;
      *out_len = HEADER + enc;
      return 0;
    }
    free(tmp);
  }
  frame = (uint8_t*)malloc(HEADER + raw_len);
  if (!frame) return -1;
  put_header(frame, 0, raw_len, raw_len, crc);
  memcpy(frame + HEADER, raw, raw_len);
  *out = frame;
  *out_len = HEADER + raw_len;
  return 0;
}

// Peek the raw payload size of a frame (for caller-side allocation).
// Returns raw_len, or -1 if the frame is malformed.
int64_t codec_raw_len(const uint8_t* frame, uint64_t frame_len) {
  if (frame_len < HEADER) return -1;
  uint32_t magic;
  uint16_t version;
  memcpy(&magic, frame, 4);
  memcpy(&version, frame + 4, 2);
  if (magic != MAGIC || version != VERSION) return -1;
  uint64_t raw_len;
  memcpy(&raw_len, frame + 8, 8);
  return (int64_t)raw_len;
}

// Decode into a caller-provided buffer of codec_raw_len() bytes.
// Returns 0 ok, -1 malformed, -2 length mismatch, -3 CRC mismatch.
int codec_decode(const uint8_t* frame, uint64_t frame_len, uint8_t* out,
                 uint64_t out_cap) {
  if (frame_len < HEADER) return -1;
  uint32_t magic;
  uint16_t version, flags;
  uint64_t raw_len, enc_len;
  uint32_t crc;
  memcpy(&magic, frame, 4);
  memcpy(&version, frame + 4, 2);
  memcpy(&flags, frame + 6, 2);
  memcpy(&raw_len, frame + 8, 8);
  memcpy(&enc_len, frame + 16, 8);
  memcpy(&crc, frame + 24, 4);
  if (magic != MAGIC || version != VERSION) return -1;
  if (flags & ~1u) return -1;  // unknown flag bits: reject, don't guess
  if (HEADER + enc_len != frame_len || out_cap < raw_len) return -2;
  if (flags & 1) {
    if (lz_decompress(frame + HEADER, enc_len, out, raw_len) != 0)
      return -1;
  } else {
    if (enc_len != raw_len) return -2;
    memcpy(out, frame + HEADER, raw_len);
  }
  if (crc32(out, raw_len) != crc) return -3;
  return 0;
}

void codec_free(uint8_t* p) { free(p); }

}  // extern "C"
