// Native hot-row probe table: the GIL-free serving cache.
//
// This is the serving sibling of native/sessions.cpp: where the session
// plane owns merge metadata, this table owns the HOT-ROW CACHE of the
// read-replica serving plane (flink_tpu/tenancy/hot_cache.py is the
// bit-identical Python fallback; flink_tpu/tenancy/hot_cache_native.py
// is the ctypes wrapper). The cost model it exists for: at cache-hit
// QPS the old path spent more time on the interpreter lock (one Python
// dict probe + per-key bookkeeping per hit, all serialized on the GIL)
// than on the probes themselves. Here a whole key batch probes in ONE
// C call — ctypes releases the GIL for the call, so concurrent serving
// clients probe in parallel with each other AND with the ingesting
// task thread.
//
// Layout (struct-of-arrays, one table per (job, operator)):
//   - open addressing over pow2 slots, linear probing, bounded window
//     (load factor <= 0.5 by construction; deletions leave tombstones
//     the probe walks past and inserts reuse);
//   - each slot holds a PACKED COMPOSED RESULT: a fixed header (key,
//     generation, entry count) plus up to ``entry_cap`` entries of
//     (namespace i64, per-column value words, a per-entry dtype tag
//     bitmask). Values are raw int64 bit patterns — float64 and int64
//     round-trip EXACTLY (the tag says which each column is);
//   - a seqlock-style even/odd STAMP per slot: writers (the publish
//     prime on the task thread, worker puts) flip the stamp odd, write,
//     flip it even; readers never take a lock — they re-check the stamp
//     around the copy and a torn read RETRIES, then falls to the miss
//     path. A reader can never observe a mixed-generation row.
//
// Writers serialize on one per-table mutex (primes and puts are rare
// next to probes; the mutex is held only inside the GIL-released call),
// readers never touch it. Capacity pressure evicts the oldest
// generation in the probe window — approximate LRU by publish age,
// which is the invalidation clock anyway.
//
// Exposed as a plain C ABI for ctypes; batch arguments are raw pointers
// into NumPy buffers. All exported symbols are prefixed ``hc_`` (the
// NATIVE_SYMBOL_PREFIXES registry; flint NAT01 polices the ctypes
// declarations).

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>

namespace {

inline uint64_t mix_hash(uint64_t k) {
  uint64_t x = k ^ 0x9E3779B97F4A7C15ull;
  x ^= x >> 30;
  x *= 0xBF58476D1CE4E5B9ull;
  x ^= x >> 27;
  x *= 0x94D049BB133111EBull;
  x ^= x >> 31;
  return x;
}

// slot states
constexpr uint8_t kEmpty = 0;
constexpr uint8_t kLive = 1;
constexpr uint8_t kTomb = 2;

// counter indices (hc_stat)
enum Stat {
  kHits = 0,
  kMisses = 1,
  kEvictions = 2,
  kPrimes = 3,
  kPuts = 4,
  kTornRetries = 5,
  kTornMisses = 6,
  kOversizeDrops = 7,
  kStatCount = 8,
};

constexpr int kReadRetries = 4;

struct HotTable {
  int64_t n_slots = 0;     // pow2
  int64_t mask = 0;
  int64_t max_probe = 0;
  int64_t n_cols = 0;
  int64_t entry_cap = 0;
  std::atomic<int64_t> live{0};
  std::atomic<int64_t> stats[kStatCount];
  std::mutex write_mu;

  std::atomic<uint64_t>* stamp = nullptr;
  std::atomic<uint8_t>* state = nullptr;
  std::atomic<int64_t>* key = nullptr;
  int64_t* gen = nullptr;
  int32_t* n = nullptr;         // entries used in the slot
  int64_t* ns = nullptr;        // [n_slots * entry_cap]
  int64_t* vals = nullptr;      // [n_slots * entry_cap * n_cols]
  uint64_t* tags = nullptr;     // [n_slots * entry_cap] dtype bitmasks

  ~HotTable() {
    delete[] stamp;
    delete[] state;
    delete[] key;
    std::free(gen);
    std::free(n);
    std::free(ns);
    std::free(vals);
    std::free(tags);
  }
};

inline int64_t pow2_at_least(int64_t v) {
  int64_t p = 64;
  while (p < v) p <<= 1;
  return p;
}

// ---- writer-side slot lock (the seqlock write half). Callers hold
// write_mu, so the CAS never actually contends with another writer —
// the odd stamp exists for READERS to detect the in-progress write.
inline uint64_t lock_slot(HotTable* t, int64_t j) {
  uint64_t s = t->stamp[j].load(std::memory_order_relaxed) & ~1ull;
  t->stamp[j].store(s + 1, std::memory_order_release);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  return s;
}

inline void unlock_slot(HotTable* t, int64_t j, uint64_t s) {
  t->stamp[j].store(s + 2, std::memory_order_release);
}

// Find the slot for `k` under write_mu: (found_slot, insert_slot).
// found >= 0 when the key is live in the window; insert is the first
// reusable slot (empty/tombstone), or — window full of other live
// keys — the live slot with the OLDEST generation (the eviction
// victim), flagged via *evict.
inline void locate_for_write(HotTable* t, int64_t k, int64_t* found,
                             int64_t* insert, bool* evict) {
  *found = -1;
  *insert = -1;
  *evict = false;
  int64_t j = (int64_t)(mix_hash((uint64_t)k)) & t->mask;
  int64_t victim = -1;
  int64_t victim_gen = INT64_MAX;
  for (int64_t step = 0; step < t->max_probe; ++step) {
    uint8_t st = t->state[j].load(std::memory_order_relaxed);
    if (st == kEmpty) {
      if (*insert < 0) *insert = j;
      return;  // key cannot be past the first empty
    }
    if (st == kTomb) {
      if (*insert < 0) *insert = j;
    } else {  // live
      if (t->key[j].load(std::memory_order_relaxed) == k) {
        *found = j;
        return;
      }
      if (t->gen[j] < victim_gen) {
        victim_gen = t->gen[j];
        victim = j;
      }
    }
    j = (j + 1) & t->mask;
  }
  if (*insert < 0) {
    *insert = victim;
    *evict = true;
  }
}

inline void write_payload(HotTable* t, int64_t j, int64_t k, int64_t g,
                          int64_t cnt, const int64_t* src_ns,
                          const int64_t* src_vals,
                          const uint64_t* src_tags) {
  t->key[j].store(k, std::memory_order_relaxed);
  t->gen[j] = g;
  t->n[j] = (int32_t)cnt;
  std::memcpy(t->ns + j * t->entry_cap, src_ns, cnt * sizeof(int64_t));
  std::memcpy(t->vals + j * t->entry_cap * t->n_cols, src_vals,
              cnt * t->n_cols * sizeof(int64_t));
  std::memcpy(t->tags + j * t->entry_cap, src_tags,
              cnt * sizeof(uint64_t));
}

// erase under the slot lock (caller holds write_mu + slot stamp odd)
inline void erase_slot(HotTable* t, int64_t j) {
  if (t->state[j].load(std::memory_order_relaxed) == kLive) {
    t->state[j].store(kTomb, std::memory_order_relaxed);
    t->live.fetch_sub(1, std::memory_order_relaxed);
  }
}

}  // namespace

extern "C" {

void* hc_create(int64_t max_entries, int64_t n_cols, int64_t entry_cap) {
  if (max_entries <= 0 || n_cols <= 0 || n_cols > 63 || entry_cap <= 0)
    return nullptr;
  HotTable* t = new HotTable();
  // load factor <= 0.5: probes stay inside a short window
  t->n_slots = pow2_at_least(max_entries * 2);
  t->mask = t->n_slots - 1;
  t->max_probe = t->n_slots < 128 ? t->n_slots : 128;
  t->n_cols = n_cols;
  t->entry_cap = entry_cap;
  for (int i = 0; i < kStatCount; ++i) t->stats[i].store(0);
  t->stamp = new std::atomic<uint64_t>[t->n_slots];
  t->state = new std::atomic<uint8_t>[t->n_slots];
  t->key = new std::atomic<int64_t>[t->n_slots];
  for (int64_t i = 0; i < t->n_slots; ++i) {
    t->stamp[i].store(0, std::memory_order_relaxed);
    t->state[i].store(kEmpty, std::memory_order_relaxed);
    t->key[i].store(0, std::memory_order_relaxed);
  }
  t->gen = (int64_t*)std::calloc(t->n_slots, sizeof(int64_t));
  t->n = (int32_t*)std::calloc(t->n_slots, sizeof(int32_t));
  t->ns = (int64_t*)std::calloc(t->n_slots * entry_cap, sizeof(int64_t));
  t->vals = (int64_t*)std::calloc(t->n_slots * entry_cap * n_cols,
                                  sizeof(int64_t));
  t->tags =
      (uint64_t*)std::calloc(t->n_slots * entry_cap, sizeof(uint64_t));
  if (!t->gen || !t->n || !t->ns || !t->vals || !t->tags) {
    delete t;
    return nullptr;
  }
  return t;
}

void hc_destroy(void* h) { delete (HotTable*)h; }

int64_t hc_len(void* h) {
  return ((HotTable*)h)->live.load(std::memory_order_relaxed);
}

int64_t hc_capacity(void* h) { return ((HotTable*)h)->n_slots; }

int64_t hc_stat(void* h, int32_t which) {
  HotTable* t = (HotTable*)h;
  if (which < 0 || which >= kStatCount) return -1;
  return t->stats[which].load(std::memory_order_relaxed);
}

void hc_add_stat(void* h, int32_t which, int64_t delta) {
  // the wrapper folds Python-side overflow-path traffic into the same
  // counters so stats() reads one source whatever path served
  HotTable* t = (HotTable*)h;
  if (which < 0 || which >= kStatCount) return;
  t->stats[which].fetch_add(delta, std::memory_order_relaxed);
}

void hc_clear(void* h) {
  HotTable* t = (HotTable*)h;
  std::lock_guard<std::mutex> g(t->write_mu);
  for (int64_t j = 0; j < t->n_slots; ++j) {
    uint64_t s = lock_slot(t, j);
    erase_slot(t, j);
    t->state[j].store(kEmpty, std::memory_order_relaxed);
    unlock_slot(t, j, s);
  }
}

// Batch probe: ONE call for the whole key batch (the serving hot
// loop). Hit entries land COMPACTLY: key i's counts[i] entries follow
// the previous hits' in out_ns / out_tags (and counts[i]*n_cols value
// words in out_vals) — the caller sizes the buffers at nk*entry_cap
// worst case and bulk-converts exactly sum(counts) entries, no
// per-key stride walking.
// ``exact_gen`` < 0 = presence-implies-validity (the primed serving
// path: ANY live entry hits); >= 0 = only that generation hits.
// A torn read (stamp moved under the copy) retries, then counts a
// torn miss and reports MISS — never a mixed-generation row.
// Returns the hit count.
int64_t hc_get_batch(void* h, int64_t nk, const int64_t* keys,
                     int64_t exact_gen, uint8_t* hit, int32_t* counts,
                     int64_t* out_gen, int64_t* out_ns, int64_t* out_vals,
                     uint64_t* out_tags) {
  HotTable* t = (HotTable*)h;
  int64_t hits = 0;
  int64_t tot = 0;  // compact output cursor (entries)
  int64_t torn_retries = 0, torn_misses = 0;
  for (int64_t i = 0; i < nk; ++i) {
    const int64_t k = keys[i];
    hit[i] = 0;
    counts[i] = 0;
    bool done = false;
    for (int attempt = 0; attempt < kReadRetries && !done; ++attempt) {
      int64_t j = (int64_t)(mix_hash((uint64_t)k)) & t->mask;
      bool torn = false;
      for (int64_t step = 0; step < t->max_probe; ++step) {
        uint8_t st = t->state[j].load(std::memory_order_acquire);
        if (st == kEmpty) break;  // definitive miss for this attempt
        if (st == kLive &&
            t->key[j].load(std::memory_order_relaxed) == k) {
          uint64_t s1 = t->stamp[j].load(std::memory_order_acquire);
          if (s1 & 1) {  // write in progress
            torn = true;
            break;
          }
          int64_t g = t->gen[j];
          int32_t cnt = t->n[j];
          if (cnt > t->entry_cap) cnt = (int32_t)t->entry_cap;
          std::memcpy(out_ns + tot, t->ns + j * t->entry_cap,
                      cnt * sizeof(int64_t));
          std::memcpy(out_vals + tot * t->n_cols,
                      t->vals + j * t->entry_cap * t->n_cols,
                      cnt * t->n_cols * sizeof(int64_t));
          std::memcpy(out_tags + tot, t->tags + j * t->entry_cap,
                      cnt * sizeof(uint64_t));
          std::atomic_thread_fence(std::memory_order_acquire);
          uint64_t s2 = t->stamp[j].load(std::memory_order_relaxed);
          if (s1 != s2 ||
              t->key[j].load(std::memory_order_relaxed) != k) {
            torn = true;  // writer moved under us: retry the key
            break;
          }
          if (exact_gen >= 0 && g != exact_gen) break;  // stale: miss
          out_gen[i] = g;
          counts[i] = cnt;
          hit[i] = 1;
          tot += cnt;
          ++hits;
          done = true;
          break;
        }
        j = (j + 1) & t->mask;
      }
      if (done) break;
      if (!torn) break;  // clean miss — no point retrying
      ++torn_retries;
      if (attempt == kReadRetries - 1) ++torn_misses;
    }
  }
  t->stats[kHits].fetch_add(hits, std::memory_order_relaxed);
  t->stats[kMisses].fetch_add(nk - hits, std::memory_order_relaxed);
  if (torn_retries)
    t->stats[kTornRetries].fetch_add(torn_retries,
                                     std::memory_order_relaxed);
  if (torn_misses)
    t->stats[kTornMisses].fetch_add(torn_misses,
                                    std::memory_order_relaxed);
  return hits;
}

// Batch put (worker miss-resolution feed): whole-value replace with the
// no-downgrade rule — an existing entry tagged with a NEWER generation
// is never overwritten by a stale worker result. Entries are packed
// flat with off[nk] prefix offsets (off[i]..off[i+1] in ns/tags;
// times n_cols in vals). A value wider than entry_cap cannot be
// represented: the key is dropped instead (counted; it simply stays a
// miss — the cache is best-effort). Returns entries written.
int64_t hc_put_batch(void* h, int64_t nk, const int64_t* keys,
                     const int64_t* gens, const int64_t* off,
                     const int64_t* ns, const int64_t* vals,
                     const uint64_t* tags) {
  HotTable* t = (HotTable*)h;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t written = 0, evictions = 0, oversize = 0;
  for (int64_t i = 0; i < nk; ++i) {
    const int64_t k = keys[i];
    const int64_t cnt = off[i + 1] - off[i];
    int64_t found, insert;
    bool evict;
    locate_for_write(t, k, &found, &insert, &evict);
    if (cnt > t->entry_cap) {
      ++oversize;
      if (found >= 0) {
        uint64_t s = lock_slot(t, found);
        erase_slot(t, found);
        unlock_slot(t, found, s);
      }
      continue;
    }
    int64_t j = found >= 0 ? found : insert;
    if (j < 0) continue;  // no slot (tiny table fully torn) — skip
    if (found >= 0 && t->gen[found] > gens[i]) continue;  // no downgrade
    if (found < 0 && evict) ++evictions;
    uint64_t s = lock_slot(t, j);
    if (found < 0) {
      if (t->state[j].load(std::memory_order_relaxed) != kLive)
        t->live.fetch_add(1, std::memory_order_relaxed);
      t->state[j].store(kLive, std::memory_order_relaxed);
    }
    write_payload(t, j, k, gens[i], cnt, ns + off[i],
                  vals + off[i] * t->n_cols, tags + off[i]);
    unlock_slot(t, j, s);
    ++written;
  }
  t->stats[kPuts].fetch_add(written, std::memory_order_relaxed);
  if (evictions)
    t->stats[kEvictions].fetch_add(evictions, std::memory_order_relaxed);
  if (oversize)
    t->stats[kOversizeDrops].fetch_add(oversize,
                                       std::memory_order_relaxed);
  return written;
}

// Publish-side batch prime: ONE call folds a boundary's delta into the
// table (the task-thread half of the hit path — its cost sits inside
// the fire-deadline budget, which is why it is one GIL-released sweep
// instead of N Python put()s). Per key i:
//   updates  u_ns/u_vals/u_tags[uoff[i]..uoff[i+1]) upsert by namespace
//   removals r_ns[roff[i]..roff[i+1]) drop namespaces
//   flags bit0 (insert_ok): the updates are the key's COMPLETE composed
//     state — an ABSENT key may be created; otherwise absent keys skip
//   flags bit1 (drop): remove the key's entry entirely
// The merged entry retags with ``gen``; a key whose existing tag is
// NEWER is left alone (no downgrade). Overflow past entry_cap drops
// the key (it becomes a plain miss). Returns keys primed.
int64_t hc_prime_batch(void* h, int64_t nk, const int64_t* keys,
                       int64_t gen, const int64_t* uoff,
                       const int64_t* u_ns, const int64_t* u_vals,
                       const uint64_t* u_tags, const int64_t* roff,
                       const int64_t* r_ns, const uint8_t* flags) {
  HotTable* t = (HotTable*)h;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t primed = 0, evictions = 0, oversize = 0;
  // scratch for the merged entry
  int64_t* m_ns = (int64_t*)std::malloc(t->entry_cap * sizeof(int64_t));
  int64_t* m_vals =
      (int64_t*)std::malloc(t->entry_cap * t->n_cols * sizeof(int64_t));
  uint64_t* m_tags =
      (uint64_t*)std::malloc(t->entry_cap * sizeof(uint64_t));
  if (!m_ns || !m_vals || !m_tags) {
    std::free(m_ns);
    std::free(m_vals);
    std::free(m_tags);
    return 0;
  }
  for (int64_t i = 0; i < nk; ++i) {
    const int64_t k = keys[i];
    const uint8_t fl = flags[i];
    int64_t found, insert;
    bool evict;
    locate_for_write(t, k, &found, &insert, &evict);
    if (fl & 2) {  // drop
      if (found >= 0) {
        uint64_t s = lock_slot(t, found);
        erase_slot(t, found);
        unlock_slot(t, found, s);
        ++primed;
      }
      continue;
    }
    if (found < 0 && !(fl & 1)) continue;  // nobody cached it
    if (found >= 0 && t->gen[found] > gen) continue;  // no downgrade
    // ---- merge into scratch: surviving old entries, then upserts
    int64_t m = 0;
    bool overflow = false;
    if (found >= 0) {
      const int64_t* e_ns = t->ns + found * t->entry_cap;
      const int64_t* e_vals = t->vals + found * t->entry_cap * t->n_cols;
      const uint64_t* e_tags = t->tags + found * t->entry_cap;
      for (int32_t e = 0; e < t->n[found]; ++e) {
        bool removed = false;
        for (int64_t r = roff[i]; r < roff[i + 1]; ++r)
          if (r_ns[r] == e_ns[e]) {
            removed = true;
            break;
          }
        if (!removed)
          for (int64_t u = uoff[i]; u < uoff[i + 1]; ++u)
            if (u_ns[u] == e_ns[e]) {
              removed = true;  // superseded by the upsert below
              break;
            }
        if (removed) continue;
        if (m >= t->entry_cap) {
          overflow = true;
          break;
        }
        m_ns[m] = e_ns[e];
        std::memcpy(m_vals + m * t->n_cols, e_vals + e * t->n_cols,
                    t->n_cols * sizeof(int64_t));
        m_tags[m] = e_tags[e];
        ++m;
      }
    }
    for (int64_t u = uoff[i]; u < uoff[i + 1] && !overflow; ++u) {
      if (m >= t->entry_cap) {
        overflow = true;
        break;
      }
      m_ns[m] = u_ns[u];
      std::memcpy(m_vals + m * t->n_cols, u_vals + u * t->n_cols,
                  t->n_cols * sizeof(int64_t));
      m_tags[m] = u_tags[u];
      ++m;
    }
    if (overflow) {
      ++oversize;
      if (found >= 0) {
        uint64_t s = lock_slot(t, found);
        erase_slot(t, found);
        unlock_slot(t, found, s);
      }
      continue;
    }
    int64_t j = found >= 0 ? found : insert;
    if (j < 0) continue;
    if (found < 0 && evict) ++evictions;
    uint64_t s = lock_slot(t, j);
    if (found < 0) {
      if (t->state[j].load(std::memory_order_relaxed) != kLive)
        t->live.fetch_add(1, std::memory_order_relaxed);
      t->state[j].store(kLive, std::memory_order_relaxed);
    }
    write_payload(t, j, k, gen, m, m_ns, m_vals, m_tags);
    unlock_slot(t, j, s);
    ++primed;
  }
  std::free(m_ns);
  std::free(m_vals);
  std::free(m_tags);
  t->stats[kPrimes].fetch_add(primed, std::memory_order_relaxed);
  if (evictions)
    t->stats[kEvictions].fetch_add(evictions, std::memory_order_relaxed);
  if (oversize)
    t->stats[kOversizeDrops].fetch_add(oversize,
                                       std::memory_order_relaxed);
  return primed;
}

// Growth migration: re-insert every live entry of ``src`` into ``dst``
// (same n_cols/entry_cap — the wrapper grows within one schema). Runs
// under BOTH write mutexes; readers may still probe src concurrently
// (seqlock-safe). Returns entries migrated.
int64_t hc_migrate(void* dst_h, void* src_h) {
  HotTable* dst = (HotTable*)dst_h;
  HotTable* src = (HotTable*)src_h;
  if (dst->n_cols != src->n_cols || dst->entry_cap != src->entry_cap)
    return -1;
  std::lock_guard<std::mutex> gs(src->write_mu);
  std::lock_guard<std::mutex> gd(dst->write_mu);
  int64_t moved = 0;
  for (int64_t j = 0; j < src->n_slots; ++j) {
    if (src->state[j].load(std::memory_order_relaxed) != kLive) continue;
    const int64_t k = src->key[j].load(std::memory_order_relaxed);
    int64_t found, insert;
    bool evict;
    locate_for_write(dst, k, &found, &insert, &evict);
    int64_t t = found >= 0 ? found : insert;
    if (t < 0) continue;
    uint64_t s = lock_slot(dst, t);
    if (found < 0) {
      if (dst->state[t].load(std::memory_order_relaxed) != kLive)
        dst->live.fetch_add(1, std::memory_order_relaxed);
      dst->state[t].store(kLive, std::memory_order_relaxed);
    }
    write_payload(dst, t, k, src->gen[j], src->n[j],
                  src->ns + j * src->entry_cap,
                  src->vals + j * src->entry_cap * src->n_cols,
                  src->tags + j * src->entry_cap);
    unlock_slot(dst, t, s);
    ++moved;
  }
  return moved;
}

// Test-only hooks: hold a key's slot stamp ODD (a write frozen
// mid-flight) so the torn-read retry/fall-to-miss path is exercised
// DETERMINISTICALLY (tests/test_hotcache_native.py) — a concurrency
// race would cover it only probabilistically. Returns 1 when the key
// was found and its stamp flipped.
int64_t hc_debug_lock_slot(void* h, int64_t key) {
  HotTable* t = (HotTable*)h;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t found, insert;
  bool evict;
  locate_for_write(t, key, &found, &insert, &evict);
  if (found < 0) return 0;
  uint64_t s = t->stamp[found].load(std::memory_order_relaxed) & ~1ull;
  t->stamp[found].store(s + 1, std::memory_order_release);
  return 1;
}

int64_t hc_debug_unlock_slot(void* h, int64_t key) {
  HotTable* t = (HotTable*)h;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t found, insert;
  bool evict;
  locate_for_write(t, key, &found, &insert, &evict);
  if (found < 0) return 0;
  uint64_t s = t->stamp[found].load(std::memory_order_relaxed);
  if (s & 1) t->stamp[found].store(s + 1, std::memory_order_release);
  return 1;
}

void hc_drop(void* h, int64_t key) {
  HotTable* t = (HotTable*)h;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t found, insert;
  bool evict;
  locate_for_write(t, key, &found, &insert, &evict);
  if (found >= 0) {
    uint64_t s = lock_slot(t, found);
    erase_slot(t, found);
    unlock_slot(t, found, s);
  }
}

}  // extern "C"
