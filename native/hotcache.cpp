// Native hot-row probe table: the GIL-free serving cache.
//
// This is the serving sibling of native/sessions.cpp: where the session
// plane owns merge metadata, this table owns the HOT-ROW CACHE of the
// read-replica serving plane (flink_tpu/tenancy/hot_cache.py is the
// bit-identical Python fallback; flink_tpu/tenancy/hot_cache_native.py
// is the ctypes wrapper). The cost model it exists for: at cache-hit
// QPS the old path spent more time on the interpreter lock (one Python
// dict probe + per-key bookkeeping per hit, all serialized on the GIL)
// than on the probes themselves. Here a whole key batch probes in ONE
// C call — ctypes releases the GIL for the call, so concurrent serving
// clients probe in parallel with each other AND with the ingesting
// task thread.
//
// Layout (struct-of-arrays, one table per (job, operator)):
//   - ONE CONTIGUOUS ARENA: a fixed 4 KiB header (magic, epoch, shape,
//     stats, per-frontend counters) followed by the slot arrays at
//     computed offsets (stamp, key, gen, ns, vals, tags, n, state —
//     8-byte fields first so every array is naturally aligned). The
//     arena is either private heap (hc_create — the single-process
//     path, unchanged semantics) or a mmap-ed MAP_SHARED file
//     (hc_create_shared / hc_attach) so FRONTEND PROCESSES map the
//     same table and probe it lock-free over shared memory;
//   - open addressing over pow2 slots, linear probing, bounded window
//     (load factor <= 0.5 by construction; deletions leave tombstones
//     the probe walks past and inserts reuse);
//   - each slot holds a PACKED COMPOSED RESULT: a fixed header (key,
//     generation, entry count) plus up to ``entry_cap`` entries of
//     (namespace i64, per-column value words, a per-entry dtype tag
//     bitmask). Values are raw int64 bit patterns — float64 and int64
//     round-trip EXACTLY (the tag says which each column is);
//   - a seqlock-style even/odd STAMP per slot: writers (the publish
//     prime on the task thread, worker puts) flip the stamp odd, write,
//     flip it even; readers never take a lock — they re-check the stamp
//     around the copy and a torn read RETRIES, then falls to the miss
//     path. A reader can never observe a mixed-generation row. The
//     protocol is address-free (no pointers, no process-local state in
//     the arena), so it is exactly as safe for a reader in ANOTHER
//     process as for a reader thread in this one.
//
// Ownership across processes: the CREATOR is the only writer
// (hc_put_batch / hc_prime_batch / hc_clear / hc_drop refuse on an
// attached handle); its write mutex lives in the process-local handle,
// NOT in the arena — cross-process writer exclusion is by role, not by
// a shared lock. Attached frontends only probe (hc_get_batch), bump
// the shared stat words, and accumulate their per-frontend counters
// (hc_fe_note) — all lock-free atomics on the mapped header. The
// header's EPOCH word identifies the owner session: a new
// hc_create_shared writes a fresh epoch, so a frontend that cached an
// attachment detects owner restart by comparing hc_epoch against the
// value it saw at attach time and re-attaches (the Python manifest
// carries the expected epoch).
//
// Writers serialize on one per-table mutex (primes and puts are rare
// next to probes; the mutex is held only inside the GIL-released call),
// readers never touch it. Capacity pressure evicts the oldest
// generation in the probe window — approximate LRU by publish age,
// which is the invalidation clock anyway.
//
// Exposed as a plain C ABI for ctypes; batch arguments are raw pointers
// into NumPy buffers. All exported symbols are prefixed ``hc_`` (the
// NATIVE_SYMBOL_PREFIXES registry; flint NAT01 polices the ctypes
// declarations).

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

inline uint64_t mix_hash(uint64_t k) {
  uint64_t x = k ^ 0x9E3779B97F4A7C15ull;
  x ^= x >> 30;
  x *= 0xBF58476D1CE4E5B9ull;
  x ^= x >> 27;
  x *= 0x94D049BB133111EBull;
  x ^= x >> 31;
  return x;
}

// slot states
constexpr uint8_t kEmpty = 0;
constexpr uint8_t kLive = 1;
constexpr uint8_t kTomb = 2;

// counter indices (hc_stat)
enum Stat {
  kHits = 0,
  kMisses = 1,
  kEvictions = 2,
  kPrimes = 3,
  kPuts = 4,
  kTornRetries = 5,
  kTornMisses = 6,
  kOversizeDrops = 7,
  kStatCount = 8,
};

// per-frontend counter indices (hc_fe_note / hc_fe_stat)
enum FeStat {
  kFeProbes = 0,
  kFeHits = 1,
  kFeTornRetries = 2,
  kFeMissCrossings = 3,
  kFeStatCount = 4,
};
constexpr int kMaxFrontends = 64;

constexpr int kReadRetries = 4;

constexpr uint64_t kMagic = 0x464C4E4B48433032ull;  // "FLNKHC02"
constexpr uint64_t kLayoutVersion = 2;
constexpr int64_t kHeaderBytes = 4096;

// handle modes
constexpr int kModePrivate = 0;   // hc_create: heap arena, this process
constexpr int kModeShared = 1;    // hc_create_shared: owner, MAP_SHARED
constexpr int kModeAttached = 2;  // hc_attach: read-side mapper

// The arena header. Everything a mapper needs to bind the arrays is
// here; the magic word is written LAST (release) by the creator so an
// attacher never binds a half-initialized arena. std::atomic<int64_t>
// / <uint64_t> are lock-free and ADDRESS-FREE on every target this
// builds for (static_asserted below) — valid across processes in
// MAP_SHARED memory.
struct ArenaHeader {
  std::atomic<uint64_t> magic;
  uint64_t layout_version;
  std::atomic<uint64_t> epoch;  // owner-session word (restart detector)
  int64_t n_slots;              // pow2
  int64_t n_cols;
  int64_t entry_cap;
  int64_t arena_bytes;
  std::atomic<int64_t> live;
  std::atomic<int64_t> stats[kStatCount];
  std::atomic<int64_t> fe_stats[kMaxFrontends * kFeStatCount];
};
static_assert(sizeof(ArenaHeader) <= kHeaderBytes,
              "arena header must fit its reserved block");
static_assert(std::atomic<uint64_t>::is_always_lock_free &&
                  std::atomic<int64_t>::is_always_lock_free &&
                  std::atomic<uint8_t>::is_always_lock_free,
              "shm seqlock needs lock-free (address-free) atomics");

// Process-local handle: arena pointers + the writer-side mutex. The
// mutex is deliberately OUTSIDE the arena — only the owner process
// writes, so writer exclusion never needs to cross processes.
struct HotTable {
  ArenaHeader* hdr = nullptr;
  int64_t n_slots = 0;  // cached from hdr (hot-loop fields)
  int64_t mask = 0;
  int64_t max_probe = 0;
  int64_t n_cols = 0;
  int64_t entry_cap = 0;
  std::mutex write_mu;
  int mode = kModePrivate;
  void* base = nullptr;
  size_t map_bytes = 0;

  std::atomic<uint64_t>* stamp = nullptr;
  std::atomic<uint8_t>* state = nullptr;
  std::atomic<int64_t>* key = nullptr;
  int64_t* gen = nullptr;
  int32_t* n = nullptr;     // entries used in the slot
  int64_t* ns = nullptr;    // [n_slots * entry_cap]
  int64_t* vals = nullptr;  // [n_slots * entry_cap * n_cols]
  uint64_t* tags = nullptr; // [n_slots * entry_cap] dtype bitmasks

  ~HotTable() {
    if (base == nullptr) return;
    if (mode == kModePrivate) {
      std::free(base);
    } else {
      if (mode == kModeShared)
        // RETIRE the arena: a still-attached frontend's probe-time
        // epoch check (hc_epoch != manifest epoch) now fires and sends
        // it back to the manifest for the successor arena. The pages
        // stay valid for attached mappers until they munmap — only the
        // epoch word says "this owner session is over".
        hdr->epoch.store(0, std::memory_order_release);
      munmap(base, map_bytes);
    }
  }
};

inline int64_t pow2_at_least(int64_t v) {
  int64_t p = 64;
  while (p < v) p <<= 1;
  return p;
}

inline int64_t align64(int64_t v) { return (v + 63) & ~63ll; }

// Arena size for a shape: header block, then the arrays 8-byte fields
// first (each offset 64-aligned so every array is naturally aligned
// whatever its element width).
struct ArenaLayout {
  int64_t off_stamp, off_key, off_gen, off_ns, off_vals, off_tags;
  int64_t off_n, off_state, total;
};

inline ArenaLayout layout_for(int64_t n_slots, int64_t n_cols,
                              int64_t entry_cap) {
  ArenaLayout L;
  int64_t off = kHeaderBytes;
  L.off_stamp = off;
  off = align64(off + n_slots * 8);
  L.off_key = off;
  off = align64(off + n_slots * 8);
  L.off_gen = off;
  off = align64(off + n_slots * 8);
  L.off_ns = off;
  off = align64(off + n_slots * entry_cap * 8);
  L.off_vals = off;
  off = align64(off + n_slots * entry_cap * n_cols * 8);
  L.off_tags = off;
  off = align64(off + n_slots * entry_cap * 8);
  L.off_n = off;
  off = align64(off + n_slots * 4);
  L.off_state = off;
  off = align64(off + n_slots * 1);
  L.total = off;
  return L;
}

// Bind the handle's array pointers into an arena whose header carries
// the shape (creator already wrote it / attacher validated it).
inline void bind_arena(HotTable* t) {
  char* b = (char*)t->base;
  t->hdr = (ArenaHeader*)b;
  t->n_slots = t->hdr->n_slots;
  t->mask = t->n_slots - 1;
  t->max_probe = t->n_slots < 128 ? t->n_slots : 128;
  t->n_cols = t->hdr->n_cols;
  t->entry_cap = t->hdr->entry_cap;
  ArenaLayout L = layout_for(t->n_slots, t->n_cols, t->entry_cap);
  t->stamp = (std::atomic<uint64_t>*)(b + L.off_stamp);
  t->key = (std::atomic<int64_t>*)(b + L.off_key);
  t->gen = (int64_t*)(b + L.off_gen);
  t->ns = (int64_t*)(b + L.off_ns);
  t->vals = (int64_t*)(b + L.off_vals);
  t->tags = (uint64_t*)(b + L.off_tags);
  t->n = (int32_t*)(b + L.off_n);
  t->state = (std::atomic<uint8_t>*)(b + L.off_state);
}

// Owner-session epoch: unique across restarts of the same path (wall
// ns xor pid — two owner generations can never collide in practice,
// and equality is only ever used as a cheap "did the owner restart
// under me" check, never as an identity the data depends on).
inline uint64_t fresh_epoch() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  uint64_t e = (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
  e ^= ((uint64_t)getpid()) << 48;
  return e ? e : 1;
}

// Fill a fresh (zeroed) arena's header for a shape. The zero fill
// already IS the empty table: stamp 0 (even), state kEmpty, key 0 —
// identical to what the old per-array init stored.
inline void init_header(HotTable* t, int64_t n_slots, int64_t n_cols,
                        int64_t entry_cap, int64_t total) {
  ArenaHeader* h = (ArenaHeader*)t->base;
  h->layout_version = kLayoutVersion;
  h->n_slots = n_slots;
  h->n_cols = n_cols;
  h->entry_cap = entry_cap;
  h->arena_bytes = total;
  h->live.store(0, std::memory_order_relaxed);
  for (int i = 0; i < kStatCount; ++i)
    h->stats[i].store(0, std::memory_order_relaxed);
  for (int i = 0; i < kMaxFrontends * kFeStatCount; ++i)
    h->fe_stats[i].store(0, std::memory_order_relaxed);
  h->epoch.store(fresh_epoch(), std::memory_order_relaxed);
  // magic LAST: an attacher that raced the create sees 0 and refuses
  h->magic.store(kMagic, std::memory_order_release);
}

// ---- writer-side slot lock (the seqlock write half). Callers hold
// write_mu, so the CAS never actually contends with another writer —
// the odd stamp exists for READERS to detect the in-progress write.
inline uint64_t lock_slot(HotTable* t, int64_t j) {
  uint64_t s = t->stamp[j].load(std::memory_order_relaxed) & ~1ull;
  t->stamp[j].store(s + 1, std::memory_order_release);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  return s;
}

inline void unlock_slot(HotTable* t, int64_t j, uint64_t s) {
  t->stamp[j].store(s + 2, std::memory_order_release);
}

// Find the slot for `k` under write_mu: (found_slot, insert_slot).
// found >= 0 when the key is live in the window; insert is the first
// reusable slot (empty/tombstone), or — window full of other live
// keys — the live slot with the OLDEST generation (the eviction
// victim), flagged via *evict.
inline void locate_for_write(HotTable* t, int64_t k, int64_t* found,
                             int64_t* insert, bool* evict) {
  *found = -1;
  *insert = -1;
  *evict = false;
  int64_t j = (int64_t)(mix_hash((uint64_t)k)) & t->mask;
  int64_t victim = -1;
  int64_t victim_gen = INT64_MAX;
  for (int64_t step = 0; step < t->max_probe; ++step) {
    uint8_t st = t->state[j].load(std::memory_order_relaxed);
    if (st == kEmpty) {
      if (*insert < 0) *insert = j;
      return;  // key cannot be past the first empty
    }
    if (st == kTomb) {
      if (*insert < 0) *insert = j;
    } else {  // live
      if (t->key[j].load(std::memory_order_relaxed) == k) {
        *found = j;
        return;
      }
      if (t->gen[j] < victim_gen) {
        victim_gen = t->gen[j];
        victim = j;
      }
    }
    j = (j + 1) & t->mask;
  }
  if (*insert < 0) {
    *insert = victim;
    *evict = true;
  }
}

inline void write_payload(HotTable* t, int64_t j, int64_t k, int64_t g,
                          int64_t cnt, const int64_t* src_ns,
                          const int64_t* src_vals,
                          const uint64_t* src_tags) {
  t->key[j].store(k, std::memory_order_relaxed);
  t->gen[j] = g;
  t->n[j] = (int32_t)cnt;
  std::memcpy(t->ns + j * t->entry_cap, src_ns, cnt * sizeof(int64_t));
  std::memcpy(t->vals + j * t->entry_cap * t->n_cols, src_vals,
              cnt * t->n_cols * sizeof(int64_t));
  std::memcpy(t->tags + j * t->entry_cap, src_tags,
              cnt * sizeof(uint64_t));
}

// erase under the slot lock (caller holds write_mu + slot stamp odd)
inline void erase_slot(HotTable* t, int64_t j) {
  if (t->state[j].load(std::memory_order_relaxed) == kLive) {
    t->state[j].store(kTomb, std::memory_order_relaxed);
    t->hdr->live.fetch_sub(1, std::memory_order_relaxed);
  }
}

inline bool can_write(HotTable* t) { return t->mode != kModeAttached; }

}  // namespace

extern "C" {

void* hc_create(int64_t max_entries, int64_t n_cols, int64_t entry_cap) {
  if (max_entries <= 0 || n_cols <= 0 || n_cols > 63 || entry_cap <= 0)
    return nullptr;
  HotTable* t = new HotTable();
  // load factor <= 0.5: probes stay inside a short window
  int64_t n_slots = pow2_at_least(max_entries * 2);
  ArenaLayout L = layout_for(n_slots, n_cols, entry_cap);
  t->mode = kModePrivate;
  t->base = std::calloc(1, (size_t)L.total);
  if (t->base == nullptr) {
    delete t;
    return nullptr;
  }
  init_header(t, n_slots, n_cols, entry_cap, L.total);
  bind_arena(t);
  return t;
}

// Owner-side shared create: the same table in a MAP_SHARED file arena
// that frontend processes hc_attach. The path must be on a mmap-able
// filesystem (/dev/shm for a RAM-backed table); ftruncate zero-fills,
// which IS the empty table. The caller owns the file's lifecycle
// (unlink after hc_destroy); re-creating a table always uses a FRESH
// path — an in-place truncate under a live mapper would fault it.
void* hc_create_shared(const char* path, int64_t max_entries,
                       int64_t n_cols, int64_t entry_cap) {
  if (path == nullptr || max_entries <= 0 || n_cols <= 0 ||
      n_cols > 63 || entry_cap <= 0)
    return nullptr;
  int64_t n_slots = pow2_at_least(max_entries * 2);
  ArenaLayout L = layout_for(n_slots, n_cols, entry_cap);
  int fd = open(path, O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)L.total) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)L.total, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  close(fd);  // the mapping keeps the pages
  if (base == MAP_FAILED) return nullptr;
  HotTable* t = new HotTable();
  t->mode = kModeShared;
  t->base = base;
  t->map_bytes = (size_t)L.total;
  init_header(t, n_slots, n_cols, entry_cap, L.total);
  bind_arena(t);
  return t;
}

// Frontend-side attach: map an existing shared arena. The mapping is
// PROT_WRITE because attached probes still bump the shared stat words
// and their per-frontend counters — but the TABLE write entry points
// all refuse on an attached handle (owner-exclusive write is by role).
// Returns nullptr when the file is missing, not yet initialized
// (magic unset — creator mid-init), or shape-inconsistent.
void* hc_attach(const char* path) {
  if (path == nullptr) return nullptr;
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (int64_t)st.st_size < kHeaderBytes) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  ArenaHeader* h = (ArenaHeader*)base;
  if (h->magic.load(std::memory_order_acquire) != kMagic ||
      h->layout_version != kLayoutVersion ||
      h->arena_bytes != (int64_t)st.st_size || h->n_slots <= 0 ||
      h->n_cols <= 0 || h->n_cols > 63 || h->entry_cap <= 0 ||
      (h->n_slots & (h->n_slots - 1)) != 0) {
    munmap(base, (size_t)st.st_size);
    return nullptr;
  }
  ArenaLayout L = layout_for(h->n_slots, h->n_cols, h->entry_cap);
  if (L.total != (int64_t)st.st_size) {
    munmap(base, (size_t)st.st_size);
    return nullptr;
  }
  HotTable* t = new HotTable();
  t->mode = kModeAttached;
  t->base = base;
  t->map_bytes = (size_t)st.st_size;
  bind_arena(t);
  return t;
}

void hc_destroy(void* h) { delete (HotTable*)h; }

// Owner-session word: an attached frontend compares this against the
// epoch its manifest promised — a mismatch means a NEW owner built a
// new arena at this path's slot and the frontend must re-attach.
int64_t hc_epoch(void* h) {
  return (int64_t)((HotTable*)h)
      ->hdr->epoch.load(std::memory_order_acquire);
}

int64_t hc_arena_bytes(void* h) {
  return ((HotTable*)h)->hdr->arena_bytes;
}

int64_t hc_is_attached(void* h) {
  return ((HotTable*)h)->mode == kModeAttached ? 1 : 0;
}

int64_t hc_len(void* h) {
  return ((HotTable*)h)->hdr->live.load(std::memory_order_relaxed);
}

int64_t hc_capacity(void* h) { return ((HotTable*)h)->n_slots; }

int64_t hc_stat(void* h, int32_t which) {
  HotTable* t = (HotTable*)h;
  if (which < 0 || which >= kStatCount) return -1;
  return t->hdr->stats[which].load(std::memory_order_relaxed);
}

void hc_add_stat(void* h, int32_t which, int64_t delta) {
  // the wrapper folds Python-side overflow-path traffic into the same
  // counters so stats() reads one source whatever path served
  HotTable* t = (HotTable*)h;
  if (which < 0 || which >= kStatCount) return;
  t->hdr->stats[which].fetch_add(delta, std::memory_order_relaxed);
}

// Per-frontend counters, accumulated IN the shared header so the owner
// reads every frontend's traffic without IPC (which = FeStat index;
// fe is the frontend's pool slot). Wrap-around indices are rejected,
// not clamped — a bad id must read as zero traffic, not alias slot 0.
void hc_fe_note(void* h, int32_t fe, int64_t probes, int64_t hits,
                int64_t torn_retries, int64_t miss_crossings) {
  HotTable* t = (HotTable*)h;
  if (fe < 0 || fe >= kMaxFrontends) return;
  std::atomic<int64_t>* row = t->hdr->fe_stats + fe * kFeStatCount;
  if (probes) row[kFeProbes].fetch_add(probes, std::memory_order_relaxed);
  if (hits) row[kFeHits].fetch_add(hits, std::memory_order_relaxed);
  if (torn_retries)
    row[kFeTornRetries].fetch_add(torn_retries,
                                  std::memory_order_relaxed);
  if (miss_crossings)
    row[kFeMissCrossings].fetch_add(miss_crossings,
                                    std::memory_order_relaxed);
}

int64_t hc_fe_stat(void* h, int32_t fe, int32_t which) {
  HotTable* t = (HotTable*)h;
  if (fe < 0 || fe >= kMaxFrontends || which < 0 ||
      which >= kFeStatCount)
    return -1;
  return t->hdr->fe_stats[fe * kFeStatCount + which].load(
      std::memory_order_relaxed);
}

void hc_clear(void* h) {
  HotTable* t = (HotTable*)h;
  if (!can_write(t)) return;
  std::lock_guard<std::mutex> g(t->write_mu);
  for (int64_t j = 0; j < t->n_slots; ++j) {
    uint64_t s = lock_slot(t, j);
    erase_slot(t, j);
    t->state[j].store(kEmpty, std::memory_order_relaxed);
    unlock_slot(t, j, s);
  }
}

namespace {

// Probe core shared by hc_get_batch (in-process) and hc_get_batch_fe
// (attached frontends — same probe, plus per-frontend attribution).
// Torn counts report back so the frontend variant attributes them
// without a racy read of the SHARED cumulative stat words.
int64_t probe_batch(HotTable* t, int64_t nk, const int64_t* keys,
                    int64_t exact_gen, uint8_t* hit, int32_t* counts,
                    int64_t* out_gen, int64_t* out_ns,
                    int64_t* out_vals, uint64_t* out_tags,
                    int64_t* o_torn_retries) {
  int64_t hits = 0;
  int64_t tot = 0;  // compact output cursor (entries)
  int64_t torn_retries = 0, torn_misses = 0;
  for (int64_t i = 0; i < nk; ++i) {
    const int64_t k = keys[i];
    hit[i] = 0;
    counts[i] = 0;
    bool done = false;
    for (int attempt = 0; attempt < kReadRetries && !done; ++attempt) {
      int64_t j = (int64_t)(mix_hash((uint64_t)k)) & t->mask;
      bool torn = false;
      for (int64_t step = 0; step < t->max_probe; ++step) {
        uint8_t st = t->state[j].load(std::memory_order_acquire);
        if (st == kEmpty) break;  // definitive miss for this attempt
        if (st == kLive &&
            t->key[j].load(std::memory_order_relaxed) == k) {
          uint64_t s1 = t->stamp[j].load(std::memory_order_acquire);
          if (s1 & 1) {  // write in progress
            torn = true;
            break;
          }
          int64_t g = t->gen[j];
          int32_t cnt = t->n[j];
          if (cnt > t->entry_cap) cnt = (int32_t)t->entry_cap;
          std::memcpy(out_ns + tot, t->ns + j * t->entry_cap,
                      cnt * sizeof(int64_t));
          std::memcpy(out_vals + tot * t->n_cols,
                      t->vals + j * t->entry_cap * t->n_cols,
                      cnt * t->n_cols * sizeof(int64_t));
          std::memcpy(out_tags + tot, t->tags + j * t->entry_cap,
                      cnt * sizeof(uint64_t));
          std::atomic_thread_fence(std::memory_order_acquire);
          uint64_t s2 = t->stamp[j].load(std::memory_order_relaxed);
          if (s1 != s2 ||
              t->key[j].load(std::memory_order_relaxed) != k) {
            torn = true;  // writer moved under us: retry the key
            break;
          }
          if (exact_gen >= 0 && g != exact_gen) break;  // stale: miss
          out_gen[i] = g;
          counts[i] = cnt;
          hit[i] = 1;
          tot += cnt;
          ++hits;
          done = true;
          break;
        }
        j = (j + 1) & t->mask;
      }
      if (done) break;
      if (!torn) break;  // clean miss — no point retrying
      ++torn_retries;
      if (attempt == kReadRetries - 1) ++torn_misses;
    }
  }
  t->hdr->stats[kHits].fetch_add(hits, std::memory_order_relaxed);
  t->hdr->stats[kMisses].fetch_add(nk - hits,
                                   std::memory_order_relaxed);
  if (torn_retries)
    t->hdr->stats[kTornRetries].fetch_add(torn_retries,
                                          std::memory_order_relaxed);
  if (torn_misses)
    t->hdr->stats[kTornMisses].fetch_add(torn_misses,
                                         std::memory_order_relaxed);
  if (o_torn_retries) *o_torn_retries = torn_retries;
  return hits;
}

}  // namespace

// Batch probe: ONE call for the whole key batch (the serving hot
// loop). Hit entries land COMPACTLY: key i's counts[i] entries follow
// the previous hits' in out_ns / out_tags (and counts[i]*n_cols value
// words in out_vals) — the caller sizes the buffers at nk*entry_cap
// worst case and bulk-converts exactly sum(counts) entries, no
// per-key stride walking.
// ``exact_gen`` < 0 = presence-implies-validity (the primed serving
// path: ANY live entry hits); >= 0 = only that generation hits.
// A torn read (stamp moved under the copy) retries, then counts a
// torn miss and reports MISS — never a mixed-generation row.
// Returns the hit count.
int64_t hc_get_batch(void* h, int64_t nk, const int64_t* keys,
                     int64_t exact_gen, uint8_t* hit, int32_t* counts,
                     int64_t* out_gen, int64_t* out_ns, int64_t* out_vals,
                     uint64_t* out_tags) {
  return probe_batch((HotTable*)h, nk, keys, exact_gen, hit, counts,
                     out_gen, out_ns, out_vals, out_tags, nullptr);
}

// Frontend probe: identical to hc_get_batch, plus the caller's
// per-frontend attribution (probes/hits/torn_retries) folded into the
// shared header IN the same call — the owner reads every frontend's
// real traffic without IPC, and torn retries attribute to the frontend
// that actually saw them (not inferrable from the shared cumulative
// words under concurrency).
int64_t hc_get_batch_fe(void* h, int32_t fe, int64_t nk,
                        const int64_t* keys, int64_t exact_gen,
                        uint8_t* hit, int32_t* counts, int64_t* out_gen,
                        int64_t* out_ns, int64_t* out_vals,
                        uint64_t* out_tags) {
  HotTable* t = (HotTable*)h;
  int64_t torn = 0;
  int64_t hits = probe_batch(t, nk, keys, exact_gen, hit, counts,
                             out_gen, out_ns, out_vals, out_tags,
                             &torn);
  hc_fe_note(h, fe, nk, hits, torn, 0);
  return hits;
}

// Batch put (worker miss-resolution feed): whole-value replace with the
// no-downgrade rule — an existing entry tagged with a NEWER generation
// is never overwritten by a stale worker result. Entries are packed
// flat with off[nk] prefix offsets (off[i]..off[i+1] in ns/tags;
// times n_cols in vals). A value wider than entry_cap cannot be
// represented: the key is dropped instead (counted; it simply stays a
// miss — the cache is best-effort). Returns entries written.
int64_t hc_put_batch(void* h, int64_t nk, const int64_t* keys,
                     const int64_t* gens, const int64_t* off,
                     const int64_t* ns, const int64_t* vals,
                     const uint64_t* tags) {
  HotTable* t = (HotTable*)h;
  if (!can_write(t)) return 0;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t written = 0, evictions = 0, oversize = 0;
  for (int64_t i = 0; i < nk; ++i) {
    const int64_t k = keys[i];
    const int64_t cnt = off[i + 1] - off[i];
    int64_t found, insert;
    bool evict;
    locate_for_write(t, k, &found, &insert, &evict);
    if (cnt > t->entry_cap) {
      ++oversize;
      if (found >= 0) {
        uint64_t s = lock_slot(t, found);
        erase_slot(t, found);
        unlock_slot(t, found, s);
      }
      continue;
    }
    int64_t j = found >= 0 ? found : insert;
    if (j < 0) continue;  // no slot (tiny table fully torn) — skip
    if (found >= 0 && t->gen[found] > gens[i]) continue;  // no downgrade
    if (found < 0 && evict) ++evictions;
    uint64_t s = lock_slot(t, j);
    if (found < 0) {
      if (t->state[j].load(std::memory_order_relaxed) != kLive)
        t->hdr->live.fetch_add(1, std::memory_order_relaxed);
      t->state[j].store(kLive, std::memory_order_relaxed);
    }
    write_payload(t, j, k, gens[i], cnt, ns + off[i],
                  vals + off[i] * t->n_cols, tags + off[i]);
    unlock_slot(t, j, s);
    ++written;
  }
  t->hdr->stats[kPuts].fetch_add(written, std::memory_order_relaxed);
  if (evictions)
    t->hdr->stats[kEvictions].fetch_add(evictions,
                                        std::memory_order_relaxed);
  if (oversize)
    t->hdr->stats[kOversizeDrops].fetch_add(oversize,
                                            std::memory_order_relaxed);
  return written;
}

// Publish-side batch prime: ONE call folds a boundary's delta into the
// table (the task-thread half of the hit path — its cost sits inside
// the fire-deadline budget, which is why it is one GIL-released sweep
// instead of N Python put()s). Per key i:
//   updates  u_ns/u_vals/u_tags[uoff[i]..uoff[i+1]) upsert by namespace
//   removals r_ns[roff[i]..roff[i+1]) drop namespaces
//   flags bit0 (insert_ok): the updates are the key's COMPLETE composed
//     state — an ABSENT key may be created; otherwise absent keys skip
//   flags bit1 (drop): remove the key's entry entirely
// The merged entry retags with ``gen``; a key whose existing tag is
// NEWER is left alone (no downgrade). Overflow past entry_cap drops
// the key (it becomes a plain miss). Returns keys primed.
int64_t hc_prime_batch(void* h, int64_t nk, const int64_t* keys,
                       int64_t gen, const int64_t* uoff,
                       const int64_t* u_ns, const int64_t* u_vals,
                       const uint64_t* u_tags, const int64_t* roff,
                       const int64_t* r_ns, const uint8_t* flags) {
  HotTable* t = (HotTable*)h;
  if (!can_write(t)) return 0;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t primed = 0, evictions = 0, oversize = 0;
  // scratch for the merged entry
  int64_t* m_ns = (int64_t*)std::malloc(t->entry_cap * sizeof(int64_t));
  int64_t* m_vals =
      (int64_t*)std::malloc(t->entry_cap * t->n_cols * sizeof(int64_t));
  uint64_t* m_tags =
      (uint64_t*)std::malloc(t->entry_cap * sizeof(uint64_t));
  if (!m_ns || !m_vals || !m_tags) {
    std::free(m_ns);
    std::free(m_vals);
    std::free(m_tags);
    return 0;
  }
  for (int64_t i = 0; i < nk; ++i) {
    const int64_t k = keys[i];
    const uint8_t fl = flags[i];
    int64_t found, insert;
    bool evict;
    locate_for_write(t, k, &found, &insert, &evict);
    if (fl & 2) {  // drop
      if (found >= 0) {
        uint64_t s = lock_slot(t, found);
        erase_slot(t, found);
        unlock_slot(t, found, s);
        ++primed;
      }
      continue;
    }
    if (found < 0 && !(fl & 1)) continue;  // nobody cached it
    if (found >= 0 && t->gen[found] > gen) continue;  // no downgrade
    // ---- merge into scratch: surviving old entries, then upserts
    int64_t m = 0;
    bool overflow = false;
    if (found >= 0) {
      const int64_t* e_ns = t->ns + found * t->entry_cap;
      const int64_t* e_vals = t->vals + found * t->entry_cap * t->n_cols;
      const uint64_t* e_tags = t->tags + found * t->entry_cap;
      for (int32_t e = 0; e < t->n[found]; ++e) {
        bool removed = false;
        for (int64_t r = roff[i]; r < roff[i + 1]; ++r)
          if (r_ns[r] == e_ns[e]) {
            removed = true;
            break;
          }
        if (!removed)
          for (int64_t u = uoff[i]; u < uoff[i + 1]; ++u)
            if (u_ns[u] == e_ns[e]) {
              removed = true;  // superseded by the upsert below
              break;
            }
        if (removed) continue;
        if (m >= t->entry_cap) {
          overflow = true;
          break;
        }
        m_ns[m] = e_ns[e];
        std::memcpy(m_vals + m * t->n_cols, e_vals + e * t->n_cols,
                    t->n_cols * sizeof(int64_t));
        m_tags[m] = e_tags[e];
        ++m;
      }
    }
    for (int64_t u = uoff[i]; u < uoff[i + 1] && !overflow; ++u) {
      if (m >= t->entry_cap) {
        overflow = true;
        break;
      }
      m_ns[m] = u_ns[u];
      std::memcpy(m_vals + m * t->n_cols, u_vals + u * t->n_cols,
                  t->n_cols * sizeof(int64_t));
      m_tags[m] = u_tags[u];
      ++m;
    }
    if (overflow) {
      ++oversize;
      if (found >= 0) {
        uint64_t s = lock_slot(t, found);
        erase_slot(t, found);
        unlock_slot(t, found, s);
      }
      continue;
    }
    int64_t j = found >= 0 ? found : insert;
    if (j < 0) continue;
    if (found < 0 && evict) ++evictions;
    uint64_t s = lock_slot(t, j);
    if (found < 0) {
      if (t->state[j].load(std::memory_order_relaxed) != kLive)
        t->hdr->live.fetch_add(1, std::memory_order_relaxed);
      t->state[j].store(kLive, std::memory_order_relaxed);
    }
    write_payload(t, j, k, gen, m, m_ns, m_vals, m_tags);
    unlock_slot(t, j, s);
    ++primed;
  }
  std::free(m_ns);
  std::free(m_vals);
  std::free(m_tags);
  t->hdr->stats[kPrimes].fetch_add(primed, std::memory_order_relaxed);
  if (evictions)
    t->hdr->stats[kEvictions].fetch_add(evictions,
                                        std::memory_order_relaxed);
  if (oversize)
    t->hdr->stats[kOversizeDrops].fetch_add(oversize,
                                            std::memory_order_relaxed);
  return primed;
}

// Growth migration: re-insert every live entry of ``src`` into ``dst``
// (same n_cols/entry_cap — the wrapper grows within one schema). Runs
// under BOTH write mutexes; readers may still probe src concurrently
// (seqlock-safe). Returns entries migrated.
int64_t hc_migrate(void* dst_h, void* src_h) {
  HotTable* dst = (HotTable*)dst_h;
  HotTable* src = (HotTable*)src_h;
  if (dst->n_cols != src->n_cols || dst->entry_cap != src->entry_cap)
    return -1;
  if (!can_write(dst)) return -1;
  std::lock_guard<std::mutex> gs(src->write_mu);
  std::lock_guard<std::mutex> gd(dst->write_mu);
  int64_t moved = 0;
  for (int64_t j = 0; j < src->n_slots; ++j) {
    if (src->state[j].load(std::memory_order_relaxed) != kLive) continue;
    const int64_t k = src->key[j].load(std::memory_order_relaxed);
    int64_t found, insert;
    bool evict;
    locate_for_write(dst, k, &found, &insert, &evict);
    int64_t t = found >= 0 ? found : insert;
    if (t < 0) continue;
    uint64_t s = lock_slot(dst, t);
    if (found < 0) {
      if (dst->state[t].load(std::memory_order_relaxed) != kLive)
        dst->hdr->live.fetch_add(1, std::memory_order_relaxed);
      dst->state[t].store(kLive, std::memory_order_relaxed);
    }
    write_payload(dst, t, k, src->gen[j], src->n[j],
                  src->ns + j * src->entry_cap,
                  src->vals + j * src->entry_cap * src->n_cols,
                  src->tags + j * src->entry_cap);
    unlock_slot(dst, t, s);
    ++moved;
  }
  return moved;
}

// Test-only hooks: hold a key's slot stamp ODD (a write frozen
// mid-flight) so the torn-read retry/fall-to-miss path is exercised
// DETERMINISTICALLY (tests/test_hotcache_native.py) — a concurrency
// race would cover it only probabilistically. Returns 1 when the key
// was found and its stamp flipped.
int64_t hc_debug_lock_slot(void* h, int64_t key) {
  HotTable* t = (HotTable*)h;
  if (!can_write(t)) return 0;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t found, insert;
  bool evict;
  locate_for_write(t, key, &found, &insert, &evict);
  if (found < 0) return 0;
  uint64_t s = t->stamp[found].load(std::memory_order_relaxed) & ~1ull;
  t->stamp[found].store(s + 1, std::memory_order_release);
  return 1;
}

int64_t hc_debug_unlock_slot(void* h, int64_t key) {
  HotTable* t = (HotTable*)h;
  if (!can_write(t)) return 0;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t found, insert;
  bool evict;
  locate_for_write(t, key, &found, &insert, &evict);
  if (found < 0) return 0;
  uint64_t s = t->stamp[found].load(std::memory_order_relaxed);
  if (s & 1) t->stamp[found].store(s + 1, std::memory_order_release);
  return 1;
}

void hc_drop(void* h, int64_t key) {
  HotTable* t = (HotTable*)h;
  if (!can_write(t)) return;
  std::lock_guard<std::mutex> g(t->write_mu);
  int64_t found, insert;
  bool evict;
  locate_for_write(t, key, &found, &insert, &evict);
  if (found >= 0) {
    uint64_t s = lock_slot(t, found);
    erase_slot(t, found);
    unlock_slot(t, found, s);
  }
}

}  // extern "C"
