"""ML model functions — batched inference inside the pipeline.

reference: flink-models (flink-model-openai chat/embedding client,
flink-model-triton REST client, ~4.6k LoC) invoked from SQL ``ML_PREDICT``
via flink-table-runtime/.../operators/ml/MLPredictRunner.java (sync, one
record per request) and AsyncMLPredictRunner.java (async, bounded
in-flight), with models declared by ``CREATE MODEL`` DDL.

TPU re-design: a model is a *batched vectorized function* and the natural
provider is a jitted JAX program running on the SAME device as the keyed
state — inference fuses into the micro-batch pipeline with zero extra
host<->device round-trips for the hot path (the reference must RPC every
record to an external endpoint; here the endpoint form is the fallback,
not the default):

- :class:`JaxModel` — params + apply_fn under ``jax.jit`` with sticky
  padding buckets (batch-size changes don't recompile).
- :class:`FunctionModel` — any vectorized NumPy/Python callable.
- :class:`RemoteModel` — an external-endpoint client (the reference's
  OpenAI/Triton role). This environment is zero-egress, so transports are
  injected; the built-in operator pairs it with bounded-in-flight async
  execution (AsyncWaitOperator) like AsyncMLPredictRunner.
"""

from flink_tpu.ml.models import (
    FunctionModel,
    JaxModel,
    Model,
    ModelRegistry,
    RemoteModel,
)
from flink_tpu.ml.operators import AsyncMLPredictOperator, MLPredictOperator

__all__ = [
    "Model",
    "JaxModel",
    "FunctionModel",
    "RemoteModel",
    "ModelRegistry",
    "MLPredictOperator",
    "AsyncMLPredictOperator",
]
