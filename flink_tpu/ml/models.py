"""Model abstractions for ML_PREDICT (see flink_tpu.ml package docstring).

reference: flink-models/* providers + the model catalog objects behind
``CREATE MODEL`` (flink-table: CatalogModel with provider options).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import numpy as np

from flink_tpu.ops.segment_ops import sticky_bucket


from flink_tpu.core.annotations import public_evolving

@public_evolving
class Model:
    """A batched inference function: column arrays in, column arrays out.

    ``input_names``/``output_names`` are the declared schema (the
    reference's CatalogModel input/output schema)."""

    input_names: Sequence[str] = ()
    output_names: Sequence[str] = ()

    def predict(self, inputs: Dict[str, np.ndarray]
                ) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass


@public_evolving
class FunctionModel(Model):
    """Vectorized Python/NumPy callable as a model."""

    def __init__(self, fn: Callable[[Dict[str, np.ndarray]],
                                    Dict[str, np.ndarray]],
                 input_names: Sequence[str],
                 output_names: Sequence[str]):
        self.fn = fn
        self.input_names = tuple(input_names)
        self.output_names = tuple(output_names)

    def predict(self, inputs):
        return self.fn(inputs)


@public_evolving
class JaxModel(Model):
    """A jitted JAX program as a model — inference runs on the same device
    as the pipeline's keyed state (the TPU-native provider; where the
    reference pays one network round-trip per record to OpenAI/Triton,
    this is one kernel per micro-batch).

    ``apply_fn(params, *inputs) -> output | tuple`` is traced under
    ``jax.jit``; batches pad to sticky buckets so varying micro-batch
    sizes reuse one executable.
    """

    def __init__(self, apply_fn, params,
                 input_names: Sequence[str],
                 output_names: Sequence[str]):
        import jax

        self.params = params
        self.input_names = tuple(input_names)
        self.output_names = tuple(output_names)
        self._jitted = jax.jit(apply_fn)
        self._bucket = 0

    def predict(self, inputs):
        n = len(next(iter(inputs.values())))
        size = sticky_bucket(n, self._bucket)
        self._bucket = size
        padded = []
        for name in self.input_names:
            v = np.asarray(inputs[name])
            pad = np.zeros((size - n,) + v.shape[1:], dtype=v.dtype)
            padded.append(np.concatenate([v, pad]) if size > n else v)
        out = self._jitted(self.params, *padded)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return {name: np.asarray(col)[:n]
                for name, col in zip(self.output_names, out)}


@public_evolving
class RemoteModel(Model):
    """External inference endpoint (the reference's OpenAI/Triton client
    role). The transport is injected: ``client(inputs) -> outputs`` —
    typically an HTTP call per micro-batch. Pair with
    AsyncMLPredictOperator for bounded-in-flight overlap (reference:
    AsyncMLPredictRunner)."""

    def __init__(self, client: Callable[[Dict[str, np.ndarray]],
                                        Dict[str, np.ndarray]],
                 input_names: Sequence[str],
                 output_names: Sequence[str],
                 open_fn: Optional[Callable[[], None]] = None,
                 close_fn: Optional[Callable[[], None]] = None):
        self.client = client
        self.input_names = tuple(input_names)
        self.output_names = tuple(output_names)
        self._open_fn = open_fn
        self._close_fn = close_fn

    def open(self):
        if self._open_fn:
            self._open_fn()

    def close(self):
        if self._close_fn:
            self._close_fn()

    def predict(self, inputs):
        return self.client(inputs)


@public_evolving
class ModelRegistry:
    """Model catalog (the reference's CatalogModel store behind CREATE
    MODEL / model identifiers in ML_PREDICT)."""

    def __init__(self):
        self._models: Dict[str, Model] = {}

    def register(self, name: str, model: Model) -> None:
        self._models[name] = model

    def get(self, name: str) -> Model:
        try:
            return self._models[name]
        except KeyError:
            raise KeyError(
                f"unknown model {name!r}; registered: "
                f"{sorted(self._models)} (register with "
                "t_env.create_temporary_model or CREATE MODEL)") from None

    def create_from_options(self, name: str,
                            options: Dict[str, str]) -> None:
        """CREATE MODEL ... WITH (...) — the 'provider' option selects the
        factory (reference: model provider discovery). Built-in provider
        'python' imports ``entry`` = "module:attribute" resolving to a
        Model or a zero-arg factory."""
        provider = options.get("provider")
        if provider != "python":
            raise ValueError(
                f"unknown model provider {provider!r} (built-in: 'python'; "
                "remote providers are injected as RemoteModel instances)")
        entry = options.get("entry", "")
        mod_name, _, attr = entry.partition(":")
        if not mod_name or not attr:
            raise ValueError(
                "provider 'python' needs entry='module:attribute'")
        import importlib

        obj = getattr(importlib.import_module(mod_name), attr)
        model = obj() if callable(obj) and not isinstance(obj, Model) \
            else obj
        if not isinstance(model, Model):
            raise TypeError(f"{entry} did not resolve to a Model")
        self.register(name, model)
