"""ML_PREDICT operators.

reference: flink-table-runtime/.../operators/ml/MLPredictRunner.java (sync)
and AsyncMLPredictRunner.java (bounded in-flight async) — but batched: one
``Model.predict`` call per micro-batch instead of one request per record.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.ml.models import Model
from flink_tpu.runtime.operators import Operator


class MLPredictOperator(Operator):
    """Synchronous batched inference: appends the model's output columns
    to each batch (reference: MLPredictRunner, minus the per-record RPC)."""

    name = "ml_predict"

    def __init__(self, model: Model,
                 input_fields: Optional[Sequence[str]] = None,
                 output_prefix: str = ""):
        self.model = model
        self.input_fields = tuple(input_fields or model.input_names)
        if len(self.input_fields) != len(model.input_names):
            raise ValueError(
                f"model expects {len(model.input_names)} inputs "
                f"{tuple(model.input_names)}, got descriptor "
                f"{self.input_fields}")
        self.output_prefix = output_prefix

    def open(self, ctx):
        self.model.open()

    def process_batch(self, batch: RecordBatch, input_index: int = 0):
        if len(batch) == 0:
            # dropped, not forwarded: an empty batch without the promised
            # output columns would break downstream projections
            return []
        inputs = {
            name: np.asarray(batch[field])
            for name, field in zip(self.model.input_names,
                                   self.input_fields)
        }
        outputs = self.model.predict(inputs)
        for name in self.model.output_names:
            batch = batch.with_column(self.output_prefix + name,
                                      outputs[name])
        return [batch]

    def close(self):
        self.model.close()
        return []


class AsyncMLPredictOperator(Operator):
    """Async variant: inference overlaps with upstream processing under a
    bounded in-flight budget, results re-emitted in order (reference:
    AsyncMLPredictRunner over the async wait operator)."""

    name = "async_ml_predict"

    def __init__(self, model: Model,
                 input_fields: Optional[Sequence[str]] = None,
                 output_prefix: str = "", capacity: int = 4,
                 timeout_s: float = 30.0):
        from flink_tpu.runtime.async_operator import (
            AsyncFunction,
            AsyncWaitOperator,
        )

        predictor = MLPredictOperator(model, input_fields, output_prefix)

        class _Predict(AsyncFunction):
            def open(self):
                model.open()

            def close(self):
                model.close()

            def invoke(self, batch):
                return predictor.process_batch(batch)[0]

        self._inner = AsyncWaitOperator(_Predict(), ordered=True,
                                        capacity=capacity,
                                        timeout_ms=int(timeout_s * 1000))

    def open(self, ctx):
        self._inner.open(ctx)

    def process_batch(self, batch, input_index=0):
        return self._inner.process_batch(batch, input_index)

    def process_watermark(self, watermark, input_index=0):
        return self._inner.process_watermark(watermark, input_index)

    def close(self):
        return self._inner.close()

    def dispose(self):
        self._inner.dispose()

    # in-flight batches must ride checkpoints for exactly-once replay
    # (reference: AsyncWaitOperator state snapshot of pending elements)
    def snapshot_state(self):
        return self._inner.snapshot_state()

    def restore_state(self, state):
        self._inner.restore_state(state)
