"""Operator wrapper for the device-native CEP engine.

Plugs :class:`flink_tpu.cep.mesh_engine.MeshCepEngine` into the
DataStream/job-graph runtime the way ``DeviceIntervalJoinOperator``
plugs the join engines in: the operator opens its engine over the
task's mesh (parallelism-clamped to the device count), rides the
configured keyBy data plane (``shuffle.mode``), attaches the job
watchdog, and speaks the checkpoint protocol
(``snapshot_state``/``restore_state(key_group_filter=...)``).

Selected by ``cep.mode=device`` (``DeploymentOptions.CEP_MODE``). A
pattern outside the bounded-partial device class does NOT fail the
job: :class:`UnsupportedCepPattern` at open() routes the operator to
the host :class:`CepOperator` oracle — counted and logged
(``record_host_fallback``), never silent.
"""

from __future__ import annotations

from typing import List, Optional

from flink_tpu.cep.kernels import UnsupportedCepPattern
from flink_tpu.cep.mesh_engine import (
    MeshCepEngine,
    record_host_fallback,
)
from flink_tpu.cep.pattern import Pattern
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.operators import Operator


class MeshCepOperator(Operator):
    """Keyed CEP on the device state plane, host-oracle fallback."""

    name = "device_cep"

    def __init__(self, pattern: Pattern,
                 key_field: Optional[str] = None,
                 select=None,
                 capacity: int = 1 << 16,
                 match_capacity: int = 1 << 10,
                 spill_dir: Optional[str] = None,
                 spill_host_max_bytes: int = 0) -> None:
        self.pattern = pattern
        self.key_field = key_field
        self.select = select
        self._capacity = int(capacity)
        self._match_capacity = int(match_capacity)
        self._spill_dir = spill_dir
        self._spill_host_max_bytes = int(spill_host_max_bytes)
        self.engine: Optional[MeshCepEngine] = None

    def open(self, ctx) -> None:
        import jax

        effective = max(min(getattr(ctx, "parallelism", 1),
                            len(jax.devices())), 1)
        from flink_tpu.parallel.mesh import make_mesh

        kwargs = dict(
            key_field=self.key_field,
            select=self.select,
            capacity_per_shard=self._capacity,
            max_parallelism=getattr(ctx, "max_parallelism", 128),
            match_capacity=self._match_capacity,
            spill_dir=self._spill_dir,
            spill_host_max_bytes=self._spill_host_max_bytes,
            key_group_range=getattr(ctx, "key_group_range", None),
        )
        try:
            mesh = getattr(ctx, "mesh", None) or make_mesh(effective)
            self.engine = MeshCepEngine(
                self.pattern, mesh=mesh, backend="device",
                shuffle_mode=getattr(ctx, "shuffle_mode", "device"),
                **kwargs)
        except UnsupportedCepPattern as e:
            record_host_fallback(str(e))
            self.engine = MeshCepEngine(
                self.pattern, num_shards=1, backend="host",
                shuffle_mode="host", **kwargs)
        wd = getattr(ctx, "watchdog", None)
        if wd is not None:
            self.engine.attach_watchdog(wd)

    def process_batch(self, batch, input_index=0) -> List[RecordBatch]:
        return self.engine.process_batch(batch, input_index)

    def process_watermark(self, watermark, input_index=0
                          ) -> List[RecordBatch]:
        return self.engine.on_watermark(int(watermark))

    def close(self) -> List[RecordBatch]:
        from flink_tpu.runtime.elements import MAX_WATERMARK

        return self.engine.on_watermark(MAX_WATERMARK)

    def snapshot_state(self):
        return self.engine.snapshot()

    def restore_state(self, state, key_group_filter=None):
        self.engine.restore(state, key_group_filter=key_group_filter)

    def supports_live_rescale(self) -> bool:
        return self.engine is not None \
            and self.engine.backend == "device"

    def reshard(self, new_shards: int):
        return self.engine.reshard(new_shards)

    def spill_counters(self):
        return self.engine.spill_counters()

    def register_metrics(self, group) -> None:
        self.engine.register_metrics(group)
