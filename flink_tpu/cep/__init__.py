"""CEP — complex event processing (pattern matching on keyed streams).

reference: flink-libraries/flink-cep (NFA-based pattern matching on keyed
state + timers; see SURVEY.md §2.2).
"""

from flink_tpu.cep.nfa import KeyNFA, Match
from flink_tpu.cep.operator import CEP, CepOperator, PatternStream
from flink_tpu.cep.pattern import AfterMatchSkipStrategy, Pattern

__all__ = ["AfterMatchSkipStrategy", "CEP", "CepOperator", "KeyNFA",
           "Match", "MeshCepEngine", "MeshCepOperator", "Pattern",
           "PatternStream", "UnsupportedCepPattern",
           "compile_device_pattern"]


def __getattr__(name):
    # the device engine pulls in jax + the state-plane stack; keep the
    # host-only CEP API importable without that weight
    if name in ("MeshCepEngine", "CepMatchReplicaAdapter",
                "record_host_fallback", "host_fallbacks"):
        from flink_tpu.cep import mesh_engine

        return getattr(mesh_engine, name)
    if name in ("UnsupportedCepPattern", "compile_device_pattern",
                "DevicePatternLayout"):
        from flink_tpu.cep import kernels

        return getattr(kernels, name)
    if name == "MeshCepOperator":
        from flink_tpu.cep.operators import MeshCepOperator

        return MeshCepOperator
    raise AttributeError(name)
