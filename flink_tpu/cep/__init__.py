"""CEP — complex event processing (pattern matching on keyed streams).

reference: flink-libraries/flink-cep (NFA-based pattern matching on keyed
state + timers; see SURVEY.md §2.2).
"""

from flink_tpu.cep.nfa import KeyNFA, Match
from flink_tpu.cep.operator import CEP, CepOperator, PatternStream
from flink_tpu.cep.pattern import AfterMatchSkipStrategy, Pattern

__all__ = ["AfterMatchSkipStrategy", "CEP", "CepOperator", "KeyNFA",
           "Match", "Pattern", "PatternStream"]
