"""NFA runtime for CEP pattern matching.

reference: flink-cep/.../nfa/NFA.java (946 LoC), ComputationState.java,
SharedBuffer. The reference threads one event at a time through versioned
computation states (TAKE / IGNORE / PROCEED transitions) over a shared
event buffer.

Re-design: conditions were already evaluated batch-wide (a bool matrix
[events x stages]); the NFA advance loop per key reads only those booleans
and event timestamps. Partial matches keep indices into a per-key event
log (the SharedBuffer analog — events stored once, matches reference them).

Semantics kept from the reference:
- between-stage contiguity: ``next`` (strict — a miss kills the waiting
  partial) vs ``followedBy`` (relaxed — misses are ignored);
- loop-internal contiguity of ``times``/``oneOrMore`` is relaxed unless
  ``consecutive()`` (reference: Quantifier.ConsecutiveStrategy);
- every event may begin a new match (start state always active), including
  at stages reachable through an all-optional prefix;
- a match completes as soon as the remaining suffix is all-optional;
- ``within`` prunes partials whose span exceeds the window;
- after-match skip: NO_SKIP emits every combination, SKIP_PAST_LAST_EVENT
  discards partials and events inside the matched span;
- negative patterns compile into GUARDS on the following positive stage
  (notNext: the first event after arrival must not match; notFollowedBy:
  no event before the stage's first take may match — reference:
  NotCondition edges); a TRAILING notFollowedBy holds completed matches
  until the within-window expires, then emits (reference: timestamped
  releases of not-followed-by matches);
- ``until`` gates further loop takes once its condition fires.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.cep.pattern import (
    AfterMatchSkipStrategy,
    Contiguity,
    Pattern,
)

_VIRTUAL = -(1 << 62)  # start_ts marker for the always-active start state


@dataclasses.dataclass
class _Partial:
    """One computation state (reference: ComputationState.java)."""

    stage: int  # index into the EXEC (positive) stage list
    count: int  # takes in the current stage
    events: Tuple[Tuple[int, int], ...]  # (exec_stage_idx, event_log_idx)
    start_ts: int
    #: log index of the event whose processing created this partial (the
    #: strict notNext guard applies only to the event right after it)
    arrived: int = _VIRTUAL

    def key(self):
        return (self.stage, self.count, self.events)


@dataclasses.dataclass
class _ExecStage:
    """A positive stage with its compiled pre-guards. ``pre_negs`` holds
    (original stage index, strict) for each negative stage the pattern
    placed immediately before this one; ``tail_negative`` marks the
    synthetic wait-state a trailing notFollowedBy compiles into."""

    stage: object  # the positive Stage (None for the synthetic tail)
    orig_idx: int  # condition column in the operator's hit matrix (-1 tail)
    pre_negs: List[Tuple[int, bool]] = dataclasses.field(
        default_factory=list)
    tail_negative: bool = False


def compile_stages(pattern: Pattern) -> List[_ExecStage]:
    out: List[_ExecStage] = []
    pending_negs: List[Tuple[int, bool]] = []
    for i, st in enumerate(pattern.stages):
        if st.negated:
            pending_negs.append((i, st.contiguity is Contiguity.STRICT))
            continue
        out.append(_ExecStage(st, i, pending_negs))
        pending_negs = []
    if pending_negs:
        # trailing notFollowedBy: a wait-state released by within expiry
        out.append(_ExecStage(None, -1, pending_negs, tail_negative=True))
    return out


class IterativeContext:
    """What an iterative condition may read: the events the partial match
    has already taken, by stage name (reference:
    IterativeCondition.Context.getEventsForPattern)."""

    def __init__(self, nfa: "KeyNFA", partial: "_Partial"):
        self._nfa = nfa
        self._partial = partial

    def events_for(self, name: str) -> List[dict]:
        nfa = self._nfa
        return [nfa.event(ei) for si, ei in self._partial.events
                if nfa.exec_stages[si].stage.name == name]


@dataclasses.dataclass
class Match:
    start_ts: int
    end_ts: int
    # stage name -> list of event-log indices
    events_by_stage: Dict[str, List[int]] = dataclasses.field(
        default_factory=dict)
    #: pre-resolved events for matches released by prune() (their log
    #: entries may be compacted in the same call); None otherwise
    resolved_events: Optional[Dict[str, List[dict]]] = None


class KeyNFA:
    """Per-key NFA instance: event log + live partial matches."""

    def __init__(self, pattern: Pattern):
        self.pattern = pattern
        self.exec_stages = compile_stages(pattern)
        # the SharedBuffer analog: events stored once, referenced by index.
        # Indices are absolute; the log is compacted by rebasing on _log_base
        # (prune()) so long-running keys don't grow without bound.
        self.event_log: List[dict] = []
        self._log_base = 0
        self.partials: List[_Partial] = []
        # suffix_optional[j] == True iff all exec stages AFTER j are
        # optional (the synthetic tail-negative stage is NOT optional: it
        # must be waited out)
        n = len(self.exec_stages)
        self._suffix_optional = [True] * n
        for j in range(n - 2, -1, -1):
            nxt = self.exec_stages[j + 1]
            self._suffix_optional[j] = (
                self._suffix_optional[j + 1]
                and not nxt.tail_negative
                and nxt.stage.min_times == 0)
        # exec stage index -> until-condition column offset (appended
        # after the pattern-stage columns in the operator's hit matrix)
        self._until_col: Dict[int, int] = {}
        k = 0
        for j, es in enumerate(self.exec_stages):
            if not es.tail_negative \
                    and es.stage.until_condition is not None:
                self._until_col[j] = k
                k += 1

    def _start_stages(self) -> List[int]:
        """Exec-stage indices a fresh match may begin at (0 plus the
        stages behind an all-optional prefix)."""
        out = [0]
        for j, es in enumerate(self.exec_stages[:-1]):
            if not es.tail_negative and es.stage.min_times == 0:
                out.append(j + 1)
            else:
                break
        return out

    # -- advance -------------------------------------------------------------

    def advance(self, event: dict, ts: int,
                stage_hits: List[bool]) -> List[Match]:
        """Feed one event (with precomputed per-stage condition booleans;
        until-condition columns appended after the pattern stages);
        returns completed matches."""
        exec_stages = self.exec_stages
        within = self.pattern.within_ms
        n_stages = len(self.pattern.stages)
        skip_past = (self.pattern.skip
                     is AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)

        log_idx = self._log_base + len(self.event_log)
        self.event_log.append(event)
        matches: List[Match] = []
        new_partials: List[_Partial] = []
        seen = set()

        def emit(start_ts: int, taken, end_ts: int = ts) -> None:
            by_stage: Dict[str, List[int]] = {}
            for si, ei in taken:
                by_stage.setdefault(exec_stages[si].stage.name,
                                    []).append(ei)
            matches.append(Match(start_ts, end_ts, by_stage))

        def add(p: _Partial) -> None:
            k = p.key()
            if k not in seen:
                seen.add(k)
                new_partials.append(p)

        candidates = list(self.partials) + [
            _Partial(j, 0, (), _VIRTUAL) for j in self._start_stages()]

        matched_now = False
        for p in candidates:
            virtual = p.start_ts == _VIRTUAL
            st = exec_stages[p.stage]
            if (not virtual and within is not None
                    and ts - p.start_ts > within):
                if st.tail_negative:
                    # a trailing notFollowedBy survived its whole window:
                    # the match releases at the expiry timestamp. This
                    # does NOT trigger skip-past pruning — the released
                    # span lies entirely before the current event, so
                    # partials this event starts are outside it.
                    emit(p.start_ts, p.events, end_ts=p.start_ts + within)
                continue  # timed out (reference: pruning on within)
            # pre-guards: negative stages compiled onto this stage apply
            # while it has not taken yet (notNext only to the event right
            # after arrival — reference: NotCondition edges)
            if p.count == 0 and st.pre_negs and not virtual:
                killed = False
                for neg_idx, strict in st.pre_negs:
                    if strict and log_idx != p.arrived + 1:
                        continue
                    if bool(stage_hits[neg_idx]):
                        killed = True
                        break
                if killed:
                    continue
            if st.tail_negative:
                add(p)  # waiting out the window (guards checked above)
                continue
            if not virtual and p.count == 0 and p.stage > 0:
                prev = exec_stages[p.stage - 1]
                if not prev.tail_negative and prev.stage.greedy:
                    # the gate only applies while the loop can still TAKE
                    # (reference: greedy guards edges of live loop
                    # states): a saturated loop (taken == max_times)
                    # cannot claim the event, so the waiting state must
                    # keep its normal take/ignore behavior
                    taken_in_loop = sum(
                        1 for si, _ in p.events if si == p.stage - 1)
                    saturated = (prev.stage.max_times is not None
                                 and taken_in_loop
                                 >= prev.stage.max_times)
                    prev_hit = (not saturated
                                and bool(stage_hits[prev.orig_idx]))
                    if prev_hit and \
                            prev.stage.iterative_condition is not None:
                        # the proceed partial carries the loop's taken
                        # events, so its context evaluates the loop's
                        # match-dependent condition exactly
                        prev_hit = bool(prev.stage.iterative_condition(
                            event, IterativeContext(self, p)))
                    if prev_hit and not (
                            prev.stage.until_condition is not None
                            and bool(stage_hits[
                                n_stages
                                + self._until_col[p.stage - 1]])):
                        # greedy loop behind this fresh waiting state
                        # claims the event: the shorter-prefix branch can
                        # neither take nor ignore it — it dies, and the
                        # loop's own take spawns the longer-prefix
                        # replacement (reference:
                        # NFACompiler.updateWithGreedyCondition guards
                        # both edges with not(loop condition))
                        continue
            hit = bool(stage_hits[st.orig_idx])
            if hit and st.stage.iterative_condition is not None:
                hit = bool(st.stage.iterative_condition(
                    event, IterativeContext(self, p)))
            until_hit = (st.stage.until_condition is not None
                         and bool(stage_hits[n_stages
                                             + self._until_col[p.stage]]))
            can_take = hit and not until_hit and (
                st.stage.max_times is None
                or p.count < st.stage.max_times)
            if can_take:
                start_ts = ts if virtual else p.start_ts
                taken = p.events + ((p.stage, log_idx),)
                count = p.count + 1
                if count >= st.stage.min_times \
                        and self._suffix_optional[p.stage]:
                    emit(start_ts, taken)
                    matched_now = True
                    if skip_past:
                        break
                if st.stage.max_times is None \
                        or count < st.stage.max_times:
                    add(_Partial(p.stage, count, taken, start_ts,
                                 arrived=log_idx))
                if count >= st.stage.min_times:
                    # PROCEED: wait in the next stage, chaining past any
                    # optional stages (each may be skipped entirely)
                    j = p.stage + 1
                    while j < len(exec_stages):
                        add(_Partial(j, 0, taken, start_ts,
                                     arrived=log_idx))
                        nxt = exec_stages[j]
                        if not nxt.tail_negative \
                                and nxt.stage.min_times == 0:
                            j += 1
                        else:
                            break
                if st.stage.combinations and not virtual and p.count > 0:
                    add(p)  # allowCombinations: also skip the match event
            elif virtual:
                continue  # a start that doesn't start is nothing
            elif not hit or until_hit:
                if until_hit:
                    # the stop condition closes the loop for good: a
                    # waiting partial (any count) can never take again —
                    # satisfied loops live on through the proceed
                    # branches spawned at their last take (reference:
                    # until stops accepting elements into the loop)
                    continue
                if p.count == 0 \
                        and st.stage.contiguity is Contiguity.STRICT \
                        and p.stage > 0:
                    continue  # 'next' stage missed its immediate event
                if p.count > 0 and st.stage.consecutive_internal:
                    continue  # consecutive() loop broken
                add(p)  # IGNORE: keep waiting (relaxed)
            else:
                # hit but the loop is saturated (count == max_times): this
                # partial only survives via the proceed branch spawned at
                # its last take
                continue

        if matched_now and skip_past:
            # discard every other partial match (the reference's
            # skipPastLastEvent prunes computation states, NOT future
            # events — the break above also
            # kept this event out of any new partial
            self.partials = []
            return matches
        self.partials = new_partials
        return matches

    def event(self, abs_idx: int) -> dict:
        return self.event_log[abs_idx - self._log_base]

    def prune(self, watermark: int) -> List[Match]:
        """Drop timed-out partials and compact the event log below the
        lowest index any live partial still references (the reference
        SharedBuffer's ref-counting, done as a rebase). Returns matches
        RELEASED by the pruning: a trailing notFollowedBy partial whose
        window expired without the forbidden event completes here (the
        reference's timestamped not-followed-by releases)."""
        matches: List[Match] = []
        within = self.pattern.within_ms
        if within is not None:
            keep: List[_Partial] = []
            for p in self.partials:
                if watermark - p.start_ts <= within:
                    keep.append(p)
                elif self.exec_stages[p.stage].tail_negative:
                    # resolve events NOW — the compaction below may drop
                    # the log entries this released match references
                    by_stage: Dict[str, List[int]] = {}
                    resolved: Dict[str, List[dict]] = {}
                    for si, ei in p.events:
                        name = self.exec_stages[si].stage.name
                        by_stage.setdefault(name, []).append(ei)
                        resolved.setdefault(name, []).append(self.event(ei))
                    matches.append(Match(p.start_ts, p.start_ts + within,
                                         by_stage, resolved))
            self.partials = keep
        next_idx = self._log_base + len(self.event_log)
        if not self.partials:
            min_ref = next_idx
        else:
            min_ref = min(ei for p in self.partials for _, ei in p.events)
        if min_ref > self._log_base:
            del self.event_log[: min_ref - self._log_base]
            self._log_base = min_ref
        return matches

    @property
    def empty(self) -> bool:
        return not self.partials and not self.event_log

    # -- checkpoint ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return {
            "event_log": list(self.event_log),
            "log_base": self._log_base,
            "partials": [dataclasses.asdict(p) for p in self.partials],
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        self.event_log = list(snap["event_log"])
        self._log_base = snap.get("log_base", 0)
        self.partials = [
            _Partial(d["stage"], d["count"],
                     tuple(tuple(e) for e in d["events"]), d["start_ts"],
                     arrived=d.get("arrived", _VIRTUAL))
            for d in snap["partials"]]
