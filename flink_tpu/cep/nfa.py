"""NFA runtime for CEP pattern matching.

reference: flink-cep/.../nfa/NFA.java (946 LoC), ComputationState.java,
SharedBuffer. The reference threads one event at a time through versioned
computation states (TAKE / IGNORE / PROCEED transitions) over a shared
event buffer.

Re-design: conditions were already evaluated batch-wide (a bool matrix
[events x stages]); the NFA advance loop per key reads only those booleans
and event timestamps. Partial matches keep indices into a per-key event
log (the SharedBuffer analog — events stored once, matches reference them).

Semantics kept from the reference:
- between-stage contiguity: ``next`` (strict — a miss kills the waiting
  partial) vs ``followedBy`` (relaxed — misses are ignored);
- loop-internal contiguity of ``times``/``oneOrMore`` is relaxed unless
  ``consecutive()`` (reference: Quantifier.ConsecutiveStrategy);
- every event may begin a new match (start state always active), including
  at stages reachable through an all-optional prefix;
- a match completes as soon as the remaining suffix is all-optional;
- ``within`` prunes partials whose span exceeds the window;
- after-match skip: NO_SKIP emits every combination, SKIP_PAST_LAST_EVENT
  discards partials and events inside the matched span.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.cep.pattern import (
    AfterMatchSkipStrategy,
    Contiguity,
    Pattern,
)

_VIRTUAL = -(1 << 62)  # start_ts marker for the always-active start state


@dataclasses.dataclass
class _Partial:
    """One computation state (reference: ComputationState.java)."""

    stage: int  # index into pattern.stages
    count: int  # takes in the current stage
    events: Tuple[Tuple[int, int], ...]  # (stage_idx, event_log_idx)
    start_ts: int

    def key(self):
        return (self.stage, self.count, self.events)


@dataclasses.dataclass
class Match:
    start_ts: int
    end_ts: int
    # stage name -> list of event-log indices
    events_by_stage: Dict[str, List[int]] = dataclasses.field(
        default_factory=dict)


class KeyNFA:
    """Per-key NFA instance: event log + live partial matches."""

    def __init__(self, pattern: Pattern):
        self.pattern = pattern
        # the SharedBuffer analog: events stored once, referenced by index.
        # Indices are absolute; the log is compacted by rebasing on _log_base
        # (prune()) so long-running keys don't grow without bound.
        self.event_log: List[dict] = []
        self._log_base = 0
        self.partials: List[_Partial] = []
        # suffix_optional[j] == True iff all stages AFTER j are optional
        n = len(pattern.stages)
        self._suffix_optional = [True] * n
        for j in range(n - 2, -1, -1):
            self._suffix_optional[j] = (
                self._suffix_optional[j + 1]
                and pattern.stages[j + 1].min_times == 0)

    def _start_stages(self) -> List[int]:
        """Stage indices a fresh match may begin at (0 plus the stages behind
        an all-optional prefix)."""
        out = [0]
        for j, st in enumerate(self.pattern.stages[:-1]):
            if st.min_times == 0:
                out.append(j + 1)
            else:
                break
        return out

    # -- advance -------------------------------------------------------------

    def advance(self, event: dict, ts: int,
                stage_hits: List[bool]) -> List[Match]:
        """Feed one event (with precomputed per-stage condition booleans);
        returns completed matches."""
        stages = self.pattern.stages
        within = self.pattern.within_ms
        skip_past = (self.pattern.skip
                     is AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)

        log_idx = self._log_base + len(self.event_log)
        self.event_log.append(event)
        matches: List[Match] = []
        new_partials: List[_Partial] = []
        seen = set()

        def emit(start_ts: int, taken) -> None:
            by_stage: Dict[str, List[int]] = {}
            for si, ei in taken:
                by_stage.setdefault(stages[si].name, []).append(ei)
            matches.append(Match(start_ts, ts, by_stage))

        def add(p: _Partial) -> None:
            k = p.key()
            if k not in seen:
                seen.add(k)
                new_partials.append(p)

        candidates = list(self.partials) + [
            _Partial(j, 0, (), _VIRTUAL) for j in self._start_stages()]

        matched_now = False
        for p in candidates:
            virtual = p.start_ts == _VIRTUAL
            if (not virtual and within is not None
                    and ts - p.start_ts > within):
                continue  # timed out (reference: pruning on within)
            st = stages[p.stage]
            hit = bool(stage_hits[p.stage])
            can_take = hit and (st.max_times is None or p.count < st.max_times)
            if can_take:
                start_ts = ts if virtual else p.start_ts
                taken = p.events + ((p.stage, log_idx),)
                count = p.count + 1
                if count >= st.min_times and self._suffix_optional[p.stage]:
                    emit(start_ts, taken)
                    matched_now = True
                    if skip_past:
                        break
                if st.max_times is None or count < st.max_times:
                    add(_Partial(p.stage, count, taken, start_ts))
                if count >= st.min_times:
                    # PROCEED: wait in the next stage, chaining past any
                    # optional stages (each may be skipped entirely)
                    j = p.stage + 1
                    while j < len(stages):
                        add(_Partial(j, 0, taken, start_ts))
                        if stages[j].min_times == 0:
                            j += 1
                        else:
                            break
                if st.combinations and not virtual and p.count > 0:
                    add(p)  # allowCombinations: also skip the matching event
            elif virtual:
                continue  # a start that doesn't start is nothing
            elif not hit:
                if p.count == 0 and st.contiguity is Contiguity.STRICT \
                        and p.stage > 0:
                    continue  # 'next' stage missed its immediate event
                if p.count > 0 and st.consecutive_internal:
                    continue  # consecutive() loop broken
                add(p)  # IGNORE: keep waiting (relaxed)
            else:
                # hit but the loop is saturated (count == max_times): this
                # partial only survives via the proceed branch spawned at
                # its last take
                continue

        if matched_now and skip_past:
            # discard every other partial match (the reference's
            # skipPastLastEvent prunes computation states, NOT future
            # events — the next event starts fresh); the break above also
            # kept this event out of any new partial
            self.partials = []
            return matches
        self.partials = new_partials
        return matches

    def event(self, abs_idx: int) -> dict:
        return self.event_log[abs_idx - self._log_base]

    def prune(self, watermark: int) -> None:
        """Drop timed-out partials and compact the event log below the
        lowest index any live partial still references (the reference
        SharedBuffer's ref-counting, done as a rebase)."""
        within = self.pattern.within_ms
        if within is not None:
            self.partials = [p for p in self.partials
                             if watermark - p.start_ts <= within]
        next_idx = self._log_base + len(self.event_log)
        if not self.partials:
            min_ref = next_idx
        else:
            min_ref = min(ei for p in self.partials for _, ei in p.events)
        if min_ref > self._log_base:
            del self.event_log[: min_ref - self._log_base]
            self._log_base = min_ref

    @property
    def empty(self) -> bool:
        return not self.partials and not self.event_log

    # -- checkpoint ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return {
            "event_log": list(self.event_log),
            "log_base": self._log_base,
            "partials": [dataclasses.asdict(p) for p in self.partials],
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        self.event_log = list(snap["event_log"])
        self._log_base = snap.get("log_base", 0)
        self.partials = [
            _Partial(d["stage"], d["count"],
                     tuple(tuple(e) for e in d["events"]), d["start_ts"])
            for d in snap["partials"]]
