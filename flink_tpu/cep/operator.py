"""CEP operator: keyed NFA matching with event-time ordering.

reference: flink-cep/.../operator/CepOperator.java — buffers out-of-order
events in keyed state (a MapState of ts -> events) and advances the NFA in
timestamp order when the watermark passes, one NFA per key.

Batched re-design: per micro-batch, all stage conditions are evaluated
vectorized over the whole batch (one mask per stage); events + their
per-stage hit booleans are bucketed per key into host buffers; on watermark
advance each key's due events are sorted by timestamp and threaded through
that key's NFA. The Python loop is O(events x live partials) per key but
does no predicate work — the predicates ran columnar.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.cep.nfa import KeyNFA, Match
from flink_tpu.cep.pattern import Pattern
from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.operators import Operator


def default_select(key: Any, match: Match,
                   events_by_stage: Dict[str, List[dict]]) -> dict:
    """Default match projection: key, span, per-stage event counts."""
    row = {"key": key, "start_ts": match.start_ts, "end_ts": match.end_ts}
    for name, events in events_by_stage.items():
        row[f"{name}_count"] = len(events)
    return row


class CepOperator(Operator):
    name = "cep"

    def __init__(self, pattern: Pattern, key_field: str,
                 select: Optional[Callable] = None):
        self.pattern = pattern.validate()
        self.key_field = key_field
        self.select = select or default_select
        self._nfas: Dict[int, KeyNFA] = {}
        # pending (not yet watermark-ripe) events per key:
        # list of (ts, event_row, stage_hits tuple)
        self._pending: Dict[int, List] = {}
        self._key_values: Dict[int, Any] = {}

    # -- hooks ---------------------------------------------------------------

    def process_batch(self, batch: RecordBatch, input_index: int = 0
                      ) -> List[RecordBatch]:
        if len(batch) == 0:
            return []
        # vectorized: one mask per stage over the whole batch, with
        # until-condition columns appended (same pattern-order the NFA's
        # _until_col mapping assumes)
        cols = [st.evaluate(batch) for st in self.pattern.stages]
        cols.extend(
            np.asarray(st.until_condition(batch), dtype=bool)
            for st in self.pattern.stages if st.until_condition is not None)
        hits = np.stack(cols, axis=1)  # [n, n_stages + n_untils]
        kids = batch.key_ids
        tss = batch.timestamps
        rows = batch.to_rows()
        if self.key_field in batch.columns:
            kv = self._key_values
            for k, r in zip(kids.tolist(), rows):
                if k not in kv:
                    kv[k] = r.get(self.key_field)
        pending = self._pending
        hit_list = hits.tolist()
        for i, (k, t) in enumerate(zip(kids.tolist(), tss.tolist())):
            pending.setdefault(k, []).append((t, rows[i], hit_list[i]))
        return []

    def process_watermark(self, watermark: int, input_index: int = 0
                          ) -> List[RecordBatch]:
        out_rows: List[dict] = []
        out_ts: List[int] = []
        for k, buf in self._pending.items():
            due = [e for e in buf if e[0] <= watermark]
            if not due:
                continue
            self._pending[k] = [e for e in buf if e[0] > watermark]
            due.sort(key=lambda e: e[0])
            nfa = self._nfas.get(k)
            if nfa is None:
                nfa = self._nfas[k] = KeyNFA(self.pattern)
            for ts, row, stage_hits in due:
                for m in nfa.advance(row, ts, stage_hits):
                    # every pattern stage is present (possibly empty) so
                    # emitted rows share one schema regardless of optionals
                    events = {
                        st.name: [nfa.event(i) for i in
                                  m.events_by_stage.get(st.name, [])]
                        for st in self.pattern.stages}
                    out_rows.append(self.select(
                        self._key_values.get(k, k), m, events))
                    out_ts.append(m.end_ts)
        # prune EVERY key (idle keys must release within-expired partials
        # and their event logs), dropping empty per-key state entirely.
        # Pruning can RELEASE matches: a trailing notFollowedBy completes
        # when its window expires without the forbidden event.
        for k in list(self._nfas):
            nfa = self._nfas[k]
            for m in nfa.prune(watermark):
                events = {
                    st.name: list((m.resolved_events or {}).get(st.name,
                                                                []))
                    for st in self.pattern.stages}
                out_rows.append(self.select(
                    self._key_values.get(k, k), m, events))
                out_ts.append(m.end_ts)
            if nfa.empty:
                del self._nfas[k]
        for k in [k for k, v in self._pending.items() if not v]:
            del self._pending[k]
        # a key's id->value mapping is only needed while it has live NFA
        # state or buffered events; dropping it with them keeps state (and
        # checkpoints) bounded for high-cardinality keys
        for k in [k for k in self._key_values
                  if k not in self._nfas and k not in self._pending]:
            del self._key_values[k]
        if not out_rows:
            return []
        out = RecordBatch.from_rows(out_rows).with_timestamps(out_ts)
        return [out]

    def close(self) -> List[RecordBatch]:
        # flush everything still buffered (end of input = MAX_WATERMARK
        # already arrived through process_watermark, so usually a no-op)
        return []

    # -- checkpoint ----------------------------------------------------------

    def snapshot_state(self):
        return {
            "nfas": {k: n.snapshot() for k, n in self._nfas.items()},
            "pending": {k: list(v) for k, v in self._pending.items()},
            "key_values": dict(self._key_values),
        }

    def restore_state(self, state):
        self._nfas = {}
        for k, snap in state.get("nfas", {}).items():
            nfa = KeyNFA(self.pattern)
            nfa.restore(snap)
            self._nfas[int(k)] = nfa
        self._pending = {int(k): [tuple(e) for e in v]
                         for k, v in state.get("pending", {}).items()}
        self._key_values = dict(state.get("key_values", {}))


class CEP:
    """Entry point (reference: flink-cep/.../CEP.java + PatternStream)."""

    @staticmethod
    def pattern(keyed_stream, pattern: Pattern) -> "PatternStream":
        return PatternStream(keyed_stream, pattern)


class PatternStream:
    def __init__(self, keyed_stream, pattern: Pattern):
        self.keyed = keyed_stream
        self.pattern = pattern

    def select(self, fn: Optional[Callable] = None, name: str = "cep"):
        from flink_tpu.datastream.stream import DataStream
        from flink_tpu.graph.transformations import Transformation

        pattern, key_field = self.pattern, self.keyed.key_field
        t = Transformation(
            name=name, kind="one_input",
            operator_factory=lambda: CepOperator(pattern, key_field,
                                                 select=fn),
            inputs=[self.keyed.transformation], keyed=True,
            key_field=key_field)
        return DataStream(self.keyed.env, t)
