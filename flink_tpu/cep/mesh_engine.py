"""Device-native CEP engine over the key-group mesh.

The one-record-at-a-time shape of ``cep/operator.py`` (a Python
``O(events x partials)`` loop per key, the JVM NFA's structure) replaced
by the state-plane discipline every other engine already follows: each
key's live partial matches live as ONE int32 bitmask row of a
``[P, capacity]`` ``alive`` plane (the settled-state automaton —
``cep/kernels.py``), the last ``R`` event sequence numbers ride ``R``
ring planes (the bounded SharedBuffer of the all-consecutive pattern
class), and a watermark fire advances EVERY due key's NFA through its
due events with ONE compiled gather/scan/scatter program.

Per batch the device runs at most four programs — the pending
ingest scatter (fused keyBy exchange under ``shuffle.mode=device``),
the NFA advance, one eviction gather under budget pressure and the
within-expiry prune — all shared through the tenancy ``PROGRAM_CACHE``
and shape-bounded by the ``pad_bucket_size`` / ``sticky_bucket`` tier
discipline, so steady state compiles nothing (the CEP phase of
``tools/recompile_smoke.py``).

It rides the existing machinery end-to-end, the way ``joins/`` does:
``stage_device_exchange`` staging with the double-buffer fence
contract, cold keys spilling as ``state/paged_spill.py`` cohorts
(within-expiry applied LAZILY at reload — exact, because a spilled key
saw no events since it spilled and the keep-test is monotone in the
watermark), ``snapshot_sharded`` / ``merge_unit_snapshots`` key-group
units, live ``reshard()``, watchdog sections + boundary probes, and a
bounded FIFO **matched-pattern store** on its own ``[P, match_capacity]``
planes that publishes boundary deltas through the replica plane
(``arm_match_replica`` -> :class:`CepMatchReplicaAdapter`), so completed
matches are queryable state like any aggregate.

``backend="host"`` wraps the reference :class:`CepOperator` — the
bit-identity oracle (values AND emission order) for every pattern the
device path accepts, gated by ``tools/cep_smoke.py``. Patterns outside
the bounded-partial class raise :class:`UnsupportedCepPattern` at
construction; callers fall back LOUDLY (``record_host_fallback``).

Documented deviations from the oracle, none visible in emitted rows:

- Both backends drop events at-or-before the last fired watermark at
  ingest (``late_dropped``) BEFORE stage evaluation — a policy the
  engine applies symmetrically, not an oracle behavior (the raw
  ``CepOperator`` run standalone buffers late events forever).
- ``Match.events_by_stage`` carries synthetic per-match event indices
  (0..depth-1 split by stage), not the oracle NFA's internal event-log
  ids; the resolved event ROWS handed to ``select`` are bit-identical.
- Spilled keys whose partials all expired stay in the page tier until
  their next event reloads them (the oracle deletes idle NFAs at every
  watermark); key-id hashing makes the retained first-seen key value
  identical either way.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from flink_tpu.chaos import injection as chaos
from flink_tpu.cep.kernels import (
    build_cep_advance,
    build_cep_exchange_put,
    build_cep_gather,
    build_cep_prune,
    build_cep_put,
    compile_device_pattern,
)
from flink_tpu.cep.nfa import Match
from flink_tpu.cep.operator import CepOperator, default_select
from flink_tpu.cep.pattern import Pattern
from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.ops.segment_ops import pad_bucket_size, sticky_bucket
from flink_tpu.state.keygroups import assign_key_groups
from flink_tpu.state.paged_spill import (
    PagedSpillMap,
    reload_rows_for,
    restore_into_pages,
    run_deferred_sweeps,
    spill_page,
)
from flink_tpu.state.slot_table import SpillTier

_log = logging.getLogger(__name__)

_NEG = -(1 << 62)

# tiny non-donated slice enqueued after everything dispatched so far —
# its readiness proves the device consumed every earlier staging buffer
# (the same double-buffer fence the join engines use)
_FENCE_STEP = jax.jit(lambda a: a[:1, :1])

#: job-global count of device-ineligible patterns routed to the host
#: operator (the ``cep.host_fallbacks`` metric; loud by design)
HOST_FALLBACKS = 0


def record_host_fallback(reason: str) -> None:
    """Count + log one device-path rejection. Callers (the SQL planner,
    ``MeshCepOperator``) invoke this when ``UnsupportedCepPattern``
    sends a pattern to the host ``CepOperator`` — the fallback is
    correct but never silent."""
    global HOST_FALLBACKS
    HOST_FALLBACKS += 1
    _log.warning(
        "cep.mode=device: pattern outside the bounded-partial device "
        "class, falling back to the host CepOperator: %s", reason)


def host_fallbacks() -> int:
    return HOST_FALLBACKS


def _item(v):
    return v.item() if hasattr(v, "item") else v


class _CepShard:
    """One shard's host bookkeeping: the slot directory over the state
    planes, the host halves of the ring (int64 timestamps + event value
    columns never ride the device — x32 discipline), the pending-event
    mirror, the paged spill tier and the match-store mirror."""

    def __init__(self, capacity: int, ring: int, match_capacity: int,
                 spill_dir: Optional[str],
                 spill_host_max_bytes: int) -> None:
        C, R, M = capacity, ring, match_capacity
        self.slot_of: Dict[int, int] = {}
        # slot 0 reserved: padded staging lanes scatter there
        self.free: List[int] = list(range(C - 1, 0, -1))
        self.key_of = np.zeros(C, dtype=np.int64)
        self.alive = np.zeros(C, dtype=np.int32)
        self.ring_seq = np.zeros((C, R), dtype=np.int32)
        self.ts_hist = np.full((C, R), _NEG, dtype=np.int64)
        #: {col -> [C, R]} value ring, bound at the first batch
        self.ring_vals: Optional[Dict[str, np.ndarray]] = None
        self.touch = np.zeros(C, dtype=np.int64)
        self.spill = SpillTier(spill_dir, spill_host_max_bytes)
        self.pmap = PagedSpillMap()
        # pending mirror, append (arrival) order — the order the oracle
        # ties equal-timestamp due events by
        self.p_pos = np.zeros(0, dtype=np.int32)
        self.p_key = np.zeros(0, dtype=np.int64)
        self.p_ts = np.zeros(0, dtype=np.int64)
        self.p_seq = np.zeros(0, dtype=np.int32)
        self.p_hits = np.zeros(0, dtype=np.int32)
        self.p_vals: Optional[Dict[str, np.ndarray]] = None
        self.cursor = 1  # device pending row 0 reserved (padding sink)
        # matched-pattern store mirror (FIFO over slots 1..M-1)
        self.m_used = np.zeros(M, dtype=bool)
        self.m_key = np.zeros(M, dtype=np.int64)
        self.m_rid = np.zeros(M, dtype=np.int64)
        self.m_start = np.zeros(M, dtype=np.int64)
        self.m_end = np.zeros(M, dtype=np.int64)
        self.m_depth = np.zeros(M, dtype=np.int32)
        self.m_fseq = np.zeros(M, dtype=np.int32)
        self.m_lseq = np.zeros(M, dtype=np.int32)
        self.m_count = 0

    def bind_schema(self, schema, capacity: int, ring: int) -> None:
        if self.ring_vals is not None:
            return
        self.ring_vals = {n: np.zeros((capacity, ring), dtype=dt)
                          for n, dt in schema}
        self.p_vals = {n: np.zeros(0, dtype=dt) for n, dt in schema}


class MeshCepEngine:
    """Keyed CEP over device-resident NFA state planes.

    ``backend="device"`` requires the pattern to compile to a
    :class:`~flink_tpu.cep.kernels.DevicePatternLayout` (raises
    :class:`~flink_tpu.cep.kernels.UnsupportedCepPattern` otherwise);
    ``backend="host"`` wraps the reference operator behind the same
    interface — the oracle both the smoke and the chaos harness pin
    the device path against, bit for bit."""

    def __init__(self, pattern: Pattern,
                 key_field: Optional[str] = None,
                 select: Optional[Callable] = None,
                 mesh=None, num_shards: int = 1,
                 capacity_per_shard: int = 1 << 16,
                 max_parallelism: int = 128,
                 match_capacity: int = 1 << 10,
                 spill_dir: Optional[str] = None,
                 spill_host_max_bytes: int = 0,
                 key_group_range: Optional[Tuple[int, int]] = None,
                 backend: str = "device",
                 shuffle_mode: str = "device") -> None:
        if backend not in ("device", "host"):
            raise ValueError(
                f"backend must be 'device' or 'host', got {backend!r}")
        if shuffle_mode not in ("device", "host"):
            raise ValueError(
                f"shuffle_mode must be 'device' or 'host', got "
                f"{shuffle_mode!r}")
        self.backend = backend
        self.shuffle_mode = shuffle_mode
        self.pattern = pattern.validate()
        self.key_field = key_field
        self.select = select or default_select
        self.mesh = None
        if backend == "device":
            # raises UnsupportedCepPattern for the ineligible class —
            # the caller's cue to fall back (loudly) to the host path
            self._layout = compile_device_pattern(self.pattern)
            if mesh is None:
                from flink_tpu.parallel.mesh import make_mesh

                mesh = make_mesh(num_shards)
            self.mesh = mesh
            self.P = int(mesh.devices.size)
        else:
            self._layout = None
            self.P = int(num_shards)
            self._op = CepOperator(self.pattern, key_field,
                                   select=select)
        self.capacity = max(int(capacity_per_shard), 256)
        self.match_capacity = max(int(match_capacity), 2)
        self.max_parallelism = int(max_parallelism)
        if self.max_parallelism < self.P:
            raise ValueError(
                f"max_parallelism {max_parallelism} < shard count "
                f"{self.P}")
        self.key_group_range = key_group_range
        self.spill_dir = spill_dir
        self.spill_host_max_bytes = int(spill_host_max_bytes or 0)
        self._last_wm: Optional[int] = None
        self._flight_batch = 0
        # counters (the cep metric group reads these)
        self.matches_emitted = 0
        self.partials_pruned_within = 0
        self.late_dropped = 0
        if backend == "device":
            self._init_device_state()

    # ----------------------------------------------------- device plumbing

    def _init_device_state(self) -> None:
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec
        from flink_tpu.parallel.mesh import KEY_AXIS
        from flink_tpu.parallel.shuffle import ShuffleBufferPool

        self._sharding = NamedSharding(self.mesh,
                                       PartitionSpec(KEY_AXIS))
        self._pool = ShuffleBufferPool(generations=2)
        self._fences: List = []
        R = self._layout.ring
        self._st = [
            _CepShard(self.capacity, R, self.match_capacity,
                      (f"{self.spill_dir.rstrip('/')}/shard-{p}"
                       if self.spill_dir else None),
                      self.spill_host_max_bytes // max(self.P, 1))
            for p in range(self.P)]
        self._planes = tuple(
            jax.device_put(
                jnp.zeros((self.P, self.capacity), dtype=jnp.int32),
                self._sharding)
            for _ in range(1 + R))
        self._pend_width = pad_bucket_size(1, minimum=1024)
        self._pend = tuple(
            jax.device_put(
                jnp.zeros((self.P, self._pend_width), dtype=jnp.int32),
                self._sharding)
            for _ in range(2))
        self._match_planes = tuple(
            jax.device_put(
                jnp.zeros((self.P, self.match_capacity),
                          dtype=jnp.int32),
                self._sharding)
            for _ in range(3))
        self._schema: Optional[List[Tuple[str, np.dtype]]] = None
        self._next_seq = 1
        self._next_rid = 1
        self._clock = 1
        self._key_order: Dict[int, int] = {}
        self._order_seq = 0
        self._key_values: Dict[int, Any] = {}
        # sticky compile-shape tiers
        self._lane_bucket = 0
        self._ev_bucket = 0
        self._gather_bucket = 0
        self._prune_bucket = 0
        self._put_bucket = 0
        self._match_put_bucket = 0
        # per-depth keep bits for the within prune, static per layout
        lay = self._layout
        self._depth_mask = [0] * (R + 2)
        for q, d in enumerate(lay.depth):
            self._depth_mask[d] |= (1 << q)
        # matched-pattern replica (armed lazily)
        self._match_replica = None
        self._rep_full = False
        self._rep_up: List[set] = [set() for _ in range(self.P)]
        self._rep_freed: List[list] = [[] for _ in range(self.P)]

    # ------------------------------------------------------------- watchdog

    _watchdog = None

    def attach_watchdog(self, wd) -> None:
        self._watchdog = wd
        if wd is not None and self.mesh is not None:
            wd.rebind(self.P, [d.id for d in self.mesh.devices.flat])
            wd.set_topology(None)

    def _wd_section(self, op: str, shard: int = -1):
        wd = self._watchdog
        if wd is None:
            from flink_tpu.runtime.watchdog import NULL_SECTION

            return NULL_SECTION
        return wd.section(op, shard)

    def _wd_boundary(self) -> None:
        wd = self._watchdog
        if wd is not None:
            wd.boundary_probe()

    def _harvest_get(self, tree, op: str = "cep_fire_harvest"):
        """ONE batched D2H per harvest point (the TRC01 discipline)."""
        from flink_tpu.observe import flight_recorder as flight

        with flight.span("fire.harvest"), self._wd_section(op):
            return jax.device_get(tree)

    def _flight_ingest(self):
        from flink_tpu.observe import flight_recorder as flight

        self._flight_batch += 1
        return flight.ingest_span(self._flight_batch)

    def _flight_fire(self, watermark: int):
        from flink_tpu.observe import flight_recorder as flight

        return flight.fire_span(watermark)

    def _drain_fences(self) -> None:
        if self.backend != "device":
            return
        while self._fences:
            # flint: disable=TRC01 -- the depth-bounded fence drain is
            # the ingest backpressure point: it blocks only when the
            # host ran a full staging generation ahead of the device
            self._fences.pop(0).block_until_ready()

    def _push_fence(self) -> None:
        with self._wd_section("dispatch_fence"):
            self._fences.append(_FENCE_STEP(self._pend[0]))
        if len(self._fences) > 1:
            with self._wd_section("fence_drain"):
                # flint: disable=TRC01 -- see _drain_fences: this is
                # the designed double-buffer backpressure point
                self._fences.pop(0).block_until_ready()

    # --------------------------------------------------------------- ingest

    def _bind_schema(self, batch: RecordBatch) -> None:
        names = list(batch.names())
        if self._schema is None:
            self._schema = [(n, np.asarray(batch[n]).dtype)
                            for n in names]
            for sh in self._st:
                sh.bind_schema(self._schema, self.capacity,
                               self._layout.ring)
            return
        declared = [n for n, _ in self._schema]
        if names != declared:
            raise RuntimeError(
                f"cep input changed columns mid-stream: "
                f"{declared} -> {names}")

    def register_metrics(self, group) -> None:
        g = group.add_group("cep")
        g.gauge("matches_emitted",
                lambda: int(self.matches_emitted))
        g.gauge("live_partials", self._live_partials)
        g.gauge("partials_pruned_within",
                lambda: int(self.partials_pruned_within))
        g.gauge("late_dropped", lambda: int(self.late_dropped))
        g.gauge("host_fallbacks", lambda: int(HOST_FALLBACKS))

    def _live_partials(self) -> int:
        if self.backend == "host":
            return sum(len(n.partials) for n in self._op._nfas.values())
        Q = self._layout.n_states
        total = 0
        for sh in self._st:
            if not sh.slot_of:
                continue
            slots = np.fromiter(sh.slot_of.values(), dtype=np.int64,
                                count=len(sh.slot_of))
            total += int(self._popcount(sh.alive[slots], Q).sum())
        return total

    @staticmethod
    def _popcount(x: np.ndarray, bits: int) -> np.ndarray:
        x = np.asarray(x)
        c = np.zeros(x.shape, dtype=np.int64)
        for q in range(bits):
            c += (x >> q) & 1
        return c

    def process_batch(self, batch: RecordBatch, input_index: int = 0
                      ) -> List[RecordBatch]:
        if len(batch) == 0:
            return []
        with self._flight_ingest():
            # late-drop policy (both backends, BEFORE stage evaluation):
            # events at-or-before the last fired watermark are dropped —
            # the oracle has already advanced past them
            if self._last_wm is not None:
                late = batch.timestamps <= self._last_wm
                if late.any():
                    self.late_dropped += int(late.sum())
                    batch = batch.filter(~late)
                    if len(batch) == 0:
                        return []
            if self.backend == "host":
                return self._op.process_batch(batch)
            self._ingest_device(batch)
        return []

    def _ingest_device(self, batch: RecordBatch) -> None:
        self._bind_schema(batch)
        n = len(batch)
        kids = np.asarray(batch.key_ids, dtype=np.int64)
        tss = np.asarray(batch.timestamps, dtype=np.int64)
        # stage predicates columnar over the whole batch, packed to one
        # int32 hit bitmask per event (eligibility caps stages at 31)
        hits = np.zeros(n, dtype=np.int32)
        for s, st in enumerate(self.pattern.stages):
            m = np.asarray(st.evaluate(batch), dtype=bool)
            hits |= np.where(m, np.int32(1 << s), np.int32(0))
        if self._next_seq + n >= (1 << 31):
            raise RuntimeError(
                "cep event sequence space exhausted (int32 ring)")
        seqs = np.arange(self._next_seq, self._next_seq + n,
                         dtype=np.int32)
        self._next_seq += n
        # the oracle's bookkeeping, mirrored exactly: first-seen key
        # value, pending-dict insertion order
        if self.key_field in batch.columns:
            col = batch[self.key_field]
            kv = self._key_values
            for i, k in enumerate(kids.tolist()):
                if k not in kv:
                    kv[k] = _item(col[i])
        ko = self._key_order
        for k in kids.tolist():
            if k not in ko:
                ko[k] = self._order_seq
                self._order_seq += 1
        from flink_tpu.parallel.shuffle import shard_records

        shards = shard_records(kids, self.P, self.max_parallelism,
                               self.key_group_range)
        counts = np.bincount(shards, minlength=self.P)
        # pending-plane headroom: compact consumed rows (and grow) when
        # any shard's cursor would run off the plane
        if any(self._st[p].cursor + int(counts[p]) > self._pend_width
               for p in range(self.P)):
            self._compact_pending(counts)
        # per-record device pending position: destination cursor + rank
        # within the batch's records for that destination
        order = np.argsort(shards, kind="stable")
        offsets = np.zeros(self.P + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        rank = np.empty(n, dtype=np.int64)
        rank[order] = np.arange(n, dtype=np.int64) \
            - offsets[shards[order]]
        cursors = np.fromiter((sh.cursor for sh in self._st),
                              dtype=np.int64, count=self.P)
        pos = (cursors[shards] + rank).astype(np.int32)
        for p in np.nonzero(counts)[0].tolist():
            sh = self._st[p]
            sel = shards == p
            sh.cursor += int(counts[p])
            sh.p_pos = np.concatenate([sh.p_pos, pos[sel]])
            sh.p_key = np.concatenate([sh.p_key, kids[sel]])
            sh.p_ts = np.concatenate([sh.p_ts, tss[sel]])
            sh.p_seq = np.concatenate([sh.p_seq, seqs[sel]])
            sh.p_hits = np.concatenate([sh.p_hits, hits[sel]])
            for name, _dt in self._schema:
                col = np.asarray(batch[name])
                sh.p_vals[name] = np.concatenate(
                    [sh.p_vals[name], col[sel]])
        # dispatch: the fused keyBy exchange (device shuffle) or the
        # host-bucketed scatter — hits/seq are the only device columns.
        # Payload chaos (drop/duplicate) fires inside the staging
        # helpers; a dropped lane's pending row keeps hits=0, so its
        # partials die on the device while the host oracle matches —
        # the designed DIVERGENT negative control.
        self._pool.flip()
        if self.shuffle_mode == "device":
            from flink_tpu.parallel.shuffle import stage_device_exchange

            dst, staged, width = stage_device_exchange(
                shards, self.P, columns=[pos, hits, seqs],
                fills=[0, 0, 0], pool=self._pool)
            prog = build_cep_exchange_put(self.mesh,
                                          ("int32", "int32"))
            with self._wd_section("cep_ingest"):
                put = jax.device_put((dst, *staged), self._sharding)
                self._pend = prog(self._pend, put[0], put[1],
                                  tuple(put[2:]), width)
        else:
            from flink_tpu.parallel.shuffle import bucket_by_shard

            _, blocked = bucket_by_shard(
                shards, self.P, columns=[pos, hits, seqs],
                fills=[0, 0, 0], pool=self._pool)
            prog = build_cep_put(self.mesh, ("int32", "int32"))
            with self._wd_section("cep_ingest"):
                put = jax.device_put(tuple(blocked), self._sharding)
                self._pend = prog(self._pend, put[0], tuple(put[1:]))
        # raise/delay at the post-dispatch site: a crash lands with the
        # pending scatter already on the device queue — the hardest
        # restore case (the mirror and the plane must re-converge from
        # the last checkpoint, not from each other)
        chaos.fault_point("cep.advance", records=int(n))
        self._push_fence()
        for sh in self._st:
            run_deferred_sweeps(sh.spill, sh.pmap)

    def _compact_pending(self, incoming_counts: np.ndarray) -> None:
        """Dense rebuild of the device pending planes: consumed rows
        (already fired) drop, survivors repack from position 1, the
        plane grows a pow2 tier if survivors + the incoming batch still
        do not fit. A host device_put, not a program — compaction is
        rare (amortized by the pow2 growth) and shape-tiered."""
        need = max(
            1 + len(self._st[p].p_pos) + int(incoming_counts[p])
            for p in range(self.P))
        width = pad_bucket_size(need, minimum=1024)
        width = max(width, 1024)
        h_hits = np.zeros((self.P, width), dtype=np.int32)
        h_seq = np.zeros((self.P, width), dtype=np.int32)
        for p, sh in enumerate(self._st):
            m = len(sh.p_pos)
            if m:
                npos = np.arange(1, m + 1, dtype=np.int32)
                h_hits[p, 1:m + 1] = sh.p_hits
                h_seq[p, 1:m + 1] = sh.p_seq
                sh.p_pos = npos
            sh.cursor = 1 + m
        self._drain_fences()
        self._pend_width = width
        self._pend = tuple(
            jax.device_put(a, self._sharding) for a in (h_hits, h_seq))

    # ----------------------------------------------------------------- fire

    def on_watermark(self, watermark: int, input_index: int = 0
                     ) -> List[RecordBatch]:
        watermark = int(watermark)
        self._wd_boundary()
        if self.backend == "host":
            out = self._op.process_watermark(watermark)
            self.matches_emitted += sum(len(b) for b in out)
            self._note_wm(watermark)
            return out
        with self._flight_fire(watermark):
            out = self._fire_device(watermark)
        self._note_wm(watermark)
        return out

    def _note_wm(self, watermark: int) -> None:
        self._last_wm = (watermark if self._last_wm is None
                         else max(self._last_wm, watermark))

    def _fire_device(self, wm: int) -> List[RecordBatch]:
        lay = self._layout
        R, Q = lay.ring, lay.n_states
        lanes: Dict[int, dict] = {}
        e_max = k_max = 0
        for p, sh in enumerate(self._st):
            if not len(sh.p_ts):
                continue
            due = sh.p_ts <= wm
            if not due.any():
                continue
            mrow = np.nonzero(due)[0]
            d_key = sh.p_key[mrow]
            d_ts = sh.p_ts[mrow]
            ukeys, inv = np.unique(d_key, return_inverse=True)
            # the oracle's per-key order: due events sorted stably by
            # timestamp, ties in arrival (mirror append) order
            ev = np.lexsort((d_ts, inv))
            cnts = np.bincount(inv, minlength=len(ukeys))
            lanes[p] = {"keys": ukeys, "inv": inv, "ev": ev,
                        "cnts": cnts, "mrow": mrow, "due": due}
            e_max = max(e_max, int(cnts.max()))
            k_max = max(k_max, len(ukeys))
        out_rows: List[dict] = []
        out_ts: List[int] = []
        freed_keys: List[Tuple[int, int]] = []
        if lanes:
            self._resolve_slots(lanes)
            K = sticky_bucket(k_max, self._lane_bucket, minimum=64)
            self._lane_bucket = K
            E = sticky_bucket(e_max, self._ev_bucket, minimum=16)
            self._ev_bucket = E
            slots_b = np.zeros((self.P, K), dtype=np.int32)
            nev_b = np.zeros((self.P, K), dtype=np.int32)
            idx_b = np.zeros((self.P, K, E), dtype=np.int32)
            wok_b = np.zeros((self.P, K, E), dtype=np.int32)
            for p, d in lanes.items():
                sh = self._st[p]
                L = len(d["keys"])
                ev, inv, cnts, mrow = (d["ev"], d["inv"], d["cnts"],
                                       d["mrow"])
                starts = np.concatenate(
                    ([0], np.cumsum(cnts)[:-1])).astype(np.int64)
                flat_lane = inv[ev]
                col = np.arange(len(ev), dtype=np.int64) \
                    - starts[flat_lane]
                mrow_m = np.zeros((L, E), dtype=np.int64)
                mrow_m[flat_lane, col] = mrow[ev]
                due_ts_m = np.zeros((L, E), dtype=np.int64)
                due_ts_m[flat_lane, col] = sh.p_ts[mrow][ev]
                due_seq_m = np.zeros((L, E), dtype=np.int32)
                due_seq_m[flat_lane, col] = sh.p_seq[mrow][ev]
                due_pos_m = np.zeros((L, E), dtype=np.int32)
                due_pos_m[flat_lane, col] = sh.p_pos[mrow][ev]
                lane_slots = d["slots"]
                c_ts = np.concatenate(
                    [sh.ts_hist[lane_slots], due_ts_m], axis=1)
                c_seq = np.concatenate(
                    [sh.ring_seq[lane_slots],
                     due_seq_m.astype(np.int32)], axis=1)
                d.update(mrow_m=mrow_m, due_ts_m=due_ts_m,
                         due_seq_m=due_seq_m, c_ts=c_ts, c_seq=c_seq)
                slots_b[p, :L] = lane_slots
                nev_b[p, :L] = cnts
                idx_b[p, :L] = due_pos_m
                if lay.has_within:
                    within = int(self.pattern.within_ms)
                    wok = np.zeros((L, E), dtype=np.int32)
                    for dd in range(1, R + 1):
                        # rearranged (first_ts >= ts - within) so the
                        # _NEG history fill can't overflow int64
                        ok = c_ts[:, R - dd:R - dd + E] \
                            >= (due_ts_m - within)
                        wok |= np.where(ok, np.int32(1 << (dd - 1)),
                                        np.int32(0))
                    wok_b[p, :L] = wok
            prog = build_cep_advance(self.mesh, lay)
            with self._wd_section("cep_advance"):
                put = jax.device_put((slots_b, idx_b, wok_b, nev_b),
                                     self._sharding)
                self._planes, matches_d, alive_d = prog(
                    self._planes, self._pend, put[0], put[1], put[2],
                    put[3])
            host_m, host_alive = self._harvest_get(
                (matches_d, alive_d))
            # decode in the oracle's GLOBAL emission order: keys in
            # pending-dict insertion order, each key's due matches
            # grouped, event order within key
            korder = sorted(
                (self._key_order[int(k)], p, l, int(k))
                for p, d in lanes.items()
                for l, k in enumerate(d["keys"].tolist()))
            store_rows: Dict[int, Dict[int, Tuple[int, int, int]]] = {}
            for _, p, l, k in korder:
                d = lanes[p]
                self._decode_lane(p, l, k, d, host_m[p], out_rows,
                                  out_ts, store_rows, wm)
            if store_rows:
                self._put_matches(store_rows)
            # mirror roll-forward: the new ring is the last R of
            # (old ring ++ due events) — positions nev..nev+R-1 of the
            # concat, all real by construction
            for p, d in lanes.items():
                sh = self._st[p]
                lane_slots = d["slots"]
                L = len(lane_slots)
                a_new = host_alive[p][:L].astype(np.int32)
                sh.alive[lane_slots] = a_new
                if R:
                    take = (d["cnts"][:, None]
                            + np.arange(R, dtype=np.int64)[None, :])
                    sh.ring_seq[lane_slots] = np.take_along_axis(
                        d["c_seq"], take, axis=1)
                    sh.ts_hist[lane_slots] = np.take_along_axis(
                        d["c_ts"], take, axis=1)
                    for name, _dt in self._schema:
                        due_val = sh.p_vals[name][d["mrow_m"]]
                        c_val = np.concatenate(
                            [sh.ring_vals[name][lane_slots], due_val],
                            axis=1)
                        sh.ring_vals[name][lane_slots] = \
                            np.take_along_axis(c_val, take, axis=1)
                dead = lane_slots[a_new == 0]
                for s in dead.tolist():
                    k = int(sh.key_of[s])
                    del sh.slot_of[k]
                    sh.free.append(s)
                    freed_keys.append((k, p))
        chaos.fault_point("cep.match_fire", matches=len(out_rows))
        if lay.has_within:
            freed_keys.extend(self._prune_resident(wm, Q))
        # consume the fired pending rows; keys whose buffer emptied
        # leave the insertion-order dict (re-appearing keys re-enter
        # at the END, as the oracle's dict does)
        emptied: List[Tuple[int, int]] = []
        for p, d in lanes.items():
            sh = self._st[p]
            keep = ~d["due"]
            sh.p_pos = sh.p_pos[keep]
            sh.p_key = sh.p_key[keep]
            sh.p_ts = sh.p_ts[keep]
            sh.p_seq = sh.p_seq[keep]
            sh.p_hits = sh.p_hits[keep]
            for name, _dt in self._schema:
                sh.p_vals[name] = sh.p_vals[name][keep]
            still = np.isin(d["keys"], sh.p_key)
            for k in d["keys"][~still].tolist():
                self._key_order.pop(int(k), None)
                emptied.append((int(k), p))
        for k, p in freed_keys + emptied:
            if k in self._key_order or k in self._st[p].slot_of:
                continue
            self._key_values.pop(k, None)
        self.matches_emitted += len(out_rows)
        if self._match_replica is not None:
            self._publish_matches(wm)
        if not out_rows:
            return []
        out = RecordBatch.from_rows(out_rows).with_timestamps(out_ts)
        return [out]

    # ------------------------------------------------- fire: slot residency

    def _resolve_slots(self, lanes: Dict[int, dict]) -> None:
        """Give every due key a device slot: reuse resident ones, evict
        the coldest non-due residents when headroom runs out (one
        cohort gather + one page per shard), reload spilled keys (lazy
        within-prune applied), zero-init brand-new ones — reloads and
        news share ONE put program."""
        R = self._layout.ring
        evict: Dict[int, np.ndarray] = {}
        for p, d in lanes.items():
            sh = self._st[p]
            have = np.fromiter(
                (sh.slot_of.get(int(k), -1) for k in d["keys"]),
                dtype=np.int64, count=len(d["keys"]))
            missing = d["keys"][have < 0]
            need = len(missing) - len(sh.free)
            if need > 0:
                res_keys = np.fromiter(sh.slot_of.keys(),
                                       dtype=np.int64,
                                       count=len(sh.slot_of))
                res_slots = np.fromiter(sh.slot_of.values(),
                                        dtype=np.int64,
                                        count=len(sh.slot_of))
                cand = ~np.isin(res_keys, d["keys"])
                if int(cand.sum()) < need:
                    raise RuntimeError(
                        f"cep shard {p}: {len(d['keys'])} due keys "
                        f"exceed capacity {self.capacity}")
                ck, cs = res_keys[cand], res_slots[cand]
                cold = np.lexsort((cs, sh.touch[cs]))[:need]
                evict[p] = cs[cold]
            d["have"] = have
            d["missing"] = missing
        if evict:
            self._evict_cohorts(evict)
        put_rows: Dict[int, list] = {}
        for p, d in lanes.items():
            sh = self._st[p]
            have, missing = d["have"], d["missing"]
            reloaded: Dict[int, Tuple] = {}
            if len(missing):
                leaf_dtypes = ([np.int32, np.int32, np.int64]
                               + [dt for _n, dt in (self._schema or [])])
                r = reload_rows_for(sh.spill, sh.pmap,
                                    missing, leaf_dtypes)
                if r is not None:
                    r_keys, _rns, _dirty, vals = r
                    alive_r = np.asarray(vals[0], dtype=np.int32)
                    # lazy within-expiry: exact, because the spilled
                    # key saw no events since it spilled and the
                    # keep-test is monotone in the watermark
                    if (self._layout.has_within
                            and self._last_wm is not None
                            and len(alive_r)):
                        keep = self._keep_bits(
                            np.asarray(vals[2]), self._last_wm)
                        na = alive_r & keep
                        self.partials_pruned_within += int(
                            (self._popcount(alive_r,
                                            self._layout.n_states)
                             - self._popcount(
                                 na, self._layout.n_states)).sum())
                        alive_r = na
                    for j, rk in enumerate(r_keys.tolist()):
                        reloaded[int(rk)] = (
                            alive_r[j],
                            np.asarray(vals[1])[j],
                            np.asarray(vals[2])[j],
                            [np.asarray(v)[j] for v in vals[3:]])
            rows = put_rows.setdefault(p, [])
            for j, k in enumerate(d["keys"].tolist()):
                k = int(k)
                if d["have"][j] >= 0:
                    continue
                s = sh.free.pop()
                sh.slot_of[k] = s
                sh.key_of[s] = k
                got = reloaded.get(k)
                if got is not None:
                    alive_v, ring_v, ts_v, col_v = got
                    sh.alive[s] = alive_v
                    if R:
                        sh.ring_seq[s] = ring_v
                        sh.ts_hist[s] = ts_v
                        for (name, _dt), cv in zip(self._schema,
                                                   col_v):
                            sh.ring_vals[name][s] = cv
                else:
                    sh.alive[s] = 0
                    if R:
                        sh.ring_seq[s] = 0
                        sh.ts_hist[s] = _NEG
                        for name, _dt in self._schema:
                            sh.ring_vals[name][s] = \
                                np.zeros(R, dtype=_dt_of(
                                    self._schema, name))
                d["have"][j] = s
                rows.append((s, int(sh.alive[s]),
                             sh.ring_seq[s].copy() if R else None))
            d["slots"] = d["have"].astype(np.int64)
            sh.touch[d["slots"]] = self._clock
            self._clock += 1
        rows_max = max((len(r) for r in put_rows.values()), default=0)
        if rows_max:
            B = sticky_bucket(rows_max, self._put_bucket)
            self._put_bucket = B
            slot_b = np.zeros((self.P, B), dtype=np.int32)
            alive_b = np.zeros((self.P, B), dtype=np.int32)
            ring_bs = [np.zeros((self.P, B), dtype=np.int32)
                       for _ in range(R)]
            for p, rows in put_rows.items():
                for j, (s, av, rv) in enumerate(rows):
                    slot_b[p, j] = s
                    alive_b[p, j] = av
                    for r in range(R):
                        ring_bs[r][p, j] = rv[r]
            prog = build_cep_put(self.mesh, ("int32",) * (1 + R))
            with self._wd_section("cep_restore_put"):
                put = jax.device_put((slot_b, alive_b, *ring_bs),
                                     self._sharding)
                self._planes = prog(self._planes, put[0],
                                    tuple(put[1:]))

    def _evict_cohorts(self, evict: Dict[int, np.ndarray]) -> None:
        """Spill the chosen cold residents: ONE gather program + ONE
        harvest for every shard's cohort, then one page per shard."""
        R = self._layout.ring
        g_max = max(len(s) for s in evict.values())
        G = sticky_bucket(g_max, self._gather_bucket)
        self._gather_bucket = G
        block = np.zeros((self.P, G), dtype=np.int32)
        for p, slots in evict.items():
            block[p, :len(slots)] = slots
        prog = build_cep_gather(self.mesh, ("int32",) * (1 + R))
        with self._wd_section("evict_gather"):
            put = jax.device_put(block, self._sharding)
            gathered = prog(self._planes, put)
        host = self._harvest_get(gathered, "evict_harvest")
        for p, slots in evict.items():
            sh = self._st[p]
            m = len(slots)
            ring_rows = (np.stack([host[1 + r][p, :m]
                                   for r in range(R)], axis=1)
                         if R else np.zeros((m, 0), dtype=np.int32))
            keys = sh.key_of[slots]
            entry = {"key_id": keys.copy(), "ns": keys.copy(),
                     "dirty": np.ones(m, dtype=bool),
                     "leaf_0": host[0][p, :m].astype(np.int32),
                     "leaf_1": ring_rows,
                     "leaf_2": sh.ts_hist[slots].copy()}
            for i, (name, _dt) in enumerate(self._schema or []):
                entry[f"leaf_{3 + i}"] = sh.ring_vals[name][slots].copy()
            spill_page(sh.spill, sh.pmap, entry)
            for s in slots.tolist():
                del sh.slot_of[int(sh.key_of[s])]
                sh.free.append(int(s))

    # ------------------------------------------------------ fire: decoding

    def _decode_lane(self, p: int, l: int, k: int, d: dict,
                     m_shard: np.ndarray, out_rows: list,
                     out_ts: list, store_rows: dict, wm: int) -> None:
        lay = self._layout
        R, Q = lay.ring, lay.n_states
        sh = self._st[p]
        n_ev = int(d["cnts"][l])
        mrow = d["mrow_m"][l]
        slot = int(d["slots"][l])
        names = [n for n, _ in self._schema]
        stages = self.pattern.stages
        for j in range(n_ev):
            m = int(m_shard[l, j])
            if not m:
                continue
            if lay.skip_past:
                bits = [(m & -m).bit_length() - 1]
            else:
                bits = [b for b in range(Q + 1) if (m >> b) & 1]
            for b in bits:
                counts_vec = lay.match_counts(b)
                depth = sum(counts_vec)
                start = R + j - depth + 1
                start_ts = int(d["c_ts"][l, start])
                end_ts = int(d["due_ts_m"][l, j])
                ev_rows = []
                for pos in range(start, R + j + 1):
                    if pos >= R:
                        mi = int(mrow[pos - R])
                        ev_rows.append(
                            {n: _item(sh.p_vals[n][mi])
                             for n in names})
                    else:
                        ev_rows.append(
                            {n: _item(sh.ring_vals[n][slot, pos])
                             for n in names})
                events: Dict[str, list] = {}
                by_stage: Dict[str, list] = {}
                at = 0
                for si, st in enumerate(stages):
                    c = counts_vec[si] if si < len(counts_vec) else 0
                    events[st.name] = ev_rows[at:at + c]
                    by_stage[st.name] = list(range(at, at + c))
                    at += c
                match = Match(start_ts=start_ts, end_ts=end_ts,
                              events_by_stage=by_stage)
                out_rows.append(self.select(
                    self._key_values.get(k, k), match, events))
                out_ts.append(end_ts)
                self._store_match(p, k, start_ts, end_ts, depth,
                                  int(d["c_seq"][l, start]),
                                  int(d["due_seq_m"][l, j]),
                                  store_rows)

    # ------------------------------------------------- matched-pattern store

    def _store_match(self, p: int, key: int, start_ts: int,
                     end_ts: int, depth: int, fseq: int, lseq: int,
                     store_rows: dict) -> None:
        sh = self._st[p]
        M = self.match_capacity
        slot = 1 + (sh.m_count % (M - 1))
        sh.m_count += 1
        if sh.m_used[slot] and self._match_replica is not None:
            self._rep_freed[p].append(
                (int(sh.m_key[slot]), int(sh.m_rid[slot])))
        rid = self._next_rid
        self._next_rid += 1
        sh.m_used[slot] = True
        sh.m_key[slot] = key
        sh.m_rid[slot] = rid
        sh.m_start[slot] = start_ts
        sh.m_end[slot] = end_ts
        sh.m_depth[slot] = depth
        sh.m_fseq[slot] = fseq
        sh.m_lseq[slot] = lseq
        # last write per slot wins in the device block too (a FIFO can
        # wrap within one fire; a duplicate scatter index would be
        # order-undefined on the device)
        store_rows.setdefault(p, {})[slot] = (depth, fseq, lseq)
        if self._match_replica is not None:
            self._rep_up[p].add(slot)

    def _put_matches(self, store_rows: Dict[int, dict]) -> None:
        B = sticky_bucket(max(len(r) for r in store_rows.values()),
                          self._match_put_bucket)
        self._match_put_bucket = B
        slot_b = np.zeros((self.P, B), dtype=np.int32)
        val_bs = [np.zeros((self.P, B), dtype=np.int32)
                  for _ in range(3)]
        for p, rows in store_rows.items():
            for j, (s, vals) in enumerate(sorted(rows.items())):
                slot_b[p, j] = s
                for i in range(3):
                    val_bs[i][p, j] = vals[i]
        prog = build_cep_put(self.mesh, ("int32",) * 3)
        with self._wd_section("match_put"):
            put = jax.device_put((slot_b, *val_bs), self._sharding)
            self._match_planes = prog(self._match_planes, put[0],
                                      tuple(put[1:]))

    def arm_match_replica(self, serving: bool = False):
        """Arm the matched-pattern read replica: completed matches
        become queryable state on the serving path — the replica plane
        double-buffers the match planes and seals a generation per
        boundary publish. Returns a :class:`CepMatchReplicaAdapter`
        (bindable to a ServingPlane like any other adapter), or — with
        ``serving=True`` — a :class:`CepMatchServingAdapter`, whose
        composed results pack into the native shm hot cache so frontend
        processes serve match lookups without crossing to the owner."""
        if self.backend != "device":
            raise RuntimeError(
                "the matched-pattern replica rides the device match "
                "planes; the host oracle serves reads directly")
        from flink_tpu.tenancy.replica import ReplicaPlane

        class _Leaf:
            def __init__(self, dtype):
                self.dtype = dtype
                self.identity = np.dtype(dtype).type(0)

        plane = ReplicaPlane(self.mesh, [_Leaf(np.int32)] * 3,
                             self.match_capacity)
        plane.warm_tiers()
        self._match_replica = plane
        self._rep_full = True
        self._rep_up = [set() for _ in range(self.P)]
        self._rep_freed = [[] for _ in range(self.P)]
        cls = CepMatchServingAdapter if serving else \
            CepMatchReplicaAdapter
        return cls(plane)

    def _publish_matches(self, watermark: int) -> None:
        from flink_tpu.observe import flight_recorder as flight

        rep = self._match_replica
        with flight.span("serving.replica_publish",
                         watermark=int(watermark)):
            if rep.needs_rebuild(self.P, self.match_capacity):
                rep.rebuild(self.mesh, self.match_capacity)
                rep.warm_tiers()
                self._rep_full = True
            per_shard = {}
            for p, sh in enumerate(self._st):
                if self._rep_full:
                    up = np.nonzero(sh.m_used)[0].astype(np.int32)
                else:
                    up = np.asarray(sorted(self._rep_up[p]),
                                    dtype=np.int32)
                extra = ([(int(sh.m_start[s]), int(sh.m_end[s]))
                          for s in up.tolist()]
                         if len(up) else None)
                freed = list(self._rep_freed[p])
                per_shard[p] = {
                    "up_slots": up,
                    "up_keys": sh.m_key[up].copy(),
                    "up_ns": sh.m_rid[up].copy(),
                    "up_extra": extra,
                    "cold": [],
                    "freed": freed,
                    "fresh": bool(len(up) or freed),
                }
            rep.publish(self._match_planes, per_shard, int(watermark))
            self._rep_full = False
            self._rep_up = [set() for _ in range(self.P)]
            self._rep_freed = [[] for _ in range(self.P)]

    def query_match_batch(self, key_ids) -> List[List[dict]]:
        """LIVE point lookup against the match store: per requested
        key, its retained matches as ``[{"rid", "start_ts", "end_ts",
        "depth", "first_seq", "last_seq"}, ...]`` sorted by
        (end_ts, rid) — device columns through ONE gather + ONE read.
        The replica adapter composes the same shape at a sealed
        boundary; the parity test pins them identical."""
        key_ids = np.asarray(key_ids, dtype=np.int64)
        n = len(key_ids)
        results: List[List[dict]] = [[] for _ in range(n)]
        want: Dict[int, List[Tuple[int, int]]] = {}
        rows: List[Tuple[int, int]] = []
        per_shard: Dict[int, List[int]] = {}
        for p, sh in enumerate(self._st):
            if not sh.m_used.any():
                continue
            hit = sh.m_used & np.isin(sh.m_key, key_ids)
            for s in np.nonzero(hit)[0].tolist():
                per_shard.setdefault(p, []).append(s)
                want.setdefault(int(sh.m_key[s]), []).append(
                    (len(rows), s))
                rows.append((p, s))
        if not rows:
            return results
        G = sticky_bucket(max(len(v) for v in per_shard.values()),
                          self._match_put_bucket)
        self._match_put_bucket = G
        block = np.zeros((self.P, G), dtype=np.int32)
        at: Dict[Tuple[int, int], int] = {}
        for p, slots in per_shard.items():
            for j, s in enumerate(slots):
                block[p, j] = s
                at[(p, s)] = j
        prog = build_cep_gather(self.mesh, ("int32",) * 3)
        put = jax.device_put(block, self._sharding)
        vals = self._harvest_get(prog(self._match_planes, put),
                                 "match_query_harvest")
        for qi, kid in enumerate(key_ids.tolist()):
            got = []
            for ri, s in want.get(int(kid), ()):
                p, _s = rows[ri]
                sh = self._st[p]
                j = at[(p, s)]
                got.append({
                    "rid": int(sh.m_rid[s]),
                    "start_ts": int(sh.m_start[s]),
                    "end_ts": int(sh.m_end[s]),
                    "depth": int(vals[0][p, j]),
                    "first_seq": int(vals[1][p, j]),
                    "last_seq": int(vals[2][p, j]),
                })
            got.sort(key=lambda r: (r["end_ts"], r["rid"]))
            results[qi] = got
        return results

    # ------------------------------------------------------ fire: pruning

    def _keep_bits(self, ts_hist: np.ndarray, wm: int) -> np.ndarray:
        """Per-row keep bitmask for the within expiry: a partial of
        depth ``d`` (first event = ring position R-d) survives iff the
        watermark is still inside its window."""
        R = self._layout.ring
        within = int(self.pattern.within_ms)
        keep = np.zeros(len(ts_hist), dtype=np.int32)
        for d in range(1, R + 1):
            # rearranged (first_ts >= wm - within): MAX_WATERMARK minus
            # the _NEG history fill would overflow int64
            ok = ts_hist[:, R - d] >= (wm - within)
            keep |= np.where(ok, np.int32(self._depth_mask[d]),
                             np.int32(0))
        return keep

    def _prune_resident(self, wm: int, Q: int
                        ) -> List[Tuple[int, int]]:
        """The oracle prunes EVERY key at every watermark: expire
        within-window partials across all resident slots — host keep
        bits, one device scatter — and free slots that emptied.
        Spilled keys prune lazily at reload (exact — see module
        docstring)."""
        prune: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        freed: List[Tuple[int, int]] = []
        for p, sh in enumerate(self._st):
            if not sh.slot_of:
                continue
            slots = np.fromiter(sh.slot_of.values(), dtype=np.int64,
                                count=len(sh.slot_of))
            slots.sort()
            keep = self._keep_bits(sh.ts_hist[slots], wm)
            na = sh.alive[slots] & keep
            self.partials_pruned_within += int(
                (self._popcount(sh.alive[slots], Q)
                 - self._popcount(na, Q)).sum())
            sh.alive[slots] = na
            prune[p] = (slots, keep)
            for s in slots[na == 0].tolist():
                k = int(sh.key_of[s])
                del sh.slot_of[k]
                sh.free.append(int(s))
                freed.append((k, p))
        if prune:
            G = sticky_bucket(max(len(s) for s, _ in prune.values()),
                              self._prune_bucket)
            self._prune_bucket = G
            slot_b = np.zeros((self.P, G), dtype=np.int32)
            keep_b = np.full((self.P, G), -1, dtype=np.int32)
            for p, (slots, keep) in prune.items():
                slot_b[p, :len(slots)] = slots
                keep_b[p, :len(keep)] = keep
            prog = build_cep_prune(self.mesh)
            with self._wd_section("cep_prune"):
                put = jax.device_put((slot_b, keep_b), self._sharding)
                self._planes = (prog(self._planes[0], put[0], put[1]),
                                *self._planes[1:])
        return freed

    # ------------------------------------------------------------ snapshots

    def snapshot(self, mode: str = "full") -> Dict[str, object]:
        if self.backend == "host":
            return {
                "kind": "cep", "mode": "host",
                "op": self._op.snapshot_state(),
                "last_wm": self._last_wm,
                "counters": self._counters(),
            }
        self._drain_fences()
        R = self._layout.ring
        host = self._harvest_get(list(self._planes),
                                 "snapshot_harvest")
        schema = self._schema or []
        st_cols: Dict[str, list] = {
            "key_id": [], "alive": [], "ring_seq": [], "ts_hist": []}
        for i in range(len(schema)):
            st_cols[f"leaf_{i}"] = []
        for p, sh in enumerate(self._st):
            slots = np.fromiter(sh.slot_of.values(), dtype=np.int64,
                                count=len(sh.slot_of))
            slots.sort()
            if len(slots):
                st_cols["key_id"].append(sh.key_of[slots].copy())
                st_cols["alive"].append(
                    host[0][p][slots].astype(np.int32))
                st_cols["ring_seq"].append(
                    np.stack([host[1 + r][p][slots]
                              for r in range(R)], axis=1)
                    if R else np.zeros((len(slots), 0),
                                       dtype=np.int32))
                st_cols["ts_hist"].append(sh.ts_hist[slots].copy())
                for i, (name, _dt) in enumerate(schema):
                    st_cols[f"leaf_{i}"].append(
                        sh.ring_vals[name][slots].copy())
            # spilled cohorts, by live page rows
            for page in sorted(set(sh.pmap.sp_page[
                    ~sh.pmap.sp_dead].tolist())):
                entry = sh.spill.peek(int(page))
                if entry is None:
                    continue
                rns = np.asarray(entry["ns"], dtype=np.int64)
                live = sh.pmap.live_row_mask(int(page), rns)
                if not live.any():
                    continue
                st_cols["key_id"].append(
                    np.asarray(entry["key_id"],
                               dtype=np.int64)[live])
                st_cols["alive"].append(
                    np.asarray(entry["leaf_0"],
                               dtype=np.int32)[live])
                st_cols["ring_seq"].append(
                    np.asarray(entry["leaf_1"],
                               dtype=np.int32)[live])
                st_cols["ts_hist"].append(
                    np.asarray(entry["leaf_2"],
                               dtype=np.int64)[live])
                for i, (name, dt) in enumerate(schema):
                    st_cols[f"leaf_{i}"].append(
                        np.asarray(entry[f"leaf_{3 + i}"],
                                   dtype=dt)[live])
        state = {k: (np.concatenate(v) if v else np.zeros(
            (0, R) if k in ("ring_seq", "ts_hist") else 0,
            dtype=np.int64))
            for k, v in st_cols.items()}
        state["key_group"] = assign_key_groups(
            np.asarray(state["key_id"], dtype=np.int64),
            self.max_parallelism)
        # pending, ordered by global sequence (= arrival order)
        pend = {"key_id": [], "ts": [], "seq": [], "hits": []}
        for i in range(len(schema)):
            pend[f"leaf_{i}"] = []
        for sh in self._st:
            pend["key_id"].append(sh.p_key)
            pend["ts"].append(sh.p_ts)
            pend["seq"].append(sh.p_seq)
            pend["hits"].append(sh.p_hits)
            for i, (name, _dt) in enumerate(schema):
                pend[f"leaf_{i}"].append(
                    sh.p_vals[name] if sh.p_vals is not None
                    else np.zeros(0))
        pending = {k: (np.concatenate(v) if v else np.zeros(0))
                   for k, v in pend.items()}
        if len(pending["seq"]):
            o = np.argsort(pending["seq"], kind="stable")
            pending = {k: v[o] for k, v in pending.items()}
        pending["key_group"] = assign_key_groups(
            np.asarray(pending["key_id"], dtype=np.int64),
            self.max_parallelism)
        # matches, ordered by rid (= creation order; FIFO age)
        mt = {k: [] for k in ("key_id", "rid", "start_ts", "end_ts",
                              "depth", "first_seq", "last_seq")}
        for sh in self._st:
            used = np.nonzero(sh.m_used)[0]
            mt["key_id"].append(sh.m_key[used])
            mt["rid"].append(sh.m_rid[used])
            mt["start_ts"].append(sh.m_start[used])
            mt["end_ts"].append(sh.m_end[used])
            mt["depth"].append(sh.m_depth[used])
            mt["first_seq"].append(sh.m_fseq[used])
            mt["last_seq"].append(sh.m_lseq[used])
        matches = {k: np.concatenate(v) for k, v in mt.items()}
        if len(matches["rid"]):
            o = np.argsort(matches["rid"], kind="stable")
            matches = {k: v[o] for k, v in matches.items()}
        matches["key_group"] = assign_key_groups(
            np.asarray(matches["key_id"], dtype=np.int64),
            self.max_parallelism)
        ko_keys = np.fromiter(self._key_order.keys(), dtype=np.int64,
                              count=len(self._key_order))
        ko_vals = np.fromiter(self._key_order.values(),
                              dtype=np.int64,
                              count=len(self._key_order))
        return {
            "kind": "cep", "mode": "device",
            "layout_key": self._layout.key,
            "schema": [(n, dt.str) for n, dt in schema],
            "last_wm": self._last_wm,
            "next_seq": int(self._next_seq),
            "next_rid": int(self._next_rid),
            "order_seq": int(self._order_seq),
            "clock": int(self._clock),
            "key_order": {"key": ko_keys, "order": ko_vals},
            "key_values": dict(self._key_values),
            "counters": self._counters(),
            "spill": self.spill_counters(),
            "state": state,
            "pending": pending,
            "matches": matches,
        }

    def _counters(self) -> Dict[str, int]:
        return {"matches_emitted": int(self.matches_emitted),
                "partials_pruned_within":
                    int(self.partials_pruned_within),
                "late_dropped": int(self.late_dropped)}

    def restore(self, snap: Dict[str, object],
                key_group_filter=None) -> None:
        if snap.get("mode", "device") != self.backend:
            raise RuntimeError(
                f"cep snapshot mode {snap.get('mode')!r} != engine "
                f"backend {self.backend!r}")
        self._last_wm = snap.get("last_wm")
        c = snap.get("counters") or {}
        self.matches_emitted = int(c.get("matches_emitted", 0))
        self.partials_pruned_within = int(
            c.get("partials_pruned_within", 0))
        self.late_dropped = int(c.get("late_dropped", 0))
        if self.backend == "host":
            self._op.restore_state(snap.get("op") or {})
            return
        if _norm(snap.get("layout_key")) != _norm(self._layout.key):
            raise RuntimeError(
                "cep snapshot was taken under a different compiled "
                "pattern layout — restore into a matching engine")
        R = self._layout.ring
        self._fences = []
        schema = [(n, np.dtype(d)) for n, d in snap.get("schema", [])]
        self._schema = schema or None
        self._st = [
            _CepShard(self.capacity, R, self.match_capacity,
                      (f"{self.spill_dir.rstrip('/')}/shard-{p}"
                       if self.spill_dir else None),
                      self.spill_host_max_bytes // max(self.P, 1))
            for p in range(self.P)]
        if self._schema:
            for sh in self._st:
                sh.bind_schema(self._schema, self.capacity, R)
        import jax.numpy as jnp

        self._next_seq = max(int(snap.get("next_seq", 1)), 1)
        self._next_rid = max(int(snap.get("next_rid", 1)), 1)
        self._order_seq = int(snap.get("order_seq", 0))
        self._clock = max(int(snap.get("clock", 1)), 1)

        def _filtered(table: Dict[str, np.ndarray]
                      ) -> Dict[str, np.ndarray]:
            table = {k: np.asarray(v) for k, v in table.items()}
            if key_group_filter is None or not len(table["key_id"]):
                return table
            kg = np.asarray(table["key_group"], dtype=np.int64)
            keep = np.isin(kg, np.asarray(sorted(
                int(g) for g in key_group_filter)))
            return {k: v[keep] for k, v in table.items()}

        def _key_in_filter(keys: np.ndarray) -> np.ndarray:
            if key_group_filter is None:
                return np.ones(len(keys), dtype=bool)
            kg = assign_key_groups(keys, self.max_parallelism)
            return np.isin(kg, np.asarray(sorted(
                int(g) for g in key_group_filter)))

        from flink_tpu.parallel.shuffle import shard_records

        # ---- NFA state rows: newest-touch-agnostic, snapshot order;
        # the first capacity-1 rows per shard stay resident, the rest
        # re-home as page cohorts
        state = _filtered(snap.get("state") or {"key_id": np.zeros(0)})
        keys = np.asarray(state.get("key_id", ()), dtype=np.int64)
        put_rows: Dict[int, list] = {}
        if len(keys):
            shards = shard_records(keys, self.P, self.max_parallelism,
                                   self.key_group_range)
            alive = np.asarray(state["alive"], dtype=np.int32)
            ring_seq = np.asarray(state["ring_seq"], dtype=np.int32)
            ts_hist = np.asarray(state["ts_hist"], dtype=np.int64)
            leaves = [np.asarray(state[f"leaf_{i}"], dtype=dt)
                      for i, (_n, dt) in enumerate(schema)]
            for p in range(self.P):
                sel = np.nonzero(shards == p)[0]
                if not len(sel):
                    continue
                sh = self._st[p]
                n_res = min(len(sel), self.capacity - 1)
                res, cold = sel[:n_res], sel[n_res:]
                rows = put_rows.setdefault(p, [])
                for i in res.tolist():
                    s = sh.free.pop()
                    k = int(keys[i])
                    sh.slot_of[k] = s
                    sh.key_of[s] = k
                    sh.alive[s] = alive[i]
                    if R:
                        sh.ring_seq[s] = ring_seq[i]
                        sh.ts_hist[s] = ts_hist[i]
                        for (name, _dt), lv in zip(schema, leaves):
                            sh.ring_vals[name][s] = lv[i]
                    rows.append((s, int(alive[i]),
                                 ring_seq[i] if R else None))
                if len(cold):
                    restore_into_pages(
                        sh.spill, sh.pmap, keys[cold], keys[cold],
                        [alive[cold], ring_seq[cold], ts_hist[cold]]
                        + [lv[cold] for lv in leaves],
                        page_rows=max(self.capacity // 8, 256))
        self._planes = tuple(
            jax.device_put(
                jnp.zeros((self.P, self.capacity), dtype=jnp.int32),
                self._sharding)
            for _ in range(1 + R))
        if put_rows:
            B = sticky_bucket(max(len(r) for r in put_rows.values()),
                              self._put_bucket)
            self._put_bucket = B
            slot_b = np.zeros((self.P, B), dtype=np.int32)
            alive_b = np.zeros((self.P, B), dtype=np.int32)
            ring_bs = [np.zeros((self.P, B), dtype=np.int32)
                       for _ in range(R)]
            for p, rows in put_rows.items():
                for j, (s, av, rv) in enumerate(rows):
                    slot_b[p, j] = s
                    alive_b[p, j] = av
                    for r in range(R):
                        ring_bs[r][p, j] = rv[r]
            prog = build_cep_put(self.mesh, ("int32",) * (1 + R))
            put = jax.device_put((slot_b, alive_b, *ring_bs),
                                 self._sharding)
            self._planes = prog(self._planes, put[0], tuple(put[1:]))
        # ---- pending rows, re-appended in sequence (arrival) order
        pending = _filtered(snap.get("pending")
                            or {"key_id": np.zeros(0)})
        pkeys = np.asarray(pending.get("key_id", ()), dtype=np.int64)
        width = pad_bucket_size(1, minimum=1024)
        if len(pkeys):
            shards = shard_records(pkeys, self.P,
                                   self.max_parallelism,
                                   self.key_group_range)
            counts = np.bincount(shards, minlength=self.P)
            width = pad_bucket_size(int(counts.max()) + 1,
                                    minimum=1024)
        h_hits = np.zeros((self.P, width), dtype=np.int32)
        h_seq = np.zeros((self.P, width), dtype=np.int32)
        if len(pkeys):
            for p in range(self.P):
                sel = np.nonzero(shards == p)[0]
                sh = self._st[p]
                m = len(sel)
                if not m:
                    continue
                sh.p_pos = np.arange(1, m + 1, dtype=np.int32)
                sh.p_key = pkeys[sel]
                sh.p_ts = np.asarray(pending["ts"],
                                     dtype=np.int64)[sel]
                sh.p_seq = np.asarray(pending["seq"],
                                      dtype=np.int32)[sel]
                sh.p_hits = np.asarray(pending["hits"],
                                       dtype=np.int32)[sel]
                for i, (name, dt) in enumerate(schema):
                    sh.p_vals[name] = np.asarray(
                        pending[f"leaf_{i}"], dtype=dt)[sel]
                sh.cursor = 1 + m
                h_hits[p, 1:m + 1] = sh.p_hits
                h_seq[p, 1:m + 1] = sh.p_seq
        self._pend_width = width
        self._pend = tuple(jax.device_put(a, self._sharding)
                           for a in (h_hits, h_seq))
        # ---- match store, re-inserted in rid (FIFO age) order
        matches = _filtered(snap.get("matches")
                            or {"key_id": np.zeros(0)})
        mkeys = np.asarray(matches.get("key_id", ()), dtype=np.int64)
        M = self.match_capacity
        m_planes = [np.zeros((self.P, M), dtype=np.int32)
                    for _ in range(3)]
        if len(mkeys):
            shards = shard_records(mkeys, self.P,
                                   self.max_parallelism,
                                   self.key_group_range)
            for p in range(self.P):
                sel = np.nonzero(shards == p)[0]
                if not len(sel):
                    continue
                sh = self._st[p]
                sel = sel[-(M - 1):]  # a merged unit may exceed FIFO
                m = len(sel)
                slots = np.arange(1, m + 1)
                sh.m_used[slots] = True
                sh.m_key[slots] = mkeys[sel]
                sh.m_rid[slots] = np.asarray(matches["rid"],
                                             dtype=np.int64)[sel]
                sh.m_start[slots] = np.asarray(matches["start_ts"],
                                               dtype=np.int64)[sel]
                sh.m_end[slots] = np.asarray(matches["end_ts"],
                                             dtype=np.int64)[sel]
                sh.m_depth[slots] = np.asarray(matches["depth"],
                                               dtype=np.int32)[sel]
                sh.m_fseq[slots] = np.asarray(matches["first_seq"],
                                              dtype=np.int32)[sel]
                sh.m_lseq[slots] = np.asarray(matches["last_seq"],
                                              dtype=np.int32)[sel]
                sh.m_count = m
                m_planes[0][p, slots] = sh.m_depth[slots]
                m_planes[1][p, slots] = sh.m_fseq[slots]
                m_planes[2][p, slots] = sh.m_lseq[slots]
        self._match_planes = tuple(
            jax.device_put(a, self._sharding) for a in m_planes)
        # ---- oracle-order bookkeeping + scalar counters
        ko = snap.get("key_order") or {}
        ko_keys = np.asarray(ko.get("key", ()), dtype=np.int64)
        ko_vals = np.asarray(ko.get("order", ()), dtype=np.int64)
        if len(ko_keys):
            keep = _key_in_filter(ko_keys)
            pairs = sorted(zip(ko_vals[keep].tolist(),
                               ko_keys[keep].tolist()))
            self._key_order = {int(k): int(o) for o, k in pairs}
        else:
            self._key_order = {}
        kv = dict(snap.get("key_values") or {})
        if kv and key_group_filter is not None:
            kvk = np.asarray(list(kv.keys()), dtype=np.int64)
            keep = _key_in_filter(kvk)
            kv = {int(k): kv[int(k)]
                  for k, ok in zip(kvk.tolist(), keep) if ok}
        self._key_values = {int(k): v for k, v in kv.items()}
        sc = snap.get("spill") or {}
        pm = self._st[0].pmap
        for name, v in sc.items():
            if hasattr(pm, name):
                setattr(pm, name, getattr(pm, name) + int(v))
        if self._match_replica is not None:
            self._rep_full = True
            self._rep_up = [set() for _ in range(self.P)]
            self._rep_freed = [[] for _ in range(self.P)]

    # ---------------------------------------------- shard-granular units

    def shard_key_groups(self) -> List[Tuple[int, int]]:
        from flink_tpu.state.keygroups import shard_key_group_ranges

        return shard_key_group_ranges(self.P, self.max_parallelism,
                                      self.key_group_range)

    def snapshot_sharded(self, mode: str = "full"
                         ) -> Dict[Tuple[int, int],
                                   Dict[str, object]]:
        """One independently-restorable unit per shard's key-group
        range — the three tables split by ``key_group``, the order /
        value dicts by the key's group, scalars replicated. The union
        of the units is exactly ``snapshot()``."""
        snap = self.snapshot(mode)
        units: Dict[Tuple[int, int], Dict[str, object]] = {}
        scalars = {k: v for k, v in snap.items()
                   if k not in ("state", "pending", "matches",
                                "key_order", "key_values")}
        ko = snap["key_order"]
        ko_kg = assign_key_groups(
            np.asarray(ko["key"], dtype=np.int64),
            self.max_parallelism)
        kv_keys = np.asarray(list(snap["key_values"].keys()),
                             dtype=np.int64)
        kv_kg = assign_key_groups(kv_keys, self.max_parallelism)
        for g0, g1 in self.shard_key_groups():
            unit = dict(scalars)
            for name in ("state", "pending", "matches"):
                table = snap[name]
                kg = np.asarray(table["key_group"], dtype=np.int64)
                mask = (kg >= g0) & (kg <= g1)
                unit[name] = {k: np.asarray(v)[mask]
                              for k, v in table.items()}
            m = (ko_kg >= g0) & (ko_kg <= g1)
            unit["key_order"] = {
                "key": np.asarray(ko["key"])[m],
                "order": np.asarray(ko["order"])[m]}
            mv = (kv_kg >= g0) & (kv_kg <= g1)
            unit["key_values"] = {
                int(k): snap["key_values"][int(k)]
                for k, ok in zip(kv_keys.tolist(), mv) if ok}
            units[(int(g0), int(g1))] = unit
        return units

    def merge_unit_snapshots(self, units: List[Dict[str, object]]
                             ) -> Dict[str, object]:
        if not units:
            return {"kind": "cep", "mode": "device"}
        merged: Dict[str, object] = {
            "kind": "cep", "mode": "device",
            "layout_key": units[0].get("layout_key"),
            "schema": next((u["schema"] for u in units
                            if u.get("schema")), []),
            "last_wm": max((u.get("last_wm") for u in units
                            if u.get("last_wm") is not None),
                           default=None),
            "next_seq": max(int(u.get("next_seq", 1))
                            for u in units),
            "next_rid": max(int(u.get("next_rid", 1))
                            for u in units),
            "order_seq": max(int(u.get("order_seq", 0))
                             for u in units),
            "clock": max(int(u.get("clock", 1)) for u in units),
        }
        # counters / spill totals are replicated per unit (scalars of
        # ONE engine): element-wise max reassembles, never doubles
        for field in ("counters", "spill"):
            acc: Dict[str, int] = {}
            for u in units:
                for k, v in (u.get(field) or {}).items():
                    acc[k] = max(acc.get(k, 0), int(v))
            merged[field] = acc
        sort_by = {"state": "key_id", "pending": "seq",
                   "matches": "rid"}
        for name, by in sort_by.items():
            tables = [u.get(name) for u in units]
            tables = [t for t in tables
                      if t is not None and len(
                          np.asarray(t.get("key_id", ())))]
            if not tables:
                merged[name] = {"key_id": np.zeros(0, dtype=np.int64)}
                continue
            cols = sorted(set().union(*(set(t) for t in tables)))
            table = {k: np.concatenate(
                [np.asarray(t[k]) for t in tables]) for k in cols}
            order = np.argsort(table[by], kind="stable")
            merged[name] = {k: v[order] for k, v in table.items()}
        ko_pairs = []
        kv: Dict[int, Any] = {}
        for u in units:
            ko = u.get("key_order") or {}
            ko_pairs.extend(zip(
                np.asarray(ko.get("order", ()),
                           dtype=np.int64).tolist(),
                np.asarray(ko.get("key", ()),
                           dtype=np.int64).tolist()))
            kv.update(u.get("key_values") or {})
        ko_pairs.sort()
        merged["key_order"] = {
            "key": np.asarray([k for _o, k in ko_pairs],
                              dtype=np.int64),
            "order": np.asarray([o for o, _k in ko_pairs],
                                dtype=np.int64)}
        merged["key_values"] = kv
        return merged

    # ------------------------------------------------------------- reshard

    def reshard(self, new_shards: int, devices=None
                ) -> Dict[str, object]:
        """LIVE key-group migration to a new mesh size: every logical
        row (resident + paged + pending + retained matches) lifts off
        the old planes, the mesh rebuilds, and rows land on their new
        owners via the restore path — counters survive."""
        new_shards = int(new_shards)
        if new_shards < 1:
            raise ValueError("new_shards must be >= 1")
        t0 = time.perf_counter()
        self._drain_fences()
        chaos.fault_point("rescale.handoff", stage="drain",
                          shards=new_shards)
        if self.backend == "host":
            self.P = new_shards
            chaos.fault_point("rescale.handoff", stage="commit",
                              shards=new_shards)
            return {"shards": self.P, "rows_moved": 0,
                    "seconds": time.perf_counter() - t0}
        snap = self.snapshot()
        rows_moved = sum(
            len(np.asarray(snap[t]["key_id"]))
            for t in ("state", "pending", "matches"))
        from flink_tpu.parallel.mesh import make_mesh
        from jax.sharding import NamedSharding, PartitionSpec
        from flink_tpu.parallel.mesh import KEY_AXIS

        self.mesh = make_mesh(new_shards, devices=devices)
        self.P = int(self.mesh.devices.size)
        self._sharding = NamedSharding(self.mesh,
                                       PartitionSpec(KEY_AXIS))
        if self.max_parallelism < self.P:
            raise ValueError(
                f"cannot reshard to {new_shards}: max_parallelism "
                f"{self.max_parallelism}")
        chaos.fault_point("rescale.handoff", stage="commit",
                          shards=new_shards)
        self._pool = __import__(
            "flink_tpu.parallel.shuffle",
            fromlist=["ShuffleBufferPool"]).ShuffleBufferPool(
                generations=2)
        self.restore(snap)
        wd = self._watchdog
        if wd is not None and self.mesh is not None:
            wd.rebind(self.P,
                      [d.id for d in self.mesh.devices.flat])
        return {"shards": self.P, "rows_moved": rows_moved,
                "seconds": time.perf_counter() - t0}

    # ------------------------------------------------------------ counters

    def spill_counters(self) -> Dict[str, int]:
        if self.backend == "host":
            return {}
        out: Dict[str, int] = {}
        for sh in self._st:
            for k, v in sh.pmap.counters().items():
                out[k] = out.get(k, 0) + v
        return out

    def shard_resident_rows(self) -> List[int]:
        if self.backend == "host":
            return [0] * self.P
        return [len(sh.slot_of) for sh in self._st]


def _dt_of(schema, name):
    for n, dt in schema:
        if n == name:
            return dt
    raise KeyError(name)


def _norm(x):
    if isinstance(x, (list, tuple)):
        return tuple(_norm(i) for i in x)
    return x


from flink_tpu.tenancy.replica import ReplicaAdapter  # noqa: E402


class CepMatchReplicaAdapter(ReplicaAdapter):
    """Replica-plane view of the matched-pattern store: an index entry
    is ``key -> {rid -> (shard, slot, (start_ts, end_ts))}``, a key's
    result is the live ``query_match_batch`` shape — matches sorted by
    (end_ts, rid). Retained matches are immutable (the FIFO only
    inserts and overwrites-oldest), so the boundary delta is pure
    identity churn, like the join side tables."""

    def __init__(self, plane):
        super().__init__(plane, None)

    def compose(self, entries, vals, cold_entries, cold_result
                ) -> list:
        rows: List[dict] = []
        for rid, j, extra in entries:
            start, end = extra
            rows.append({
                "rid": int(rid),
                "start_ts": int(start),
                "end_ts": int(end),
                "depth": int(np.asarray(vals[j][0]).item()),
                "first_seq": int(np.asarray(vals[j][1]).item()),
                "last_seq": int(np.asarray(vals[j][2]).item()),
            })
        rows.sort(key=lambda d: (d["end_ts"], d["rid"]))
        return rows


class CepMatchServingAdapter(CepMatchReplicaAdapter):
    """The ServingPlane/frontend-tier variant: composes each key's
    matches as ``{rid -> {start_ts, end_ts, depth, first_seq,
    last_seq}}`` — the ``{int namespace -> {column -> int}}`` shape the
    native hot cache packs into its shm arenas, so FRONTEND processes
    serve match lookups straight off the shared table (the list shape
    the base adapter returns rides the owner-side overflow store, which
    frontends cannot map). :meth:`match_rows` decodes a composed result
    back to the live ``query_match_batch`` row list, bit-identically.

    The publish feed is KILL-ONLY: retained matches are immutable (the
    FIFO inserts and overwrites-oldest, never edits), so a boundary's
    delta for a key is pure identity churn — dropping the key's cached
    entry (PrimeDelta flags bit1) is both correct and complete, and the
    base class's value-column finish (which needs an aggregate the
    match store does not have) never runs."""

    def compose(self, entries, vals, cold_entries, cold_result
                ) -> dict:
        out: Dict[int, dict] = {}
        for rid, j, extra in entries:
            start, end = extra
            out[int(rid)] = {
                "start_ts": int(start),
                "end_ts": int(end),
                "depth": int(np.asarray(vals[j][0]).item()),
                "first_seq": int(np.asarray(vals[j][1]).item()),
                "last_seq": int(np.asarray(vals[j][2]).item()),
            }
        return out

    @staticmethod
    def match_rows(result) -> List[dict]:
        """Decode one composed/served result back to the live
        ``query_match_batch`` shape: rows sorted by (end_ts, rid)."""
        rows = [{"rid": int(rid),
                 "start_ts": int(cols["start_ts"]),
                 "end_ts": int(cols["end_ts"]),
                 "depth": int(cols["depth"]),
                 "first_seq": int(cols["first_seq"]),
                 "last_seq": int(cols["last_seq"])}
                for rid, cols in (result or {}).items()]
        rows.sort(key=lambda d: (d["end_ts"], d["rid"]))
        return rows

    def _on_publish(self, gen: int, per_shard: Dict[int, dict],
                    harvest, prev_index) -> None:
        cache = self._cache
        if cache is None:
            return
        from flink_tpu.tenancy.hot_cache import PrimeDelta

        touched: set = set()
        for d in per_shard.values():
            touched.update(
                int(k) for k in np.asarray(d["up_keys"]).tolist())
            touched.update(int(k) for k, _ns in d["freed"])
        if not touched:
            return
        kids = np.asarray(sorted(touched), dtype=np.int64)
        zeros = np.zeros(len(kids) + 1, dtype=np.int64)
        empty = np.zeros(0, dtype=np.int64)
        cache.prime_batch(
            self._cache_job, self._cache_op, gen,
            PrimeDelta(keys=kids, uoff=zeros, u_ns=empty, u_cols=[],
                       roff=zeros, r_ns=empty,
                       flags=np.full(len(kids), 2, dtype=np.uint8)))
