"""Compiled device programs for the mesh CEP engine.

The device NFA is a *settled-state bitmask automaton*: for the
bounded-partial pattern class (every stage positive with finite
``times``, loop stages ``consecutive()``, stage-to-stage contiguity
STRICT — see :func:`compile_device_pattern`) a partial match is fully
described by its per-stage take counts ``(c_0 .. c_s)``, and the number
of distinct count vectors is a small static constant ``Q`` of the
pattern alone.  Each key's live partials therefore pack into ONE int32
``alive`` bitmask (bit ``q`` = "a partial in settled state ``q`` is
live"), and one event advances ALL keys' NFAs with pure bit algebra —
no per-key host loop, no dynamic partial lists.

State ids are assigned in the host oracle's *candidate order* (depth
descending, then take/proceed path lexicographic with T < P — the order
``KeyNFA.advance`` walks its partials list, proven inductively against
``cep/nfa.py``), so emission order falls out of ascending bit order:
under ``SKIP_PAST_LAST_EVENT`` the winning match is the lowest set bit,
under ``NO_SKIP`` multiple completions on one event emit in bit order
with the virtual-start completion (bit ``Q``) last.  Bit-identity with
the host ``CepOperator`` — values AND emission order — is the contract
``tools/cep_smoke.py`` gates.

Event references ride ``ring`` planes: the last ``R = Σ max_i − 1``
processed event sequence numbers per key, shifted one step per event —
a live partial of depth ``d`` references exactly the ``d`` most recent
processed events (all-consecutive class), so a bounded ring IS the
SharedBuffer for this pattern class.  ``within`` gating stays x32-safe:
int64 timestamps never reach the device — the host packs, per
(key, event), a ``wok`` bitmask whose bit ``d−1`` says "a partial of
depth ``d`` is still inside the window at this event".

Program families, all cached in the shared tenancy
:data:`~flink_tpu.tenancy.program_cache.PROGRAM_CACHE`:

- **cep-advance**: keyed on ``(device ids, compiled pattern layout)`` —
  two engines running the same pattern shape on the same mesh share the
  executable (the multi-tenant zero-recompile contract; gated by the
  CEP phase of ``tools/recompile_smoke.py``).  One ``lax.scan`` over
  the due-event axis, transitions unrolled over the ``Q`` settled
  states inside.
- **cep-prune**: the watermark ``within``-expiry scatter
  (``alive &= keep`` at slot cohorts).
- put / exchange-put / gather: the CEP planes are all-int32 ``[P,
  capacity]`` columns — exactly the join engines' plane shape — so the
  staging scatter, the fused keyBy exchange+scatter and the cohort
  gather reuse the ``join-put`` / ``join-exchange-put`` /
  ``join-gather`` families as-is (re-exported below).  Same executables,
  shared across tenants AND across engine kinds — the ROADMAP item-5
  direction (one state-plane kernel library) applied instead of a
  fourth hand-rolled copy.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flink_tpu.cep.pattern import (
    AfterMatchSkipStrategy,
    Contiguity,
    Pattern,
)
from flink_tpu.joins.kernels import (  # noqa: F401  (re-exported families)
    _mesh_key,
    build_join_exchange_put as build_cep_exchange_put,
    build_join_gather as build_cep_gather,
    build_join_put as build_cep_put,
)
from flink_tpu.parallel.mesh import KEY_AXIS, shard_map
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE

#: bitmask budget: settled states live in one int32 ``alive`` plane and
#: the virtual-start completion needs one more match bit
MAX_STATES = 30
#: total take budget: ring depth R = Σ max_i − 1 rides int32 planes and
#: the ``wok`` window bitmask spends bit d−1 per live depth d
MAX_TOTAL_TAKES = 32


class UnsupportedCepPattern(ValueError):
    """The pattern is outside the device engine's bounded-partial class
    — the caller must fall back (LOUDLY) to the host ``CepOperator``."""


@dataclasses.dataclass(frozen=True)
class DevicePatternLayout:
    """The compiled pattern layout: the static transition tables of the
    settled-state automaton.  ``key`` (times × skip × within-gating) is
    the PROGRAM_CACHE component — everything else derives from it."""

    #: per-stage (min_times, max_times)
    times: Tuple[Tuple[int, int], ...]
    skip_past: bool
    has_within: bool
    #: settled states' count vectors, in candidate-rank order (= id)
    counts: Tuple[Tuple[int, ...], ...]
    #: per-state current stage / depth (= number of events taken)
    stage: Tuple[int, ...]
    depth: Tuple[int, ...]
    #: per-state successor bits (None = transition impossible)
    take_bit: Tuple[Optional[int], ...]
    proceed_bit: Tuple[Optional[int], ...]
    #: per-state "a take here completes the pattern"
    match_state: Tuple[bool, ...]
    #: the virtual start's successors / completion (single-stage case)
    v_take: Optional[int]
    v_proceed: Optional[int]
    v_match: bool
    #: ring planes: Σ max_i − 1 event-ref registers per key
    ring: int

    @property
    def n_states(self) -> int:
        return len(self.counts)

    @property
    def n_stages(self) -> int:
        return len(self.times)

    @property
    def key(self) -> Tuple:
        return (self.times, self.skip_past, self.has_within)

    def match_counts(self, bit: int) -> Tuple[int, ...]:
        """Final per-stage take counts of a completion on ``bit`` (the
        settled state's counts plus the completing take; bit
        ``n_states`` is the virtual single-event completion)."""
        if bit == self.n_states:
            return (1,)
        c = self.counts[bit]
        return c[:-1] + (c[-1] + 1,)


def _rank_path(counts: Tuple[int, ...]) -> str:
    """The candidate-order sort key: the partial's take/proceed history
    as a string with T='a' < P='b' — ``KeyNFA.advance`` appends a
    candidate's take-continuation before its proceed child, and deeper
    partials precede shallower ones in the partials list."""
    s = len(counts) - 1
    return "".join("a" * c + ("b" if i < s else "")
                   for i, c in enumerate(counts))


def compile_device_pattern(pattern: Pattern) -> DevicePatternLayout:
    """Compile ``pattern`` to the settled-state layout, or raise
    :class:`UnsupportedCepPattern` naming the first disqualifier.

    The device class — scoped honestly, not aspirationally: every
    stage positive (no notNext/notFollowedBy), finite ``times`` (no
    unbounded oneOrMore), loop stages ``consecutive()``, stage
    contiguity STRICT past the first stage, no until / greedy /
    combinations / iterative conditions, and skip strategy NO_SKIP or
    SKIP_PAST_LAST_EVENT.  Everything here keeps the partial-match set
    collapsible to one count vector per partial; each relaxation
    reintroduces combinatorial partials (which event subsets were
    skipped) that a fixed-width bitmask cannot carry — those patterns
    run on the host ``CepOperator``, loudly."""
    pattern = pattern.validate()
    stages = pattern.stages
    if not stages:
        raise UnsupportedCepPattern("empty pattern")
    for i, st in enumerate(stages):
        if st.negated:
            raise UnsupportedCepPattern(
                f"stage {st.name!r}: negative stages (notNext/"
                "notFollowedBy) need the host NFA's invalidation walk")
        if st.until_condition is not None:
            raise UnsupportedCepPattern(
                f"stage {st.name!r}: until() stop conditions")
        if st.iterative_condition is not None:
            raise UnsupportedCepPattern(
                f"stage {st.name!r}: iterative (match-context) "
                "conditions are per-partial, not columnar")
        if st.greedy:
            raise UnsupportedCepPattern(f"stage {st.name!r}: greedy()")
        if st.combinations:
            raise UnsupportedCepPattern(
                f"stage {st.name!r}: allowCombinations() makes the "
                "partial set combinatorial in skipped-event subsets")
        if st.max_times is None:
            raise UnsupportedCepPattern(
                f"stage {st.name!r}: unbounded oneOrMore/timesOrMore")
        if st.min_times < 1:
            raise UnsupportedCepPattern(
                f"stage {st.name!r}: optional stages")
        if i > 0 and st.contiguity is not Contiguity.STRICT:
            raise UnsupportedCepPattern(
                f"stage {st.name!r}: relaxed contiguity (followedBy) "
                "keeps ignored-event partials alive indefinitely")
        if st.max_times > 1 and not st.consecutive_internal:
            raise UnsupportedCepPattern(
                f"stage {st.name!r}: non-consecutive loop (times/"
                "oneOrMore without .consecutive())")
    d_total = sum(st.max_times for st in stages)
    if d_total > MAX_TOTAL_TAKES:
        raise UnsupportedCepPattern(
            f"pattern takes up to {d_total} events > {MAX_TOTAL_TAKES}"
            " (int32 ring/window budget)")

    times = tuple((int(st.min_times), int(st.max_times))
                  for st in stages)
    n = len(times)
    # enumerate the settled states: completed stages carry
    # c_i ∈ [min_i, max_i] (the proceed happened at a legal count);
    # the current stage carries c_s ∈ [1, max_s−1] when s == 0 (stage-0
    # partials exist only mid-loop) and c_s ∈ [0, max_s−1] otherwise
    # (count max_s is never STORED: the take at max either proceeds,
    # completes or dies — exactly the oracle's ``count+1 < max`` gate)
    states = []

    def _extend(s: int, prefix: Tuple[int, ...]) -> None:
        if len(prefix) == s:
            lo = 1 if s == 0 else 0
            for c in range(lo, times[s][1]):
                states.append(prefix + (c,))
            return
        i = len(prefix)
        for c in range(times[i][0], times[i][1] + 1):
            _extend(s, prefix + (c,))

    for s in range(n):
        _extend(s, ())
    states.sort(key=lambda c: (-sum(c), _rank_path(c)))
    if len(states) > MAX_STATES:
        raise UnsupportedCepPattern(
            f"{len(states)} settled states > {MAX_STATES} "
            "(int32 alive-bitmask budget)")
    sid = {c: q for q, c in enumerate(states)}

    take_bit, proceed_bit, match_state = [], [], []
    for c in states:
        s = len(c) - 1
        nc = c[-1] + 1
        take_bit.append(sid.get(c[:-1] + (nc,))
                        if nc < times[s][1] else None)
        proceed_bit.append(sid.get(c[:-1] + (nc, 0))
                           if (s + 1 < n and nc >= times[s][0])
                           else None)
        match_state.append(s == n - 1 and nc >= times[s][0])
    v_take = sid.get((1,)) if times[0][1] > 1 else None
    v_proceed = (sid.get((1, 0))
                 if (n > 1 and times[0][0] <= 1) else None)
    v_match = n == 1 and times[0][0] <= 1

    return DevicePatternLayout(
        times=times,
        skip_past=(pattern.skip
                   is AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT),
        has_within=pattern.within_ms is not None,
        counts=tuple(states),
        stage=tuple(len(c) - 1 for c in states),
        depth=tuple(sum(c) for c in states),
        take_bit=tuple(take_bit),
        proceed_bit=tuple(proceed_bit),
        match_state=tuple(match_state),
        v_take=v_take,
        v_proceed=v_proceed,
        v_match=v_match,
        ring=max(d_total - 1, 0),
    )


def build_cep_advance(mesh: Mesh, layout: DevicePatternLayout):
    """The batched NFA advance: gather each due key's state row,
    ``lax.scan`` its due events through the settled-state transition
    algebra, scatter the final state back and emit the per-event match
    bitmasks — every key's whole fire in ONE compiled program."""
    key = (_mesh_key(mesh), layout.key)
    return PROGRAM_CACHE.get_or_build(
        "cep-advance", key, lambda: _build_cep_advance(mesh, layout))


def _build_cep_advance(mesh: Mesh, layout: DevicePatternLayout):
    R = layout.ring
    n_state = 1 + R  # alive + ring planes
    Q = layout.n_states
    depth = layout.depth
    stage = layout.stage
    take_bit = layout.take_bit
    proceed_bit = layout.proceed_bit
    match_state = layout.match_state
    has_within = layout.has_within
    skip_past = layout.skip_past
    v_take, v_proceed, v_match = (layout.v_take, layout.v_proceed,
                                  layout.v_match)

    @partial(jax.jit, donate_argnums=(0,))
    def advance(state, pending, slots, idx, wok, nev):
        def local(*args):
            al = args[0][0]                       # [C] alive bitmask
            rings = [a[0] for a in args[1:n_state]]
            ph = args[n_state][0]                 # [PB] pending hits
            ps = args[n_state + 1][0]             # [PB] pending seqs
            s = args[n_state + 2][0]              # [K] slots
            ix = args[n_state + 3][0]             # [K, E] pending rows
            wk = args[n_state + 4][0]             # [K, E] window bits
            nv = args[n_state + 5][0]             # [K] due counts
            k_n, e_n = ix.shape
            h_ek = ph[ix].T                       # [E, K]
            q_ek = ps[ix].T
            w_ek = wk.T
            ok_ek = (jax.lax.broadcasted_iota(
                jnp.int32, (k_n, e_n), 1) < nv[:, None]).T

            def step(carry, xs):
                a, rs = carry[0], list(carry[1:])
                h, sq, w, ok = xs
                na = jnp.zeros_like(a)
                m = jnp.zeros_like(a)
                # unrolled over the Q settled states: every state dies
                # on a miss in this pattern class (STRICT + consecutive
                # — no ignore edges), so alive_next collects only
                # take/proceed successors
                for q in range(Q):
                    t = (a >> q) & 1
                    if has_within:
                        t = t & ((w >> (depth[q] - 1)) & 1)
                    t = t & ((h >> stage[q]) & 1)
                    if match_state[q]:
                        m = m | (t << q)
                    if take_bit[q] is not None:
                        na = na | (t << take_bit[q])
                    if proceed_bit[q] is not None:
                        na = na | (t << proceed_bit[q])
                # the virtual start candidate — walked LAST, as the
                # oracle does (bit Q for its single-event completion)
                hv = h & 1
                if v_match:
                    m = m | (hv << Q)
                if v_take is not None:
                    na = na | (hv << v_take)
                if v_proceed is not None:
                    na = na | (hv << v_proceed)
                if skip_past:
                    # the match consumed its events: every partial dies
                    # and the matched event starts nothing
                    na = jnp.where(m != 0, 0, na)
                nrs = rs[1:] + [sq] if R else []
                a = jnp.where(ok, na, a)
                rs = [jnp.where(ok, nr, r)
                      for nr, r in zip(nrs, rs)]
                return ((a, *rs), jnp.where(ok, m, 0))

            carry0 = (al[s], *[r[s] for r in rings])
            carry, m_seq = jax.lax.scan(
                step, carry0, (h_ek, q_ek, w_ek, ok_ek))
            a_f = carry[0]
            # padded lanes carry slot 0 with nev == 0: their carry is
            # the untouched row-0 value, so the scatter is a no-op
            al2 = al.at[s].set(a_f)
            rings2 = [r.at[s].set(f)
                      for r, f in zip(rings, carry[1:])]
            return (al2[None], *[r[None] for r in rings2],
                    m_seq.T[None], a_f[None])

        out = shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_state + 6),
            out_specs=(P(KEY_AXIS),) * (n_state + 2),
        )(*state, *pending, slots, idx, wok, nev)
        return out[:n_state], out[n_state], out[n_state + 1]

    return advance


def build_cep_prune(mesh: Mesh):
    """The watermark within-expiry: ``alive[p, slots] &= keep`` — one
    scatter over the resident cohort (keep bits host-computed from the
    per-depth window test; spilled keys prune lazily at reload)."""
    key = (_mesh_key(mesh),)
    return PROGRAM_CACHE.get_or_build(
        "cep-prune", key, lambda: _build_cep_prune(mesh))


def _build_cep_prune(mesh: Mesh):
    @partial(jax.jit, donate_argnums=(0,))
    def prune(alive, slots, keep):
        def local(al, s, k):
            # padded lanes carry slot 0 and keep == −1 (all ones)
            upd = al[0][s[0]] & k[0]
            return al.at[0, s[0]].set(upd)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * 3,
            out_specs=P(KEY_AXIS),
        )(alive, slots, keep)

    return prune
