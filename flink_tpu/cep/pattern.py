"""CEP Pattern API.

reference: flink-libraries/flink-cep/.../pattern/Pattern.java (begin/next/
followedBy/where/times/oneOrMore/optional/within) and
AfterMatchSkipStrategy.java.

Re-design: conditions are *vectorized* — a condition is a function
``batch -> bool mask`` evaluated once per micro-batch for all events (the
expensive part), so the per-event NFA loop only reads precomputed booleans.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, List, Optional

import numpy as np

from flink_tpu.core.records import RecordBatch


class Contiguity(enum.Enum):
    STRICT = "next"  # reference: Pattern.next
    RELAXED = "followed_by"  # reference: Pattern.followedBy


class AfterMatchSkipStrategy(enum.Enum):
    """reference: cep/nfa/aftermatch/AfterMatchSkipStrategy.java."""

    NO_SKIP = "no_skip"
    SKIP_PAST_LAST_EVENT = "skip_past_last_event"


@dataclasses.dataclass
class Stage:
    name: str
    condition: Optional[Callable[[RecordBatch], np.ndarray]] = None
    contiguity: Contiguity = Contiguity.STRICT
    min_times: int = 1
    max_times: Optional[int] = 1  # None = unbounded (oneOrMore)
    # loop-internal contiguity of times()/one_or_more(); the reference
    # defaults to relaxed, .consecutive() opts into strict
    consecutive_internal: bool = False
    # allowCombinations(): a matching event may ALSO be skipped inside the
    # loop, yielding non-adjacent combinations (reference: followedByAny
    # internal strategy)
    combinations: bool = False

    def evaluate(self, batch: RecordBatch) -> np.ndarray:
        if self.condition is None:
            return np.ones(len(batch), dtype=bool)
        return np.asarray(self.condition(batch), dtype=bool)


class Pattern:
    """Fluent pattern builder.

    Example (reference docs' canonical fraud pattern)::

        Pattern.begin("small").where(lambda b: b["amount"] < 1.0) \\
               .next("big").where(lambda b: b["amount"] > 500.0) \\
               .within(60_000)
    """

    def __init__(self, stages: List[Stage], within_ms: Optional[int] = None,
                 skip: AfterMatchSkipStrategy = AfterMatchSkipStrategy.NO_SKIP):
        self.stages = stages
        self.within_ms = within_ms
        self.skip = skip

    # -- construction --------------------------------------------------------

    @staticmethod
    def begin(name: str,
              skip: AfterMatchSkipStrategy = AfterMatchSkipStrategy.NO_SKIP
              ) -> "Pattern":
        return Pattern([Stage(name)], skip=skip)

    # Builder methods are persistent: each returns a NEW Pattern (stages are
    # never mutated in place), so a shared prefix can safely branch into
    # several derived patterns — the same linked-object semantics as the
    # reference's Pattern.next/followedBy returning fresh Pattern nodes.

    def _append(self, stage: Stage) -> "Pattern":
        return Pattern(self.stages + [stage], self.within_ms, self.skip)

    def _amend_last(self, **changes) -> "Pattern":
        stages = self.stages[:-1] + [
            dataclasses.replace(self.stages[-1], **changes)]
        return Pattern(stages, self.within_ms, self.skip)

    def next(self, name: str) -> "Pattern":
        return self._append(Stage(name, contiguity=Contiguity.STRICT))

    def followed_by(self, name: str) -> "Pattern":
        return self._append(Stage(name, contiguity=Contiguity.RELAXED))

    # -- stage modifiers (apply to the LAST stage) ---------------------------

    def where(self, condition: Callable[[RecordBatch], np.ndarray]
              ) -> "Pattern":
        prev = self.stages[-1].condition
        if prev is None:
            combined = condition
        else:  # multiple where() = AND (reference: RichAndCondition)
            def combined(b, prev=prev, cond=condition):
                return (np.asarray(prev(b), dtype=bool)
                        & np.asarray(cond(b), dtype=bool))
        return self._amend_last(condition=combined)

    def or_where(self, condition) -> "Pattern":
        prev = (self.stages[-1].condition
                or (lambda b: np.zeros(len(b), dtype=bool)))

        def combined(b, prev=prev, cond=condition):
            return (np.asarray(prev(b), dtype=bool)
                    | np.asarray(cond(b), dtype=bool))

        return self._amend_last(condition=combined)

    def times(self, n: int, max_n: Optional[int] = None) -> "Pattern":
        return self._amend_last(min_times=n,
                                max_times=n if max_n is None else max_n)

    def one_or_more(self) -> "Pattern":
        return self._amend_last(min_times=1, max_times=None)

    def allow_combinations(self) -> "Pattern":
        """reference: Pattern.allowCombinations()."""
        return self._amend_last(combinations=True)

    def consecutive(self) -> "Pattern":
        """reference: Pattern.consecutive() — strict contiguity inside a
        times()/oneOrMore() loop."""
        return self._amend_last(consecutive_internal=True)

    def optional(self) -> "Pattern":
        return self._amend_last(min_times=0)

    def within(self, ms: int) -> "Pattern":
        return Pattern(self.stages, ms, self.skip)

    def with_skip_strategy(self, skip: AfterMatchSkipStrategy) -> "Pattern":
        return Pattern(self.stages, self.within_ms, skip)

    # -- validation ----------------------------------------------------------

    def validate(self) -> "Pattern":
        names = [s.name for s in self.stages]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate stage names: {names}")
        if all(s.min_times == 0 for s in self.stages):
            raise ValueError("pattern cannot be entirely optional")
        return self
