"""CEP Pattern API.

reference: flink-libraries/flink-cep/.../pattern/Pattern.java (begin/next/
followedBy/where/times/oneOrMore/optional/within) and
AfterMatchSkipStrategy.java.

Re-design: conditions are *vectorized* — a condition is a function
``batch -> bool mask`` evaluated once per micro-batch for all events (the
expensive part), so the per-event NFA loop only reads precomputed booleans.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, List, Optional

import numpy as np

from flink_tpu.core.records import RecordBatch


class Contiguity(enum.Enum):
    STRICT = "next"  # reference: Pattern.next
    RELAXED = "followed_by"  # reference: Pattern.followedBy


class AfterMatchSkipStrategy(enum.Enum):
    """reference: cep/nfa/aftermatch/AfterMatchSkipStrategy.java."""

    NO_SKIP = "no_skip"
    SKIP_PAST_LAST_EVENT = "skip_past_last_event"


@dataclasses.dataclass
class Stage:
    name: str
    condition: Optional[Callable[[RecordBatch], np.ndarray]] = None
    contiguity: Contiguity = Contiguity.STRICT
    min_times: int = 1
    max_times: Optional[int] = 1  # None = unbounded (oneOrMore)
    # loop-internal contiguity of times()/one_or_more(); the reference
    # defaults to relaxed, .consecutive() opts into strict
    consecutive_internal: bool = False
    # allowCombinations(): a matching event may ALSO be skipped inside the
    # loop, yielding non-adjacent combinations (reference: followedByAny
    # internal strategy)
    combinations: bool = False
    #: negative pattern (notNext / notFollowedBy): an event matching this
    #: stage's condition INVALIDATES partial matches instead of extending
    #: them (reference: Pattern.notNext/notFollowedBy + NotCondition)
    negated: bool = False
    #: oneOrMore().until(cond): the loop stops accepting events once an
    #: event satisfies cond (the until event itself is not consumed by the
    #: loop; reference: Pattern.until / IterativeCondition stop condition)
    until_condition: Optional[Callable[[RecordBatch], np.ndarray]] = None
    #: greedy(): the loop consumes as many matching events as possible —
    #: an event matching the loop condition can neither be taken nor
    #: ignored by the FOLLOWING stage's fresh waiting state (reference:
    #: Quantifier.greedy + NFACompiler.updateWithGreedyCondition)
    greedy: bool = False
    #: iterative (match-context) condition evaluated per (event, partial)
    #: with access to the events already taken — reference:
    #: IterativeCondition.filter(event, ctx). ANDed with ``condition``.
    iterative_condition: Optional[Callable] = None

    def evaluate(self, batch: RecordBatch) -> np.ndarray:
        if self.condition is None:
            return np.ones(len(batch), dtype=bool)
        return np.asarray(self.condition(batch), dtype=bool)


class Pattern:
    """Fluent pattern builder.

    Example (reference docs' canonical fraud pattern)::

        Pattern.begin("small").where(lambda b: b["amount"] < 1.0) \\
               .next("big").where(lambda b: b["amount"] > 500.0) \\
               .within(60_000)
    """

    def __init__(self, stages: List[Stage], within_ms: Optional[int] = None,
                 skip: AfterMatchSkipStrategy = AfterMatchSkipStrategy.NO_SKIP):
        self.stages = stages
        self.within_ms = within_ms
        self.skip = skip

    # -- construction --------------------------------------------------------

    @staticmethod
    def begin(name: str,
              skip: AfterMatchSkipStrategy = AfterMatchSkipStrategy.NO_SKIP
              ) -> "Pattern":
        return Pattern([Stage(name)], skip=skip)

    # Builder methods are persistent: each returns a NEW Pattern (stages are
    # never mutated in place), so a shared prefix can safely branch into
    # several derived patterns — the same linked-object semantics as the
    # reference's Pattern.next/followedBy returning fresh Pattern nodes.

    def _append(self, stage: Stage) -> "Pattern":
        return Pattern(self.stages + [stage], self.within_ms, self.skip)

    def _amend_last(self, **changes) -> "Pattern":
        stages = self.stages[:-1] + [
            dataclasses.replace(self.stages[-1], **changes)]
        return Pattern(stages, self.within_ms, self.skip)

    def next(self, name: str) -> "Pattern":
        return self._append(Stage(name, contiguity=Contiguity.STRICT))

    def followed_by(self, name: str) -> "Pattern":
        return self._append(Stage(name, contiguity=Contiguity.RELAXED))

    def not_next(self, name: str) -> "Pattern":
        """The event immediately after the previous stage's match must NOT
        satisfy this stage (reference: Pattern.notNext)."""
        return self._append(Stage(name, contiguity=Contiguity.STRICT,
                                  negated=True))

    def not_followed_by(self, name: str) -> "Pattern":
        """No event between the previous stage's match and the following
        stage's match may satisfy this stage (reference:
        Pattern.notFollowedBy). As the LAST stage it requires within():
        the match emits once the window expires without the forbidden
        event."""
        return self._append(Stage(name, contiguity=Contiguity.RELAXED,
                                  negated=True))

    # -- stage modifiers (apply to the LAST stage) ---------------------------

    def where(self, condition: Callable[[RecordBatch], np.ndarray]
              ) -> "Pattern":
        prev = self.stages[-1].condition
        if prev is None:
            combined = condition
        else:  # multiple where() = AND (reference: RichAndCondition)
            def combined(b, prev=prev, cond=condition):
                return (np.asarray(prev(b), dtype=bool)
                        & np.asarray(cond(b), dtype=bool))
        return self._amend_last(condition=combined)

    def or_where(self, condition) -> "Pattern":
        prev = (self.stages[-1].condition
                or (lambda b: np.zeros(len(b), dtype=bool)))

        def combined(b, prev=prev, cond=condition):
            return (np.asarray(prev(b), dtype=bool)
                    | np.asarray(cond(b), dtype=bool))

        return self._amend_last(condition=combined)

    def times(self, n: int, max_n: Optional[int] = None) -> "Pattern":
        return self._amend_last(min_times=n,
                                max_times=n if max_n is None else max_n)

    def one_or_more(self) -> "Pattern":
        return self._amend_last(min_times=1, max_times=None)

    def times_or_more(self, n: int) -> "Pattern":
        """At least n takes, unbounded above (reference:
        Pattern.timesOrMore)."""
        return self._amend_last(min_times=n, max_times=None)

    def until(self, condition: Callable[[RecordBatch], np.ndarray]
              ) -> "Pattern":
        """Stop the last stage's loop once an event satisfies
        ``condition`` (reference: Pattern.until — only meaningful on an
        unbounded quantifier)."""
        if self.stages[-1].max_times is not None:
            raise ValueError("until() applies to oneOrMore()/"
                             "timesOrMore() stages")
        return self._amend_last(until_condition=condition)

    def allow_combinations(self) -> "Pattern":
        """reference: Pattern.allowCombinations()."""
        return self._amend_last(combinations=True)

    def greedy(self) -> "Pattern":
        """The loop consumes as many matching events as possible before
        the next stage may proceed (reference: Pattern.greedy() — only
        meaningful on a times()/oneOrMore() loop whose condition overlaps
        the following stage's)."""
        last = self.stages[-1]
        if last.max_times == 1:
            raise ValueError(
                "greedy() applies to times()/oneOrMore() loop stages")
        if last.combinations:
            raise ValueError(
                "greedy() cannot combine with allowCombinations() "
                "(reference restriction)")
        return self._amend_last(greedy=True)

    def where_iterative(self, condition: Callable) -> "Pattern":
        """Match-context condition ``fn(event_row, ctx) -> bool`` where
        ``ctx.events_for(stage_name)`` returns the events the partial
        match has already taken for a stage (reference:
        IterativeCondition.filter(value, ctx) /
        ctx.getEventsForPattern). ANDed with any vectorized where()."""
        prev = self.stages[-1].iterative_condition
        if prev is None:
            combined = condition
        else:
            def combined(ev, ctx, prev=prev, cond=condition):
                return bool(prev(ev, ctx)) and bool(cond(ev, ctx))
        return self._amend_last(iterative_condition=combined)

    def consecutive(self) -> "Pattern":
        """reference: Pattern.consecutive() — strict contiguity inside a
        times()/oneOrMore() loop."""
        return self._amend_last(consecutive_internal=True)

    def optional(self) -> "Pattern":
        return self._amend_last(min_times=0)

    def within(self, ms: int) -> "Pattern":
        return Pattern(self.stages, ms, self.skip)

    def with_skip_strategy(self, skip: AfterMatchSkipStrategy) -> "Pattern":
        return Pattern(self.stages, self.within_ms, skip)

    # -- validation ----------------------------------------------------------

    def validate(self) -> "Pattern":
        names = [s.name for s in self.stages]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate stage names: {names}")
        positives = [s for s in self.stages if not s.negated]
        if not positives:
            raise ValueError("pattern needs at least one positive stage")
        if all(s.min_times == 0 for s in positives):
            raise ValueError("pattern cannot be entirely optional")
        if self.stages[0].negated:
            raise ValueError("a pattern cannot begin with notNext/"
                             "notFollowedBy (reference restriction)")
        for s in self.stages:
            if s.negated and (s.min_times != 1 or s.max_times != 1
                              or s.combinations):
                raise ValueError(
                    f"negative stage {s.name!r} cannot carry quantifiers "
                    "(reference: not-patterns reject oneOrMore/times)")
            if s.negated and s.condition is None:
                raise ValueError(
                    f"negative stage {s.name!r} needs a where() condition")
        for i, s in enumerate(self.stages[:-1]):
            nxt = self.stages[i + 1]
            if s.negated and not nxt.negated and nxt.min_times == 0:
                raise ValueError(
                    f"negative stage {s.name!r} cannot precede optional "
                    f"stage {nxt.name!r}: the branch that skips the "
                    "optional stage would lose the guard (reference: "
                    "notFollowedBy/notNext before optional is rejected)")
        if self.stages[-1].negated:
            if self.stages[-1].contiguity is Contiguity.STRICT:
                raise ValueError("a pattern cannot end with notNext "
                                 "(reference restriction)")
            if self.within_ms is None:
                raise ValueError(
                    "a pattern ending with notFollowedBy requires "
                    "within() — the match emits at window expiry "
                    "(reference restriction)")
        return self
