"""Stream operators — batched re-design of the reference's operator model.

The reference's ``StreamOperator`` processes one element at a time
(reference: streaming/api/operators/AbstractStreamOperator.java,
OneInputStreamOperator.processElement). Here an operator processes a
``RecordBatch`` per call and reacts to watermark advances. All operators are
single-owner (called from one task loop), mirroring the mailbox threading
discipline (reference: tasks/mailbox/MailboxProcessor.java:214).

User functions are *vectorized*: a map function takes and returns a
RecordBatch (columnar), not a single element. A row-at-a-time adapter exists
for convenience (``RowMapFunction``) but the batch form is the idiomatic one —
it is what keeps the TPU path wide.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.runtime.elements import Watermark
from flink_tpu.runtime.watermarks import WatermarkValve
from flink_tpu.state.keygroups import hash_keys_to_i64
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.windowing.assigners import WindowAssigner
from flink_tpu.windowing.windower import SliceSharedWindower


class Operator:
    """Base operator. Subclasses override the hooks they need."""

    name: str = "operator"

    def open(self, ctx: "OperatorContext") -> None:
        pass

    def process_batch(self, batch: RecordBatch, input_index: int = 0
                      ) -> List[RecordBatch]:
        raise NotImplementedError

    def process_watermark(self, watermark: int, input_index: int = 0
                          ) -> List[RecordBatch]:
        return []

    #: operators that react to wall-clock ticks (processing-time windows /
    #: timers) set this so the executor loop knows to tick them
    uses_processing_time: bool = False

    def on_processing_time(self, now_ms: int) -> List[RecordBatch]:
        """Wall-clock tick (reference: WindowOperator.onProcessingTime:497 /
        InternalTimerService processing-time timers)."""
        return []

    def close(self) -> List[RecordBatch]:
        return []

    def dispose(self) -> None:
        """Release resources without emitting (failure/cancel path; the
        reference's StreamOperator.close vs dispose split)."""

    # asynchronous outputs (deferred window fires — see
    # flink_tpu.runtime.pending). The executor holds back this operator's
    # output watermark while pending outputs exist and polls them each
    # loop iteration (reference: AsyncExecutionController in-flight drain).
    def has_pending_output(self) -> bool:
        return False

    def poll_pending_output(self, wait: bool = False) -> List[RecordBatch]:
        return []

    # checkpointing
    def snapshot_state(self) -> Optional[Dict[str, Any]]:
        return None

    def restore_state(self, state: Dict[str, Any]) -> None:
        pass


def _ctx_topology(ctx, mesh):
    """Resolve the context's host-topology declaration against the
    engine's actual mesh: an int (``shuffle.hosts``) factors the mesh
    size; a :class:`~flink_tpu.parallel.mesh.HostTopology` is used when
    it covers. A declaration that cannot factor THIS mesh (e.g. a
    stage sub-mesh of a different size) falls back to the flat
    exchange rather than failing the job."""
    decl = getattr(ctx, "host_topology", None)
    if decl is None:
        return None
    size = int(mesh.devices.size)
    if isinstance(decl, int):
        if decl > 1 and size % decl == 0:
            from flink_tpu.parallel.mesh import HostTopology

            return HostTopology(decl, size // decl)
        return None
    return decl if decl.num_shards == size else None


class OperatorContext:
    """Per-operator runtime context (task info, metrics hook)."""

    def __init__(self, operator_index: int = 0, parallelism: int = 1,
                 max_parallelism: int = 128, metrics=None,
                 async_fires: bool = False, max_dispatch_ahead: int = 4,
                 mesh=None, key_group_range=None, memory_manager=None,
                 shuffle_mode: str = "device", watchdog=None,
                 pane_preagg: bool = True, host_topology=None):
        self.operator_index = operator_index
        self.parallelism = parallelism
        self.max_parallelism = max_parallelism
        self.metrics = metrics
        #: managed device-memory pool shared by the job's stateful
        #: operators (flink_tpu/core/memory.py; None = unlimited)
        self.memory_manager = memory_manager
        #: explicit device mesh for the keyed engine (mesh x stage: a
        #: keyed subtask opens its engine over a private sub-mesh)
        self.mesh = mesh
        #: (first, last) key groups this task owns — the mesh engine
        #: shards WITHIN this range when set (None: the full key space)
        self.key_group_range = key_group_range
        #: the hosting executor supports deferred fire harvesting +
        #: watermark holdback (LocalExecutor's loop); executors that
        #: forward watermarks eagerly must leave this off
        self.async_fires = async_fires
        #: per-batch fence depth (execution.pipeline.max-dispatch-batches)
        self.max_dispatch_ahead = max_dispatch_ahead
        #: keyBy data plane for mesh engines (shuffle.mode):
        #: "device" = in-program exchange, "host" = explicit fallback
        self.shuffle_mode = shuffle_mode
        #: (hosts, local) factorization of the mesh (shuffle.hosts) —
        #: an int host count or a HostTopology; mesh engines then run
        #: the two-level ICI/DCN exchange (parallel/exchange2.py)
        self.host_topology = host_topology
        #: DeviceWatchdog (runtime/watchdog.py) the mesh engines attach
        #: when watchdog.enabled — deadline-tracked device sections +
        #: batch-boundary shard-health probes; None = disabled
        self.watchdog = watchdog
        #: incremental pane pre-aggregation for the panes window layout
        #: (latency.pane-preagg): per-window running partials combined
        #: at absorb, so a fire gathers one closing pane. The other
        #: latency-tier knob (latency.fire-deadline-ms) lives on the
        #: EXECUTOR, which owns the batch loop and the autoscale policy.
        self.pane_preagg = pane_preagg


class MapOperator(Operator):
    name = "map"

    def __init__(self, fn: Callable[[RecordBatch], RecordBatch]):
        self.fn = fn

    def process_batch(self, batch, input_index=0):
        out = self.fn(batch)
        return [out] if out is not None and len(out) else []


class FilterOperator(Operator):
    name = "filter"

    def __init__(self, predicate: Callable[[RecordBatch], np.ndarray]):
        self.predicate = predicate

    def process_batch(self, batch, input_index=0):
        mask = np.asarray(self.predicate(batch), dtype=bool)
        out = batch.filter(mask)
        return [out] if len(out) else []


class FlatMapOperator(Operator):
    name = "flat_map"

    def __init__(self, fn: Callable[[RecordBatch], List[RecordBatch]]):
        self.fn = fn

    def process_batch(self, batch, input_index=0):
        return [b for b in self.fn(batch) if b is not None and len(b)]


class KeyByOperator(Operator):
    """Attaches the int64 key identity column (``__key_id__``).

    The actual routing (key group -> shard) happens at the exchange edge /
    device sharding, mirroring the split between KeyedStream (API) and
    KeyGroupStreamPartitioner (runtime) in the reference
    (reference: streaming/runtime/partitioner/KeyGroupStreamPartitioner.java:55).
    """

    name = "key_by"

    def __init__(self, key_field: str):
        self.key_field = key_field

    def process_batch(self, batch, input_index=0):
        key_ids = hash_keys_to_i64(batch[self.key_field])
        return [batch.with_column(KEY_ID_FIELD, key_ids)]


class WindowAggOperator(Operator):
    """keyBy -> window -> aggregate on the TPU slot table.

    reference semantics: WindowOperator.java / WindowAggOperator.java (see
    flink_tpu.windowing.windower docstring for the mapping).
    """

    name = "window_agg"

    def __init__(self, assigner: WindowAssigner, agg: AggregateFunction,
                 key_field: str, capacity: int = 1 << 16,
                 allowed_lateness: int = 0, spill: dict = None,
                 fire_projector=None, window_layout: str = "auto",
                 state_backend: str = "tpu-slot-table"):
        self.window_layout = window_layout
        self.state_backend = state_backend
        self.assigner = assigner
        self.agg = agg
        self.key_field = key_field
        self.capacity = capacity
        self.allowed_lateness = allowed_lateness
        self.spill = spill
        self.fire_projector = fire_projector
        #: processing-time assigner: records are stamped with wall-clock
        #: arrival time; fires come from on_processing_time ticks
        #: (reference: WindowOperator.onProcessingTime:497)
        self.uses_processing_time = bool(
            getattr(assigner, "is_processing_time", False))
        self.windower: Optional[SliceSharedWindower] = None
        self._key_values: Dict[int, Any] = {}  # key_id -> original key value
        #: sorted-array mirror of _key_values for vectorized lookups on the
        #: fire path (np.searchsorted instead of a per-key Python loop);
        #: rebuilt lazily whenever the dict has grown
        self._kv_ids: np.ndarray = np.empty(0, np.int64)
        self._kv_vals: np.ndarray = np.empty(0, object)
        self._keys_hashed = False
        #: wall-clock ms from watermark advance to fired results on host
        #: (the p99 window-fire latency metric; reference measures this at
        #: WindowOperator.emitWindowContents). Bounded reservoir — a
        #: long-running job must not leak host memory.
        from collections import deque

        self.fire_latencies_ms = deque(maxlen=8192)
        #: monotonic fire-sample count — the reservoir above is BOUNDED
        #: (its len saturates at maxlen), so counters and "any new
        #: fires since last tick?" checks read this instead
        self.fires_total = 0
        #: dispatched-but-unharvested fires (FIFO; see poll_pending_output)
        self._pending = deque()
        self._async_fires = False
        #: bound on in-flight fires: beyond it the oldest is harvested
        #: synchronously (backpressure — pending results are small, but a
        #: catch-up burst firing hundreds of windows must not hoard buffers)
        self._max_pending = 32
        #: per-batch dispatch fences bounding how far the host runs ahead
        #: of the device queue — keeps fire kernels (and their latency)
        #: from queueing behind an unbounded scatter backlog
        self._fences = deque()
        self._max_dispatch_ahead = 4  # overridden from ctx in open()

    def open(self, ctx):
        import jax

        # reactive clamp: never build a mesh larger than the devices that
        # exist (reference: AdaptiveScheduler scales the plan to available
        # resources rather than failing the job)
        effective = min(ctx.parallelism, len(jax.devices()))
        if effective > 1:
            # parallelism > 1 selects the mesh-sharded engine: state lives
            # in [P, capacity] device arrays sharded over the key-group
            # mesh axis, records are routed by the reference's key-group
            # formula (reference: Execution.java:572 deploy() expands a
            # vertex into parallel subtasks; KeyGroupStreamPartitioner.java:55
            # routes by key group — here the "subtasks" are mesh shards of
            # one jitted program)
            from flink_tpu.parallel.mesh import make_mesh
            from flink_tpu.parallel.sharded_windower import MeshWindowEngine

            self._reject_backend_on_mesh()
            mesh = getattr(ctx, "mesh", None) or make_mesh(effective)
            spill = dict(self.spill or {})
            self.windower = MeshWindowEngine(
                self.assigner, self.agg, mesh,
                capacity_per_shard=self.capacity,
                max_parallelism=ctx.max_parallelism,
                allowed_lateness=self.allowed_lateness,
                fire_projector=self.fire_projector,
                # the budget is per device: every mesh shard owns one
                # chip's HBM (state capacity ⟂ parallelism, the RocksDB
                # contract)
                max_device_slots=spill.get("max_device_slots", 0),
                spill_dir=spill.get("spill_dir"),
                spill_host_max_bytes=spill.get("spill_host_max_bytes", 0),
                key_group_range=getattr(ctx, "key_group_range", None),
                memory=self._managed_memory(ctx),
                # engine-level dispatch-ahead follows the task's
                # pipeline depth (execution.pipeline.max-dispatch-batches)
                max_dispatch_ahead=getattr(ctx, "max_dispatch_ahead", 2),
                # keyBy data plane (shuffle.mode): in-program device
                # exchange by default, host bucketing as the fallback
                shuffle_mode=getattr(ctx, "shuffle_mode", "device"),
                # (hosts, local) factorization (shuffle.hosts): the
                # two-level ICI/DCN exchange on a pod-spanning mesh
                host_topology=_ctx_topology(ctx, mesh))
        else:
            table_kwargs, placement = self._table_kwargs()
            if self._managed_memory(ctx) is not None:
                table_kwargs["memory"] = self._managed_memory(ctx)
            has_spill = bool(self.spill and any(self.spill.values()))
            # 'auto' currently resolves to the slot layout: the pane
            # layout's dense fires measure SLOWER on CPU, and its win case
            # — removing the per-fire host->device slot matrix on the
            # transfer-constrained TPU link — is not yet hardware-measured
            # (bench.py measures both layouts and reports the better).
            # Flip 'auto' here once the TPU numbers land. An explicit
            # 'panes' is honored for aligned windows without spill; note
            # its footprint is DENSE ([ring_rows, key_capacity] per leaf),
            # so high-ratio sliding windows multiply HBM by the slice
            # count.
            use_panes = self.window_layout == "panes"
            if use_panes and has_spill:
                raise ValueError(
                    "state.window-layout=panes has no spill tier — use "
                    "'slots' (or 'auto') with state.spill.* options")
            if use_panes and placement is not None:
                raise ValueError(
                    "state.window-layout=panes supports only the default "
                    "placement; state.backend placements (host-heap) use "
                    "the slot layout")
            if use_panes:
                # pane/ring layout: fires are pure device reductions with
                # no per-fire host->device transfer (state/pane_table.py)
                from flink_tpu.windowing.windower import PaneWindower

                self.windower = PaneWindower(
                    self.assigner, self.agg, capacity=self.capacity,
                    max_parallelism=ctx.max_parallelism,
                    allowed_lateness=self.allowed_lateness,
                    fire_projector=self.fire_projector,
                    memory=self._managed_memory(ctx),
                    # latency tier: per-window partials combined at
                    # absorb, fires gather one closing pane
                    preagg=getattr(ctx, "pane_preagg", True))
            else:
                self.windower = SliceSharedWindower(
                    self.assigner, self.agg, capacity=self.capacity,
                    max_parallelism=ctx.max_parallelism,
                    allowed_lateness=self.allowed_lateness,
                    spill=table_kwargs,
                    fire_projector=self.fire_projector)
        self._resolve_async_fires(ctx)

    def _managed_memory(self, ctx):
        """(MemoryManager, unique owner) for device-state accounting, or
        None when no budget is configured (flink_tpu/core/memory.py)."""
        mm = getattr(ctx, "memory_manager", None)
        if mm is None:
            return None
        return (mm, f"{self.name}#{id(self):x}")

    def _reject_backend_on_mesh(self) -> None:
        if self.state_backend not in ("tpu-slot-table",):
            # fail loudly, never degrade silently (same contract as
            # execution.stage-fallback): the mesh engine shards state
            # over the device mesh — a placement backend cannot apply
            raise ValueError(
                f"state.backend={self.state_backend!r} is not supported "
                "at operator parallelism > 1: mesh-sharded state is "
                "placed by the device mesh itself. Use the default "
                "'tpu-slot-table' backend, or run placement-backed "
                "state at parallelism 1 / stage-parallel subtasks "
                "(execution.stage-parallelism), where each subtask owns "
                "a single-device engine that honors the placement")

    def _table_kwargs(self):
        """(SlotTable kwargs incl. backend placement, placement) — the
        spill options plus the state backend's device commitment (one
        implementation for aligned and session windows)."""
        from flink_tpu.state.backends import resolve_placement

        placement = resolve_placement(self.state_backend)
        kwargs = dict(self.spill or {})
        if placement is not None:
            kwargs["device"] = placement
        return kwargs, placement

    def _resolve_async_fires(self, ctx) -> None:
        """Deferred fire harvesting needs both an engine that can dispatch
        async (single-device slot/pane/session engines declare
        supports_async_fires) and an executor that holds back watermarks
        while fires are in flight (ctx.async_fires)."""
        self._async_fires = bool(
            getattr(ctx, "async_fires", False)
            and getattr(self.windower, "supports_async_fires", False))
        self._max_dispatch_ahead = int(
            getattr(ctx, "max_dispatch_ahead", self._max_dispatch_ahead))
        # device watchdog (watchdog.enabled): deadline-tracked device
        # interactions + shard quarantine on the mesh engines
        wd = getattr(ctx, "watchdog", None)
        if wd is not None and hasattr(self.windower, "attach_watchdog"):
            self.windower.attach_watchdog(wd)

    def process_batch(self, batch, input_index=0):
        if self.key_field in batch.columns:
            keys = batch[self.key_field]
            if keys.dtype.kind not in "iu":
                # remember original key values for emission (dict check is
                # O(uniques) and does NOT touch the sorted fire-path
                # mirror — rebuilding that here would cost O(K log K) per
                # batch while the key space is still growing)
                self._keys_hashed = True
                kid = batch.key_ids
                uniq, first = np.unique(kid, return_index=True)
                kv = self._key_values
                for i, j in zip(uniq.tolist(), first.tolist()):
                    if i not in kv:
                        kv[i] = keys[j]
        if self.uses_processing_time:
            import time as _time

            # arrival time IS the record time in the processing-time
            # domain — a whole micro-batch arrives at one instant
            now = int(_time.time() * 1000)
            batch = batch.with_timestamps(
                np.full(len(batch), now, dtype=np.int64))
        elif not batch.has_timestamps:
            # validate where timestamps are REQUIRED (covers every
            # untimed source: raw collections, mixed unions, ...) — the
            # alternative is a bare KeyError inside the windower
            raise RuntimeError(
                f"event-time window {self.name!r} received records "
                "without timestamps — assign a WatermarkStrategy / "
                "timestamp_field on every input (or use a "
                "processing-time window)")
        self.windower.process_batch(batch)
        if self._async_fires:
            # the mesh engines fence on the engine itself (their state
            # is the sharded [P, cap] arrays, not a .table); the
            # single-device engines fence on their slot/pane table
            fence_src = getattr(self.windower, "make_fence", None)
            if fence_src is None:
                table = getattr(self.windower, "table", None)
                fence_src = getattr(table, "make_fence", None) \
                    if table is not None else None
            fence = fence_src() if fence_src is not None else None
            if fence is not None:
                self._fences.append(fence)
                while len(self._fences) > self._max_dispatch_ahead:
                    # flint: disable=TRC01 -- the depth-bounded fence
                    # drain IS the task loop's dispatch-ahead
                    # backpressure point (blocks only past the bound)
                    self._fences.popleft().block_until_ready()
        return []

    def process_watermark(self, watermark, input_index=0):
        from flink_tpu.runtime.elements import MAX_WATERMARK

        if self.uses_processing_time and watermark < MAX_WATERMARK:
            # event-time watermarks don't drive processing-time windows;
            # only the end-of-input MAX flushes what remains (reference:
            # processing-time windows fire on close at endOfInput)
            return []
        import time as _time

        from flink_tpu.runtime.pending import PendingFire

        t0 = _time.perf_counter()
        fired = self.windower.on_watermark(
            watermark, async_ok=self._async_fires) \
            if self._async_fires else self.windower.on_watermark(watermark)
        outs = []
        fired_sync = False
        for b in fired:
            if isinstance(b, PendingFire):
                self._pending.append(b)
            else:
                fired_sync = True
                outs.append(self._reattach_keys(b))
        if fired_sync:
            # one sample per watermark advance, like the async path's one
            # sample per fire-to-harvest span
            self.fire_latencies_ms.append((_time.perf_counter() - t0) * 1e3)
            self.fires_total += 1
        while len(self._pending) > self._max_pending:
            outs.extend(self._harvest_one())
        return outs

    def has_pending_output(self) -> bool:
        return bool(self._pending)

    def poll_pending_output(self, wait: bool = False):
        outs = []
        while self._pending:
            if not wait and not self._pending[0].ready():
                break
            outs.extend(self._harvest_one())
        return outs

    def _harvest_one(self) -> List[RecordBatch]:
        import time as _time

        pf = self._pending.popleft()
        batch = pf.harvest()
        # fire latency = watermark advance (dispatch) -> results on host,
        # the same span the synchronous path measures
        self.fire_latencies_ms.append(
            (_time.perf_counter() - pf.dispatched_at) * 1e3)
        self.fires_total += 1
        if batch is None or len(batch) == 0:
            return []
        return [self._reattach_keys(batch)]

    def on_processing_time(self, now_ms: int):
        if not self.uses_processing_time:
            return []
        # window [start, end) is complete once the wall clock passes end
        fired = self.windower.on_watermark(now_ms - 1)
        return [self._reattach_keys(b) for b in fired]

    def _kv_sync(self) -> None:
        """Rebuild the sorted lookup arrays iff _key_values grew (restore,
        new keys). O(K log K) per rebuild, amortized to nothing once the
        key set stabilizes."""
        if len(self._kv_ids) != len(self._key_values):
            ids = np.fromiter(self._key_values.keys(), np.int64,
                              len(self._key_values))
            order = np.argsort(ids, kind="stable")
            self._kv_ids = ids[order]
            vals = np.empty(len(ids), object)
            vals[:] = list(self._key_values.values())
            self._kv_vals = vals[order]

    def _reattach_keys(self, batch: RecordBatch) -> RecordBatch:
        kid = batch.key_ids
        if self._keys_hashed:
            # vectorized id -> value: searchsorted on the sorted mirror (no
            # per-key Python loop on the hot fire path)
            self._kv_sync()
            kidv = np.ascontiguousarray(kid, dtype=np.int64)
            if len(self._kv_ids):
                pos = np.minimum(np.searchsorted(self._kv_ids, kidv),
                                 len(self._kv_ids) - 1)
                vals = self._kv_vals[pos]
                miss = self._kv_ids[pos] != kidv
                if miss.any():
                    vals[miss] = None
            else:
                vals = np.full(len(kidv), None, object)
        else:
            vals = kid
        return batch.with_column(self.key_field, vals)

    def close(self):
        return []

    def dispose(self):
        self._pending.clear()
        self._fences.clear()
        release = getattr(self.windower, "release_memory", None)
        if release is None:
            table = getattr(self.windower, "table", None)
            release = getattr(table, "release_memory", None)
        if release is not None:
            release()

    def _check_no_pending(self) -> None:
        # the hosting executor must drain (and forward) in-flight fires
        # before a snapshot — silently dropping them here would lose fired
        # windows that the bookkeeper already marked fired
        if self._pending:
            raise RuntimeError(
                "snapshot with in-flight async fires; the executor must "
                "drain pending outputs (poll_pending_output(wait=True)) "
                "before snapshotting")

    def snapshot_state(self):
        self._check_no_pending()
        return {
            "windower": self.windower.snapshot(),
            "key_values": dict(self._key_values),
            "keys_hashed": self._keys_hashed,
        }

    def snapshot_state_delta(self):
        """Incremental variant: the keyed table ships only dirty rows +
        tombstones; host metadata (bookkeeping, key values) is small and
        written full (reference: incremental checkpoints still write fresh
        metadata, only SSTs are shared)."""
        self._check_no_pending()
        return {
            "windower": self.windower.snapshot(mode="delta"),
            "key_values": dict(self._key_values),
            "keys_hashed": self._keys_hashed,
        }

    def snapshot_state_savepoint(self):
        """Savepoint variant: full state, but keeps incremental dirty
        tracking intact — a savepoint is a side artifact and must not
        change what the next delta checkpoint contains."""
        self._check_no_pending()
        return {
            "windower": self.windower.snapshot(mode="savepoint"),
            "key_values": dict(self._key_values),
            "keys_hashed": self._keys_hashed,
        }

    def query_state(self, key_value, namespace=None):
        """Queryable-state point lookup: {window_end -> result columns} for
        one key — a batch of one (thin wrapper; every read routes through
        :meth:`query_state_batch`, so a single lookup costs the same one
        gather + one device read a batch does, never one RTT per key)."""
        return self.query_state_batch([key_value], namespace)[0]

    def query_state_batch(self, key_values, namespace=None):
        """Batched queryable-state lookup: one {window_end -> result
        columns} dict per requested key, request order — window values
        composed from per-slice partial accumulators, so sliding/
        cumulative windows return true window results, not slice
        fragments (reference: queryable state KvState lookup). The whole
        batch is served by ONE gather program + ONE device read (the
        serving-plane contract). Served on the task loop at a batch
        boundary, so reads are race-free (single-owner discipline, like
        the reference's mailbox). ``namespace`` restricts every key to
        one window end."""
        from flink_tpu.state.keygroups import hash_keys_to_i64

        key_ids = hash_keys_to_i64(np.asarray(key_values))
        w = self.windower
        if hasattr(w, "query_batch"):            # mesh engines
            outs = w.query_batch(key_ids)
        elif hasattr(w, "query_windows_batch"):  # slot-table windower
            outs = w.query_windows_batch(key_ids)
        else:                                    # pane layout: per key
            outs = [w.query_windows(int(k)) for k in key_ids]
        if namespace is not None:
            ns = int(namespace)
            outs = [({ns: out[ns]} if ns in out else {}) for out in outs]
        return outs

    def restore_state(self, state, key_group_filter=None):
        if key_group_filter is not None:
            # subtask-expansion restore: keep only this instance's key
            # groups from the (merged, logical) snapshot (reference:
            # key-group-range filtered restore on rescale)
            self.windower.restore(state["windower"],
                                  key_group_filter=key_group_filter)
        else:
            self.windower.restore(state["windower"])
        # empty sub-dicts are pruned by the checkpoint codec
        self._key_values = dict(state.get("key_values", {}))
        self._kv_ids = np.empty(0, np.int64)  # lookup mirror: force rebuild
        self._kv_vals = np.empty(0, object)
        self._keys_hashed = state.get("keys_hashed", False)

    # ------------------------------------------------------ elastic rescale

    @property
    def supports_live_rescale(self) -> bool:
        """True when the hosting engine can migrate key groups in place
        (mesh engines); False means the cold path — checkpoint-restore
        at the new parallelism (restore_state(key_group_filter=...))."""
        return hasattr(self.windower, "reshard")

    def reshard(self, new_shards: int) -> Dict[str, Any]:
        """Live rescale of the mesh engine between mesh shard counts —
        drain in-flight async fires FIRST (their device buffers
        reference the pre-reshard arrays); the hosting executor's
        _drain_pending(wait=True) boundary does exactly that."""
        if not self.supports_live_rescale:
            raise RuntimeError(
                f"operator {self.name!r} runs a single-device engine — "
                "live reshard needs the mesh engine (parallelism > 1); "
                "rescale it cold via checkpoint-restore-at-new-"
                "parallelism")
        if self._pending:
            raise RuntimeError(
                "reshard with in-flight async fires; the executor must "
                "drain pending outputs (poll_pending_output(wait=True)) "
                "before rescaling")
        # operator-held fences reference the old plane; the engine
        # drains its own dispatch fences (a superset) inside reshard
        self._fences.clear()
        return self.windower.reshard(new_shards)

    # ------------------------------------------------------ replica serving

    def arm_serving_replica(self, publish_interval_ms: float = 0.0):
        """Arm the engine's read replica (tenancy/replica.py) and return
        its serving adapter, or None when the engine cannot host one
        (single-device layouts serve through the legacy control-queue
        path). Must run on the task thread before/between batches — the
        session cluster calls it at submit/restart."""
        w = self.windower
        if not hasattr(w, "arm_replica"):
            return None
        from flink_tpu.tenancy.replica import WindowReplicaAdapter

        plane = w.arm_replica()
        plane.min_interval_s = float(publish_interval_ms) / 1e3
        return WindowReplicaAdapter(plane, w.agg, w.assigner)

    # ----------------------------------------------------- state observability

    def spill_counters(self) -> Optional[Dict[str, int]]:
        """The engine's spill traffic counters (None when the engine has
        none) — surfaced as the job metric tree's ``state`` group."""
        eng = self.windower
        fn = getattr(eng, "spill_counters", None)
        if fn is None:
            table = getattr(eng, "table", None)
            fn = getattr(table, "spill_counters", None)
        return fn() if fn is not None else None

    def shard_resident_rows(self) -> List[int]:
        """Resident rows per shard (one entry for single-device engines)."""
        eng = self.windower
        fn = getattr(eng, "shard_resident_rows", None)
        if fn is not None:
            return fn()
        table = getattr(eng, "table", None)
        index = getattr(table, "index", None)
        if index is not None:
            return [int(index.slot_used.sum())]
        return []

    def key_imbalance(self) -> float:
        """max/mean resident rows per shard (1.0 for single-device)."""
        eng = self.windower
        fn = getattr(eng, "key_imbalance", None)
        return float(fn()) if fn is not None else 1.0


class SessionWindowAggOperator(WindowAggOperator):
    """Merging session windows (reference: WindowOperator + MergingWindowSet;
    see flink_tpu.windowing.sessions for the host/device split). Shares the
    key-reattachment / latency / snapshot plumbing with WindowAggOperator;
    only the windower implementation differs."""

    name = "session_window_agg"

    def __init__(self, gap: int, agg: AggregateFunction, key_field: str,
                 capacity: int = 1 << 16, allowed_lateness: int = 0,
                 spill: dict = None, state_backend: str = "tpu-slot-table"):
        super().__init__(assigner=None, agg=agg, key_field=key_field,
                         capacity=capacity, allowed_lateness=allowed_lateness,
                         spill=spill, state_backend=state_backend)
        self.gap = gap

    def open(self, ctx):
        import jax

        from flink_tpu.windowing.sessions import SessionWindower

        effective = min(ctx.parallelism, len(jax.devices()))
        if effective > 1:
            # parallelism > 1 selects the mesh-sharded session engine —
            # session merges are shard-local (keys own their sessions), so
            # the metadata stays global and only state shards (reference:
            # keyed state locality of MergingWindowSet state)
            from flink_tpu.parallel.mesh import make_mesh
            from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

            self._reject_backend_on_mesh()
            mesh = getattr(ctx, "mesh", None) or make_mesh(effective)
            spill = dict(self.spill or {})
            self.windower = MeshSessionEngine(
                self.gap, self.agg, mesh,
                capacity_per_shard=self.capacity,
                max_parallelism=ctx.max_parallelism,
                allowed_lateness=self.allowed_lateness,
                # per-device budget, same contract as the window engine
                max_device_slots=spill.get("max_device_slots", 0),
                spill_dir=spill.get("spill_dir"),
                spill_host_max_bytes=spill.get("spill_host_max_bytes", 0),
                key_group_range=getattr(ctx, "key_group_range", None),
                memory=self._managed_memory(ctx),
                # sessions default to the paged (cohort) spill layout,
                # same as the single-device engine
                spill_layout=spill.get("spill_layout", "pages"),
                # engine-level dispatch-ahead follows the task's
                # pipeline depth (execution.pipeline.max-dispatch-batches)
                max_dispatch_ahead=getattr(ctx, "max_dispatch_ahead", 2),
                # keyBy data plane (shuffle.mode)
                shuffle_mode=getattr(ctx, "shuffle_mode", "device"),
                host_topology=_ctx_topology(ctx, mesh))
        else:
            table_kwargs, _ = self._table_kwargs()
            if self._managed_memory(ctx) is not None:
                table_kwargs["memory"] = self._managed_memory(ctx)
            self.windower = SessionWindower(
                self.gap, self.agg, capacity=self.capacity,
                max_parallelism=ctx.max_parallelism,
                allowed_lateness=self.allowed_lateness,
                spill=table_kwargs)
        self._resolve_async_fires(ctx)

    def arm_serving_replica(self, publish_interval_ms: float = 0.0):
        """Session form: the adapter composes {session_end -> columns}
        from the published (key, sid) rows' END payloads."""
        w = self.windower
        if not hasattr(w, "arm_replica"):
            return None
        from flink_tpu.tenancy.replica import SessionReplicaAdapter

        plane = w.arm_replica()
        plane.min_interval_s = float(publish_interval_ms) / 1e3
        return SessionReplicaAdapter(plane, w.agg)

    def query_state_batch(self, key_values, namespace=None):
        """Session variant: the keys' live sessions are host metadata
        ({key -> [(start, end, sid)]}); their accumulators are read
        through ONE gather + ONE device read for the whole batch. One
        {session_end -> result columns} dict per key, request order."""
        from flink_tpu.state.keygroups import hash_keys_to_i64

        key_ids = hash_keys_to_i64(np.asarray(key_values))
        w = self.windower
        if hasattr(w, "query_batch"):              # mesh engine
            outs = w.query_batch(key_ids)
        else:                                      # single-device engine
            outs = w.query_sessions_batch(key_ids)
        if namespace is not None:
            ns = int(namespace)
            outs = [({ns: out[ns]} if ns in out else {}) for out in outs]
        return outs


class UnionOperator(Operator):
    """Pass-through merge of multiple inputs; watermark = min over inputs
    (valve handled by the task wiring)."""

    name = "union"

    def __init__(self, require_consistent_time: bool = False):
        #: SQL UNION ALL sets this: its output feeds relational operators
        #: that assume event-time consistency, so a timed/untimed mix
        #: must fail HERE with the cause, not inside a window kernel.
        #: The DataStream API leaves it off — mixing is valid when
        #: nothing downstream uses event time.
        self._require_consistent_time = require_consistent_time
        self._timed: Optional[bool] = None

    def process_batch(self, batch, input_index=0):
        if self._require_consistent_time:
            timed = batch.has_timestamps
            if self._timed is None:
                self._timed = timed
            elif timed != self._timed:
                raise RuntimeError(
                    "union inputs disagree on event time: some carry "
                    "timestamps and some do not — assign timestamps on "
                    "every branch (or none)")
        return [batch]


class SinkOperator(Operator):
    """Owns the sink lifecycle: open on task start, close on drain
    (reference: Sink V2 writer lifecycle)."""

    name = "sink"

    def __init__(self, sink):
        self.sink = sink

    def open(self, ctx):
        self.sink.open(ctx.operator_index)

    def process_batch(self, batch, input_index=0):
        self.sink.write(batch)
        return []

    def snapshot_state(self):
        # sinks with writer state (e.g. KafkaSink's round-robin cursor)
        # participate in checkpoints (reference: SinkWriter state)
        snap = getattr(self.sink, "snapshot_state", None)
        return snap() if snap else None

    def restore_state(self, state, key_group_filter=None):
        restore = getattr(self.sink, "restore_state", None)
        if restore:
            restore(state)

    def close(self):
        self.sink.close()
        return []

    def dispose(self):
        self.sink.close()
