"""Watermark generation and multi-input merging.

reference: flink-core/.../eventtime/BoundedOutOfOrdernessWatermarks.java (the
standard generator) and
flink-runtime/.../streaming/runtime/watermarkstatus/StatusWatermarkValve.java
(per-channel min-merge). Batched re-design: a generator sees a whole batch's
timestamp column at once (one vectorized max), not one record at a time.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.elements import MIN_WATERMARK


from flink_tpu.core.annotations import public

class WatermarkGenerator:
    def on_batch(self, batch: RecordBatch) -> Optional[int]:
        """Observe a batch; return a new watermark value or None."""
        raise NotImplementedError


class BoundedOutOfOrdernessWatermarks(WatermarkGenerator):
    def __init__(self, max_out_of_orderness_ms: int):
        self.delay = max_out_of_orderness_ms
        self._max_ts = MIN_WATERMARK

    def on_batch(self, batch: RecordBatch) -> Optional[int]:
        if len(batch) == 0 or not batch.has_timestamps:
            return None
        m = int(batch.timestamps.max())
        if m > self._max_ts:
            self._max_ts = m
        return self._max_ts - self.delay - 1


class MonotonousTimestamps(BoundedOutOfOrdernessWatermarks):
    def __init__(self):
        super().__init__(0)


@public
@dataclasses.dataclass
class WatermarkStrategy:
    """Factory + timestamp assignment, mirroring the reference's
    WatermarkStrategy builder (flink-core/.../eventtime/WatermarkStrategy.java)."""

    generator_factory: Callable[[], WatermarkGenerator]
    timestamp_field: Optional[str] = None

    @staticmethod
    def for_bounded_out_of_orderness(ms: int) -> "WatermarkStrategy":
        return WatermarkStrategy(lambda: BoundedOutOfOrdernessWatermarks(ms))

    @staticmethod
    def for_monotonous_timestamps() -> "WatermarkStrategy":
        return WatermarkStrategy(MonotonousTimestamps)

    @staticmethod
    def no_watermarks() -> "WatermarkStrategy":
        class _Never(WatermarkGenerator):
            def on_batch(self, batch):
                return None

        return WatermarkStrategy(_Never)

    def with_timestamp_field(self, field: str) -> "WatermarkStrategy":
        return dataclasses.replace(self, timestamp_field=field)

    def create(self) -> WatermarkGenerator:
        return self.generator_factory()

    def assign_timestamps(self, batch: RecordBatch) -> RecordBatch:
        if self.timestamp_field is not None:
            return batch.with_timestamps(
                np.asarray(batch[self.timestamp_field], dtype=np.int64))
        return batch


class WatermarkValve:
    """Min-merge of per-input watermarks (reference: StatusWatermarkValve.java).

    Emits the combined watermark only when it advances. Idle channels
    (reference: WatermarkStatus.IDLE — an idle source must not hold back
    the combined watermark) are excluded from the min until they produce a
    watermark again.
    """

    def __init__(self, num_inputs: int):
        self._wms = [MIN_WATERMARK] * max(num_inputs, 1)
        self._idle = [False] * max(num_inputs, 1)
        self._combined = MIN_WATERMARK

    def advance(self, input_index: int, value: int) -> Optional[int]:
        self._idle[input_index] = False  # a watermark reactivates the channel
        if value > self._wms[input_index]:
            self._wms[input_index] = value
        return self._recompute()

    def mark_idle(self, input_index: int) -> Optional[int]:
        self._idle[input_index] = True
        return self._recompute()

    def _recompute(self) -> Optional[int]:
        active = [w for w, idle in zip(self._wms, self._idle) if not idle]
        if not active:
            return None  # all idle: hold the last combined value
        combined = min(active)
        if combined > self._combined:
            self._combined = combined
            return combined
        return None

    @property
    def combined(self) -> int:
        return self._combined
