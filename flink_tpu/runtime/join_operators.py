"""Two-input join operators.

reference: window join / coGroup
(streaming/api/datastream/JoinedStreams.java, CoGroupedStreams.java — buffer
both sides as window state, join on fire) and interval join
(streaming/api/operators/co/IntervalJoinOperator.java — per-key sorted
buffers, relative time bounds, watermark-driven cleanup).

Batched re-design: sides are buffered as columnar batches per *slice* on the
host (joins are data movement, not arithmetic — NumPy's sort-join is the
right tool; the device stays busy with the aggregation operators). Window
lifecycle (pending windows, retention, cleanup) reuses SliceBookkeeper.
Equality join uses a vectorized sort + searchsorted matcher.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.operators import Operator
from flink_tpu.windowing.assigners import WindowAssigner
from flink_tpu.windowing.bookkeeping import SliceBookkeeper
from flink_tpu.windowing.windower import WINDOW_END_FIELD, WINDOW_START_FIELD


def equi_join_indices(left_keys: np.ndarray, right_keys: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """All (i, j) with left_keys[i] == right_keys[j], vectorized."""
    if len(left_keys) == 0 or len(right_keys) == 0:
        e = np.empty(0, dtype=np.int64)
        return e, e.copy()
    order_r = np.argsort(right_keys, kind="stable")
    rs = right_keys[order_r]
    lo = np.searchsorted(rs, left_keys, side="left")
    hi = np.searchsorted(rs, left_keys, side="right")
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        e = np.empty(0, dtype=np.int64)
        return e, e.copy()
    l_idx = np.repeat(np.arange(len(left_keys), dtype=np.int64), counts)
    # per-match offset within each left row's range
    starts = np.repeat(lo, counts)
    within = np.arange(total, dtype=np.int64) - np.repeat(
        np.cumsum(counts) - counts, counts)
    r_idx = order_r[starts + within]
    return l_idx, r_idx


def _filter_by_key_groups(cols: Dict[str, np.ndarray], key_group_filter,
                          max_parallelism: int) -> Dict[str, np.ndarray]:
    """Keep only rows whose key belongs to the owned key groups — the
    key-group-range-scoped restore of buffered join state (reference:
    keyed state restore is key-group scoped; join buffers are keyed
    state)."""
    from flink_tpu.state.keygroups import assign_key_groups

    kid = np.asarray(cols[KEY_ID_FIELD], dtype=np.int64)
    groups = assign_key_groups(kid, max_parallelism)
    keep = np.isin(groups, np.fromiter(key_group_filter, dtype=np.int32,
                                       count=len(key_group_filter)))
    return {k: np.asarray(v)[keep] for k, v in cols.items()}


def _merge_columns(left: RecordBatch, right: RecordBatch,
                   l_idx: np.ndarray, r_idx: np.ndarray,
                   suffixes=("_l", "_r")) -> Dict[str, np.ndarray]:
    cols: Dict[str, np.ndarray] = {}
    lcols = {k: v[l_idx] for k, v in left.columns.items()}
    rcols = {k: v[r_idx] for k, v in right.columns.items()}
    for k, v in lcols.items():
        if k in rcols and k not in (KEY_ID_FIELD,):
            cols[k + suffixes[0]] = v
        else:
            cols[k] = v
    for k, v in rcols.items():
        if k in lcols:
            if k == KEY_ID_FIELD:
                continue
            cols[k + suffixes[1]] = v
        else:
            cols[k] = v
    return cols


class WindowJoinOperator(Operator):
    """INNER equi-join of two keyed streams per window."""

    name = "window_join"

    def __init__(self, assigner: WindowAssigner, suffixes=("_l", "_r"),
                 key_fields: Optional[Tuple[str, str]] = None):
        self.assigner = assigner
        self.suffixes = suffixes
        self.key_fields = key_fields
        self.book = SliceBookkeeper(assigner)
        # slice_end -> [left batches], [right batches]
        self._buf: Dict[int, Tuple[List[RecordBatch], List[RecordBatch]]] = {}
        self._max_parallelism = 128

    def open(self, ctx):
        self._max_parallelism = getattr(ctx, "max_parallelism", 128)

    def process_batch(self, batch, input_index=0):
        if len(batch) == 0:
            return []
        slice_ends = self.assigner.assign_slice_ends(batch.timestamps)
        live = self.book.live_mask(slice_ends)
        if live is not None:
            slice_ends = slice_ends[live]
            batch = batch.filter(live)
            if len(batch) == 0:
                return []
        self.book.register_slices(slice_ends)
        # split batch by slice
        order = np.argsort(slice_ends, kind="stable")
        se_sorted = slice_ends[order]
        boundaries = np.nonzero(np.diff(se_sorted))[0] + 1
        idx_chunks = np.split(order, boundaries)
        firsts = np.concatenate(([0], boundaries))
        for se, idxs in zip(se_sorted[firsts].tolist(), idx_chunks):
            sides = self._buf.setdefault(se, ([], []))
            sides[input_index].append(batch.take(idxs))
        return []

    def process_watermark(self, watermark, input_index=0):
        out: List[RecordBatch] = []
        while True:
            w_end = self.book.next_window(watermark)
            if w_end is None:
                break
            b = self._fire(w_end)
            if b is not None and len(b):
                out.append(b)
            self.book.mark_fired(w_end)
        for se in self.book.expired_slices(watermark):
            self._buf.pop(se, None)
        return out

    def _fire(self, window_end: int) -> Optional[RecordBatch]:
        lefts: List[RecordBatch] = []
        rights: List[RecordBatch] = []
        for se in self.assigner.slice_ends_for_window(window_end):
            sides = self._buf.get(se)
            if sides:
                lefts.extend(sides[0])
                rights.extend(sides[1])
        if not lefts or not rights:
            return None
        left = RecordBatch.concat(lefts)
        right = RecordBatch.concat(rights)
        l_idx, r_idx = equi_join_indices(left.key_ids, right.key_ids)
        if len(l_idx) == 0:
            return None
        # the window's own timestamp replaces the per-record ones; an
        # identically-named join key stays a single unsuffixed column
        left = left.drop(TIMESTAMP_FIELD)
        right = right.drop(TIMESTAMP_FIELD)
        if self.key_fields and self.key_fields[0] == self.key_fields[1]:
            right = right.drop(self.key_fields[1])
        cols = _merge_columns(left, right, l_idx, r_idx, self.suffixes)
        m = len(l_idx)
        cols[WINDOW_START_FIELD] = np.full(
            m, self.assigner.window_start(window_end), dtype=np.int64)
        cols[WINDOW_END_FIELD] = np.full(m, window_end, dtype=np.int64)
        cols[TIMESTAMP_FIELD] = np.full(m, window_end - 1, dtype=np.int64)
        return RecordBatch(cols)

    def snapshot_state(self):
        return {
            "book": self.book.snapshot(),
            "buf": {
                se: ([dict(b.columns) for b in l], [dict(b.columns) for b in r])
                for se, (l, r) in self._buf.items()
            },
        }

    def restore_state(self, state, key_group_filter=None):
        self.book.restore(state["book"])
        buf = state.get("buf", {})
        if key_group_filter is not None:
            buf = {
                se: ([_filter_by_key_groups(c, key_group_filter,
                                            self._max_parallelism)
                      for c in l],
                     [_filter_by_key_groups(c, key_group_filter,
                                            self._max_parallelism)
                      for c in r])
                for se, (l, r) in buf.items()
            }
        self._buf = {
            se: ([RecordBatch(c) for c in l], [RecordBatch(c) for c in r])
            for se, (l, r) in buf.items()
        }


class IntervalJoinOperator(Operator):
    """Keyed interval join: left at t matches right in [t+lower, t+upper].

    reference: streaming/api/operators/co/IntervalJoinOperator.java —
    re-designed over columnar side buffers pruned by watermark instead of
    per-key MapState buckets + per-record timers.

    ``left_outer`` (LEFT JOIN): a left row whose interval expires without
    any match emits once, null-padded on the right, at the moment the
    watermark proves no match can still arrive (t + upper) — the
    reference's outer interval-join semantics. ``right_columns`` names
    the right schema so null-padded rows keep one stable shape even
    before any right row is seen."""

    name = "interval_join"

    def __init__(self, lower: int, upper: int, suffixes=("_l", "_r"),
                 left_outer: bool = False,
                 right_columns: Optional[List[str]] = None):
        assert lower <= upper
        self.lower = lower
        self.upper = upper
        self.suffixes = suffixes
        self.left_outer = left_outer
        if left_outer and right_columns is None:
            raise ValueError(
                "LEFT interval join needs the right-side column names "
                "(null padding must have a stable schema)")
        self.right_columns = list(right_columns) if right_columns \
            else None
        self._left: List[RecordBatch] = []
        #: per-buffered-left-row "has matched" flags, parallel to the
        #: concatenation of self._left (only maintained when left_outer)
        self._left_matched: List[np.ndarray] = []
        self._right: List[RecordBatch] = []
        #: right column -> observed dtype (set at the first right batch;
        #: drives type-correct null padding in _pad_unmatched)
        self._right_dtypes: Dict[str, np.dtype] = {}
        self._max_parallelism = 128

    def _observe_right(self, batch: RecordBatch) -> RecordBatch:
        """First-right-batch schema contract (LEFT JOIN only).

        The declared ``right_columns`` drive the null-padded schema, so
        a drift between declaration and the actual right batches would
        silently give matched and padded rows different schemas — raise
        instead. Integer/bool right columns are coerced to float64 at
        the buffering boundary: SQL NULL has no integer representation
        in a dense column, so BOTH matched and padded emissions carry
        float64 (one schema), rather than int in matched and float-NaN
        in padded."""
        if not self.left_outer:
            return batch
        observed = [c for c in batch.names()
                    if c not in (KEY_ID_FIELD, TIMESTAMP_FIELD)]
        declared = [c for c in self.right_columns
                    if c not in (KEY_ID_FIELD, TIMESTAMP_FIELD)]
        if set(observed) != set(declared):
            raise RuntimeError(
                "LEFT interval join: declared right columns "
                f"{sorted(declared)} != right batch columns "
                f"{sorted(observed)} — null padding would produce "
                "a different schema than matches")
        cols = dict(batch.columns)
        for c in observed:
            v = np.asarray(cols[c])
            if v.dtype.kind in "iub":
                # float64 only round-trips integers up to 2^53 — larger
                # values (snowflake-style IDs) must go through object
                # dtype or they'd be silently rounded. Compare in the
                # ORIGINAL dtype: casting uint64 >= 2^63 to int64 first
                # would wrap and sneak past the guard.
                big = (v.dtype.itemsize >= 8 and len(v)
                       and (int(v.max()) > (1 << 53)
                            or int(v.min()) < -(1 << 53)))
                v = v.astype(object) if big else v.astype(np.float64)
            elif v.dtype.kind in "US":
                # fixed-width numpy strings can't hold a None pad —
                # carry strings as object so NULL is representable
                v = v.astype(object)
            cols[c] = v
            prev = self._right_dtypes.setdefault(c, v.dtype)
            if prev != v.dtype:
                raise RuntimeError(
                    f"LEFT interval join: right column {c!r} changed "
                    f"carry dtype across batches ({prev} -> {v.dtype}; "
                    "an int64 value above 2^53 arrived after the column "
                    "was established as float64) — emitted schemas "
                    "would diverge")
        return RecordBatch(cols)

    def open(self, ctx):
        self._max_parallelism = getattr(ctx, "max_parallelism", 128)

    def process_batch(self, batch, input_index=0):
        if len(batch) == 0:
            return []
        out = []
        if input_index == 0:
            matches, l_hit = self._join(
                batch, RecordBatch.concat(self._right), left_is_new=True)
            self._left.append(batch)
            if self.left_outer:
                flags = np.zeros(len(batch), dtype=bool)
                if l_hit is not None:
                    flags[l_hit] = True
                self._left_matched.append(flags)
        else:
            batch = self._observe_right(batch)
            matches, l_hit = self._join(
                RecordBatch.concat(self._left), batch, left_is_new=False)
            self._right.append(batch)
            if self.left_outer and l_hit is not None and \
                    len(self._left_matched):
                merged = (self._left_matched[0]
                          if len(self._left_matched) == 1
                          else np.concatenate(self._left_matched))
                merged[l_hit] = True
                self._left_matched = [merged]
        if matches is not None and len(matches):
            out.append(matches)
        return out

    def _join(self, left: RecordBatch, right: RecordBatch,
              left_is_new: bool):
        """(matched batch or None, matching LEFT row indices or None)."""
        if len(left) == 0 or len(right) == 0:
            return None, None
        l_idx, r_idx = equi_join_indices(left.key_ids, right.key_ids)
        if len(l_idx) == 0:
            return None, None
        lt = left.timestamps[l_idx]
        rt = right.timestamps[r_idx]
        ok = (rt >= lt + self.lower) & (rt <= lt + self.upper)
        # each side's raw timestamp column must not survive into the merged
        # schema (it would come out as suffixed __ts___l/__ts___r junk); the
        # result's timestamp is computed below from lt/rt
        left = left.drop(TIMESTAMP_FIELD)
        right = right.drop(TIMESTAMP_FIELD)
        # (duplicate avoidance is structural: a pair is emitted by whichever
        # side arrives second — the new batch is joined only against the
        # other side's buffer, never its own)
        l_idx, r_idx = l_idx[ok], r_idx[ok]
        if len(l_idx) == 0:
            return None, None
        cols = _merge_columns(left, right, l_idx, r_idx, self.suffixes)
        cols[TIMESTAMP_FIELD] = np.maximum(lt[ok], rt[ok])
        return RecordBatch(cols), l_idx

    def _pad_unmatched(self, rows: RecordBatch) -> RecordBatch:
        """Null-extend expired unmatched left rows with the SAME column
        naming _merge_columns produces for matches."""
        lts = rows.timestamps
        left_b = rows.drop(TIMESTAMP_FIELD)
        rset = set(self.right_columns)
        cols: Dict[str, np.ndarray] = {}
        for k, v in left_b.columns.items():
            if k in rset and k != KEY_ID_FIELD:
                cols[k + self.suffixes[0]] = v
            else:
                cols[k] = v
        n = len(rows)
        for k in self.right_columns:
            if k in (KEY_ID_FIELD, TIMESTAMP_FIELD):
                continue
            name = k + self.suffixes[1] if k in left_b.columns else k
            dt = self._right_dtypes.get(k)
            if dt is not None and dt.kind in "OUS":
                # string/object right column: SQL NULL is None, not NaN
                cols[name] = np.full(n, None, dtype=object)
            else:
                cols[name] = np.full(n, np.nan,
                                     dtype=dt if dt is not None
                                     else np.float64)
        cols[TIMESTAMP_FIELD] = lts
        return RecordBatch(cols)

    def process_watermark(self, watermark, input_index=0):
        # prune buffers: left rows can only match right in
        # [t+lower, t+upper]; once watermark passes t+upper the left row
        # is dead (and symmetrically for right). A dead UNMATCHED left
        # row is exactly when LEFT JOIN null-extends.
        out: List[RecordBatch] = []
        min_left_ts = watermark - self.upper
        if self.left_outer and self._left:
            merged = RecordBatch.concat(self._left)
            matched = (self._left_matched[0]
                       if len(self._left_matched) == 1
                       else np.concatenate(self._left_matched)) \
                if self._left_matched else np.zeros(len(merged), bool)
            dead = merged.timestamps < min_left_ts
            expired = dead & ~matched
            if expired.any():
                out.append(self._pad_unmatched(merged.filter(expired)))
            keep = ~dead
            self._left = [merged.filter(keep)] if keep.any() else []
            self._left_matched = [matched[keep]] if keep.any() else []
        else:
            self._left = self._prune(self._left, min_left_ts)
        self._right = self._prune(self._right, watermark + self.lower)
        return out

    def close(self):
        from flink_tpu.runtime.elements import MAX_WATERMARK

        # end of input: every buffered left row's interval has expired
        return self.process_watermark(MAX_WATERMARK)

    @staticmethod
    def _prune(batches: List[RecordBatch], min_ts: int) -> List[RecordBatch]:
        if not batches:
            return batches
        merged = RecordBatch.concat(batches)
        if len(merged) == 0:
            return []
        keep = merged.timestamps >= min_ts
        if keep.all():
            return [merged]
        return [merged.filter(keep)]

    def snapshot_state(self):
        snap = {
            "left": [dict(b.columns) for b in self._left],
            "right": [dict(b.columns) for b in self._right],
        }
        if self.left_outer:
            # ONE flags array aligned to the CONCATENATION of the left
            # buffers — a right-side match merges the per-batch arrays,
            # so batch-parallel storage would misalign on restore
            if self._left_matched:
                snap["ij_matched"] = np.concatenate(
                    [np.asarray(m) for m in self._left_matched])
            else:
                snap["ij_matched"] = np.zeros(
                    sum(len(b) for b in self._left), dtype=bool)
            # padding dtypes must survive a restore even when the right
            # buffer was pruned empty — else a post-restore pad of a
            # string column would fall back to float NaN
            snap["ij_right_dtypes"] = {
                k: str(v) for k, v in self._right_dtypes.items()}
        return snap

    def restore_state(self, state, key_group_filter=None):
        left = state.get("left", [])
        right = state.get("right", [])
        if self.left_outer and left:
            # normalize the left side to ONE batch + one flags array so
            # the key-group filter applies to both identically
            merged = RecordBatch.concat([RecordBatch(
                {k: np.asarray(v) for k, v in c.items()}) for c in left])
            matched = np.asarray(
                state.get("ij_matched",
                          np.zeros(len(merged), dtype=bool)), dtype=bool)
            if key_group_filter is not None:
                from flink_tpu.state.keygroups import assign_key_groups

                kid = np.asarray(merged.key_ids, dtype=np.int64)
                groups = assign_key_groups(kid, self._max_parallelism)
                keep = np.isin(groups,
                               np.asarray(sorted(key_group_filter)))
                merged = merged.filter(keep)
                matched = matched[keep]
            self._left = [merged] if len(merged) else []
            self._left_matched = [matched] if len(merged) else []
        else:
            if key_group_filter is not None:
                left = [_filter_by_key_groups(c, key_group_filter,
                                              self._max_parallelism)
                        for c in left]
            self._left = [RecordBatch(c) for c in left]
            if self.left_outer:
                self._left_matched = [np.zeros(len(b), dtype=bool)
                                      for b in self._left]
        if key_group_filter is not None:
            right = [_filter_by_key_groups(c, key_group_filter,
                                           self._max_parallelism)
                     for c in right]
        self._right = [RecordBatch(c) for c in right]
        self._right_dtypes = {
            k: np.dtype(v)
            for k, v in state.get("ij_right_dtypes", {}).items()}


class TemporalJoinOperator(Operator):
    """Event-time temporal join: each left row joins the RIGHT VERSION
    valid at the left row's event time.

    reference: flink-table/flink-table-runtime/.../operators/join/temporal/
    TemporalRowTimeJoinOperator.java (and the planner's
    StreamExecTemporalJoin) — the right input is a versioned stream keyed
    by the join key, versioned by its rowtime; a left row at t matches
    the latest right version with version_ts <= t. Correctness needs
    version completeness, so left rows wait for the COMBINED watermark
    (the valve min across both inputs) before joining; late left rows
    drop.

    Re-design: per watermark advance, ready left rows sort once by
    (key, ts) and each key segment binary-searches its version history
    (columnar, sorted) — no per-row state lookups. Version state
    compacts to {versions newer than the watermark} + {the single
    latest version at-or-before it} per key, the reference's
    cleanupState contract.
    """

    name = "temporal_join"

    def __init__(self, suffixes=("_l", "_r")):
        self.suffixes = suffixes
        self._left: List[RecordBatch] = []
        self._versions: List[RecordBatch] = []
        self._max_parallelism = 128
        self.late_left_dropped = 0
        self._emitted_wm = -(1 << 62)

    def open(self, ctx):
        self._max_parallelism = getattr(ctx, "max_parallelism", 128)

    def process_batch(self, batch, input_index=0):
        if len(batch) == 0:
            return []
        if input_index == 0:
            late = batch.timestamps <= self._emitted_wm
            if late.any():
                self.late_left_dropped += int(late.sum())
                batch = batch.filter(~late)
            if len(batch):
                self._left.append(batch)
        else:
            self._versions.append(batch)
        return []

    def process_watermark(self, watermark, input_index=0):
        self._emitted_wm = max(self._emitted_wm, watermark)
        if not self._left:
            self._compact(watermark)
            return []
        left = RecordBatch.concat(self._left)
        ready_mask = left.timestamps <= watermark
        self._left = [left.filter(~ready_mask)] \
            if (~ready_mask).any() else []
        ready = left.filter(ready_mask)
        if len(ready) == 0:
            self._compact(watermark)
            return []
        out = self._join(ready)
        self._compact(watermark)
        return [out] if out is not None and len(out) else []

    def close(self):
        from flink_tpu.runtime.elements import MAX_WATERMARK

        return self.process_watermark(MAX_WATERMARK)

    def _sorted_versions(self):
        if not self._versions:
            return None
        v = RecordBatch.concat(self._versions)
        if len(v) == 0:
            return None
        order = np.lexsort((v.timestamps, v.key_ids))
        v = v.take(order)
        self._versions = [v]
        return v

    def _join(self, ready: RecordBatch) -> Optional[RecordBatch]:
        v = self._sorted_versions()
        if v is None:
            return None
        order = np.lexsort((ready.timestamps, ready.key_ids))
        ready = ready.take(order)
        lk, lt = ready.key_ids, ready.timestamps
        vk, vt = v.key_ids, v.timestamps
        # per-key version segment for every ready row (both sides sorted
        # by key, so one vectorized searchsorted each)
        lo = np.searchsorted(vk, lk, side="left")
        hi = np.searchsorted(vk, lk, side="right")
        pick = np.full(len(ready), -1, dtype=np.int64)
        # binary-search each key segment once for all its ready rows
        starts = np.flatnonzero(np.r_[True, lk[1:] != lk[:-1]])
        bounds = np.r_[starts, len(lk)]
        for s in range(len(bounds) - 1):
            a, b = bounds[s], bounds[s + 1]
            if lo[a] >= hi[a]:
                continue  # no versions for this key
            seg = vt[lo[a]:hi[a]]
            pos = np.searchsorted(seg, lt[a:b], side="right") - 1
            ok = pos >= 0
            pick[a:b][ok] = lo[a] + pos[ok]
        matched = pick >= 0
        l_idx = np.flatnonzero(matched)
        r_idx = pick[matched]
        if len(l_idx) == 0:
            return None  # INNER: left rows with no valid version drop
        lts = lt[l_idx]
        cols = _merge_columns(ready.drop(TIMESTAMP_FIELD),
                              v.drop(TIMESTAMP_FIELD),
                              l_idx, r_idx, self.suffixes)
        cols[TIMESTAMP_FIELD] = lts
        return RecordBatch(cols)

    def _compact(self, watermark: int) -> None:
        """Keep versions newer than the watermark plus each key's single
        latest version at-or-before it (any future left row joins one of
        those)."""
        v = self._sorted_versions()
        if v is None:
            return
        vk, vt = v.key_ids, v.timestamps
        future = vt > watermark
        # latest at-or-before per key: the last index of each key's
        # prefix segment (vt sorted within key)
        is_last_of_prefix = np.r_[
            (vk[1:] != vk[:-1]) | future[1:], True] & ~future
        keep = future | is_last_of_prefix
        if not keep.all():
            self._versions = [v.filter(keep)]

    def snapshot_state(self):
        return {
            "left": [dict(b.columns) for b in self._left],
            "tj_versions": [dict(b.columns) for b in self._versions],
            "max_ts": self._emitted_wm,
        }

    def restore_state(self, state, key_group_filter=None):
        def rebuild(cols_list):
            out = []
            for cols in cols_list:
                cols = {k: np.asarray(c) for k, c in cols.items()}
                if key_group_filter is not None:
                    cols = _filter_by_key_groups(
                        cols, key_group_filter, self._max_parallelism)
                out.append(RecordBatch(cols))
            return out

        self._left = rebuild(state.get("left", []))
        self._versions = rebuild(state.get("tj_versions", []))
        self._emitted_wm = state.get("max_ts", -(1 << 62))
