"""OVER windowed aggregation — per-row frames over an event-time order.

reference: StreamExecOverAggregate
(flink-table/flink-table-planner/.../stream/StreamExecOverAggregate.java)
lowering to the flink-table-runtime over-window functions:
RowTimeRowsBoundedPrecedingFunction (ROWS BETWEEN n PRECEDING),
RowTimeRangeBoundedPrecedingFunction (RANGE BETWEEN INTERVAL ... PRECEDING)
and RowTimeRangeUnboundedPrecedingFunction — each buffers rows per key
until the watermark passes their timestamp, then emits every input row
extended with aggregates over its frame.

Re-design: rows buffer in columnar batches; a watermark advance sorts the
ready rows ONCE by (key, rowtime) and computes every frame with
vectorized prefix scans per key segment (cumulative sums for SUM/COUNT/
AVG, per-segment accumulate/sliding windows for MIN/MAX) instead of the
reference's per-row state lookups. Frame context that future rows still
need — the last ``n`` rows (ROWS), rows within the interval (RANGE), or
a running accumulator (UNBOUNDED) — carries over per key.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.elements import MAX_WATERMARK
from flink_tpu.runtime.operators import Operator

#: (func, arg_field or None for COUNT(*), output name)
OverSpec = Tuple[str, Optional[str], str]


def _seg_bounds(kid: np.ndarray) -> np.ndarray:
    """Start index of each key segment in a (key-sorted) array, plus the
    end sentinel."""
    n = len(kid)
    starts = np.flatnonzero(np.r_[True, kid[1:] != kid[:-1]])
    return np.r_[starts, n]


class OverAggOperator(Operator):
    """Event-time OVER aggregation, partitioned by ``key_field``."""

    name = "over_agg"

    def __init__(self, key_field: str, specs: List[OverSpec],
                 mode: str = "ROWS", preceding: Optional[int] = None):
        if mode not in ("ROWS", "RANGE"):
            raise ValueError(f"unsupported OVER mode {mode!r}")
        self.key_field = key_field
        self.specs = list(specs)
        self.mode = mode
        self.preceding = preceding
        #: buffered not-yet-ready batches
        self._pending: List[RecordBatch] = []
        #: ROWS/RANGE: per-key context rows (already emitted, still in
        #: frame reach): kid -> {"ts": array, spec index -> value array}
        self._context: Dict[int, Dict[str, np.ndarray]] = {}
        #: UNBOUNDED: kid -> per-spec accumulator tuples
        self._accs: Dict[int, List[Tuple[float, float]]] = {}
        self._emitted_wm = -(1 << 62)
        self.late_records_dropped = 0

    def open(self, ctx) -> None:
        self.max_parallelism = getattr(ctx, "max_parallelism", 128)

    # ------------------------------------------------------------- ingest

    def process_batch(self, batch: RecordBatch,
                      input_index: int = 0) -> List[RecordBatch]:
        if len(batch) == 0:
            return []
        if not batch.has_timestamps:
            raise RuntimeError(
                "OVER aggregation requires event-time rows (ORDER BY "
                "rowtime) — assign a watermark strategy")
        late = batch.timestamps <= self._emitted_wm
        if late.any():
            self.late_records_dropped += int(late.sum())
            batch = batch.filter(~late)
            if len(batch) == 0:
                return []
        self._pending.append(batch)
        return []

    # -------------------------------------------------------------- fire

    def process_watermark(self, watermark, input_index=0):
        if not self._pending:
            self._emitted_wm = max(self._emitted_wm, watermark)
            return []
        buf = RecordBatch.concat(self._pending)
        ready_mask = buf.timestamps <= watermark
        self._pending = ([buf.filter(~ready_mask)]
                         if (~ready_mask).any() else [])
        ready = buf.filter(ready_mask)
        self._emitted_wm = max(self._emitted_wm, watermark)
        if len(ready) == 0:
            return []
        out = self._compute(ready)
        return [out] if out is not None and len(out) else []

    def close(self) -> List[RecordBatch]:
        return self.process_watermark(MAX_WATERMARK)

    # ------------------------------------------------------------ compute

    def _key_ids(self, batch: RecordBatch) -> np.ndarray:
        if KEY_ID_FIELD in batch.columns:
            return np.asarray(batch[KEY_ID_FIELD], dtype=np.int64)
        from flink_tpu.state.keygroups import hash_keys_to_i64

        return hash_keys_to_i64(batch[self.key_field])

    def _arg_values(self, batch: RecordBatch, n: int) -> List[np.ndarray]:
        vals = []
        for func, field, _ in self.specs:
            if field is None:
                vals.append(np.ones(n, dtype=np.float64))
            else:
                vals.append(np.asarray(batch[field], dtype=np.float64))
        return vals

    def _compute(self, ready: RecordBatch) -> Optional[RecordBatch]:
        n = len(ready)
        kid = self._key_ids(ready)
        ts = np.asarray(ready.timestamps, dtype=np.int64)
        order = np.lexsort((ts, kid))
        ready = ready.take(order)
        kid, ts = kid[order], ts[order]
        vals = self._arg_values(ready, n)
        if self.preceding is None:
            # UNBOUNDED PRECEDING; RANGE includes rowtime peers, ROWS
            # counts physical rows only
            outs = self._compute_unbounded(
                kid, ts, vals, peers=self.mode == "RANGE")
        else:
            outs = self._compute_bounded(kid, ts, vals)
        out = ready
        for (_, _, out_name), col in zip(self.specs, outs):
            out = out.with_column(out_name, col)
        return out

    # -- UNBOUNDED PRECEDING: running accumulators per key

    def _compute_unbounded(self, kid, ts, vals,
                           peers: bool = True) -> List[np.ndarray]:
        bounds = _seg_bounds(kid)
        outs = [np.empty(len(kid), dtype=np.float64)
                for _ in self.specs]
        for s in range(len(bounds) - 1):
            lo, hi = bounds[s], bounds[s + 1]
            k = int(kid[lo])
            seg_ts = ts[lo:hi]
            # SQL RANGE frames include the current row's PEERS (equal
            # rowtime): every row takes the value at its peer group's
            # last row (reference: RowTimeRangeUnboundedPrecedingFunction
            # aggregates per-timestamp groups); ROWS frames end at the
            # current physical row
            peer_last = (np.searchsorted(seg_ts, seg_ts, side="right")
                         - 1) if peers \
                else np.arange(hi - lo)
            accs = self._accs.get(k)
            if accs is None:
                accs = [(0.0, 0.0)] * len(self.specs)
            new_accs = []
            for i, (func, _, _) in enumerate(self.specs):
                seg = vals[i][lo:hi]
                a_sum, a_cnt = accs[i]
                if func in ("SUM", "AVG", "COUNT"):
                    cs = np.cumsum(seg) + a_sum
                    cn = np.arange(1, hi - lo + 1, dtype=np.float64) \
                        + a_cnt
                    row = (cs if func == "SUM"
                           else cn if func == "COUNT"
                           else cs / cn)
                    outs[i][lo:hi] = row[peer_last]
                    new_accs.append((float(cs[-1]), float(cn[-1])))
                elif func == "MIN":
                    init = a_sum if a_cnt else np.inf
                    acc = np.minimum.accumulate(np.minimum(seg, init))
                    outs[i][lo:hi] = acc[peer_last]
                    new_accs.append((float(acc[-1]), 1.0))
                else:  # MAX
                    init = a_sum if a_cnt else -np.inf
                    acc = np.maximum.accumulate(np.maximum(seg, init))
                    outs[i][lo:hi] = acc[peer_last]
                    new_accs.append((float(acc[-1]), 1.0))
            self._accs[k] = new_accs
        return outs

    # -- ROWS n / RANGE interval PRECEDING: context rows per key

    def _compute_bounded(self, kid, ts, vals) -> List[np.ndarray]:
        bounds = _seg_bounds(kid)
        outs = [np.empty(len(kid), dtype=np.float64)
                for _ in self.specs]
        for s in range(len(bounds) - 1):
            lo, hi = bounds[s], bounds[s + 1]
            k = int(kid[lo])
            ctx = self._context.get(k)
            c = 0 if ctx is None else len(ctx["ts"])
            seg_ts = (ts[lo:hi] if c == 0
                      else np.concatenate([ctx["ts"], ts[lo:hi]]))
            m = len(seg_ts)
            # frame [start, end) for each NEW row (positions c..m-1):
            # ROWS counts physical rows; RANGE is timestamp-bounded and
            # includes the current row's PEERS (equal rowtime — SQL
            # frame semantics, reference:
            # RowTimeRangeBoundedPrecedingFunction)
            pos = np.arange(c, m)
            if self.mode == "ROWS":
                starts = np.maximum(pos - self.preceding, 0)
                ends = pos + 1
            else:
                starts = np.searchsorted(
                    seg_ts, seg_ts[c:] - self.preceding, side="left")
                ends = np.searchsorted(seg_ts, seg_ts[c:], side="right")
            segs = [vals[i][lo:hi] if c == 0 else np.concatenate(
                [ctx[f"v{i}"], vals[i][lo:hi]])
                for i in range(len(self.specs))]
            for i, (func, _, _) in enumerate(self.specs):
                seg = segs[i]
                if func in ("SUM", "AVG", "COUNT"):
                    cs = np.r_[0.0, np.cumsum(seg)]
                    tot = cs[ends] - cs[starts]
                    cnt = (ends - starts).astype(np.float64)
                    outs[i][lo:hi] = (tot if func == "SUM"
                                      else cnt if func == "COUNT"
                                      else tot / cnt)
                else:
                    red = np.minimum if func == "MIN" else np.maximum
                    ident = np.inf if func == "MIN" else -np.inf
                    # per-row reduce over [starts[j], ends[j]); reduceat
                    # on interleaved boundaries does all frames in one
                    # pass. A sentinel identity element keeps every
                    # index < len (ends may equal m), and start == end
                    # cannot occur (a frame always holds its own row).
                    seg_p = np.r_[seg, ident]
                    idx = np.empty(2 * len(pos), dtype=np.int64)
                    idx[0::2] = starts
                    idx[1::2] = ends
                    outs[i][lo:hi] = red.reduceat(seg_p, idx)[0::2]
            # retain context for future rows of this key
            if self.mode == "ROWS":
                keep_from = max(m - self.preceding, 0)
            else:
                keep_from = int(np.searchsorted(
                    seg_ts, seg_ts[-1] - self.preceding, side="left"))
            new_ctx = {"ts": seg_ts[keep_from:]}
            for i, seg in enumerate(segs):
                new_ctx[f"v{i}"] = seg[keep_from:]
            if len(new_ctx["ts"]):
                self._context[k] = new_ctx
            else:
                self._context.pop(k, None)
        return outs

    # --------------------------------------------------------------- state

    def snapshot_state(self) -> Dict[str, Any]:
        pending = (RecordBatch.concat(self._pending).to_pydict()
                   if self._pending else None)
        return {
            "over_pending": pending,
            "over_context": {str(k): {kk: np.asarray(v)
                                      for kk, v in ctx.items()}
                             for k, ctx in self._context.items()},
            "over_accs": {str(k): [list(a) for a in accs]
                          for k, accs in self._accs.items()},
            "over_emitted_wm": self._emitted_wm,
        }

    def restore_state(self, state: Dict[str, Any],
                      key_group_filter=None) -> None:
        from flink_tpu.state.keygroups import assign_key_groups

        def _keep(kid_int: int) -> bool:
            if key_group_filter is None:
                return True
            g = assign_key_groups(
                np.asarray([kid_int], dtype=np.int64),
                self.max_parallelism)[0]
            return g in key_group_filter

        pending = state.get("over_pending")
        self._pending = []
        if pending:
            batch = RecordBatch.from_pydict(
                {k: np.asarray(v) for k, v in pending.items()
                 if k != TIMESTAMP_FIELD},
                timestamps=np.asarray(pending[TIMESTAMP_FIELD])
                if TIMESTAMP_FIELD in pending else None)
            if key_group_filter is not None and len(batch):
                kid = self._key_ids(batch)
                groups = assign_key_groups(kid, self.max_parallelism)
                mask = np.isin(groups,
                               np.asarray(sorted(key_group_filter)))
                batch = batch.filter(mask)
            if len(batch):
                self._pending = [batch]
        self._context = {
            int(k): {kk: np.asarray(v) for kk, v in ctx.items()}
            for k, ctx in state.get("over_context", {}).items()
            if _keep(int(k))}
        self._accs = {
            int(k): [tuple(a) for a in accs]
            for k, accs in state.get("over_accs", {}).items()
            if _keep(int(k))}
        self._emitted_wm = state.get("over_emitted_wm", -(1 << 62))
