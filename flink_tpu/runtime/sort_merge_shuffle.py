"""Sort-merge (blocking, file-backed) shuffle — the batch data plane.

reference: flink-runtime/.../io/network/partition/SortMergeResultPartition.java:1
— for high-parallelism batch jobs the pipelined per-subpartition buffers
are replaced by ONE spill file per producer partition: records buffer in
memory, sort by subpartition when the budget fills, and append as a
*region* whose per-subpartition byte ranges go into an index
(PartitionedFileWriter). Consumers read their subpartition's ranges
sequentially (SortMergePartitionedFileReader), turning P x C random
small reads into a few sequential scans.

Columnar re-design: the buffered unit is a RecordBatch, so "sorting by
subpartition" is grouping already-split batches — no per-record sort at
all. A region flush concatenates each subpartition's buffered batches,
encodes them with the native framed codec (LZ + CRC,
flink_tpu/native/codec.py), and appends one contiguous range per
subpartition. Events (barriers, END_OF_PARTITION) keep their order
relative to data: an event forces a region flush and is recorded in
each subpartition's item stream.

The transport is BLOCKING in the reference sense — data is readable as
soon as its region is flushed (the hybrid-shuffle property), and
backpressure is the disk, not credits. Select with
``shuffle.service: sort-merge`` (stage/batch pipelines).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.shuffle_spi import (
    END_OF_PARTITION,
    InputGate,
    ResultPartitionWriter,
    ShuffleService,
    register_shuffle_service,
)


def _encode(batch: RecordBatch) -> bytes:
    from flink_tpu.native.codec import codec_available, encode_batch

    if codec_available():
        return b"C" + encode_batch(batch)
    import pickle

    return b"P" + pickle.dumps(dict(batch.columns))


def _decode(data: bytes) -> RecordBatch:
    if data[:1] == b"C":
        from flink_tpu.native.codec import decode_batch

        return decode_batch(data[1:])
    import pickle

    return RecordBatch(pickle.loads(data[1:]))


class _SMPartition:
    """One producer partition: a single spill file + per-subpartition
    item streams (byte ranges and in-band events, in emission order)."""

    def __init__(self, partition_id: str, num_subpartitions: int,
                 directory: str):
        self.partition_id = partition_id
        self.num_subpartitions = num_subpartitions
        self.path = os.path.join(
            directory, f"{partition_id.replace('/', '_')}.data")
        self._f = open(self.path, "wb")
        self._offset = 0
        #: per subpartition: [("range", offset, length) | ("event", ev)]
        self.items: List[List[Tuple]] = [
            [] for _ in range(num_subpartitions)]
        self.finished = False
        self.lock = threading.Lock()
        self.grew = threading.Condition(self.lock)
        self.regions = 0

    def ensure(self, num: int) -> None:
        with self.lock:
            while len(self.items) < num:
                self.items.append([])
            self.num_subpartitions = max(self.num_subpartitions, num)

    def append_region(self, per_sub: Dict[int, List[RecordBatch]]) -> None:
        """Write one region: each subpartition's buffered batches become
        one contiguous encoded range (the PartitionedFileWriter step)."""
        blobs = []
        for sub in sorted(per_sub):
            batches = per_sub[sub]
            if not batches:
                continue
            merged = (batches[0] if len(batches) == 1
                      else RecordBatch.concat(batches))
            blobs.append((sub, _encode(merged)))
        with self.lock:
            for sub, blob in blobs:
                self._f.write(blob)
                self.items[sub].append(
                    ("range", self._offset, len(blob)))
                self._offset += len(blob)
            if blobs:
                self._f.flush()  # readable as soon as flushed (hybrid)
                self.regions += 1
            self.grew.notify_all()

    def append_event(self, event) -> None:
        with self.lock:
            for stream in self.items:
                stream.append(("event", event))
            self.grew.notify_all()

    def finish(self) -> None:
        with self.lock:
            self.finished = True
            self._f.close()
            self.grew.notify_all()


class SortMergeWriter(ResultPartitionWriter):
    """Buffers emitted batches up to a byte budget, then flushes a
    region (reference: SortBuffer + flush at capacity)."""

    def __init__(self, partition: _SMPartition, budget_bytes: int):
        self.partition = partition
        self.budget = budget_bytes
        self._buf: Dict[int, List[RecordBatch]] = {}
        self._buffered = 0

    def emit(self, subpartition: int, batch: RecordBatch) -> None:
        if batch is None or len(batch) == 0:
            return
        self._buf.setdefault(subpartition, []).append(batch)
        self._buffered += sum(
            getattr(c, "nbytes", 64) for c in batch.columns.values())
        if self._buffered >= self.budget:
            self._flush()

    def _flush(self) -> None:
        if self._buf:
            self.partition.append_region(self._buf)
            self._buf = {}
            self._buffered = 0

    def broadcast_event(self, event) -> None:
        # order-preserving: pending data must land before the event
        self._flush()
        self.partition.append_event(event)

    def close(self) -> None:
        self.broadcast_event(END_OF_PARTITION)
        self.partition.finish()


class SortMergeGate(InputGate):
    """Sequential reader over each producer's subpartition ranges."""

    def __init__(self, partitions: List[_SMPartition], subpartition: int):
        self._parts = partitions
        self._sub = subpartition
        self.num_channels = len(partitions)
        self._cursor = [0] * len(partitions)
        self._files: List[Optional[object]] = [None] * len(partitions)
        self._rr = 0

    def _read(self, ch: int, item) -> object:
        kind = item[0]
        if kind == "event":
            return item[1]
        _, offset, length = item
        f = self._files[ch]
        if f is None:
            f = open(self._parts[ch].path, "rb")
            self._files[ch] = f
        f.seek(offset)
        return _decode(f.read(length))

    def poll(self, timeout: float = 0.0):
        import time as _t

        deadline = _t.monotonic() + timeout if timeout else None
        while True:
            for i in range(self.num_channels):
                ch = (self._rr + i) % self.num_channels
                part = self._parts[ch]
                with part.lock:
                    cur = self._cursor[ch]
                    stream = part.items[self._sub] \
                        if self._sub < len(part.items) else []
                    if cur >= len(stream):
                        continue
                    item = stream[cur]
                    self._cursor[ch] = cur + 1
                self._rr = (ch + 1) % self.num_channels
                return ch, self._read(ch, item)
            if deadline is None:
                return None
            remaining = deadline - _t.monotonic()
            if remaining <= 0:
                return None
            # wait for any producer to flush a region or finish
            part = self._parts[self._rr]
            with part.lock:
                if self._cursor[self._rr] >= len(
                        part.items[self._sub]
                        if self._sub < len(part.items) else []):
                    part.grew.wait(timeout=min(0.05, remaining))

    def take_inflight(self, channel: int, checkpoint_id: int) -> list:
        return []  # blocking shuffle: nothing is in flight to persist

    def close(self) -> None:
        for f in self._files:
            if f is not None:
                f.close()


class SortMergeShuffleService(ShuffleService):
    """reference: SortMergeResultPartition + its ShuffleServiceFactory
    wiring. One spill directory per service instance; partitions create
    lazily from either side (producer or consumer may register first)."""

    def __init__(self, spill_dir: Optional[str] = None,
                 memory_budget_bytes: int = 16 << 20):
        self._own_dir = spill_dir is None
        self.directory = spill_dir or tempfile.mkdtemp(
            prefix="flink-tpu-sort-merge-")
        os.makedirs(self.directory, exist_ok=True)
        self.budget = int(memory_budget_bytes)
        self._parts: Dict[str, _SMPartition] = {}
        self._lock = threading.Lock()

    def _partition(self, partition_id: str,
                   num_subpartitions: int) -> _SMPartition:
        with self._lock:
            part = self._parts.get(partition_id)
            if part is None:
                part = _SMPartition(partition_id, num_subpartitions,
                                    self.directory)
                self._parts[partition_id] = part
            else:
                part.ensure(num_subpartitions)
            return part

    def create_partition(self, partition_id: str, num_subpartitions: int,
                         credits_per_channel: Optional[int] = None
                         ) -> ResultPartitionWriter:
        return SortMergeWriter(
            self._partition(partition_id, num_subpartitions), self.budget)

    def create_gate(self, partition_ids: Sequence[str], subpartition: int
                    ) -> InputGate:
        parts = [self._partition(pid, subpartition + 1)
                 for pid in partition_ids]
        return SortMergeGate(parts, subpartition)

    def cancel(self) -> None:
        pass

    def close(self) -> None:
        with self._lock:
            parts = list(self._parts.values())
        for part in parts:
            if not part.finished:
                part.finish()
        if self._own_dir:
            shutil.rmtree(self.directory, ignore_errors=True)


register_shuffle_service("sort-merge", SortMergeShuffleService)
