"""Stream elements: record batches, watermarks, checkpoint barriers.

The reference interleaves StreamRecord / Watermark / CheckpointBarrier /
WatermarkStatus in one element stream (reference:
flink-runtime/.../streaming/runtime/streamrecord/StreamElement.java). Here the
record granularity is a whole columnar batch; watermarks and barriers flow
between batches, which makes barrier alignment trivial (a barrier IS a batch
boundary — see SURVEY.md §7 step 6).
"""

from __future__ import annotations

import dataclasses

MAX_WATERMARK = (1 << 62)  # end-of-input flush (reference: Watermark.MAX_WATERMARK)
MIN_WATERMARK = -(1 << 62)


@dataclasses.dataclass(frozen=True)
class Watermark:
    """Event-time watermark: no records with ts <= value will arrive later."""

    value: int

    def __le__(self, other):
        return self.value <= other.value


@dataclasses.dataclass(frozen=True)
class CheckpointBarrier:
    """Aligned checkpoint barrier (reference:
    runtime/io/checkpointing/CheckpointBarrierHandler.java). In a micro-batch
    engine alignment degenerates to 'snapshot between two batches'."""

    checkpoint_id: int
    timestamp: int = 0


@dataclasses.dataclass(frozen=True)
class EndOfInput:
    """Signals a finite source is drained (bounded streams / tests)."""
