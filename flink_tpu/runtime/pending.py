"""Asynchronous window-fire results.

The tunneled TPU link in this environment has a ~35-70 ms one-way latency:
a single synchronous ``np.asarray(device_array)`` costs ~100 ms of host
wall-clock even for a 16-byte result. The reference overlaps operator
output with network/state I/O threads (reference:
runtime/asyncprocessing/AsyncExecutionController.java:57,364-369 — in-flight
record contexts drain asynchronously while the mailbox keeps processing).

Re-design for the XLA dispatch model: a window fire is *dispatched* (kernel
enqueued, ``copy_to_host_async`` started on every output buffer) and
*harvested* later, when the DMA has already landed — the executor keeps
ingesting source batches in between, so the link latency is hidden behind
useful work instead of stalling the pipeline. Event-time correctness is
preserved by watermark holdback: the executor does not forward a watermark
past an operator with pending fires until those fires' results have been
emitted downstream (see LocalExecutor._drain_pending).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

import numpy as np


class PendingFire:
    """A dispatched-but-unharvested fire: device output buffers (async host
    copies already in flight) plus a host-side finisher that assembles the
    final result batch once the bytes land."""

    __slots__ = ("arrays", "build", "dispatched_at", "watchdog")

    def __init__(self, arrays: Sequence,
                 build: Callable[[List[np.ndarray]], object],
                 watchdog=None):
        self.arrays = list(arrays)
        self.build = build
        #: optional DeviceWatchdog: the harvest is a deadline-tracked
        #: section (a fire whose D2H never lands is a dead device)
        self.watchdog = watchdog
        self.dispatched_at = time.perf_counter()
        for a in self.arrays:
            copy = getattr(a, "copy_to_host_async", None)
            if copy is not None:
                copy()

    def ready(self) -> bool:
        """True when every output buffer's computation has finished (the
        async host copy then completes at DMA speed, not link-RTT speed)."""
        return all(a.is_ready() for a in self.arrays)

    def harvest(self) -> Optional[object]:
        """Materialize host values and build the result (blocks only on
        buffers whose async copy has not yet landed).

        All buffers are fetched in ONE ``jax.device_get`` call: on the
        tunneled link each device->host read pays the full RTT, but
        concurrent reads pipeline (measured: 8 serial fetches 526 ms, one
        batched device_get 68 ms), so a fire with k output columns costs
        one RTT instead of k."""
        import jax

        from flink_tpu.chaos import injection as chaos
        from flink_tpu.observe import flight_recorder as flight

        # chaos: a harvest failure — the fire was dispatched but its
        # D2H results never land (link loss mid-coalesced-harvest)
        chaos.fault_point("harvest.pending_fire",
                          arrays=len(self.arrays))
        with flight.span("fire.harvest"):
            if self.watchdog is not None:
                with self.watchdog.section("pending_harvest"):
                    host = jax.device_get(self.arrays)
            else:
                host = jax.device_get(self.arrays)
            return self.build([np.asarray(a) for a in host])
