"""Local (pre-shuffle) window aggregation — the two-phase agg's local half.

The reference splits hot aggregations into a local pre-aggregation before
the keyed exchange and a global aggregation after it (reference:
flink-table-runtime/.../aggregate/MiniBatchLocalGroupAggFunction.java +
MiniBatchGlobalGroupAggFunction.java; enabled by the
table.optimizer.agg-phase-strategy TWO_PHASE rule). The local side shrinks
the shuffle to at most one row per (key, window-slice) per batch and
defuses key skew: a hot key's records collapse on every source subtask
before they converge on the one keyed subtask that owns the key (SURVEY
§2.9 local/global row; hard-part (e)).

Re-design: the combiner runs on the *source* stage over columnar batches —
one lexsort + one ufunc.reduceat per accumulator leaf, no per-record code.
Its output rows carry explicit per-leaf partial values in reserved
``__agg_leaf_{i}__`` columns; the window operator detects those columns and
folds them with each leaf's own reduce method (slot_table.scatter_valued)
instead of re-running ``map_input``. Because each output row stays inside
its source records' window slice (it carries their max timestamp), window
assignment downstream is unchanged.
"""

from __future__ import annotations

from typing import List

import numpy as np

from flink_tpu.core.records import (
    KEY_ID_FIELD,
    TIMESTAMP_FIELD,
    RecordBatch,
)
from flink_tpu.state.keygroups import hash_keys_to_i64
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.windowing.assigners import WindowAssigner

#: reserved column prefix marking a batch as locally pre-aggregated
PARTIAL_LEAF_PREFIX = "__agg_leaf_"

# host-side reduceat per reduce kind
_REDUCEAT = {
    "sum": np.add.reduceat,
    "max": np.maximum.reduceat,
    "min": np.minimum.reduceat,
}


def is_partial_batch(batch: RecordBatch) -> bool:
    return (PARTIAL_LEAF_PREFIX + "0") in batch.columns


def partial_leaf_values(batch: RecordBatch,
                        agg: AggregateFunction) -> tuple:
    """The per-leaf partial value columns of a combined batch."""
    return tuple(
        np.asarray(batch[PARTIAL_LEAF_PREFIX + str(i)], dtype=l.dtype)
        for i, l in enumerate(agg.leaves))


class LocalWindowCombiner:
    """Collapses a batch to one row per (key, slice) with per-leaf partial
    aggregates. Stateless across batches (state lives only in the keyed
    stage, so checkpoints need nothing from the combiner — same property
    the reference's local agg gets from flushing on every mini-batch)."""

    def __init__(self, assigner: WindowAssigner, agg: AggregateFunction,
                 key_field: str):
        if assigner.is_merging:
            raise ValueError("local combine requires an aligned (slicing) "
                             "window assigner")
        self.assigner = assigner
        self.agg = agg
        self.key_field = key_field

    def combine(self, batch: RecordBatch) -> RecordBatch:
        n = len(batch)
        if n == 0 or is_partial_batch(batch):
            return batch
        key_ids = hash_keys_to_i64(batch[self.key_field])
        slice_ends = self.assigner.assign_slice_ends(batch.timestamps)
        order = np.lexsort((slice_ends, key_ids))
        k_s = key_ids[order]
        s_s = slice_ends[order]
        boundary = np.empty(n, dtype=bool)
        boundary[0] = True
        np.logical_or(k_s[1:] != k_s[:-1], s_s[1:] != s_s[:-1],
                      out=boundary[1:])
        starts = np.nonzero(boundary)[0]
        values = self.agg.map_input_valued(batch)
        cols = {
            # representative original key value per group (all rows in a
            # group share the key, so the first is exact)
            self.key_field: np.asarray(batch[self.key_field])[order][starts],
            # already-computed key identities: the partitioner reuses them
            # instead of re-hashing the combined rows
            KEY_ID_FIELD: k_s[starts],
            # max source timestamp per group: stays inside the slice and
            # never runs ahead of the batch's watermark contribution
            TIMESTAMP_FIELD: np.maximum.reduceat(
                np.asarray(batch.timestamps)[order], starts),
        }
        for i, (leaf, v) in enumerate(zip(self.agg.leaves, values)):
            cols[PARTIAL_LEAF_PREFIX + str(i)] = _REDUCEAT[leaf.reduce](
                np.asarray(v)[order], starts).astype(leaf.dtype)
        return RecordBatch(cols)
