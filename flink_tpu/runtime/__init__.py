from flink_tpu.runtime.elements import Watermark, CheckpointBarrier, MAX_WATERMARK
from flink_tpu.runtime.watermarks import (
    WatermarkStrategy,
    BoundedOutOfOrdernessWatermarks,
    WatermarkValve,
)

__all__ = [
    "Watermark",
    "CheckpointBarrier",
    "MAX_WATERMARK",
    "WatermarkStrategy",
    "BoundedOutOfOrdernessWatermarks",
    "WatermarkValve",
]
