"""Async I/O — overlap external lookups with stream processing.

reference: streaming/api/operators/async/AsyncWaitOperator.java (+
api/datastream/AsyncDataStream.java): per-record async requests with a
bounded in-flight queue, ORDERED / UNORDERED result emission, timeouts,
and queue capacity as natural backpressure.

Batched re-design: the unit of async work is a whole RecordBatch (one
external call per micro-batch — e.g. one batched RPC / one device inference
dispatch), run on a thread pool. Capacity bounds in-flight *batches*; when
full, ``process_batch`` blocks on the oldest future (credit-based
backpressure, like the reference's queue-full wait at
AsyncWaitOperator.java addToWorkQueue). Results surface on subsequent
operator calls and at close (the drain).
"""

from __future__ import annotations

import collections
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from concurrent.futures import TimeoutError as _FutTimeout
from typing import Callable, List, Optional

from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.operators import Operator


class AsyncFunction:
    """Override ``invoke``; optional ``timeout`` fallback (the reference's
    AsyncFunction.timeout — default re-raises, failing the job)."""

    def invoke(self, batch: RecordBatch) -> RecordBatch:
        raise NotImplementedError

    def timeout(self, batch: RecordBatch) -> Optional[RecordBatch]:
        raise TimeoutError("async request timed out")

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass


class _FnAsyncFunction(AsyncFunction):
    def __init__(self, fn: Callable[[RecordBatch], RecordBatch]):
        self.fn = fn

    def invoke(self, batch):
        return self.fn(batch)


class AsyncWaitOperator(Operator):
    name = "async_wait"

    def __init__(self, fn, ordered: bool = True, capacity: int = 8,
                 timeout_ms: Optional[int] = None, workers: int = 8):
        self.fn = fn if isinstance(fn, AsyncFunction) else _FnAsyncFunction(fn)
        self.ordered = ordered
        self.capacity = max(int(capacity), 1)
        self.timeout_s = timeout_ms / 1000.0 if timeout_ms else None
        self.workers = workers
        self._pool: Optional[ThreadPoolExecutor] = None
        # (future, input_batch) in submission order
        self._inflight: collections.deque = collections.deque()

    def open(self, ctx):
        self._pool = ThreadPoolExecutor(
            max_workers=min(self.workers, self.capacity),
            thread_name_prefix="async-wait")
        self.fn.open()

    # -- result harvesting ---------------------------------------------------

    def _result(self, fut: Future, batch: RecordBatch) -> Optional[RecordBatch]:
        try:
            return fut.result(timeout=self.timeout_s)
        except (TimeoutError, _FutTimeout):
            fut.cancel()
            return self.fn.timeout(batch)

    def _harvest(self, block_for_one: bool) -> List[RecordBatch]:
        outs: List[RecordBatch] = []
        inflight = self._inflight
        if self.ordered:
            while inflight and (inflight[0][0].done() or block_for_one):
                fut, b = inflight.popleft()
                r = self._result(fut, b)
                if r is not None and len(r):
                    outs.append(r)
                block_for_one = False
        else:
            if block_for_one and inflight and not any(
                    f.done() for f, _ in inflight):
                wait([f for f, _ in inflight], timeout=self.timeout_s,
                     return_when=FIRST_COMPLETED)
            pending = collections.deque()
            for fut, b in inflight:
                if fut.done():
                    r = self._result(fut, b)
                    if r is not None and len(r):
                        outs.append(r)
                else:
                    pending.append((fut, b))
            # timeout path: if still over capacity, force the oldest
            while len(pending) >= self.capacity:
                fut, b = pending.popleft()
                r = self._result(fut, b)
                if r is not None and len(r):
                    outs.append(r)
            self._inflight = pending
        return outs

    # -- operator hooks ------------------------------------------------------

    def process_batch(self, batch, input_index=0):
        outs = self._harvest(block_for_one=len(self._inflight) >= self.capacity)
        self._inflight.append((self._pool.submit(self.fn.invoke, batch), batch))
        return outs

    def process_watermark(self, watermark, input_index=0):
        # a watermark may not overtake pending results: drain everything
        # in-flight first (the reference stalls the watermark in the
        # ordered queue the same way)
        outs: List[RecordBatch] = []
        while self._inflight:
            if self.ordered:
                outs.extend(self._harvest(block_for_one=True))
            else:
                fut, b = self._inflight.popleft()
                r = self._result(fut, b)
                if r is not None and len(r):
                    outs.append(r)
        return outs

    def close(self):
        outs = self.process_watermark(None)
        self.fn.close()
        self._pool.shutdown(wait=False)
        return outs

    def dispose(self):
        for fut, _ in self._inflight:
            fut.cancel()
        self._inflight.clear()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self.fn.close()

    # -- checkpoint ----------------------------------------------------------
    # reference: AsyncWaitOperator snapshots its work queue of *input*
    # elements and replays the async requests on restore — results of
    # in-flight requests have not been emitted yet, so replaying keeps
    # emission exactly-once (the async function itself runs at-least-once,
    # as in the reference).

    def snapshot_state(self):
        return {
            "pending_inputs": [dict(b.columns) for _, b in self._inflight],
        }

    def restore_state(self, state):
        for cols in state.get("pending_inputs", []):
            b = RecordBatch(cols)
            self._inflight.append((self._pool.submit(self.fn.invoke, b), b))
