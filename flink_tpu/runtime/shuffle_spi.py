"""Shuffle SPI — the pluggable data plane between subtasks.

reference: flink-runtime/.../runtime/shuffle/ShuffleEnvironment.java (TM-side
factory for writers/readers), ShuffleServiceFactory.java (pluggability),
io/network/api/writer/RecordWriter.java:105 (emit -> channel selection),
io/network/partition/consumer/RemoteInputChannel.java:114,374 (credit-based
flow control: the receiver grants credits equal to free buffers; the sender
only sends with credit, bounding in-flight data and producing natural
backpressure).

TPU re-design: the unit in flight is a columnar RecordBatch (not a serialized
record), and a "buffer" of credit is one batch. Two built-in transports:

- ``LocalShuffleService`` — bounded in-process queues (threads within one
  TaskExecutor / process). The credit IS the queue bound.
- ``RpcShuffleService`` (flink_tpu/cluster/rpc_shuffle.py) — batches travel
  over gRPC between task executors; credits are granted back over the same
  channel. Registered under ``shuffle.service: grpc``.

Both implement this SPI, so the execution layer is transport-agnostic — the
seam a DCN/ICI transport slots into without rewrites (SURVEY §2.8 mapping).

Within one keyed mesh operator, the data plane is NOT this module: records
reach device shards via sharded device_put + XLA collectives
(flink_tpu/parallel/shuffle.py). This SPI connects *subtasks* — pipeline
stages and parallel instances — the role Netty plays in the reference.
"""

from __future__ import annotations

import queue as _q
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from flink_tpu.core.records import RecordBatch

#: sentinel channel events (travel in-band, like the reference's
#: EndOfPartitionEvent / CheckpointBarrier)
END_OF_PARTITION = "__eop__"


class Barrier:
    """Checkpoint barrier riding the data channels (reference:
    io/network/api/CheckpointBarrier). Aligned handling is the consumer's
    job; ``unaligned`` barriers instead OVERTAKE queued data (reference:
    CheckpointBarrier.asUnaligned + the priority-event path of
    CheckpointedInputGate) — the overtaken batches become channel state
    in the snapshot so the checkpoint never waits behind a backpressured
    backlog. Savepoints are always aligned (reference: savepoints force
    alignment)."""

    __slots__ = ("checkpoint_id", "savepoint", "stop", "unaligned")

    def __init__(self, checkpoint_id: int, savepoint: Optional[str] = None,
                 stop: bool = False, unaligned: bool = False):
        self.checkpoint_id = checkpoint_id
        self.savepoint = savepoint
        self.stop = stop
        self.unaligned = unaligned and savepoint is None

    def __repr__(self):
        return f"Barrier({self.checkpoint_id})"


class ResultPartitionWriter:
    """One producer subtask's view of its output partition: emit a batch to
    one subpartition (consumer channel), broadcast events to all."""

    def emit(self, subpartition: int, batch: RecordBatch) -> None:
        raise NotImplementedError

    def broadcast_event(self, event) -> None:
        raise NotImplementedError

    def close(self) -> None:
        """Broadcast END_OF_PARTITION and release resources."""
        raise NotImplementedError


class InputGate:
    """One consumer subtask's view of its inputs: a union of channels, one
    per producer subtask."""

    num_channels: int

    def poll(self, timeout: float = 0.0):
        """Next (channel_index, item) where item is a RecordBatch, Barrier,
        a watermark (int), or END_OF_PARTITION. None on timeout."""
        raise NotImplementedError

    def take_inflight(self, channel: int, checkpoint_id: int) -> list:
        """Batches an unaligned barrier overtook on ``channel`` (channel
        state). Transports without overtaking return [] — the consumer's
        capture-while-polling then covers all pre-barrier data."""
        return []

    def close(self) -> None:
        raise NotImplementedError


class ShuffleService:
    """SPI: creates the writers/readers connecting subtasks (reference:
    ShuffleEnvironment.createResultPartitionWriters / createInputGates)."""

    def create_partition(self, partition_id: str, num_subpartitions: int,
                         credits_per_channel: int = 2
                         ) -> ResultPartitionWriter:
        raise NotImplementedError

    def create_gate(self, partition_ids: Sequence[str], subpartition: int
                    ) -> InputGate:
        """A gate consuming subpartition ``subpartition`` of every listed
        partition (one channel per producer)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Local (in-process) transport with credit-based flow control
# ---------------------------------------------------------------------------


class _Subpartition:
    """One (producer, consumer-channel) pipe. ``credits`` mirrors the
    reference's buffer-backed credit: the producer blocks once
    ``credits_per_channel`` items are in flight; consuming an item grants
    the credit back (RemoteInputChannel.notifyCreditAvailable).

    Unaligned barriers use ``put_front``: the barrier jumps ahead of the
    queued data batches, and those overtaken batches are recorded as the
    channel's in-flight state for that checkpoint (reference:
    ChannelStateWriterImpl persisting the buffers a priority barrier
    overtook)."""

    def __init__(self, credits_per_channel: int):
        import collections

        self._data = collections.deque()
        self._prio = collections.deque()
        self._cond = threading.Condition()
        self.credits = threading.Semaphore(credits_per_channel)
        #: checkpoint_id -> [overtaken RecordBatches] (consumer pops)
        self._inflight: Dict[int, list] = {}

    def put(self, item, is_event: bool, cancelled: Callable[[], bool]) -> None:
        if not is_event:
            # events (watermarks, barriers, EOP) ride credit-free like the
            # reference's priority events — only data consumes credit
            while not self.credits.acquire(timeout=0.05):
                if cancelled():
                    return
        with self._cond:
            self._data.append(item)
            self._cond.notify()

    def put_front(self, barrier) -> None:
        """Unaligned barrier: overtake queued data, snapshotting the
        overtaken batches as this channel's in-flight state."""
        with self._cond:
            self._inflight.setdefault(barrier.checkpoint_id, []).extend(
                b for b in self._data if isinstance(b, RecordBatch))
            self._prio.append(barrier)
            self._cond.notify()

    def take_inflight(self, checkpoint_id: int) -> list:
        with self._cond:
            return self._inflight.pop(checkpoint_id, [])

    def get(self, timeout: float):
        with self._cond:
            if not self._prio and not self._data:
                if not timeout or not self._cond.wait_for(
                        lambda: self._prio or self._data, timeout):
                    raise _q.Empty
            item = self._prio.popleft() if self._prio else \
                self._data.popleft()
        if isinstance(item, RecordBatch):
            self.credits.release()
        return item


class LocalShuffleService(ShuffleService):
    """In-process transport: subtasks are threads, channels are bounded
    queues. Also the reference's default for its MiniCluster tests."""

    def __init__(self, default_credits: int = 2):
        self._partitions: Dict[str, "_LocalPartition"] = {}
        self._lock = threading.Lock()
        self._cancelled = threading.Event()
        self._default_credits = default_credits

    def cancel(self) -> None:
        """Release all producers blocked on credits (job teardown)."""
        self._cancelled.set()

    def _partition(self, partition_id: str, num_subpartitions: int,
                   credits: Optional[int] = None) -> "_LocalPartition":
        with self._lock:
            part = self._partitions.get(partition_id)
            if part is None:
                part = _LocalPartition(partition_id, num_subpartitions,
                                       credits or self._default_credits)
                self._partitions[partition_id] = part
            else:
                # a gate may materialize the partition before its writer
                # (the SPI mandates no ordering) — grow to the larger view,
                # re-crediting if the writer brought an explicit window
                part.ensure(num_subpartitions, credits)
            return part

    def create_partition(self, partition_id: str, num_subpartitions: int,
                         credits_per_channel: int = 2) -> "LocalWriter":
        part = self._partition(partition_id, num_subpartitions,
                               credits=credits_per_channel)
        return LocalWriter(part, self._cancelled)

    def create_gate(self, partition_ids: Sequence[str], subpartition: int
                    ) -> "LocalGate":
        parts = [self._partition(pid, subpartition + 1)
                 for pid in partition_ids]
        return LocalGate(parts, subpartition)


class _LocalPartition:
    def __init__(self, partition_id: str, num_subpartitions: int,
                 credits_per_channel: int):
        self.partition_id = partition_id
        self.credits = credits_per_channel
        self.subpartitions = [
            _Subpartition(credits_per_channel)
            for _ in range(num_subpartitions)
        ]

    def ensure(self, num: int, credits: Optional[int] = None) -> None:
        """Grow to ``num`` subpartitions. A WIDER credit window from the
        writer grants the extra permits to channels materialized
        gate-first with the default (gates hold channel references, so
        the semaphore is adjusted in place; narrowing is not supported —
        outstanding credits cannot be revoked)."""
        if credits is not None and credits > self.credits:
            extra = credits - self.credits
            self.credits = credits
            for sp in self.subpartitions:
                for _ in range(extra):
                    sp.credits.release()
        while len(self.subpartitions) < num:
            self.subpartitions.append(_Subpartition(self.credits))


class LocalWriter(ResultPartitionWriter):
    def __init__(self, partition: _LocalPartition, cancelled: threading.Event):
        self.partition = partition
        self._cancelled = cancelled

    def emit(self, subpartition: int, batch: RecordBatch) -> None:
        self.partition.subpartitions[subpartition].put(
            batch, is_event=False, cancelled=self._cancelled.is_set)

    def broadcast_event(self, event) -> None:
        if isinstance(event, Barrier) and event.unaligned:
            for sp in self.partition.subpartitions:
                sp.put_front(event)
            return
        for sp in self.partition.subpartitions:
            sp.put(event, is_event=True, cancelled=self._cancelled.is_set)

    def close(self) -> None:
        self.broadcast_event(END_OF_PARTITION)


class LocalGate(InputGate):
    """Fair-ish polling over the channels of one subpartition index."""

    def __init__(self, partitions: List[_LocalPartition], subpartition: int):
        self._chans = [p.subpartitions[subpartition] for p in partitions]
        self.num_channels = len(self._chans)
        self._rr = 0

    def poll(self, timeout: float = 0.0):
        n = self.num_channels
        deadline = None
        while True:
            for i in range(n):
                ch = (self._rr + i) % n
                try:
                    item = self._chans[ch].get(timeout=0)
                    self._rr = (ch + 1) % n
                    return ch, item
                except _q.Empty:
                    continue
            if not timeout:
                return None
            if deadline is None:
                import time as _t

                deadline = _t.monotonic() + timeout
                continue
            import time as _t

            if _t.monotonic() >= deadline:
                return None
            # block briefly on one channel to avoid spinning
            try:
                item = self._chans[self._rr].get(timeout=min(
                    0.01, max(deadline - _t.monotonic(), 0.001)))
                ch = self._rr
                self._rr = (ch + 1) % n
                return ch, item
            except _q.Empty:
                continue

    def take_inflight(self, channel: int, checkpoint_id: int) -> list:
        return self._chans[channel].take_inflight(checkpoint_id)

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Partitioners (reference: streaming/runtime/partitioner/*)
# ---------------------------------------------------------------------------


class Partitioner:
    """Routes a batch's records to output subpartitions (reference:
    StreamPartitioner.selectChannel — but vectorized: one call splits a
    whole batch into per-channel sub-batches)."""

    def partition(self, batch: RecordBatch, num_channels: int
                  ) -> List[Tuple[int, RecordBatch]]:
        raise NotImplementedError


class KeyGroupPartitioner(Partitioner):
    """keyBy routing: key -> key group -> owning subtask (reference:
    KeyGroupStreamPartitioner.java:55)."""

    def __init__(self, key_field: str, max_parallelism: int = 128):
        self.key_field = key_field
        self.max_parallelism = max_parallelism

    def partition(self, batch, num_channels):
        import numpy as np

        from flink_tpu.state.keygroups import (
            assign_key_groups,
            hash_keys_to_i64,
            key_group_to_operator_index,
        )

        key_ids = hash_keys_to_i64(batch[self.key_field])
        groups = assign_key_groups(key_ids, self.max_parallelism)
        targets = key_group_to_operator_index(
            groups, self.max_parallelism, num_channels)
        return [(ch, batch.filter(targets == ch))
                for ch in np.unique(targets).tolist()]


class RebalancePartitioner(Partitioner):
    """Round-robin at batch granularity: each whole micro-batch goes to
    the next channel (reference: RebalancePartitioner — per record there;
    the batch IS the unit here, keeping batches device-sized)."""

    def __init__(self):
        self._next = 0

    def partition(self, batch, num_channels):
        ch = self._next
        self._next = (ch + 1) % num_channels
        return [(ch, batch)]


class BroadcastPartitioner(Partitioner):
    """Every channel sees every record (reference: BroadcastPartitioner —
    backs broadcast state)."""

    def partition(self, batch, num_channels):
        return [(ch, batch) for ch in range(num_channels)]


class ForwardPartitioner(Partitioner):
    """Producer subtask i feeds consumer subtask i only (reference:
    ForwardPartitioner — the chaining-eligible edge)."""

    def __init__(self, producer_index: int):
        self.producer_index = producer_index

    def partition(self, batch, num_channels):
        return [(self.producer_index % num_channels, batch)]


class RescalePartitioner(Partitioner):
    """Round-robin over the consumer subset assigned to this producer
    (reference: RescalePartitioner — locality-friendly redistribution for
    producer/consumer parallelism ratios)."""

    def __init__(self, producer_index: int, num_producers: int):
        self.producer_index = producer_index
        self.num_producers = num_producers
        self._i = 0

    def partition(self, batch, num_channels):
        if num_channels >= self.num_producers:
            per = num_channels // self.num_producers
            base = self.producer_index * per
            span = per if self.producer_index < self.num_producers - 1 \
                else num_channels - base
        else:
            base = self.producer_index * num_channels // self.num_producers
            span = 1
        ch = base + (self._i % max(span, 1))
        self._i += 1
        return [(ch, batch)]


# ---------------------------------------------------------------------------
# Factory registry (reference: ShuffleServiceFactory discovery)
# ---------------------------------------------------------------------------

_FACTORIES: Dict[str, Callable[[], ShuffleService]] = {
    "local": LocalShuffleService,
}


def register_shuffle_service(name: str,
                             factory: Callable[[], ShuffleService]) -> None:
    _FACTORIES[name] = factory


#: built-in services that register themselves on import — configuring
#: shuffle.service must not require the user to import the module
_LAZY_MODULES = {
    "grpc": "flink_tpu.cluster.rpc_shuffle",
    "sort-merge": "flink_tpu.runtime.sort_merge_shuffle",
}


def create_shuffle_service(name: str = "local") -> ShuffleService:
    if name not in _FACTORIES and name in _LAZY_MODULES:
        import importlib

        importlib.import_module(_LAZY_MODULES[name])
    try:
        factory = _FACTORIES[name]
    except KeyError:
        raise ValueError(
            f"unknown shuffle.service {name!r}; registered: "
            f"{sorted(_FACTORIES)}") from None
    return factory()
