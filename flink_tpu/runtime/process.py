"""Process functions: the low-level user API with state, timers, and side
outputs.

reference: flink-core/.../api/common/functions (ProcessFunction lives at
streaming/api/functions/ProcessFunction.java, KeyedProcessFunction.java,
co/CoProcessFunction.java, co/BroadcastProcessFunction.java); timers in
streaming/api/operators/InternalTimerServiceImpl.java; side outputs via
OutputTag (flink-core/.../util/OutputTag.java) and
ProcessOperator.ContextImpl.output.

Batched re-design: a process function sees whole RecordBatches; timer
registration is vectorized (arrays of (key_id, timestamp) pairs registered in
one call); ``on_timer`` receives one batch of fired timers per watermark
advance instead of one callback per timer. Keyed state handles are the
vectorized states of flink_tpu.state.keyed_state.
"""

from __future__ import annotations

import dataclasses
import heapq
import time as _time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.elements import MIN_WATERMARK
from flink_tpu.runtime.operators import Operator
from flink_tpu.state.keyed_state import KeyedStateStore


from flink_tpu.core.annotations import public

@public
@dataclasses.dataclass(frozen=True)
class OutputTag:
    """Names a side output (reference: flink-core/.../util/OutputTag.java)."""

    name: str


@dataclasses.dataclass(frozen=True)
class TaggedBatch:
    """A batch routed to a side output instead of the main output."""

    tag: OutputTag
    batch: RecordBatch


class TimerService:
    """Keyed timers, both time domains.

    reference: InternalTimerServiceImpl.java keeps two key-grouped priority
    queues (:53-58) and fires event-time timers on advanceWatermark (:314).
    Here one binary heap per domain holds (timestamp, key_id) pairs with a
    set for dedup (registering the same (key, ts) twice fires once — the
    reference's timer semantics).
    """

    def __init__(self, clock: Callable[[], int] = None):
        self._event: List[Tuple[int, int]] = []
        self._event_set: set = set()
        self._proc: List[Tuple[int, int]] = []
        self._proc_set: set = set()
        self.current_watermark = MIN_WATERMARK
        self.clock = clock or (lambda: int(_time.time() * 1000))

    # -- registration (vectorized) ------------------------------------------

    def register_event_time_timers(self, key_ids, timestamps) -> None:
        for k, t in zip(np.atleast_1d(np.asarray(key_ids)).tolist(),
                        np.atleast_1d(np.asarray(timestamps)).tolist()):
            pair = (int(t), int(k))
            if pair not in self._event_set:
                self._event_set.add(pair)
                heapq.heappush(self._event, pair)

    def register_processing_time_timers(self, key_ids, timestamps) -> None:
        for k, t in zip(np.atleast_1d(np.asarray(key_ids)).tolist(),
                        np.atleast_1d(np.asarray(timestamps)).tolist()):
            pair = (int(t), int(k))
            if pair not in self._proc_set:
                self._proc_set.add(pair)
                heapq.heappush(self._proc, pair)

    def delete_event_time_timers(self, key_ids, timestamps) -> None:
        # lazy deletion: drop from the dedup set; heap entries are skipped
        # at fire time (the reference eagerly removes; lazy keeps O(1))
        for k, t in zip(np.atleast_1d(np.asarray(key_ids)).tolist(),
                        np.atleast_1d(np.asarray(timestamps)).tolist()):
            self._event_set.discard((int(t), int(k)))

    # -- firing --------------------------------------------------------------

    @staticmethod
    def _pop_due(heap, dedup, bound) -> Tuple[np.ndarray, np.ndarray]:
        keys, tss = [], []
        while heap and heap[0][0] <= bound:
            t, k = heapq.heappop(heap)
            if (t, k) in dedup:
                dedup.discard((t, k))
                keys.append(k)
                tss.append(t)
        return (np.asarray(keys, dtype=np.int64),
                np.asarray(tss, dtype=np.int64))

    def advance_watermark(self, wm: int) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (key_ids, timestamps) of fired event-time timers, in
        timestamp order."""
        self.current_watermark = wm
        return self._pop_due(self._event, self._event_set, wm)

    def advance_processing_time(self, now: int) -> Tuple[np.ndarray, np.ndarray]:
        return self._pop_due(self._proc, self._proc_set, now)

    def has_processing_time_timers(self) -> bool:
        return bool(self._proc_set)

    # -- checkpoint ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return {
            "event": sorted(self._event_set),
            "proc": sorted(self._proc_set),
            "watermark": self.current_watermark,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        self._event = [tuple(p) for p in snap["event"]]
        self._event_set = set(self._event)
        heapq.heapify(self._event)
        self._proc = [tuple(p) for p in snap["proc"]]
        self._proc_set = set(self._proc)
        heapq.heapify(self._proc)
        self.current_watermark = snap.get("watermark", MIN_WATERMARK)


class Collector:
    """Accumulates main + side outputs of one function invocation."""

    def __init__(self):
        self.out: List[Any] = []

    def collect(self, batch: RecordBatch) -> None:
        if batch is not None and len(batch):
            self.out.append(batch)

    def output(self, tag: OutputTag, batch: RecordBatch) -> None:
        if batch is not None and len(batch):
            self.out.append(TaggedBatch(tag, batch))


class ProcessContext(Collector):
    """Runtime context handed to process functions."""

    def __init__(self, timer_service: TimerService,
                 state_store: Optional[KeyedStateStore] = None,
                 aec=None):
        super().__init__()
        self._timers = timer_service
        self._store = state_store
        self._aec = aec

    def timer_service(self) -> TimerService:
        return self._timers

    @property
    def current_watermark(self) -> int:
        return self._timers.current_watermark

    def state(self, descriptor):
        if self._store is None:
            raise RuntimeError(
                "keyed state requires a KeyedStream (use key_by first)")
        return self._store.get_state(descriptor)

    def async_state(self, descriptor):
        """StateFuture-returning view of a keyed state (State V2 analog;
        reference: runtime/state/v2/). Ops queue into the operator's
        AsyncExecutionController and execute in coalesced waves — drained
        automatically at the end of every invocation and before every
        snapshot, or on any ``StateFuture.value()``."""
        from flink_tpu.state.async_state import make_async_view

        if self._aec is None:
            raise RuntimeError(
                "async state requires a keyed process operator")
        return make_async_view(self._aec, self.state(descriptor))


@public
class ProcessFunction:
    """Vectorized ProcessFunction: override ``process_batch`` (and
    ``on_timer`` for keyed variants)."""

    def open(self, ctx) -> None:
        pass

    def process_batch(self, batch: RecordBatch, ctx: ProcessContext) -> None:
        raise NotImplementedError

    def on_timer(self, key_ids: np.ndarray, timestamps: np.ndarray,
                 ctx: ProcessContext) -> None:
        pass

    def close(self, ctx: ProcessContext) -> None:
        pass


KeyedProcessFunction = ProcessFunction  # keyed-ness comes from the stream


@public
class CoProcessFunction:
    """Two-input process function (reference: co/CoProcessFunction.java)."""

    def open(self, ctx) -> None:
        pass

    def process_batch1(self, batch: RecordBatch, ctx: ProcessContext) -> None:
        raise NotImplementedError

    def process_batch2(self, batch: RecordBatch, ctx: ProcessContext) -> None:
        raise NotImplementedError

    def on_timer(self, key_ids, timestamps, ctx) -> None:
        pass

    def close(self, ctx) -> None:
        pass


@public
class BroadcastProcessFunction:
    """reference: co/BroadcastProcessFunction.java +
    KeyedBroadcastProcessFunction.java. ``process_broadcast`` sees every
    broadcast-side batch and may write broadcast state;
    ``process_batch`` reads it."""

    def open(self, ctx) -> None:
        pass

    def process_batch(self, batch: RecordBatch, ctx,
                      broadcast_state: Dict[Any, Any]) -> None:
        raise NotImplementedError

    def process_broadcast(self, batch: RecordBatch, ctx,
                          broadcast_state: Dict[Any, Any]) -> None:
        raise NotImplementedError

    def on_timer(self, key_ids, timestamps, ctx) -> None:
        pass

    def close(self, ctx) -> None:
        pass


class ProcessOperator(Operator):
    """Drives a (Keyed)ProcessFunction.

    reference: streaming/api/operators/ProcessOperator.java and
    KeyedProcessOperator.java (timer callbacks via Triggerable).
    """

    name = "process"

    def __init__(self, fn: ProcessFunction, keyed: bool = False,
                 state_capacity: int = 1 << 12, clock=None):
        self.fn = fn
        self.keyed = keyed
        self.state_capacity = state_capacity
        self._clock = clock
        self.timer_service: Optional[TimerService] = None
        self.store: Optional[KeyedStateStore] = None

    def open(self, ctx):
        self.timer_service = TimerService(clock=self._clock)
        self.store = KeyedStateStore(
            self.state_capacity,
            clock=self._clock) if self.keyed else None
        if self.keyed:
            from flink_tpu.state.async_state import AsyncExecutionController

            self.aec = AsyncExecutionController()
        else:
            self.aec = None
        self.fn.open(self._ctx())

    def _ctx(self) -> ProcessContext:
        return ProcessContext(self.timer_service, self.store, aec=self.aec)

    def _drain_processing_time(self, ctx: ProcessContext) -> None:
        if self.timer_service.has_processing_time_timers():
            keys, tss = self.timer_service.advance_processing_time(
                self.timer_service.clock())
            if len(keys):
                self.fn.on_timer(keys, tss, ctx)

    def _drain_async(self) -> None:
        # every invocation boundary is a drain point: no async state op
        # survives past the call that submitted it (reference:
        # AsyncExecutionController.drainInflightRecords before barriers)
        if self.aec is not None:
            self.aec.drain()

    def process_batch(self, batch, input_index=0):
        ctx = self._ctx()
        self.fn.process_batch(batch, ctx)
        self._drain_processing_time(ctx)
        self._drain_async()
        return ctx.out

    def process_watermark(self, watermark, input_index=0):
        ctx = self._ctx()
        keys, tss = self.timer_service.advance_watermark(watermark)
        if len(keys):
            self.fn.on_timer(keys, tss, ctx)
        self._drain_processing_time(ctx)
        self._drain_async()
        if self.store is not None:
            # TTL sweep rides watermark advance (processing-time based;
            # the watermark is just the cadence, like the reference's
            # background cleanup riding other activity)
            self.store.sweep_expired()
        return ctx.out

    #: processing-time timers must fire on an idle stream too — the
    #: executor's wall-clock tick drives them between batches (reference:
    #: ProcessingTimeService scheduled triggers)
    uses_processing_time = True

    def on_processing_time(self, now_ms: int):
        ctx = self._ctx()
        self._drain_processing_time(ctx)
        self._drain_async()
        return ctx.out

    def close(self):
        ctx = self._ctx()
        self.fn.close(ctx)
        self._drain_async()
        return ctx.out

    def snapshot_state(self):
        self._drain_async()
        snap = {"timers": self.timer_service.snapshot()}
        if self.store is not None:
            snap["keyed_state"] = self.store.snapshot()
        fn_snap = getattr(self.fn, "snapshot_state", None)
        if fn_snap is not None:
            snap["fn"] = fn_snap()
        return snap

    def restore_state(self, state):
        self.timer_service.restore(state["timers"])
        if self.store is not None and "keyed_state" in state:
            self.store.restore(state["keyed_state"])
        fn_restore = getattr(self.fn, "restore_state", None)
        if fn_restore is not None and "fn" in state:
            fn_restore(state["fn"])


class CoProcessOperator(ProcessOperator):
    """Two-input variant (reference: co/CoProcessOperator.java,
    KeyedCoProcessOperator.java)."""

    name = "co_process"

    def process_batch(self, batch, input_index=0):
        ctx = self._ctx()
        if input_index == 0:
            self.fn.process_batch1(batch, ctx)
        else:
            self.fn.process_batch2(batch, ctx)
        self._drain_processing_time(ctx)
        self._drain_async()
        return ctx.out


class BroadcastProcessOperator(ProcessOperator):
    """Input 0 = data side, input 1 = broadcast side. Broadcast state is a
    plain host dict replicated per parallel instance by construction (every
    instance sees every broadcast batch — reference:
    api/datastream/BroadcastConnectedStream.java semantics)."""

    name = "broadcast_process"

    def __init__(self, fn: BroadcastProcessFunction, keyed: bool = False,
                 state_capacity: int = 1 << 12, clock=None):
        super().__init__(fn, keyed=keyed, state_capacity=state_capacity,
                         clock=clock)
        self.broadcast_state: Dict[Any, Any] = {}

    def process_batch(self, batch, input_index=0):
        ctx = self._ctx()
        if input_index == 1:
            self.fn.process_broadcast(batch, ctx, self.broadcast_state)
        else:
            self.fn.process_batch(batch, ctx, self.broadcast_state)
        self._drain_processing_time(ctx)
        self._drain_async()
        return ctx.out

    def snapshot_state(self):
        snap = super().snapshot_state()
        snap["broadcast"] = dict(self.broadcast_state)
        return snap

    def restore_state(self, state):
        super().restore_state(state)
        self.broadcast_state = dict(state.get("broadcast", {}))


class SideOutputSelectOperator(Operator):
    """Selector node placed on a side-output edge; the executor routes
    TaggedBatches with a matching tag here and unwraps them."""

    name = "side_output"

    def __init__(self, tag: OutputTag):
        self.tag = tag

    def process_batch(self, batch, input_index=0):
        return [batch]
