"""Adaptive micro-batch sizing — the buffer-debloater analog.

The reference tunes in-flight network buffers so queued data represents a
configured latency (reference: runtime/throughput/BufferDebloater.java,
BufferSizeEMA.java, ThroughputCalculator.java). In the micro-batch engine
the knob is the batch size itself: a batch is processed in
``records / throughput`` seconds, and a window can only fire at a batch
boundary, so the batch size bounds the fire-latency floor. The controller
holds ``batch = throughput_ema * target_latency * headroom`` so that each
batch costs a fraction of the latency budget, leaving the rest for the
fire itself.
"""

from __future__ import annotations


class BatchSizeController:
    """EMA throughput -> batch size targeting a latency budget.

    ``observe(records, elapsed_s)`` is called once per processed batch;
    ``size`` is the current recommendation. Growth/shrink per step is
    bounded (x2 / /2) so one noisy measurement cannot swing the size, and
    the result is clamped to [min_size, max_size] and rounded to a power
    of two so XLA sees a tiny set of shapes (sticky buckets downstream
    would otherwise re-pad anyway).
    """

    def __init__(self, initial: int, min_size: int, max_size: int,
                 target_latency_ms: float, alpha: float = 0.3,
                 headroom: float = 0.5):
        self.min_size = max(int(min_size), 16)
        self.max_size = max(int(max_size), self.min_size)
        self.target_s = float(target_latency_ms) / 1000.0
        self.alpha = float(alpha)
        self.headroom = float(headroom)
        self._rate_ema: float = 0.0
        self.size = int(min(max(initial, self.min_size), self.max_size))

    def observe(self, records: int, elapsed_s: float) -> int:
        if records <= 0 or elapsed_s <= 0:
            return self.size
        rate = records / elapsed_s
        self._rate_ema = (rate if self._rate_ema == 0.0
                          else self.alpha * rate
                          + (1 - self.alpha) * self._rate_ema)
        want = self._rate_ema * self.target_s * self.headroom
        # bounded step: at most double or halve per observation
        want = min(max(want, self.size / 2), self.size * 2)
        want = min(max(int(want), self.min_size), self.max_size)
        # round down to a power of two (stable XLA shape set) — but the
        # configured bounds dominate: never round below min_size
        p2 = 1 << max(want.bit_length() - 1, 4)
        self.size = min(max(p2, self.min_size), self.max_size)
        return self.size
