"""Event-time Top-N / rank operator.

reference: flink-table-runtime rank operators
(flink-table-runtime/.../operators/rank/ — AppendOnlyTopNFunction et al.),
which back the SQL Top-N idiom
``SELECT ... FROM (SELECT *, ROW_NUMBER() OVER (PARTITION BY p ORDER BY s)
AS rn FROM t) WHERE rn <= N`` — the pattern Nexmark Q5 uses to pick the
hot item per window.

Re-design for the micro-batch engine: rows are buffered per partition key on
the host; when the watermark passes a partition's timestamp (for window-fired
rows the partition is complete at ts = window_end - 1), the partition is
sorted vectorized (np.lexsort over the order-by columns) and the top-N rows
are emitted with their rank attached. Late-arriving rows for an already
emitted partition are dropped (append-only streams).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.operators import Operator
from flink_tpu.table.expressions import Expr


class RankOperator(Operator):
    name = "rank"

    def __init__(self, partition_by: Tuple[Expr, ...],
                 order_by: Tuple[Tuple[Expr, bool], ...],
                 rank_field: str = "rownum",
                 top_n: Optional[int] = None,
                 rank_kind: str = "ROW_NUMBER"):
        self.partition_by = partition_by
        self.order_by = order_by
        self.rank_field = rank_field
        self.top_n = top_n
        self.rank_kind = rank_kind
        # partition tuple -> (max_ts, [RecordBatch...])
        self._buffers: Dict[tuple, List[RecordBatch]] = {}
        self._buffer_ts: Dict[tuple, int] = {}
        self._emitted: set = set()

    def process_batch(self, batch: RecordBatch, input_index: int = 0
                      ) -> List[RecordBatch]:
        if len(batch) == 0:
            return []
        part_cols = [np.asarray(e.eval(batch)) for e in self.partition_by]
        if not part_cols:
            keys = [()] * len(batch)
        else:
            keys = list(zip(*[c.tolist() for c in part_cols]))
        ts = batch.timestamps if batch.has_timestamps else \
            np.zeros(len(batch), dtype=np.int64)
        uniq = {}
        for i, k in enumerate(keys):
            uniq.setdefault(k, []).append(i)
        for k, idxs in uniq.items():
            if k in self._emitted:
                continue  # late for an already-ranked partition
            sub = batch.take(np.asarray(idxs, dtype=np.int64))
            self._buffers.setdefault(k, []).append(sub)
            self._buffer_ts[k] = max(self._buffer_ts.get(k, 0),
                                     int(ts[idxs].max()))
        return []

    def process_watermark(self, watermark: int, input_index: int = 0
                          ) -> List[RecordBatch]:
        ready = [k for k, t in self._buffer_ts.items() if t <= watermark]
        out: List[RecordBatch] = []
        for k in ready:
            batches = self._buffers.pop(k)
            del self._buffer_ts[k]
            self._emitted.add(k)
            merged = RecordBatch.concat(batches)
            ranked = self._rank(merged)
            if ranked is not None and len(ranked):
                out.append(ranked)
        return out

    def _rank(self, batch: RecordBatch) -> Optional[RecordBatch]:
        n = len(batch)
        if n == 0:
            return None
        sort_cols = []
        for e, desc in reversed(self.order_by):
            v = np.asarray(e.eval(batch))
            if v.dtype == object:
                v = np.array([str(x) for x in v])
            sort_cols.append(-v if desc and v.dtype.kind in "iuf" else v)
        order = np.lexsort(sort_cols) if sort_cols else np.arange(n)
        ranked = batch.take(order)
        if self.rank_kind == "RANK" and self.order_by:
            vals = np.stack([np.asarray(e.eval(ranked), dtype=np.float64)
                             for e, _ in self.order_by], axis=1)
            new_group = np.any(vals[1:] != vals[:-1], axis=1)
            # RANK with gaps: a row's rank = 1 + index of the first row of
            # its tie group
            group_start = np.concatenate([[0], np.flatnonzero(new_group) + 1])
            starts = np.zeros(n, dtype=np.int64)
            starts[group_start] = group_start
            rank = np.maximum.accumulate(starts) + 1
        else:
            rank = np.arange(1, n + 1, dtype=np.int64)
        ranked = ranked.with_column(self.rank_field, rank)
        if self.top_n is not None:
            ranked = ranked.filter(rank <= self.top_n)
        return ranked

    def close(self) -> List[RecordBatch]:
        # end of stream: flush everything still buffered
        return self.process_watermark(np.iinfo(np.int64).max)

    def snapshot_state(self):
        return {
            "buffers": {k: [b.columns for b in v]
                        for k, v in self._buffers.items()},
            "buffer_ts": dict(self._buffer_ts),
            "emitted": list(self._emitted),
        }

    def restore_state(self, state):
        self._buffers = {k: [RecordBatch(c) for c in v]
                         for k, v in state.get("buffers", {}).items()}
        self._buffer_ts = dict(state.get("buffer_ts", {}))
        self._emitted = set(tuple(e) if isinstance(e, list) else e
                            for e in state.get("emitted", []))
