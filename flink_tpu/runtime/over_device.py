"""Device OVER aggregation — every frame of every key in one fused kernel.

reference: the flink-table-runtime over-window functions
(RowTimeRowsBoundedPrecedingFunction.java:1,
RowTimeRangeBoundedPrecedingFunction.java,
RowTimeRangeUnboundedPrecedingFunction.java) process one row at a time
against per-key MapState frame buffers.

Re-design: the host engine (over_agg.py) already collapsed that to one
vectorized pass per key segment — but it still loops Python/NumPy per
key. This engine removes the loop: one jitted XLA kernel computes every
frame of every key in the fire:

- segments ride a boundary-flag column (no key values enter the kernel);
- SUM / COUNT / AVG: global prefix sums, frame totals by gather
  (``cs[end] - cs[start]``) — segment bases cancel;
- ROWS MIN/MAX: a segmented running-min (``lax.associative_scan`` with a
  (flag, value) combiner) covers frames clipped at the segment start;
  full-width frames use the classic two-overlapping-power-of-two-block
  trick (static window => static shift/depth, fully unrolled by XLA);
- RANGE bounds: timestamps are monotonicized across segments
  (``g = seg_idx * 2^41 + ts_rel``) so ONE global ``searchsorted``
  yields every per-segment frame bound; peers (equal rowtime) fall out
  of the right-bound search;
- UNBOUNDED accumulators are synthetic context rows (value = running
  aggregate, weight = running count) prepended to their segment, so
  carry-over costs nothing in the kernel.

Context rows (the last ``n`` rows / interval tail per key, or the
accumulator rows) live in FLAT host arrays, filtered per fire with
``np.isin`` and merged back vectorized — no per-key Python anywhere.

Falls back to the host engine (engine='host' or unsupported shapes —
bounded RANGE MIN/MAX, oversized timestamp spans) at plan time.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.over_agg import OverAggOperator, OverSpec

#: per-segment timestamp offset for the monotonicized RANGE search; spans
#: (ts range + preceding) must stay below it — guarded at fire time
_TS_OFFSET = np.int64(1) << 41

#: timestamp sentinel of synthetic accumulator context rows (UNBOUNDED
#: carry-over) — below every real timestamp so they sort to their
#: segment's head
_SYNTH_TS = -(np.int64(1) << 60)

_SUMLIKE = ("SUM", "AVG", "COUNT")


def device_supported(specs: List[OverSpec], mode: str,
                     preceding: Optional[int]) -> bool:
    """Bounded RANGE MIN/MAX needs variable-width window reductions —
    the one frame family without a clean scan/gather form; keep it on
    the host engine."""
    if mode == "RANGE" and preceding is not None:
        return all(f in _SUMLIKE for f, _, _ in specs)
    return True


def _floor_log2(w: int) -> int:
    return max(w.bit_length() - 1, 0)


def _build_kernel(funcs: Tuple[str, ...], mode: str,
                  preceding: Optional[int]):
    """Returns jit(boundary, seg_start, starts, ends, peer_last,
    vals[S,n], wts[S,n]) -> (outs[S,n], run_sums[S,n], run_cnts[S,n]).

    Index arrays (frame bounds, peer positions, segment starts) arrive
    precomputed from the host — they are int64 searchsorted/accumulate
    over tiny arrays, which NumPy does in microseconds, while the
    float scans/gathers (the FLOP- and bandwidth-heavy part) fuse into
    one XLA program. This split also sidesteps 32-bit-int truncation
    under the default JAX_ENABLE_X64=0."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    unbounded = preceding is None

    def seg_scan(op, boundary, x):
        """Segmented running reduce: op over each segment prefix."""

        def combine(a, b):
            f1, v1 = a
            f2, v2 = b
            return f1 | f2, jnp.where(f2, v2, op(v1, v2))

        _, out = lax.associative_scan(combine, (boundary, x))
        return out

    def kernel(boundary, seg_start, starts, ends, peer_last, vals, wts):
        n = boundary.shape[0]
        idx = jnp.arange(n)
        outs, run_sums, run_cnts = [], [], []
        for i, func in enumerate(funcs):
            v, w = vals[i], wts[i]
            cs = jnp.concatenate([jnp.zeros(1, v.dtype), jnp.cumsum(v)])
            cw = jnp.concatenate([jnp.zeros(1, w.dtype), jnp.cumsum(w)])
            if unbounded:
                # prefix aggregate from segment start (bases cancel via
                # the gather at seg_start); peers via peer_last gather
                run_s = jnp.take(cs, idx + 1) - jnp.take(cs, seg_start)
                run_c = jnp.take(cw, idx + 1) - jnp.take(cw, seg_start)
                if func in _SUMLIKE:
                    row = (run_s if func == "SUM"
                           else run_c if func == "COUNT"
                           else run_s / run_c)
                else:
                    op = jnp.minimum if func == "MIN" else jnp.maximum
                    row = seg_scan(op, boundary, v)
                outs.append(jnp.take(row, peer_last))
                run_sums.append(row if func in ("MIN", "MAX") else run_s)
                run_cnts.append(run_c)
            else:
                if func in _SUMLIKE:
                    tot = jnp.take(cs, ends) - jnp.take(cs, starts)
                    cnt = jnp.take(cw, ends) - jnp.take(cw, starts)
                    outs.append(tot if func == "SUM"
                                else cnt if func == "COUNT"
                                else tot / cnt)
                else:  # ROWS MIN/MAX (RANGE MIN/MAX is host-only)
                    op = jnp.minimum if func == "MIN" else jnp.maximum
                    ident = np.inf if func == "MIN" else -np.inf
                    run = seg_scan(op, boundary, v)
                    wwin = preceding + 1
                    k = _floor_log2(wwin)
                    # m covers [j - 2^k + 1, j] after k doubling steps
                    m = v
                    for step in range(k):
                        sh = 1 << step
                        m = op(m, jnp.concatenate(
                            [jnp.full(sh, ident, m.dtype), m[:-sh]]))
                    rest = wwin - (1 << k)
                    two_block = op(m, jnp.take(
                        m, jnp.maximum(idx - rest, 0)))
                    pos = idx - seg_start
                    outs.append(jnp.where(pos >= wwin - 1,
                                          two_block, run))
                run_sums.append(jnp.take(cs, idx + 1))
                run_cnts.append(jnp.take(cw, idx + 1))
        return (jnp.stack(outs), jnp.stack(run_sums),
                jnp.stack(run_cnts))

    return jax.jit(kernel)


class DeviceOverAggOperator(OverAggOperator):
    """OverAggOperator with the fused device compute path.

    Inherits ingest/late-row/watermark handling; replaces ``_compute``
    and keeps context in flat arrays (kid, ts, per-spec val/weight)
    instead of per-key dicts.
    """

    name = "over_agg_device"

    def __init__(self, key_field: str, specs: List[OverSpec],
                 mode: str = "ROWS", preceding: Optional[int] = None):
        super().__init__(key_field, specs, mode=mode, preceding=preceding)
        if not device_supported(specs, mode, preceding):
            raise ValueError(
                "bounded RANGE MIN/MAX has no device form — use the "
                "host engine (table.exec.over.engine=host)")
        S = len(specs)
        self._ctx_kid = np.empty(0, dtype=np.int64)
        self._ctx_ts = np.empty(0, dtype=np.int64)
        self._ctx_val = [np.empty(0) for _ in range(S)]
        self._ctx_wt = [np.empty(0) for _ in range(S)]
        self._fallback = False
        self._kernel = _build_kernel(
            tuple(f for f, _, _ in specs), mode, preceding)

    def _degrade_to_host(self) -> None:
        if not self._fallback:
            self._fallback = True
            for k in np.unique(self._ctx_kid).tolist():
                mask = self._ctx_kid == k
                if self.preceding is None:
                    self._accs[int(k)] = [
                        (float(self._ctx_val[i][mask][0]),
                         float(self._ctx_wt[i][mask][0]))
                        for i in range(len(self.specs))]
                else:
                    o = np.argsort(self._ctx_ts[mask], kind="stable")
                    ctx = {"ts": self._ctx_ts[mask][o]}
                    for i in range(len(self.specs)):
                        ctx[f"v{i}"] = self._ctx_val[i][mask][o]
                    self._context[int(k)] = ctx
            self._ctx_kid = np.empty(0, dtype=np.int64)
            self._ctx_ts = np.empty(0, dtype=np.int64)
            self._ctx_val = [np.empty(0) for _ in self.specs]
            self._ctx_wt = [np.empty(0) for _ in self.specs]

    # ------------------------------------------------------------ compute

    def _compute(self, ready: RecordBatch) -> Optional[RecordBatch]:
        n = len(ready)
        S = len(self.specs)
        kid = self._key_ids(ready)
        ts = np.asarray(ready.timestamps, dtype=np.int64)
        order = np.lexsort((ts, kid))
        ready = ready.take(order)
        kid, ts = kid[order], ts[order]
        vals = self._arg_values(ready, n)
        wts = [np.ones(n) for _ in range(S)]

        # pull context rows of the keys present in this fire
        hit = np.isin(self._ctx_kid, kid)
        c_kid, c_ts = self._ctx_kid[hit], self._ctx_ts[hit]
        c_val = [v[hit] for v in self._ctx_val]
        c_wt = [w[hit] for w in self._ctx_wt]

        all_kid = np.concatenate([c_kid, kid])
        all_ts = np.concatenate([c_ts, ts])
        is_new = np.r_[np.zeros(len(c_kid), bool), np.ones(n, bool)]
        # context ts <= emitted watermark < new-row ts, so a stable sort
        # by (kid, ts) lands context first and keeps the emitted rows in
        # ready order
        o2 = np.lexsort((all_ts, all_kid))
        all_kid, all_ts, is_new = all_kid[o2], all_ts[o2], is_new[o2]
        all_val = [np.concatenate([cv, v])[o2]
                   for cv, v in zip(c_val, vals)]
        all_wt = [np.concatenate([cw, w])[o2] for cw, w in zip(c_wt, wts)]

        m = len(all_kid)
        boundary = np.r_[True, all_kid[1:] != all_kid[:-1]]
        # synthetic accumulator rows (ts = _SYNTH_TS) sit at their
        # segment head by construction; clamping them to ts_rel = 0
        # (below every real row's >= 1) keeps the monotonicized search
        # exact while the span guard sees only REAL timestamps —
        # otherwise the 2^60 sentinel trips the guard on the second fire
        # and RANGE UNBOUNDED silently degrades to the host engine
        # forever
        synth = all_ts == _SYNTH_TS
        real_ts = all_ts[~synth]
        base = real_ts.min() if len(real_ts) else np.int64(0)
        ts_rel = np.where(synth, np.int64(0), all_ts - base + 1)
        if self._fallback or (self.mode == "RANGE" and (
                int(ts_rel.max()) + (self.preceding or 0) >= _TS_OFFSET
                or int(boundary.sum()) >= (1 << 21))):
            # the fire exceeds the monotonicized search's span budget
            # (ts range + preceding >= 2^41, or >= 2M segments): degrade
            # PERMANENTLY to the host engine, converting flat context to
            # its per-key form first so no frame history is lost
            self._degrade_to_host()
            return super()._compute(ready)

        # host-side index arrays (vectorized int64; see _build_kernel)
        idx = np.arange(m, dtype=np.int64)
        seg_start = np.maximum.accumulate(np.where(boundary, idx, 0))
        if self.mode == "RANGE":
            # monotonicize timestamps across segments so ONE global
            # searchsorted yields every per-segment frame bound
            g = np.cumsum(boundary.astype(np.int64)) * _TS_OFFSET + ts_rel
            ends = np.searchsorted(g, g, side="right")
            starts = (np.searchsorted(
                g, g - np.int64(self.preceding), side="left")
                if self.preceding is not None else idx * 0)
            peer_last = ends - 1
        else:
            ends = idx + 1
            starts = (np.maximum(idx - self.preceding, seg_start)
                      if self.preceding is not None else idx * 0)
            peer_last = idx

        # pad to a power of two (bounded compilation count); the pad is
        # its own trailing segment and never emitted
        mp = max(1 << math.ceil(math.log2(max(m, 16))), 16)
        pad = mp - m

        def p(a, fill=0):
            return np.r_[a, np.full(pad, fill, dtype=a.dtype)] \
                if pad else a

        boundary_p = np.r_[boundary, np.zeros(pad, bool)]
        if pad:
            boundary_p[m] = True
        pad_idx = np.arange(m, mp, dtype=np.int64)
        i32 = np.int32
        import jax

        # ONE batched D2H for all three kernel outputs (per-array
        # np.asarray pays one link round-trip per output)
        outs, run_s, run_c = jax.device_get(self._kernel(
            boundary_p,
            np.r_[seg_start, pad_idx].astype(i32),
            np.r_[starts, pad_idx].astype(i32),
            np.r_[ends, pad_idx + 1].astype(i32),
            np.r_[peer_last, pad_idx].astype(i32),
            np.stack([p(v) for v in all_val]),
            np.stack([p(w) for w in all_wt])))
        outs = outs[:, :m]

        out = ready
        for (_, _, out_name), col in zip(self.specs, outs):
            out = out.with_column(out_name, col[is_new])

        self._update_context(all_kid, all_ts, all_val, boundary,
                             run_s[:, :m], run_c[:, :m], hit)
        return out

    # ------------------------------------------------------- context upkeep

    def _update_context(self, all_kid, all_ts, all_val, boundary,
                        run_s, run_c, hit) -> None:
        m = len(all_kid)
        seg_last = np.r_[np.flatnonzero(boundary)[1:] - 1, m - 1]
        if self.preceding is None:
            # one accumulator row per key: value = running aggregate at
            # the segment's last row, weight = running count; ts below
            # every real row so it sorts first next fire
            keep_kid = all_kid[seg_last]
            keep_ts = np.full(len(seg_last), _SYNTH_TS, dtype=np.int64)
            keep_val = [run_s[i][seg_last] for i in range(len(self.specs))]
            keep_wt = [run_c[i][seg_last] for i in range(len(self.specs))]
        else:
            # broadcast each segment's last index over its rows
            starts = np.flatnonzero(boundary)
            lengths = np.diff(np.r_[starts, m])
            seg_end = np.repeat(seg_last, lengths)
            if self.mode == "ROWS":
                # the last `preceding` rows of each segment stay in reach
                keep = (seg_end - np.arange(m)) < self.preceding
            else:
                keep = all_ts >= all_ts[seg_end] - self.preceding
            keep_kid = all_kid[keep]
            keep_ts = all_ts[keep]
            keep_val = [v[keep] for v in all_val]
            keep_wt = [np.ones(int(keep.sum()))
                       for _ in range(len(self.specs))]
        # merge with untouched context (keys absent from this fire)
        miss = ~hit
        self._ctx_kid = np.concatenate([self._ctx_kid[miss], keep_kid])
        self._ctx_ts = np.concatenate([self._ctx_ts[miss], keep_ts])
        self._ctx_val = [np.concatenate([cv[miss], kv])
                         for cv, kv in zip(self._ctx_val, keep_val)]
        self._ctx_wt = [np.concatenate([cw[miss], kw])
                        for cw, kw in zip(self._ctx_wt, keep_wt)]

    # --------------------------------------------------------------- state

    def snapshot_state(self) -> Dict[str, Any]:
        snap = super().snapshot_state()
        snap["over_device_ctx"] = {
            "kid": self._ctx_kid.copy(),
            "ts": self._ctx_ts.copy(),
            "val": [v.copy() for v in self._ctx_val],
            "wt": [w.copy() for w in self._ctx_wt],
        }
        return snap

    def restore_state(self, state: Dict[str, Any],
                      key_group_filter=None) -> None:
        super().restore_state(state, key_group_filter=key_group_filter)
        ctx = state.get("over_device_ctx")
        if ctx is None:
            return
        kid = np.asarray(ctx["kid"], dtype=np.int64)
        keep = np.ones(len(kid), bool)
        if key_group_filter is not None and len(kid):
            from flink_tpu.state.keygroups import assign_key_groups

            groups = assign_key_groups(kid, self.max_parallelism)
            keep = np.isin(groups, np.asarray(sorted(key_group_filter)))
        self._ctx_kid = kid[keep]
        self._ctx_ts = np.asarray(ctx["ts"], dtype=np.int64)[keep]
        self._ctx_val = [np.asarray(v)[keep] for v in ctx["val"]]
        self._ctx_wt = [np.asarray(w)[keep] for w in ctx["wt"]]
