"""Unwindowed GROUP BY — running keyed aggregation with upsert emission.

reference: flink-table-runtime/.../aggregate/GroupAggFunction.java:85
(processElement reads accState.value(), folds one record, writes back, and
emits the updated row downstream) and its MiniBatch variant
(MiniBatchGroupAggFunction.java:163 finishBundle).

Re-design: the per-key accumulators live in the device SlotTable under a
single namespace (namespace 0 — there is no window dimension); a micro-batch
folds in with ONE scatter kernel per accumulator leaf, then the current value
of every key *touched by the batch* is read back and emitted as an upsert
(latest-value-wins, matching the reference's retract+insert pair collapsed
into one changelog-upsert row — the reference emits UPDATE_BEFORE/UPDATE_AFTER;
downstream consumers here key on the group columns).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.operators import Operator
from flink_tpu.state.slot_table import SlotTable
from flink_tpu.windowing.aggregates import AggregateFunction

_GLOBAL_NS = 0


class GroupAggOperator(Operator):
    name = "group_agg"

    def __init__(self, agg: AggregateFunction, key_field: str,
                 capacity: int = 1 << 16,
                 emit_on_watermark_only: bool = False):
        self.agg = agg
        self.key_field = key_field
        self.capacity = capacity
        #: True = suppress per-batch upserts, emit one final table per
        #: watermark advance (MiniBatch-style deduped emission)
        self.emit_on_watermark_only = emit_on_watermark_only
        self.table: Optional[SlotTable] = None
        self._key_values: Dict[int, Any] = {}
        self._keys_hashed = False
        self._dirty: set = set()
        self._max_ts = 0

    def open(self, ctx):
        self.table = SlotTable(self.agg, capacity=self.capacity,
                               max_parallelism=ctx.max_parallelism)

    def process_batch(self, batch: RecordBatch, input_index: int = 0
                      ) -> List[RecordBatch]:
        if len(batch) == 0:
            return []
        if batch.has_timestamps:
            self._max_ts = max(self._max_ts, int(batch.timestamps.max()))
        if self.key_field in batch.columns:
            keys = batch[self.key_field]
            if keys.dtype.kind not in "iu":
                self._keys_hashed = True
                kid = batch.key_ids
                uniq, first = np.unique(kid, return_index=True)
                for i, j in zip(uniq.tolist(), first.tolist()):
                    self._key_values.setdefault(i, keys[j])
        namespaces = np.full(len(batch), _GLOBAL_NS, dtype=np.int64)
        slots = self.table.lookup_or_insert(batch.key_ids, namespaces)
        self.table.scatter(slots, self.agg.map_input(batch))
        if self.emit_on_watermark_only:
            self._dirty.update(np.unique(slots).tolist())
            return []
        out = self._emit_slots(np.unique(slots))
        return [out] if out is not None else []

    def process_watermark(self, watermark, input_index=0):
        if not self.emit_on_watermark_only or not self._dirty:
            return []
        slots = np.fromiter(self._dirty, dtype=np.int64)
        self._dirty.clear()
        out = self._emit_slots(slots)
        return [out] if out is not None else []

    def _emit_slots(self, slots: np.ndarray) -> Optional[RecordBatch]:
        if len(slots) == 0:
            return None
        results = self.table.fire(slots[:, None].astype(np.int32))
        kid = self.table.keys_of_slots(slots)
        if self._keys_hashed:
            kv = np.array([self._key_values.get(int(i)) for i in kid],
                          dtype=object)
        else:
            kv = kid
        cols = {
            KEY_ID_FIELD: kid,
            self.key_field: kv,
            TIMESTAMP_FIELD: np.full(len(slots), self._max_ts, dtype=np.int64),
        }
        cols.update(results)
        return RecordBatch(cols)

    def snapshot_state(self):
        return {
            "table": self.table.snapshot(),
            "key_values": dict(self._key_values),
            "keys_hashed": self._keys_hashed,
            "max_ts": self._max_ts,
        }

    def snapshot_state_delta(self):
        """Incremental: dirty rows + tombstones only (see
        SlotTable.snapshot_delta)."""
        return {
            "table": self.table.snapshot_delta(),
            "key_values": dict(self._key_values),
            "keys_hashed": self._keys_hashed,
            "max_ts": self._max_ts,
        }

    def snapshot_state_savepoint(self):
        """Full state without resetting the incremental base."""
        return {
            "table": self.table.snapshot(reset_dirty=False),
            "key_values": dict(self._key_values),
            "keys_hashed": self._keys_hashed,
            "max_ts": self._max_ts,
        }

    def query_state(self, key_value, namespace=None):
        """Queryable-state point lookup (see WindowAggOperator)."""
        from flink_tpu.state.keygroups import hash_keys_to_i64

        key_id = int(hash_keys_to_i64(np.asarray([key_value]))[0])
        return self.table.query(key_id, namespace)

    def restore_state(self, state):
        self.table.restore(state["table"])
        self._key_values = dict(state.get("key_values", {}))
        self._keys_hashed = state.get("keys_hashed", False)
        self._max_ts = state.get("max_ts", 0)
