"""Unwindowed GROUP BY — running keyed aggregation with changelog emission.

reference: flink-table-runtime/.../aggregate/GroupAggFunction.java:85
(processElement reads accState.value(), folds one record, writes back, and
emits retract+insert pairs downstream; `firstRow` decides INSERT vs
UPDATE_BEFORE/UPDATE_AFTER, and a row-count accumulator decides DELETE) and
its MiniBatch variant (MiniBatchGroupAggFunction.java:163 finishBundle).

Re-design: the per-key accumulators live in the device SlotTable under a
single namespace (namespace 0 — there is no window dimension); a micro-batch
folds in with ONE scatter kernel per accumulator leaf. Emission is a
changelog (RowKind column, flink_tpu.core.records.ROWKIND_FIELD):

- first value of a key             -> INSERT
- updated value                    -> UPDATE_BEFORE(prev) + UPDATE_AFTER(new)
- row count falls to zero          -> DELETE(prev)

The UPDATE_BEFORE image is the value at the key's previous *emission*
(tracked in host arrays), so no extra device read is needed — exactly the
reference's contract, where the retraction carries the previously emitted
row. Retraction INPUT (a second-level aggregate over an updating stream) is
consumed by folding each row's contribution with its changelog sign in one
signed scatter; this requires every accumulator leaf to be additive
(``AggregateFunction.retractable`` — COUNT/SUM/AVG yes, MAX/MIN no, like
the reference's retractable agg function family).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.core.records import (
    KEY_ID_FIELD,
    ROWKIND_DELETE,
    ROWKIND_FIELD,
    ROWKIND_INSERT,
    ROWKIND_UPDATE_AFTER,
    ROWKIND_UPDATE_BEFORE,
    TIMESTAMP_FIELD,
    RecordBatch,
    rowkind_signs,
)
from flink_tpu.runtime.operators import Operator
from flink_tpu.state.slot_table import SlotTable
from flink_tpu.windowing.aggregates import AggregateFunction

_GLOBAL_NS = 0


class GroupAggOperator(Operator):
    name = "group_agg"

    def __init__(self, agg: AggregateFunction, key_field: str,
                 capacity: int = 1 << 16,
                 emit_on_watermark_only: bool = False,
                 generate_update_before: bool = True,
                 ttl_ms: Optional[int] = None, clock=None):
        self.agg = agg
        self.key_field = key_field
        self.capacity = capacity
        #: True = suppress per-batch emission, emit one deduped changelog
        #: per watermark advance (MiniBatch-style emission)
        self.emit_on_watermark_only = emit_on_watermark_only
        #: False = upsert mode: UPDATE_AFTER only (no retraction images),
        #: DELETEs still emitted — for upsert-keyed sinks
        self.generate_update_before = generate_update_before
        #: idle-state retention: accumulators untouched for ttl_ms are
        #: dropped (slot freed, snapshot shrinks); a key arriving after
        #: expiry re-INSERTs — the reference's documented
        #: `table.exec.state.ttl` semantics (reference: StateTtlConfig +
        #: GroupAggFunction's stateRetentionTime cleanup timer). Silent
        #: drop, no DELETE emission, like the reference.
        self.ttl_ms = ttl_ms
        from flink_tpu.state.ttl import SweepGate, default_clock

        self._clock = clock or default_clock
        self._sweep_gate = SweepGate(ttl_ms) if ttl_ms else None
        self.table: Optional[SlotTable] = None
        self._key_values: Dict[int, Any] = {}
        self._keys_hashed = False
        self._dirty: set = set()
        self._max_ts = 0
        # per-slot changelog bookkeeping (host; grown with the table)
        self._row_counts = np.zeros(0, dtype=np.int64)
        self._emitted_mask = np.zeros(0, dtype=bool)
        self._last_emitted: Dict[str, np.ndarray] = {}
        #: per-slot last-update processing-time stamp (-1 = free)
        self._last_update = np.zeros(0, dtype=np.int64)

    def open(self, ctx):
        mm = getattr(ctx, "memory_manager", None)
        self.table = SlotTable(
            self.agg, capacity=self.capacity,
            max_parallelism=ctx.max_parallelism,
            memory=(mm, f"{self.name}#{id(self):x}") if mm else None)

    def dispose(self):
        if self.table is not None:
            self.table.release_memory()

    # ------------------------------------------------------------- host state

    def _ensure_host_capacity(self, n: int) -> None:
        if n <= len(self._row_counts):
            return
        size = max(n, 2 * len(self._row_counts), 1024)
        grown = np.zeros(size, dtype=np.int64)
        grown[: len(self._row_counts)] = self._row_counts
        self._row_counts = grown
        mask = np.zeros(size, dtype=bool)
        mask[: len(self._emitted_mask)] = self._emitted_mask
        self._emitted_mask = mask
        stamps = np.full(size, -1, dtype=np.int64)
        stamps[: len(self._last_update)] = self._last_update
        self._last_update = stamps
        for name, arr in self._last_emitted.items():
            g = np.zeros(size, dtype=arr.dtype)
            g[: len(arr)] = arr
            self._last_emitted[name] = g

    # --------------------------------------------------------------- TTL

    def _maybe_sweep_ttl(self) -> None:
        """Vectorized idle-state expiry: one masked scan per sweep
        interval (ttl/4, floor 1 ms) instead of the reference's
        per-key cleanup timers."""
        if not self.ttl_ms:
            return
        now = self._clock()
        if not self._sweep_gate.should_sweep(now):
            return
        n = len(self._last_update)
        if n == 0:
            return
        stamps = self._last_update
        expired = np.nonzero((stamps != -1)
                             & (now - stamps > self.ttl_ms))[0]
        if not len(expired):
            return
        if self._keys_hashed:
            for kid in self.table.keys_of_slots(expired).tolist():
                self._key_values.pop(int(kid), None)
        self.table.free_slots(expired)
        self._row_counts[expired] = 0
        self._emitted_mask[expired] = False
        stamps[expired] = -1
        if self._dirty:
            self._dirty.difference_update(expired.tolist())

    # ----------------------------------------------------------------- ingest

    def process_batch(self, batch: RecordBatch, input_index: int = 0
                      ) -> List[RecordBatch]:
        if len(batch) == 0:
            return []
        if batch.has_timestamps:
            self._max_ts = max(self._max_ts, int(batch.timestamps.max()))
        if self.key_field in batch.columns:
            keys = batch[self.key_field]
            if keys.dtype.kind not in "iu":
                self._keys_hashed = True
                kid = batch.key_ids
                uniq, first = np.unique(kid, return_index=True)
                for i, j in zip(uniq.tolist(), first.tolist()):
                    self._key_values.setdefault(i, keys[j])
        namespaces = np.full(len(batch), _GLOBAL_NS, dtype=np.int64)
        slots = self.table.lookup_or_insert(batch.key_ids, namespaces)
        kinds = batch.row_kinds
        signs = None if kinds is None else rowkind_signs(np.asarray(kinds))
        if signs is None or not (signs < 0).any():
            # append-only input (possibly an all-INSERT changelog) — the
            # plain scatter path works for every aggregate, incl. MAX/MIN
            self.table.scatter(slots, self.agg.map_input(batch))
            signs = None
        else:
            if not self.agg.retractable:
                raise ValueError(
                    "aggregate over an updating (retraction) input requires "
                    "retractable accumulators (COUNT/SUM/AVG); "
                    f"{type(self.agg).__name__} has MAX/MIN-style leaves "
                    "(reference: GroupAggFunction requires retract() for "
                    "update streams)")
            self.table.scatter_signed(
                slots, self.agg.map_input_signed(batch, signs))
        self._ensure_host_capacity(int(slots.max()) + 1)
        np.add.at(self._row_counts, slots,
                  1 if signs is None else signs.astype(np.int64))
        if self.ttl_ms:
            self._last_update[slots] = self._clock()
            self._maybe_sweep_ttl()
        if self.emit_on_watermark_only:
            self._dirty.update(np.unique(slots).tolist())
            return []
        out = self._emit_slots(np.unique(slots))
        return [out] if out is not None else []

    def process_watermark(self, watermark, input_index=0):
        if not self.emit_on_watermark_only or not self._dirty:
            self._maybe_sweep_ttl()
            return []
        slots = np.fromiter(self._dirty, dtype=np.int64)
        self._dirty.clear()
        out = self._emit_slots(slots)
        self._maybe_sweep_ttl()
        return [out] if out is not None else []

    # --------------------------------------------------------------- emission

    def _emit_slots(self, slots: np.ndarray) -> Optional[RecordBatch]:
        if len(slots) == 0:
            return None
        results = self.table.fire(slots[:, None].astype(np.int32))
        self._ensure_host_capacity(int(slots.max()) + 1)
        counts = self._row_counts[slots]
        live = counts > 0
        was_emitted = self._emitted_mask[slots]
        # lazily allocate last-emitted storage from the first result dtypes
        for name, col in results.items():
            if name not in self._last_emitted:
                self._last_emitted[name] = np.zeros(
                    len(self._row_counts), dtype=np.asarray(col).dtype)

        segments: List[Dict[str, np.ndarray]] = []

        def _segment(slot_sel: np.ndarray, kind: int, from_prev: bool):
            if not slot_sel.any():
                return
            sl = slots[slot_sel]
            if from_prev:
                vals = {n: self._last_emitted[n][sl] for n in results}
            else:
                vals = {n: np.asarray(results[n])[slot_sel] for n in results}
            segments.append({
                "slots": sl,
                ROWKIND_FIELD: np.full(len(sl), kind, dtype=np.int8),
                **vals,
            })

        upd = live & was_emitted
        if self.generate_update_before:
            _segment(upd, ROWKIND_UPDATE_BEFORE, from_prev=True)
        _segment(~live & was_emitted, ROWKIND_DELETE, from_prev=True)
        _segment(live & ~was_emitted, ROWKIND_INSERT, from_prev=False)
        _segment(upd, ROWKIND_UPDATE_AFTER, from_prev=False)

        # roll the changelog bookkeeping forward
        for name in results:
            arr = self._last_emitted[name]
            arr[slots[live]] = np.asarray(results[name])[live]
        self._emitted_mask[slots] = live

        if not segments:
            return None
        all_slots = np.concatenate([s.pop("slots") for s in segments])
        kid = self.table.keys_of_slots(all_slots)
        if self._keys_hashed:
            kv = np.array([self._key_values.get(int(i)) for i in kid],
                          dtype=object)
        else:
            kv = kid
        cols: Dict[str, np.ndarray] = {
            KEY_ID_FIELD: kid,
            self.key_field: kv,
            TIMESTAMP_FIELD: np.full(len(all_slots), self._max_ts,
                                     dtype=np.int64),
        }
        for name in segments[0]:
            cols[name] = np.concatenate([s[name] for s in segments])
        return RecordBatch(cols)

    # ------------------------------------------------------------- checkpoint

    def _host_state(self):
        # the changelog bookkeeping is stored LOGICALLY (keyed by key_id,
        # not by physical slot) so snapshots merge across subtasks and
        # restore into any slot layout (key-group re-assignment, multi-slot
        # union — same portability contract as the slot table rows)
        interesting = np.nonzero((self._row_counts != 0)
                                 | self._emitted_mask)[0]
        # minibatch emission state: slots whose change is still pending a
        # watermark flush must survive a restore or their final rows would
        # be silently lost (batch mode defers ALL emission to end-of-input)
        dirty = np.zeros(len(interesting), dtype=bool)
        if self._dirty:
            dirty = np.isin(interesting,
                            np.fromiter(self._dirty, dtype=np.int64))
        cl = {
            "key_id": self.table.keys_of_slots(interesting),
            "count": self._row_counts[interesting],
            "emitted": self._emitted_mask[interesting],
            "dirty": dirty,
            "last": {n: a[interesting]
                     for n, a in self._last_emitted.items()},
        }
        if self.ttl_ms:
            # stamps travel logically so restore resumes each key's
            # REMAINING lifetime (reference: TTL state restores with its
            # original timestamps)
            cl["ttl_last_update"] = self._last_update[interesting]
        return {
            "key_values": dict(self._key_values),
            "keys_hashed": self._keys_hashed,
            "max_ts": self._max_ts,
            "changelog": cl,
        }

    def snapshot_state(self):
        return {"table": self.table.snapshot(), **self._host_state()}

    def snapshot_state_delta(self):
        """Incremental: dirty rows + tombstones only (see
        SlotTable.snapshot_delta)."""
        return {"table": self.table.snapshot_delta(), **self._host_state()}

    def snapshot_state_savepoint(self):
        """Full state without resetting the incremental base."""
        return {"table": self.table.snapshot(reset_dirty=False),
                **self._host_state()}

    def query_state(self, key_value, namespace=None):
        """Queryable-state point lookup (see WindowAggOperator)."""
        from flink_tpu.state.keygroups import hash_keys_to_i64

        key_id = int(hash_keys_to_i64(np.asarray([key_value]))[0])
        return self.table.query(key_id, namespace)

    def restore_state(self, state, key_group_filter=None):
        self.table.restore(state["table"],
                           key_group_filter=key_group_filter)
        self._key_values = dict(state.get("key_values", {}))
        self._keys_hashed = state.get("keys_hashed", False)
        self._max_ts = state.get("max_ts", 0)
        self._row_counts = np.zeros(0, dtype=np.int64)
        self._emitted_mask = np.zeros(0, dtype=bool)
        self._last_update = np.zeros(0, dtype=np.int64)
        self._last_emitted = {}
        cl = state.get("changelog")
        if cl is None and "row_counts" in state:
            # legacy (round-2 snapshot) slot-indexed format: only valid
            # when restoring into the same slot layout, which holds because
            # the table rows above restored in snapshot order — but NOT
            # under a key-group filter, which compacts the table and
            # misaligns every slot index
            if key_group_filter is not None:
                raise RuntimeError(
                    "legacy slot-indexed changelog state cannot be "
                    "restored with a key-group filter (stage-parallel "
                    "restore) — take a fresh savepoint with the current "
                    "version first")
            self._row_counts = np.asarray(state["row_counts"],
                                          dtype=np.int64)
            self._emitted_mask = np.asarray(state["emitted_mask"],
                                            dtype=bool)
            self._last_emitted = {
                n: np.asarray(a)
                for n, a in state.get("last_emitted", {}).items()}
            return
        if cl is None or len(np.asarray(cl.get("key_id", ()))) == 0:
            return
        key_ids = np.asarray(cl["key_id"], dtype=np.int64)
        counts = np.asarray(cl["count"], dtype=np.int64)
        emitted = np.asarray(cl["emitted"], dtype=bool)
        dirty = np.asarray(cl.get("dirty", np.zeros(len(key_ids), bool)),
                           dtype=bool)
        if key_group_filter is not None:
            from flink_tpu.state.keygroups import assign_key_groups

            groups = assign_key_groups(key_ids, self.table.max_parallelism)
            keep = np.isin(groups, np.asarray(sorted(key_group_filter)))
            key_ids, counts, emitted, dirty = (
                key_ids[keep], counts[keep], emitted[keep], dirty[keep])
            cl_last = {n: np.asarray(a)[keep]
                       for n, a in cl.get("last", {}).items()}
        else:
            cl_last = {n: np.asarray(a) for n, a in cl.get("last", {}).items()}
        if len(key_ids) == 0:
            return
        # re-key the logical changelog onto this instance's slot layout
        ns = np.full(len(key_ids), _GLOBAL_NS, dtype=np.int64)
        slots = self.table.lookup_or_insert(key_ids, ns)
        self._ensure_host_capacity(int(slots.max()) + 1)
        self._row_counts[slots] = counts
        self._emitted_mask[slots] = emitted
        self._dirty.update(int(s) for s in slots[dirty])
        if self.ttl_ms and "ttl_last_update" in cl:
            stamps = np.asarray(cl["ttl_last_update"], dtype=np.int64)
            if key_group_filter is not None:
                stamps = stamps[keep]
            self._last_update[slots] = stamps
        for n, a in cl_last.items():
            arr = np.zeros(len(self._row_counts), dtype=a.dtype)
            arr[slots] = a
            self._last_emitted[n] = arr
