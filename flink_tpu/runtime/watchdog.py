"""Device watchdog: deadline-tracked device interactions + shard
quarantine — the DETECTION half of partial failover.

reference: the reference detects a dead TaskManager through heartbeat
timeouts (flink-runtime HeartbeatManager / TaskManagerRunner) and scopes
the restart to the failed pipelined region
(RestartPipelinedRegionFailoverStrategy). The mesh engines' analog of a
TaskManager is a SHARD (one device + its host-side slice of state), and
its "heartbeats" are the device interactions the engine performs anyway:
dispatch fences, fire harvests, batched ``device_get`` reads, serving
lookups. This module wraps those in deadline-tracked sections.

Design (micro-batch discipline):

- **Sections** (:meth:`DeviceWatchdog.section`) time one device
  interaction. A section that exceeds ``deadline_ms`` records a MISS —
  it never raises mid-interaction, because the engine may be half way
  through a batch whose partial effects on *surviving* shards could not
  be rolled back shard-locally.
- **Boundary probes** (:meth:`DeviceWatchdog.boundary_probe`) run at
  batch boundaries (top of ``process_batch`` / ``on_watermark``), where
  the engine is consistent at a known source position. The probe (a)
  fires the chaos ``device.lost`` fault point once per live shard, so a
  seeded plan can kill an exact shard at an exact boundary, and (b)
  escalates accumulated deadline misses: timeout -> retry (the next
  sections get another chance, with the same escalating-attempt
  bookkeeping ``run_recoverable`` uses) -> declare dead once the miss
  budget is spent. Declaring a shard dead quarantines it and raises
  :class:`ShardFailedError` — the signal the partial-failover path
  (``chaos.harness.run_shard_loss_verify``, and the executors' restart
  handling) consumes.
- Heartbeat gauges live in the job metric tree under a ``watchdog``
  group (:meth:`register_metrics`).

A real (non-injected) device failure surfaces as an exception from the
device interaction itself; callers translate it to a shard failure with
:meth:`declare_dead` where the failing shard is identifiable, and fall
back to whole-job restart where it is not.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from flink_tpu.chaos import injection as chaos


class ShardFailedError(RuntimeError):
    """A mesh shard was declared dead (device lost or persistently past
    its deadline). Recovery is SHARD-GRANULAR: survivors keep their live
    state; only the failed shard's key groups restore from its
    checkpoint unit and replay their range of the stream."""

    def __init__(self, shard: int, reason: str) -> None:
        super().__init__(
            f"shard {shard} declared dead: {reason} — partial failover "
            "(restore only that shard's key groups, replay only its "
            "range)")
        self.shard = int(shard)
        self.reason = reason


class HostFailedError(ShardFailedError):
    """A whole HOST (one process's slice of the mesh — ``local_devices``
    shards, one contiguous key-group range) was declared dead: the
    chaos ``host.lost`` point fired, or every one of the host's shards
    uniformly ran past the deadline-miss budget while other hosts
    stayed healthy — the attribution signature of a lost process /
    severed DCN link, not of one wedged chip. Recovery is
    HOST-GRANULAR: survivors evacuate, the dead host's ``k`` shard
    units restore, its contiguous range replays (bounded by the
    per-host share of the stream)."""

    def __init__(self, host: int, shards, reason: str) -> None:
        self.host = int(host)
        self.shards = tuple(int(s) for s in shards)
        # ShardFailedError compat: .shard carries the first member so
        # shard-granular consumers still attribute SOMETHING sensible
        RuntimeError.__init__(
            self,
            f"host {host} declared dead (shards "
            f"{list(self.shards)}): {reason} — host failover (restore "
            "that host's key-group ranges, replay only its span)")
        self.shard = self.shards[0] if self.shards else -1
        self.reason = reason


class MeshStalledError(RuntimeError):
    """EVERY live shard is past its deadline-miss budget at once.

    The engines' device programs are SPMD — whole-mesh sections charge
    a miss to every shard, so a uniform streak carries NO shard
    attribution. Quarantining an arbitrary shard (e.g. shard 0) would
    evacuate a healthy device onto the actually-wedged one and burn the
    loss budget on wrong-shard failovers; the honest escalation is a
    WHOLE-JOB failure (restart strategy -> full restore), which this
    error routes to. Shard-granular deadline attribution needs
    per-shard sections (``section(op, shard=k)``) — serving probes or
    per-device harvests."""


class _Section:
    """One timed device interaction (slotted: sections sit on per-batch
    paths the host-prep gate measures)."""

    __slots__ = ("_wd", "_op", "_shard", "_t0")

    def __init__(self, wd: "DeviceWatchdog", op: str, shard: int) -> None:
        self._wd = wd
        self._op = op
        self._shard = shard

    def __enter__(self) -> "_Section":
        self._t0 = self._wd._clock()
        # an injected slow device: a `delay`-kind rule here stretches
        # the section past its deadline, which is exactly how a real
        # wedged device program manifests (no exception — just time)
        chaos.fault_point("watchdog.deadline", op=self._op,
                          shard=self._shard)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._wd._observe(self._op, self._shard,
                          self._wd._clock() - self._t0,
                          failed=exc_type is not None)


class _NullSection:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


NULL_SECTION = _NullSection()


class DeviceWatchdog:
    """Deadline policy + shard health for one engine's device mesh.

    ``deadline_ms``: a section slower than this records a miss
    (0 disables deadline tracking — sections still heartbeat).
    ``max_misses``: consecutive deadline misses a shard survives before
    the next boundary probe declares it dead (the timeout -> retry ->
    declare-dead escalation; each miss is one spent "retry attempt",
    the same budget shape ``run_recoverable``'s strategy counts).
    A successful in-deadline section resets the shard's streak.
    """

    def __init__(self, num_shards: int, deadline_ms: float = 0.0,
                 max_misses: int = 3,
                 clock: Callable[[], float] = time.perf_counter,
                 device_ids: Optional[List[int]] = None) -> None:
        self.deadline_ms = float(deadline_ms)
        self.max_misses = max(int(max_misses), 1)
        self._clock = clock
        self.quarantined: set = set()
        #: PHYSICAL device ids ever quarantined (when the engine told
        #: us the shard->device mapping via rebind) — the cross-job
        #: dedup key: N tenants sharing a mesh each quarantine the same
        #: dead device, and the arbiter must count it ONCE, not N times
        self.quarantined_devices: set = set()
        self.sections_timed = 0
        self.deadline_misses = 0
        self.declared_dead = 0
        self.rebind(num_shards, device_ids)

    # ----------------------------------------------------------- lifecycle

    def rebind(self, num_shards: int,
               device_ids: Optional[List[int]] = None) -> None:
        """Point the watchdog at a rebuilt mesh of ``num_shards`` shards
        (after a partial failover the survivors renumber 0..P-2).
        Cumulative counters and the quarantine HISTORY (incl. device
        ids) survive; per-shard streaks reset with the new numbering.
        ``device_ids``: the shard->physical-device mapping, when the
        engine knows it."""
        self.num_shards = int(num_shards)
        now = self._clock()
        self._misses: List[int] = [0] * self.num_shards
        self._last_beat: List[float] = [now] * self.num_shards
        self._device_ids = (list(device_ids)
                            if device_ids is not None else None)
        self.quarantined = set()
        t = getattr(self, "_topology", None)
        if t is not None and t.num_shards != self.num_shards:
            # a failover/reshard renumbered the shards: the (hosts,
            # local) mapping no longer applies — host attribution is
            # off until an engine re-declares a topology
            self._topology = None

    #: HostTopology for HOST-granular escalation (None = shard-only)
    _topology = None

    def set_topology(self, topology) -> None:
        """Teach the watchdog the mesh's (hosts, local) factorization:
        the boundary probe then (a) fires the chaos ``host.lost`` point
        once per live host and (b) escalates a miss streak that
        uniformly covers exactly one host's shards — while other hosts
        stay healthy — to :class:`HostFailedError` instead of picking
        one member shard."""
        if topology is not None:
            topology.check_covers(self.num_shards)
        self._topology = topology

    # ------------------------------------------------------------ sections

    def section(self, op: str, shard: int = -1) -> _Section:
        """Context manager timing one device interaction. ``shard=-1``
        for whole-mesh programs (a miss then counts against every live
        shard — the mesh runs SPMD, so a wedged program implicates the
        mesh until a shard-attributable signal arrives)."""
        return _Section(self, op, shard)

    def _observe(self, op: str, shard: int, seconds: float,
                 failed: bool = False) -> None:
        self.sections_timed += 1
        now = self._clock()
        targets = ([shard] if 0 <= shard < self.num_shards
                   else range(self.num_shards))
        missed = (self.deadline_ms > 0
                  and seconds * 1000.0 > self.deadline_ms)
        for p in targets:
            if missed:
                self._misses[p] += 1
                self.deadline_misses += 1
            elif not failed:
                self._misses[p] = 0
                self._last_beat[p] = now
        if missed:
            # one instant per missed section (not per implicated shard:
            # a whole-mesh section carries no shard attribution) — lands
            # in the flight-recorder timeline next to the batch/fire
            # spans that were running when the device went quiet
            from flink_tpu.observe import flight_recorder as flight

            flight.instant("watchdog.miss",
                           shard=shard if 0 <= shard < self.num_shards
                           else -1)

    # ------------------------------------------------------------- boundary

    def boundary_probe(self) -> None:
        """The batch-boundary health check — the ONLY place a shard is
        declared dead, so the raising point always sees an engine that
        is consistent at a known source position (the micro-batch analog
        of failing over at a barrier, not mid-record)."""
        topo = self._topology
        if chaos.armed():
            if topo is not None:
                for h in range(topo.num_hosts):
                    members = [p for p in topo.shards_of_host(h)
                               if p not in self.quarantined]
                    if not members:
                        continue
                    try:
                        chaos.fault_point("host.lost", host=h)
                    except chaos.InjectedFault as f:
                        self.declare_host_dead(
                            h, members, f"host.lost injected ({f})")
            for p in range(self.num_shards):
                if p in self.quarantined:
                    continue
                try:
                    chaos.fault_point("device.lost", shard=p)
                except chaos.InjectedFault as f:
                    self.declare_dead(p, f"device.lost injected ({f})")
        live = [p for p in range(self.num_shards)
                if p not in self.quarantined]
        offenders = [p for p in live
                     if self._misses[p] >= self.max_misses]
        if not offenders:
            return
        if len(offenders) == len(live) and len(live) > 1:
            # uniform streak from whole-mesh (SPMD) sections: no shard
            # attribution exists — escalate to a WHOLE-JOB failure
            # instead of quarantining an arbitrary healthy device
            raise MeshStalledError(
                f"all {len(live)} live shards are past the deadline-"
                f"miss budget ({self.max_misses} misses at "
                f"{self.deadline_ms} ms) — mesh-wide stall, no shard "
                "attribution: whole-job restart")
        if topo is not None:
            # HOST escalation: a streak that uniformly covers EXACTLY
            # one host's live shards — no offenders anywhere else — is
            # the signature of a lost PROCESS (or severed DCN link),
            # not one wedged chip: declare the host, not a member. A
            # streak that spills outside one host carries mixed
            # attribution and stays shard-granular below.
            off = set(offenders)
            for h in range(topo.num_hosts):
                members = {p for p in topo.shards_of_host(h)
                           if p in live}
                if members and off == members:
                    self.declare_host_dead(
                        h, sorted(members),
                        f"uniform deadline-miss streak across all "
                        f"{len(members)} live shards of host {h} "
                        f"(budget {self.max_misses}, deadline "
                        f"{self.deadline_ms} ms)")
        p = offenders[0]
        self.declare_dead(
            p, f"{self._misses[p]} consecutive deadline misses "
               f"(budget {self.max_misses}, deadline "
               f"{self.deadline_ms} ms)")

    def declare_dead(self, shard: int, reason: str) -> None:
        self.quarantined.add(int(shard))
        if self._device_ids is not None \
                and 0 <= int(shard) < len(self._device_ids):
            self.quarantined_devices.add(self._device_ids[int(shard)])
        self.declared_dead += 1
        raise ShardFailedError(int(shard), reason)

    def declare_host_dead(self, host: int, shards,
                          reason: str) -> None:
        """Quarantine every shard of ``host`` at once and raise the
        host-granular failure (the escalation ladder's HOST level)."""
        for p in shards:
            self.quarantined.add(int(p))
            if self._device_ids is not None \
                    and 0 <= int(p) < len(self._device_ids):
                self.quarantined_devices.add(self._device_ids[int(p)])
        self.declared_dead += 1
        self.hosts_declared_dead += 1
        raise HostFailedError(int(host), shards, reason)

    #: hosts declared dead over the watchdog's lifetime
    hosts_declared_dead = 0

    # -------------------------------------------------------------- signals

    def available(self, total_devices: int) -> int:
        """Devices usable for (re)scaling: a quarantined shard's device
        is out of the budget until an operator replaces it — the signal
        the autoscale bound clamping subtracts."""
        return max(int(total_devices) - len(self.quarantined), 1)

    def heartbeat_age_s(self) -> float:
        """Age of the STALEST live shard's last healthy interaction."""
        now = self._clock()
        ages = [now - self._last_beat[p] for p in range(self.num_shards)
                if p not in self.quarantined]
        return max(ages) if ages else 0.0

    def misses_by_shard(self) -> Dict[int, int]:
        return {p: m for p, m in enumerate(self._misses) if m}

    def register_metrics(self, group) -> None:
        """Heartbeat/health gauges under ``<scope>.watchdog``."""
        g = group.add_group("watchdog")
        g.gauge("sections_timed", lambda: self.sections_timed)
        g.gauge("deadline_misses", lambda: self.deadline_misses)
        g.gauge("shards_quarantined", lambda: len(self.quarantined))
        g.gauge("declared_dead", lambda: self.declared_dead)
        g.gauge("hosts_declared_dead",
                lambda: self.hosts_declared_dead)
        g.gauge("heartbeat_age_s", lambda: self.heartbeat_age_s())


def watchdog_from_config(config, num_shards: int
                         ) -> Optional[DeviceWatchdog]:
    """Build a watchdog from ``watchdog.*`` config, or None when
    disabled (the default — sections then cost one attribute check)."""
    from flink_tpu.core.config import WatchdogOptions

    if not config.get(WatchdogOptions.ENABLED):
        return None
    return DeviceWatchdog(
        num_shards,
        deadline_ms=config.get(WatchdogOptions.DEADLINE_MS),
        max_misses=config.get(WatchdogOptions.MAX_MISSES))
