from flink_tpu.checkpoint.sharded import ShardedCheckpointStorage
from flink_tpu.checkpoint.storage import CheckpointStorage, CheckpointMetadata

__all__ = ["CheckpointStorage", "CheckpointMetadata",
           "ShardedCheckpointStorage"]
