"""Checkpoint storage: durable snapshots + recovery.

reference: runtime/checkpoint/CheckpointCoordinator.java:575 (trigger),
runtime/state/filesystem (FsCheckpointStorage), savepoint format docs.
Re-design for the micro-batch engine: a checkpoint is a directory holding
(a) one .npz per stateful operator with its logical slot-table snapshot
(key_id / namespace / key_group / leaf arrays) — key-group indexed so restore
can re-shard (the rescale contract), and (b) a JSON manifest with source
positions and job metadata. Barrier alignment is structural (snapshot happens
between micro-batches), so ALIGNMENT costs nothing — but a barrier queued
behind a credit-stalled exchange still waits for the backlog, so the
stage-parallel executor supports unaligned checkpoints
(execution.checkpointing.unaligned): barriers overtake queued batches and
the overtaken data is stored under ``__channel_state__.*`` entries, replayed
through the consumer on restore (reference:
runtime/checkpoint/channel/ChannelStateWriterImpl.java).
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
import shutil
import tempfile
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.chaos import injection as chaos


class CheckpointCorruptedError(RuntimeError):
    """A snapshot failed integrity verification (torn write, bit rot,
    truncation). Callers fall back to an older complete checkpoint —
    silently restoring corrupt state is the one unforgivable failure
    mode (reference: Flink checkpoints fail loudly on corrupt streams;
    RocksDB verifies block checksums on read)."""


#: Snapshot format version (reference: TypeSerializerSnapshot versioning +
#: savepoint format versions). Bump when the on-disk layout changes and
#: register a migration; restore fails precisely on unknown versions.
#: v1 = round-1 layout (uncompressed, no version field); v2 = same logical
#: layout, compressed .npz allowed, version field present.
FORMAT_VERSION = 2

#: from_version -> fn(states: {uid: state}) -> states, migrating one step
#: forward. Chained until FORMAT_VERSION is reached.
_MIGRATIONS: Dict[int, Any] = {
    1: lambda states: states,  # v1 -> v2: layout unchanged, read-compatible
}


def register_migration(from_version: int, fn) -> None:
    """Install a one-step snapshot migration (from_version -> +1)."""
    _MIGRATIONS[from_version] = fn


@dataclasses.dataclass
class CheckpointMetadata:
    checkpoint_id: int
    timestamp_ms: int
    job_name: str
    operator_states: List[str]  # uids with .npz payloads
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)
    format_version: int = FORMAT_VERSION
    #: filename -> CRC32 of every payload file, computed before the
    #: atomic rename; verified on read so a torn/corrupted snapshot is
    #: DETECTED instead of silently restored. Empty for pre-CRC
    #: snapshots (read-compatible: verification simply skips).
    file_crcs: Dict[str, int] = dataclasses.field(default_factory=dict)


# --------------------------------------------------------------------------
# Directory-level snapshot IO (shared by periodic checkpoints, savepoints and
# the state processor API). A snapshot directory is self-contained:
# manifest.json + one .npz / .meta.pkl pair per stateful operator.
# --------------------------------------------------------------------------


def _split_state(state: Dict[str, Any]):
    """Separate flat numpy arrays (npz-able) from pickled host metadata."""
    arrays: Dict[str, np.ndarray] = {}

    def walk(prefix: str, obj: Any):
        if isinstance(obj, np.ndarray) and obj.dtype != object:
            arrays[prefix] = obj
        elif isinstance(obj, dict) and all(isinstance(k, str) for k in obj):
            sub_meta = {}
            for k, v in obj.items():
                r = walk(f"{prefix}.{k}" if prefix else k, v)
                if r is not None:
                    sub_meta[k] = r
            if sub_meta:
                return sub_meta
            return None
        else:
            return obj
        return None

    m = walk("", state)
    meta = m if isinstance(m, dict) else {}
    return arrays, {"meta": meta}


def _set_path(d: Dict[str, Any], dotted: str, value: Any) -> None:
    parts = dotted.split(".")
    cur = d
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = value


def _merge(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        else:
            dst[k] = v


def write_snapshot_dir(final_dir: str, checkpoint_id: int, job_name: str,
                       operator_states: Dict[str, Dict[str, Any]],
                       extra: Optional[Dict[str, Any]] = None,
                       compress: bool = True) -> str:
    """Write a self-contained snapshot directory (tmp + atomic rename).

    An existing target is replaced only if it is itself a snapshot directory
    (manifest.json present) or empty — never an arbitrary user directory.
    """
    if os.path.exists(final_dir) and os.listdir(final_dir) and \
            not os.path.exists(os.path.join(final_dir, "manifest.json")):
        raise FileExistsError(
            f"refusing to replace non-snapshot directory {final_dir!r}")
    # chaos: a raise here models a write that failed before anything
    # became visible; the tmp-dir discipline below guarantees no
    # half-written chk dir appears (recoverable faults retry in place)
    chaos.io_point("checkpoint.write", checkpoint_id=checkpoint_id)
    parent = os.path.dirname(os.path.abspath(final_dir)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp_dir = tempfile.mkdtemp(
        prefix=f".snap-{checkpoint_id}-", dir=parent)
    try:
        uids = []
        for uid, state in operator_states.items():
            uids.append(uid)
            arrays, meta = _split_state(state)
            if arrays:
                # compressed by default (reference compresses state with
                # lz4/snappy, root pom.xml:168,225); np.load autodetects
                save = np.savez_compressed if compress else np.savez
                save(os.path.join(tmp_dir, f"op-{uid}.npz"), **arrays)
            with open(os.path.join(tmp_dir, f"op-{uid}.meta.pkl"), "wb") as f:
                pickle.dump(meta, f)
        file_crcs = {
            name: _file_crc32(os.path.join(tmp_dir, name))
            for name in sorted(os.listdir(tmp_dir))
        }
        manifest = CheckpointMetadata(
            checkpoint_id=checkpoint_id,
            timestamp_ms=int(time.time() * 1000),
            job_name=job_name,
            operator_states=uids,
            extra=extra or {},
            file_crcs=file_crcs)
        with open(os.path.join(tmp_dir, "manifest.json"), "w") as f:
            json.dump(dataclasses.asdict(manifest), f)
        if os.path.exists(final_dir):
            shutil.rmtree(final_dir)
        os.rename(tmp_dir, final_dir)
    except BaseException:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        raise
    # chaos: a TORN write — the rename was durable but a payload file's
    # contents were not (lost page-cache flush on power loss; the
    # failure mode fsync-less storage actually exhibits). kind="drop"
    # truncates a file, kind="corrupt" flips one byte; either way the
    # manifest CRCs make the snapshot detectably — not silently — bad.
    # Tear kinds ONLY: raising after the rename would model a failure
    # of a checkpoint that is in fact durable (the caller would discard
    # its committed epoch while restore skips the replay — a harness
    # false positive, not a real failure mode). Pre-visibility crashes
    # belong to the checkpoint.write point above.
    rule = chaos.payload_action("checkpoint.write.torn",
                                kinds=("drop", "corrupt"),
                                checkpoint_id=checkpoint_id)
    if rule is not None:
        _tear_snapshot_file(final_dir, truncate=(rule.kind == "drop"))
    return final_dir


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            data = f.read(chunk)
            if not data:
                return crc
            crc = zlib.crc32(data, crc)


def _tear_snapshot_file(snapshot_dir: str, truncate: bool) -> None:
    """Damage the first payload file (chaos-only helper): truncate to
    half, or flip one byte in the middle."""
    victims = sorted(n for n in os.listdir(snapshot_dir)
                     if n != "manifest.json")
    if not victims:
        return
    path = os.path.join(snapshot_dir, victims[0])
    size = os.path.getsize(path)
    if truncate:
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
    else:
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")


def verify_snapshot_files(snapshot_dir: str,
                          file_crcs: Dict[str, int]) -> None:
    """Check every manifest-recorded file exists and matches its CRC32;
    raises :class:`CheckpointCorruptedError` naming the first bad file.
    Pre-CRC snapshots (empty dict) verify vacuously."""
    for name, want in file_crcs.items():
        path = os.path.join(snapshot_dir, name)
        if not os.path.exists(path):
            raise CheckpointCorruptedError(
                f"snapshot {snapshot_dir!r} is incomplete: {name!r} is "
                "missing (torn write?) — restore from an older complete "
                "checkpoint")
        got = _file_crc32(path)
        if got != int(want):
            raise CheckpointCorruptedError(
                f"snapshot {snapshot_dir!r} is corrupt: {name!r} CRC32 "
                f"{got:#010x} != manifest {int(want):#010x} (torn write "
                "or bit rot) — restore from an older complete checkpoint")


def read_manifest(snapshot_dir: str) -> Dict[str, Any]:
    with open(os.path.join(snapshot_dir, "manifest.json")) as f:
        return json.load(f)


def read_snapshot_dir(snapshot_dir: str,
                      verify: bool = True) -> Dict[str, Dict[str, Any]]:
    """Read a snapshot directory back into operator-uid -> state dicts.

    Integrity first: with ``verify`` (the default) every payload file is
    CRC-checked against the manifest before any state is materialized —
    a torn or corrupted snapshot raises :class:`CheckpointCorruptedError`
    instead of restoring garbage. Prior-version snapshots are migrated
    forward step by step; a snapshot from a NEWER format fails with a
    precise error (reference: TypeSerializerSnapshot compatibility
    resolution)."""
    # chaos: transient read failures retry with backoff in place
    # (storage I/O is a recoverable site); persistent ones crash
    chaos.io_point("checkpoint.read", path=snapshot_dir)
    manifest = read_manifest(snapshot_dir)
    if verify:
        verify_snapshot_files(snapshot_dir,
                              manifest.get("file_crcs") or {})
    version = int(manifest.get("format_version", 1))
    if version > FORMAT_VERSION:
        raise RuntimeError(
            f"snapshot {snapshot_dir!r} has format version {version}, but "
            f"this build reads at most {FORMAT_VERSION} — it was written "
            "by a newer framework version")
    out: Dict[str, Dict[str, Any]] = {}
    for uid in manifest["operator_states"]:
        state: Dict[str, Any] = {}
        npz_path = os.path.join(snapshot_dir, f"op-{uid}.npz")
        if os.path.exists(npz_path):
            with np.load(npz_path, allow_pickle=False) as z:
                for k in z.files:
                    _set_path(state, k, z[k])
        with open(os.path.join(snapshot_dir, f"op-{uid}.meta.pkl"), "rb") as f:
            meta = pickle.load(f)["meta"]
        _merge(state, meta)
        out[uid] = state
    while version < FORMAT_VERSION:
        migrate = _MIGRATIONS.get(version)
        if migrate is None:
            raise RuntimeError(
                f"snapshot {snapshot_dir!r} has format version {version} "
                f"and no migration to {version + 1} is registered")
        out = migrate(out)
        version += 1
    return out


# --------------------------------------------------------------------------
# Incremental checkpoints: a delta snapshot stores only rows dirtied since
# its base checkpoint plus freed-namespace tombstones (reference:
# RocksIncrementalSnapshotStrategy uploads only new SST files; the chain is
# re-materialized at restore). A checkpoint's manifest extra carries
# {"incremental": true, "base": <id>}.
# --------------------------------------------------------------------------


def is_delta_table(d: Any) -> bool:
    return isinstance(d, dict) and bool(np.asarray(d.get("__delta__", False)))


def _pack_rows(key_ids, namespaces) -> np.ndarray:
    out = np.empty(len(key_ids), dtype=[("k", "<i8"), ("n", "<i8")])
    out["k"] = np.asarray(key_ids, dtype=np.int64)
    out["n"] = np.asarray(namespaces, dtype=np.int64)
    return out


def apply_table_delta(base: Optional[Dict[str, Any]],
                      delta: Dict[str, Any]) -> Dict[str, Any]:
    """Materialize base rows + delta upserts - tombstones (whole freed
    namespaces and TTL-expired (key, ns) pairs)."""
    meta = ("__delta__", "freed_namespaces",
            "tombstone_key_id", "tombstone_namespace")
    cols = [k for k in delta if k not in meta]
    delta_rows = {c: np.asarray(delta[c]) for c in cols}
    if base is None or len(np.asarray(base.get("key_id", ()))) == 0:
        return delta_rows
    freed = np.asarray(delta.get("freed_namespaces", ()), dtype=np.int64)
    keep = np.ones(len(base["key_id"]), dtype=bool)
    if len(freed):
        keep &= ~np.isin(np.asarray(base["namespace"], dtype=np.int64),
                         freed)
    tomb_k = np.asarray(delta.get("tombstone_key_id", ()), dtype=np.int64)
    packed_base = None  # built once; base can be millions of rows
    if len(tomb_k) or len(delta_rows["key_id"]):
        packed_base = _pack_rows(base["key_id"], base["namespace"])
    if len(tomb_k):
        tomb_n = np.asarray(delta["tombstone_namespace"], dtype=np.int64)
        keep &= ~np.isin(packed_base, _pack_rows(tomb_k, tomb_n))
    if len(delta_rows["key_id"]):
        keep &= ~np.isin(
            packed_base,
            _pack_rows(delta_rows["key_id"], delta_rows["namespace"]))
    return {
        c: np.concatenate([np.asarray(base[c])[keep], delta_rows[c]])
        for c in cols
    }


def merge_incremental_state(base: Dict[str, Any],
                            delta: Dict[str, Any]) -> Dict[str, Any]:
    """Merge one operator's delta state onto its base state: delta tables
    apply row-wise, other dict values recurse, leaves replace; base keys
    absent from the delta are kept."""
    out = dict(base)
    for k, v in delta.items():
        if is_delta_table(v):
            prior = base.get(k) if isinstance(base.get(k), dict) else None
            out[k] = apply_table_delta(prior, v)
        elif isinstance(v, dict) and isinstance(base.get(k), dict):
            out[k] = merge_incremental_state(base[k], v)
        else:
            out[k] = v
    return out


def read_checkpoint_chain(snapshot_dir: str) -> Dict[str, Dict[str, Any]]:
    """Read a checkpoint, materializing its incremental chain if any.

    Delta checkpoints reference their base by id; bases live as sibling
    chk-<id> directories.
    """
    manifest = read_manifest(snapshot_dir)
    states = read_snapshot_dir(snapshot_dir)
    extra = manifest.get("extra", {})
    if not extra.get("incremental"):
        # a full-manifest checkpoint should not carry delta tables; if one
        # does (writer bug / tampering), materializing it as-if-complete
        # would silently drop state — fail loudly instead
        def assert_no_delta(state, path):
            for k, v in state.items():
                if is_delta_table(v):
                    raise RuntimeError(
                        f"full checkpoint {snapshot_dir!r} contains a "
                        f"delta-marked table at {path + (k,)!r}")
                if isinstance(v, dict):
                    assert_no_delta(v, path + (k,))

        for uid, st in states.items():
            assert_no_delta(st, (uid,))
        return states
    base_dir = os.path.join(os.path.dirname(os.path.abspath(snapshot_dir)),
                            f"chk-{extra['base']}")
    if not os.path.isdir(base_dir):
        raise RuntimeError(
            f"incremental checkpoint {snapshot_dir!r} references missing "
            f"base chk-{extra['base']} — was it deleted outside retain()?")
    base_states = read_checkpoint_chain(base_dir)
    out: Dict[str, Dict[str, Any]] = dict(base_states)
    for uid, st in states.items():
        out[uid] = merge_incremental_state(base_states.get(uid, {}), st)
    return out


def checkpoint_chain_ids(root: str, checkpoint_id: int) -> List[int]:
    """All checkpoint ids the given checkpoint transitively depends on
    (including itself)."""
    ids = [checkpoint_id]
    cur = checkpoint_id
    while True:
        d = os.path.join(root, f"chk-{cur}")
        if not os.path.isdir(d):
            break
        extra = read_manifest(d).get("extra", {})
        if not extra.get("incremental"):
            break
        cur = int(extra["base"])
        ids.append(cur)
    return ids


def retain_verified_anchors(ids, keep: int, verify_ok, chain_ids,
                            verified_cache: set, delete) -> None:
    """The ONE torn-aware retention core shared by the flat and sharded
    checkpoint stores: scan newest-first, anchor the ``keep`` newest
    checkpoints whose ``verify_ok`` passes (memoized in
    ``verified_cache`` — checkpoints are immutable after the atomic
    rename), keep everything at/above the oldest anchor plus every id
    an anchor's incremental chain needs, delete the rest unread. If
    nothing verifies, delete nothing (GC must never strand the job).

    ``verify_ok(cid)`` must verify the WHOLE restorable artifact —
    including incremental base chains: an anchor whose base is corrupt
    is not restorable, and anchoring it would let GC delete the older
    complete snapshots the fallback needs."""
    anchors = []
    needed = set()
    for i in reversed(ids):
        if len(anchors) >= keep:
            break
        if i not in verified_cache:
            if not verify_ok(i):
                continue  # torn/corrupt: not an anchor; kept only if
                # newer than the oldest anchor (harmless forensics)
            verified_cache.add(i)
        anchors.append(i)
        needed.update(chain_ids(i))
    if not anchors:
        return
    floor = min(anchors)
    for i in ids:
        if i >= floor or i in needed:
            continue
        delete(i)


def resolve_snapshot_dir(path: str) -> str:
    """Accept either a self-contained snapshot dir (savepoint / single
    checkpoint) or a checkpoint root holding chk-N children (newest wins)."""
    if os.path.exists(os.path.join(path, "manifest.json")):
        return path
    ids = [int(n[4:]) for n in os.listdir(path)
           if n.startswith("chk-") and n[4:].isdigit()] if os.path.isdir(
               path) else []
    if ids:
        return os.path.join(path, f"chk-{max(ids)}")
    raise RuntimeError(
        f"no checkpoint or savepoint found at {path!r} (expected "
        "manifest.json or chk-N subdirectories)")


class CheckpointStorage:
    """Directory-per-checkpoint layout:

    <root>/chk-<id>/manifest.json
    <root>/chk-<id>/op-<uid>.npz           (numpy arrays of the slot table)
    <root>/chk-<id>/op-<uid>.meta.pkl      (host-side metadata: pending
                                            windows, key-value maps, rng...)
    Writes go to a temp dir then atomically rename — a half-written
    checkpoint is never visible (the reference gets this from
    FsCheckpointStorage's exclusive scope + atomic rename semantics).
    """

    def __init__(self, root: str, compress: bool = True):
        self.root = root
        self.compress = compress
        #: checkpoint ids that passed a FULL CRC verification in this
        #: process — snapshots are immutable after the atomic rename,
        #: so retention never pays the verify I/O for the same id twice
        self._verified_ids: set = set()
        os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------------ write

    def write_checkpoint(self, checkpoint_id: int, job_name: str,
                         operator_states: Dict[str, Dict[str, Any]],
                         extra: Optional[Dict[str, Any]] = None) -> str:
        return write_snapshot_dir(self._dir(checkpoint_id), checkpoint_id,
                                  job_name, operator_states, extra,
                                  compress=self.compress)

    # ------------------------------------------------------------------- read

    def read_checkpoint(self, checkpoint_id: int,
                        verify: bool = True) -> Dict[str, Dict[str, Any]]:
        """``verify=False`` skips the CRC pass — for callers that just
        verified this id via ``latest_checkpoint_id(verify=True)`` and
        would otherwise read every payload file twice."""
        return read_snapshot_dir(self._dir(checkpoint_id), verify=verify)

    def latest_checkpoint_id(self,
                             verify: bool = False) -> Optional[int]:
        """Newest COMPLETE checkpoint id, or None.

        A chk dir without a manifest.json (crash mid-write outside the
        atomic-rename discipline, or external tampering) is never
        complete and is always skipped. With ``verify``, every payload
        file is additionally CRC-checked against the manifest, so torn
        and bit-flipped snapshots are skipped too and the newest id
        that PASSES wins — the fallback the crash-restore harness
        relies on."""
        ids = []
        for name in os.listdir(self.root):
            if name.startswith("chk-"):
                try:
                    ids.append(int(name[4:]))
                except ValueError:
                    pass
        for i in sorted(ids, reverse=True):
            d = self._dir(i)
            if not os.path.exists(os.path.join(d, "manifest.json")):
                continue
            if verify:
                try:
                    verify_snapshot_files(
                        d, read_manifest(d).get("file_crcs") or {})
                except (CheckpointCorruptedError, OSError,
                        ValueError):
                    continue
            return i
        return None

    def retain(self, keep: int) -> None:
        """Drop all but the newest ``keep`` COMPLETE checkpoints —
        never a checkpoint that a retained incremental checkpoint still
        references as (part of) its base chain (reference: shared-state
        registry refcounting in SharedStateRegistry), and never the
        fallback chain below a torn/corrupt newest: retention anchors
        on the ``keep`` newest checkpoints that PASS verification
        (including every link of an incremental chain — a delta whose
        base is corrupt is not restorable), so a torn chk-N can never
        strand the job with zero restorable checkpoints. Shared core:
        :func:`retain_verified_anchors`."""
        if keep <= 0:
            return
        all_ids = sorted(
            int(n[4:]) for n in os.listdir(self.root)
            if n.startswith("chk-") and n[4:].isdigit())

        def verify_ok(i: int) -> bool:
            try:
                # the whole restorable artifact: the checkpoint AND its
                # incremental base chain
                for cid in checkpoint_chain_ids(self.root, i):
                    d = self._dir(cid)
                    verify_snapshot_files(
                        d, read_manifest(d).get("file_crcs") or {})
                return True
            except (CheckpointCorruptedError, OSError, ValueError):
                return False

        retain_verified_anchors(
            all_ids, keep, verify_ok,
            lambda i: checkpoint_chain_ids(self.root, i),
            self._verified_ids,
            lambda i: shutil.rmtree(self._dir(i), ignore_errors=True))

    # ---------------------------------------------------------------- helpers

    def _dir(self, checkpoint_id: int) -> str:
        return os.path.join(self.root, f"chk-{checkpoint_id}")

