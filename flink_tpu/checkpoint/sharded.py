"""Shard-granular checkpoints: one independently-restorable unit per
key-group range — lose one shard, restore one unit.

reference: the reference's checkpoint is ALREADY key-group ranged on
disk (KeyedStateHandle carries a KeyGroupRange; restore hands each
subtask only the handles intersecting its range) and its failover
strategy restarts only the failed pipelined region
(RestartPipelinedRegionFailoverStrategy). This module composes the two
for the micro-batch mesh engines:

Layout::

    <root>/chk-<id>/manifest.json          (top manifest: unit index +
                                            per-unit source positions)
    <root>/chk-<id>/shard-<g0>-<g1>/       (one write_snapshot_dir unit:
        manifest.json + CRCs               its OWN manifest + per-file
        op-unit.npz / op-unit.meta.pkl     CRC32s — independently
                                            verifiable and restorable)

Every unit rides the existing ``write_snapshot_dir`` discipline
(tmp + atomic rename, per-file CRC32s, the ``checkpoint.write`` /
``checkpoint.write.torn`` chaos points), so a torn write damages ONE
unit, and the read path falls back to that RANGE's unit in an older
checkpoint instead of discarding the whole chk-N. Per-unit source
positions make the fallback's cost visible and bounded: only the
fallen-back range replays the extra distance.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.checkpoint.storage import (
    CheckpointCorruptedError,
    merge_incremental_state,
    read_manifest,
    read_snapshot_dir,
    verify_snapshot_files,
    write_snapshot_dir,
)

GroupRange = Tuple[int, int]


def _unit_dirname(g0: int, g1: int) -> str:
    return f"shard-{int(g0)}-{int(g1)}"


def _parse_unit_dirname(name: str) -> Optional[GroupRange]:
    if not name.startswith("shard-"):
        return None
    parts = name[6:].split("-")
    if len(parts) != 2 or not all(p.lstrip("-").isdigit() for p in parts):
        return None
    return (int(parts[0]), int(parts[1]))


def _ranges_intersect(a: GroupRange, b: GroupRange) -> bool:
    return a[0] <= b[1] and b[0] <= a[1]


class ShardedCheckpointStorage:
    """Directory-per-checkpoint, unit-per-key-group-range layout (see
    module docstring). The unit of corruption, fallback and restore is
    the RANGE, never the whole checkpoint."""

    def __init__(self, root: str, compress: bool = True,
                 traces=None):
        from flink_tpu.metrics.traces import default_collector

        self.root = root
        self.compress = compress
        #: TraceCollector receiving write/restore spans (reference:
        #: the checkpoint/recovery Span reporting — SURVEY §5); the
        #: process-default collector unless the owner threads its own
        self.traces = traces or default_collector()
        #: ids whose EVERY unit passed full CRC verification in this
        #: process (units are immutable after the atomic rename) — the
        #: retention scan never re-reads a verified checkpoint
        self._verified_ids: set = set()
        os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------------ write

    def write_checkpoint(self, checkpoint_id: int, job_name: str,
                         units: Dict[GroupRange, Dict[str, Any]],
                         positions: Dict[GroupRange, int],
                         incremental_base: Optional[int] = None) -> str:
        """Write one checkpoint of per-range units. ``positions`` maps
        each range to ITS source position (equal across ranges in
        steady state; they diverge after a fallback or partial
        failover, and restore replays each range from its own).
        ``incremental_base``: record each unit as a delta over the same
        range's unit in chk-<base> (the per-shard increment chain)."""
        from flink_tpu.observe import flight_recorder as flight

        with flight.span("checkpoint.write"), \
                self.traces.span("checkpoint", "sharded-write") as sp:
            sp.set_attribute("checkpointId", int(checkpoint_id))
            sp.set_attribute("units", len(units))
            sp.set_attribute("incremental", incremental_base is not None)
            return self._write_checkpoint_inner(
                checkpoint_id, job_name, units, positions,
                incremental_base)

    def _write_checkpoint_inner(self, checkpoint_id: int, job_name: str,
                                units, positions,
                                incremental_base: Optional[int]) -> str:
        final_dir = self._dir(checkpoint_id)
        parent = os.path.dirname(os.path.abspath(final_dir)) or "."
        os.makedirs(parent, exist_ok=True)
        if os.path.exists(final_dir) and os.listdir(final_dir) and \
                not os.path.exists(os.path.join(final_dir,
                                                "manifest.json")):
            raise FileExistsError(
                f"refusing to replace non-checkpoint dir {final_dir!r}")
        tmp_dir = tempfile.mkdtemp(prefix=f".schk-{checkpoint_id}-",
                                   dir=parent)
        try:
            index: Dict[str, Dict[str, Any]] = {}
            for (g0, g1), state in units.items():
                extra: Dict[str, Any] = {
                    "source_pos": int(positions[(g0, g1)]),
                    "key_groups": [int(g0), int(g1)],
                }
                if incremental_base is not None:
                    extra["incremental"] = True
                    extra["base"] = int(incremental_base)
                write_snapshot_dir(
                    os.path.join(tmp_dir, _unit_dirname(g0, g1)),
                    checkpoint_id, job_name, {"unit": state},
                    extra=extra, compress=self.compress)
                index[_unit_dirname(g0, g1)] = {
                    "key_groups": [int(g0), int(g1)],
                    "source_pos": int(positions[(g0, g1)]),
                }
            manifest = {
                "checkpoint_id": int(checkpoint_id),
                "job_name": job_name,
                "timestamp_ms": int(time.time() * 1000),
                "sharded": True,
                "units": index,
            }
            with open(os.path.join(tmp_dir, "manifest.json"), "w") as f:
                json.dump(manifest, f)
            if os.path.exists(final_dir):
                shutil.rmtree(final_dir)
            os.rename(tmp_dir, final_dir)
        except BaseException:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
        return final_dir

    # ------------------------------------------------------------------- read

    def checkpoint_ids(self) -> List[int]:
        ids = []
        for name in os.listdir(self.root):
            if name.startswith("chk-") and name[4:].isdigit() \
                    and os.path.exists(os.path.join(
                        self.root, name, "manifest.json")):
                ids.append(int(name[4:]))
        return sorted(ids)

    def latest_checkpoint_id(self) -> Optional[int]:
        ids = self.checkpoint_ids()
        return ids[-1] if ids else None

    def unit_ranges(self, checkpoint_id: int) -> List[GroupRange]:
        manifest = self._top_manifest(checkpoint_id)
        return sorted(tuple(u["key_groups"])
                      for u in manifest["units"].values())

    def _top_manifest(self, checkpoint_id: int) -> Dict[str, Any]:
        with open(os.path.join(self._dir(checkpoint_id),
                               "manifest.json")) as f:
            return json.load(f)

    def _read_unit_dir(self, path: str, verify: bool
                       ) -> Tuple[Dict[str, Any], int]:
        """(state, source_pos) of one unit dir, materializing its
        per-range incremental chain (each link verified when asked)."""
        states = read_snapshot_dir(path, verify=verify)
        manifest = read_manifest(path)
        extra = manifest.get("extra", {})
        state = states["unit"]
        if extra.get("incremental"):
            g0, g1 = extra["key_groups"]
            base_dir = os.path.join(self._dir(int(extra["base"])),
                                    _unit_dirname(g0, g1))
            if not os.path.isdir(base_dir):
                raise CheckpointCorruptedError(
                    f"delta unit {path!r} references missing base "
                    f"chk-{extra['base']} for range {g0}-{g1}")
            base_state, _ = self._read_unit_dir(base_dir, verify)
            state = merge_incremental_state(base_state, state)
        return state, int(extra["source_pos"])

    def read_unit(self, checkpoint_id: int, key_range: GroupRange,
                  verify: bool = True) -> Tuple[Dict[str, Any], int]:
        return self._read_unit_dir(
            os.path.join(self._dir(checkpoint_id),
                         _unit_dirname(*key_range)),
            verify)

    def latest_units_for_groups(
            self, groups) -> Optional[Tuple[int, List[Dict[str, Any]],
                                            int]]:
        """The newest checkpoint whose units COVERING ``groups`` all
        pass verification: ``(checkpoint_id, unit_states, source_pos)``
        with ``source_pos`` the MIN over the covering units (replay
        from there re-produces every covered group's state). A torn or
        corrupt covering unit fails THIS checkpoint for this range only
        — the search falls back to the previous checkpoint's covering
        units, never discarding the siblings' recovery options. None
        when no checkpoint covers the groups (cold start for that
        range)."""
        from flink_tpu.observe import flight_recorder as flight

        gset = set(int(g) for g in groups)
        lo, hi = min(gset), max(gset)
        with flight.span("checkpoint.restore"), \
                self.traces.span("recovery", "restore-units") as sp:
            sp.set_attribute("key_groups", [lo, hi])
            fallbacks = 0
            for cid in reversed(self.checkpoint_ids()):
                covering = [r for r in self.unit_ranges(cid)
                            if _ranges_intersect(r, (lo, hi))]
                if not covering:
                    continue
                try:
                    read = [self.read_unit(cid, r, verify=True)
                            for r in covering]
                except (CheckpointCorruptedError, OSError, ValueError):
                    fallbacks += 1
                    sp.set_attribute("fallbacks", fallbacks)
                    continue
                sp.set_attribute("checkpointId", cid)
                sp.set_attribute("units", len(covering))
                return (cid, [state for state, _ in read],
                        min(pos for _, pos in read))
            sp.set_attribute("checkpointId", None)
            return None

    def read_all_units_with_fallback(
            self) -> Optional[Tuple[int, List[Tuple[GroupRange,
                                                    Dict[str, Any],
                                                    int]], int]]:
        """Whole-job restore with PER-UNIT fallback: the newest
        checkpoint's ranges, each range's state coming from the newest
        checkpoint where ITS unit verifies. Returns ``(newest_id,
        [(range, state, source_pos)], corrupt_units_skipped)`` — a
        range whose every unit is corrupt restores cold (absent from
        the list). None when no checkpoint exists at all."""
        ids = self.checkpoint_ids()
        if not ids:
            return None
        newest = ids[-1]
        out: List[Tuple[GroupRange, Dict[str, Any], int]] = []
        skipped = 0
        for r in self.unit_ranges(newest):
            found = None
            for cid in reversed(ids):
                if r not in set(map(tuple, self.unit_ranges(cid))):
                    continue
                try:
                    state, pos = self.read_unit(cid, r, verify=True)
                except (CheckpointCorruptedError, OSError, ValueError):
                    skipped += 1
                    continue
                found = (r, state, pos)
                break
            if found is not None:
                out.append(found)
        return newest, out, skipped

    # -------------------------------------------------------------- retention

    def _chain_ids(self, cid: int) -> set:
        """``cid`` plus every checkpoint id its units' incremental
        chains reference (union over ranges)."""
        out = {cid}
        for r in self.unit_ranges(cid):
            cur = cid
            while True:
                path = os.path.join(self._dir(cur), _unit_dirname(*r))
                extra = read_manifest(path).get("extra", {})
                if not extra.get("incremental"):
                    break
                cur = int(extra["base"])
                out.add(cur)
        return out

    def retain(self, keep: int) -> None:
        """Drop all but the newest ``keep`` checkpoints whose EVERY
        unit — including each unit's incremental base chain — passes
        CRC verification; never the fallback chain below a torn newest
        (everything newer than the oldest anchor stays too: torn units
        there still fall back INTO the anchors). Shared core:
        :func:`flink_tpu.checkpoint.storage.retain_verified_anchors`.
        """
        from flink_tpu.checkpoint.storage import (
            retain_verified_anchors,
        )

        if keep <= 0:
            return
        ids = self.checkpoint_ids()

        def verify_ok(cid: int) -> bool:
            try:
                for r in self.unit_ranges(cid):
                    cur = cid
                    while True:
                        path = os.path.join(self._dir(cur),
                                            _unit_dirname(*r))
                        verify_snapshot_files(
                            path, read_manifest(path).get("file_crcs")
                            or {})
                        extra = read_manifest(path).get("extra", {})
                        if not extra.get("incremental"):
                            break
                        cur = int(extra["base"])
                return True
            except (CheckpointCorruptedError, OSError, ValueError,
                    KeyError):
                return False

        retain_verified_anchors(
            ids, keep, verify_ok, self._chain_ids, self._verified_ids,
            lambda cid: shutil.rmtree(self._dir(cid),
                                      ignore_errors=True))

    # ---------------------------------------------------------------- helpers

    def _dir(self, checkpoint_id: int) -> str:
        return os.path.join(self.root, f"chk-{checkpoint_id}")
