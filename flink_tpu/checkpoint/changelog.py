"""State changelog (DSTL) — write-ahead log of state changes for
near-instant checkpoints.

reference: flink-dstl/flink-dstl-dfs FsStateChangelogWriter + the changelog
state backend wrapper (flink-statebackend-changelog): every state mutation
is appended to a durable log; a checkpoint is just the log offset (fast,
O(1)); periodically the backend *materializes* a full snapshot and truncates
the log so recovery replay stays bounded.

Re-design for the slot-table engine: mutations arrive batch-granular
(one scatter = a whole micro-batch of AggregateFunction.add), so a log
entry is a columnar frame (key_ids / namespaces / per-leaf value arrays) —
sequential appends of a few hundred KB, not per-record writes. Frees are
namespace tombstone entries. Replay = re-running the scatters/frees against
a fresh SlotTable, which re-runs the same jitted kernels.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

_MAGIC = b"FTCL"
_HEADER = struct.Struct("<4sQ")  # magic, payload length


class ChangelogWriter:
    """Append-only framed log of state changes for one task.

    Entry = (sequence_number, op_uid, kind, payload). Truncation rewrites
    the log keeping only entries after the materialized offset (the
    reference truncates uploaded segments the same way).
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        # recover: find the byte end of the last intact frame and TRIM any
        # torn tail before appending — otherwise every post-crash append
        # would sit behind unreadable bytes and be lost to read_entries
        self._next_seq = 0
        valid_end = 0
        if os.path.exists(path):
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                while True:
                    header = f.read(_HEADER.size)
                    if len(header) < _HEADER.size:
                        break
                    magic, length = _HEADER.unpack(header)
                    if magic != _MAGIC or f.tell() + length > size:
                        break  # torn/garbage tail
                    blob = f.read(length)
                    try:
                        seq = pickle.loads(blob)[0]
                    except Exception:
                        break
                    self._next_seq = seq + 1
                    valid_end = f.tell()
            if size > valid_end:
                with open(path, "r+b") as f:
                    f.truncate(valid_end)
        self._f = open(path, "ab")

    def append(self, op_uid: str, kind: str, payload: Dict[str, Any]) -> int:
        """Append one change entry; returns its sequence number."""
        seq = self._next_seq
        blob = pickle.dumps((seq, op_uid, kind, payload),
                            protocol=pickle.HIGHEST_PROTOCOL)
        self._f.write(_HEADER.pack(_MAGIC, len(blob)))
        self._f.write(blob)
        self._next_seq += 1
        return seq

    @property
    def next_sequence(self) -> int:
        """The offset a checkpoint records: everything below is durable
        once ``flush`` returns."""
        return self._next_seq

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def truncate(self, up_to_seq: int) -> None:
        """Drop entries with seq < up_to_seq (state below is materialized)."""
        self.flush()
        keep = [(s, u, k, p) for s, u, k, p in read_entries(self.path)
                if s >= up_to_seq]
        self._f.close()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            for entry in keep:
                blob = pickle.dumps(entry, protocol=pickle.HIGHEST_PROTOCOL)
                f.write(_HEADER.pack(_MAGIC, len(blob)))
                f.write(blob)
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")

    def close(self) -> None:
        try:
            self.flush()
        except (OSError, ValueError):
            pass
        self._f.close()


def read_entries(path: str
                 ) -> Iterator[Tuple[int, str, str, Dict[str, Any]]]:
    """Yield (seq, op_uid, kind, payload); tolerates a torn final frame
    (crash mid-append) by stopping at it, like the reference's recoverable
    stream handling."""
    if not os.path.exists(path):
        return
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        while True:
            header = f.read(_HEADER.size)
            if len(header) < _HEADER.size:
                return
            magic, length = _HEADER.unpack(header)
            if magic != _MAGIC or f.tell() + length > size:
                return  # torn write: entry was not durable
            blob = f.read(length)
            try:
                yield pickle.loads(blob)
            except Exception:
                return


class TableChangelog:
    """Binds a ChangelogWriter to one operator's SlotTable: logs every
    logical mutation so the table can be reconstructed by replay."""

    def __init__(self, writer: ChangelogWriter, op_uid: str):
        self.writer = writer
        self.op_uid = op_uid

    def log_scatter(self, key_ids: np.ndarray, namespaces: np.ndarray,
                    values: Tuple[np.ndarray, ...]) -> None:
        self.writer.append(self.op_uid, "scatter", {
            "key_id": np.asarray(key_ids, dtype=np.int64),
            "namespace": np.asarray(namespaces, dtype=np.int64),
            "values": tuple(np.asarray(v) for v in values),
        })

    def log_free(self, namespaces: List[int]) -> None:
        self.writer.append(self.op_uid, "free",
                           {"namespaces": [int(n) for n in namespaces]})


class ChangelogKeyedBackend:
    """Changelog-wrapped keyed state: instant checkpoints, bounded replay.

    The wrapper owns a SlotTable plus the log bindings; ``checkpoint()``
    is an offset record, ``materialize()`` writes a full logical snapshot
    and truncates the log (reference: periodic materialization in the
    changelog backend), ``restore()`` loads the materialized part then
    replays the log tail.
    """

    def __init__(self, agg, log_dir: str, op_uid: str = "op",
                 capacity: int = 1 << 16, max_parallelism: int = 128):
        from flink_tpu.state.slot_table import SlotTable

        self.table = SlotTable(agg, capacity=capacity,
                               max_parallelism=max_parallelism)
        self.log_dir = log_dir
        self.op_uid = op_uid
        self.writer = ChangelogWriter(os.path.join(log_dir, "changelog.bin"))
        self._changelog = TableChangelog(self.writer, op_uid)
        self._materialized_seq = 0

    # -- mutations (log + apply) --------------------------------------------

    def scatter(self, key_ids: np.ndarray, namespaces: np.ndarray,
                values: Tuple[np.ndarray, ...]) -> None:
        self._changelog.log_scatter(key_ids, namespaces, values)
        slots = self.table.lookup_or_insert(key_ids, namespaces)
        self.table.scatter(slots, values)

    def free_namespaces(self, namespaces: List[int]) -> None:
        self._changelog.log_free(namespaces)
        self.table.free_namespaces(namespaces)

    # -- checkpointing -------------------------------------------------------

    def checkpoint(self) -> Dict[str, Any]:
        """O(1): persist the log, record the offset. No state transfer."""
        self.writer.flush()
        return {"changelog_seq": self.writer.next_sequence,
                "materialized_seq": self._materialized_seq}

    def materialize(self) -> Dict[str, Any]:
        """Full snapshot at the current offset. Does NOT discard anything:
        older checkpoints stay restorable until their retention owner calls
        ``truncate_subsumed`` (reference: materialization never invalidates
        retained checkpoints; truncation follows checkpoint subsumption)."""
        self.writer.flush()
        snap = self.table.snapshot()
        seq = self.writer.next_sequence
        path = os.path.join(self.log_dir, f"materialized-{seq}.npz")
        # atomic-rename discipline (as write_snapshot_dir / truncate): a
        # crash mid-write must not leave a torn file restore() would pick
        # as its replay base
        # tmp name must end in .npz (np.savez appends it otherwise) and
        # must NOT match the "materialized-" scan prefix restore() uses
        for name in os.listdir(self.log_dir):
            if name.startswith(".tmp-materialized-"):  # torn earlier write
                try:
                    os.remove(os.path.join(self.log_dir, name))
                except OSError:
                    pass
        tmp = os.path.join(self.log_dir, f".tmp-materialized-{seq}.npz")
        np.savez(tmp, **snap)
        os.replace(tmp, path)
        self._materialized_seq = seq
        return {"changelog_seq": seq, "materialized_seq": seq}

    def truncate_subsumed(self, up_to_seq: int) -> None:
        """Discard log entries / materializations no checkpoint needs any
        more: call with the smallest ``changelog_seq`` among RETAINED
        checkpoints. Keeps the newest materialization at or below that
        point (the replay base) and drops everything older."""
        base = 0
        for name in os.listdir(self.log_dir):
            if name.startswith("materialized-") and name.endswith(".npz"):
                s = int(name[len("materialized-"):-4])
                if s <= up_to_seq:
                    base = max(base, s)
        self.writer.truncate(base)
        for name in os.listdir(self.log_dir):
            if name.startswith("materialized-") and name.endswith(".npz"):
                if int(name[len("materialized-"):-4]) < base:
                    os.remove(os.path.join(self.log_dir, name))

    def restore(self, checkpoint: Dict[str, Any]) -> None:
        """Materialized part + replay of the log tail up to the recorded
        offset — mutations logged after the checkpoint are NOT applied
        (exactly-once: the checkpoint cut is the log offset)."""
        target_seq = checkpoint["changelog_seq"]
        mat_seq = 0
        mat_path = None
        for name in os.listdir(self.log_dir):
            if name.startswith("materialized-") and name.endswith(".npz"):
                s = int(name[len("materialized-"):-4])
                if s <= target_seq and s >= mat_seq:
                    mat_seq, mat_path = s, os.path.join(self.log_dir, name)
        if mat_path is not None:
            with np.load(mat_path, allow_pickle=False) as z:
                self.table.restore({k: z[k] for k in z.files})
        self._materialized_seq = mat_seq
        log_path = os.path.join(self.log_dir, "changelog.bin")
        entries = [e for e in read_entries(log_path)]
        # the replay range [mat_seq, target_seq) must actually be present —
        # a checkpoint whose prefix was truncated away is NOT restorable
        # and must fail loudly, never return empty state
        if mat_path is None and target_seq > 0 and (
                not entries or entries[0][0] > 0):
            raise RuntimeError(
                f"checkpoint at changelog_seq={target_seq} is not "
                "restorable: no materialization at or below it and the log "
                "does not start at 0 (truncated past the checkpoint?)")
        if mat_seq < target_seq:
            have = {s for s, _, _, _ in entries}
            missing = [s for s in range(mat_seq, target_seq)
                       if s not in have]
            if missing:
                raise RuntimeError(
                    f"checkpoint at changelog_seq={target_seq} is not "
                    f"restorable: log entries {missing[:5]}... are gone "
                    "(truncated or lost past the checkpoint)")
        for seq, uid, kind, payload in entries:
            if seq < mat_seq or seq >= target_seq or uid != self.op_uid:
                continue
            if kind == "scatter":
                slots = self.table.lookup_or_insert(payload["key_id"],
                                                    payload["namespace"])
                self.table.scatter(slots, payload["values"])
            elif kind == "free":
                self.table.free_namespaces(payload["namespaces"])

    def close(self) -> None:
        self.writer.close()
