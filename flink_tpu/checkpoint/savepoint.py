"""Savepoints: user-triggered, user-owned, portable snapshots.

reference semantics being re-implemented (not ported):
- trigger/stop-with-savepoint: CheckpointCoordinator.triggerSavepoint +
  StopWithSavepoint scheduler flow (reference:
  runtime/checkpoint/CheckpointCoordinator.java:575 trigger path;
  runtime/scheduler/stopwithsavepoint/*).
- restore claim modes (reference: runtime/state/StateBackend.java:168
  supportsNoClaimRestoreMode, flink-runtime RestoreMode / docs "claim" vs
  "no-claim"): NO_CLAIM never mutates the restored artifact and the first
  new checkpoint is self-contained; CLAIM transfers ownership — the job may
  delete the savepoint once it is subsumed by a newer checkpoint.

In this engine every snapshot directory is already *canonical*: the keyed
state inside is logical (key_id / namespace / key_group / leaf arrays — see
SlotTable.snapshot), so any savepoint can restore at any parallelism
(key-group re-sharding) on any backend. The savepoint/checkpoint format
difference of the reference collapses to a manifest flag.
"""

from __future__ import annotations

import enum
import os
import shutil
from typing import Any, Dict, Optional

from flink_tpu.checkpoint.storage import (
    read_manifest,
    resolve_snapshot_dir,
    write_snapshot_dir,
)


class RestoreMode(enum.Enum):
    """Ownership semantics for the artifact a job restores from."""

    #: never touch the restored snapshot; it stays user-owned (default)
    NO_CLAIM = "no-claim"
    #: the job takes ownership: once a newer checkpoint completes, the
    #: restored artifact is deleted like any other subsumed checkpoint
    CLAIM = "claim"

    @staticmethod
    def of(value) -> "RestoreMode":
        if isinstance(value, RestoreMode):
            return value
        return RestoreMode(str(value).lower().replace("_", "-"))


def write_savepoint(path: str, job_name: str,
                    operator_states: Dict[str, Dict[str, Any]],
                    checkpoint_id: int = 0,
                    extra: Optional[Dict[str, Any]] = None) -> str:
    """Write a self-contained savepoint directory at ``path``.

    The target must not already hold data (the reference likewise refuses a
    non-empty savepoint target) — savepoints never overwrite anything.
    """
    if os.path.exists(path) and os.listdir(path):
        raise FileExistsError(
            f"savepoint target {path!r} already exists and is not empty")
    meta = {"savepoint": True}
    meta.update(extra or {})
    return write_snapshot_dir(path, checkpoint_id, job_name,
                              operator_states, extra=meta)


def check_savepoint_target(path: str) -> None:
    """Fail-fast validation of a savepoint target: existing data and
    unwritable parents are detected BEFORE any irreversible job action
    (e.g. the drain of stop-with-savepoint)."""
    if os.path.exists(path) and os.listdir(path):
        raise FileExistsError(
            f"savepoint target {path!r} already exists and is not empty")
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    if not os.access(parent, os.W_OK):
        raise PermissionError(f"cannot write savepoint under {parent!r}")


def is_savepoint(snapshot_dir: str) -> bool:
    try:
        return bool(read_manifest(snapshot_dir).get("extra", {})
                    .get("savepoint"))
    except (OSError, ValueError):
        return False


class ClaimedArtifact:
    """Tracks a restored snapshot under CLAIM mode: once the restored job
    completes a NEWER checkpoint, the claimed artifact is subsumed and
    deleted (reference: claim-mode ownership transfer)."""

    def __init__(self, restored_dir: str, mode: RestoreMode,
                 own_checkpoint_root: Optional[str]):
        self.restored_dir = os.path.abspath(restored_dir)
        self.mode = mode
        self._own_root = (os.path.abspath(own_checkpoint_root)
                          if own_checkpoint_root else None)
        self._disposed = False

    def on_checkpoint_complete(self, new_checkpoint_dir: str) -> None:
        if self._disposed or self.mode is not RestoreMode.CLAIM:
            return
        new_dir = os.path.abspath(new_checkpoint_dir)
        if new_dir == self.restored_dir:
            return
        # never delete a sibling of the job's own chain out from under the
        # retention policy — claim only applies to external artifacts
        if (self._own_root is not None
                and os.path.dirname(self.restored_dir) == self._own_root):
            self._disposed = True
            return
        shutil.rmtree(self.restored_dir, ignore_errors=True)
        self._disposed = True


def prepare_restore(path: str, mode=RestoreMode.NO_CLAIM,
                    own_checkpoint_root: Optional[str] = None):
    """Resolve a restore target and its ownership tracker.

    Returns (snapshot_dir, ClaimedArtifact).
    """
    snapshot_dir = resolve_snapshot_dir(path)
    return snapshot_dir, ClaimedArtifact(
        snapshot_dir, RestoreMode.of(mode), own_checkpoint_root)
