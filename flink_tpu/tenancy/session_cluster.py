"""The tenancy session cluster: N concurrent jobs, ONE device mesh.

reference: a Flink *session cluster* keeps a dispatcher + shared
TaskManagers alive across job submissions (slot sharing decides
co-residency). Here the shared substrate is the device mesh and the
XLA program cache: every job is a stepwise :class:`LocalExecutor` run
(``run_stepwise`` — the same loop single-job execution drives), and ONE
scheduler thread interleaves their scheduling quanta with deficit-
round-robin fairness. Single-owner discipline is preserved — exactly
one thread ever touches engine state — so jobs need no locks, reads
(queryable state) stay race-free, and checkpoint cuts stay aligned
per job.

What each quantum pays / observes:

- the job's program-cache traffic is attributed to it
  (:mod:`program_cache`) — job K+1 on a warm cluster must show zero
  misses AND zero XLA compiles (gated by ``tools/serving_smoke.py``);
- the job's quota ledger enforces its resident-row budget
  (:mod:`quotas`) — over-budget jobs shed their own cold rows;
- the serving plane's coalesced lookup batches land on the job's
  control queue and are served at its next batch boundary
  (:mod:`serving`);
- every ``arbitrate_every_s`` the shard arbiter re-divides the shard
  budget between jobs and posts LIVE ``RescaleRequest``\\ s
  (:mod:`arbiter` — PR 4's key-group migration, per job).

Failure containment: one job's crash never unwinds its siblings — the
failed job restarts from its latest complete checkpoint (bounded
attempts, cold restart when none exists) while the others keep their
quanta.
"""

from __future__ import annotations

import os
import queue as _q
import time
from typing import Any, Dict, List, Optional

from flink_tpu.tenancy.fairness import DeficitRoundRobin
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE
from flink_tpu.tenancy.quotas import QuotaLedger, TenantQuota
from flink_tpu.tenancy.serving import ServingPlane


class TenantJob:
    """One submitted job's scheduling state inside the cluster."""

    def __init__(self, name: str, graph, config, quota: TenantQuota):
        self.name = name
        self.graph = graph
        self.config = config
        self.quota = quota
        self.ledger = QuotaLedger(job=name, quota=quota)
        self.control: "_q.Queue" = _q.Queue()
        self.gen = None          # the run_stepwise generator
        self.handle = None       # JobHandle (first yield)
        self.result = None
        self.error: Optional[BaseException] = None
        self.finished = False
        self.restarts = 0
        self.records_total = 0
        #: wall time of this job's quanta (scheduler view; the operator
        #: busy breakdown lives on the handle)
        self.sched_s = 0.0
        self._pending_rescale = None
        #: failed arbiter-driven rescales (harvested each tick; the
        #: last error is kept so the operator can see WHY)
        self.rescale_errors = 0
        self.last_rescale_error: Optional[BaseException] = None

    @property
    def busy_ms(self) -> float:
        return self.handle.busy_ms() if self.handle is not None else 0.0


class SessionCluster:
    """Run N jobs multiplexed over one device mesh (see module doc).

    Usage::

        cluster = SessionCluster()
        cluster.submit(env_a, "job-a")
        cluster.submit(env_b, "job-b", quota=TenantQuota(500_000))
        results = cluster.run()          # drives all jobs to completion
        cluster.lookup("job-a", "window_agg(SumAggregate)", key=7)

    ``run()`` owns the scheduling thread (call it from one thread);
    lookups may come from any number of client threads concurrently —
    they coalesce into device batches on the serving plane.
    """

    def __init__(self, quantum_records: int = 8192,
                 max_restarts: int = 2,
                 arbiter=None, arbitrate_every_s: float = 0.0,
                 serving: Optional[ServingPlane] = None,
                 serving_workers: int = 2,
                 serving_cache_entries: int = 1 << 18,
                 serving_shm_dir: Optional[str] = None):
        self.jobs: Dict[str, TenantJob] = {}
        self.drr = DeficitRoundRobin(quantum=quantum_records)
        #: serving_workers — threads draining the per-(job, operator,
        #: shard) lookup queues (each queue owned by exactly ONE
        #: worker); serving_cache_entries — hot-row cache LRU bound
        #: (0 disables the cache: every lookup resolves on the replica);
        #: serving_shm_dir — arms the multi-process frontend tier (the
        #: hot cache allocates shm arenas there; FrontendPool attaches)
        self.serving = serving or ServingPlane(
            workers=serving_workers,
            cache_entries=serving_cache_entries,
            shm_dir=serving_shm_dir)
        self.max_restarts = int(max_restarts)
        self.arbiter = arbiter
        self.arbitrate_every_s = float(arbitrate_every_s)
        self._last_arbitration = 0.0
        from flink_tpu.metrics import MetricRegistry

        self.registry = MetricRegistry()
        root = self.registry.root_group("cluster", "session")
        self._tenancy_group = root.add_group("tenancy")
        self._register_cluster_gauges()

    # ------------------------------------------------------------ submission

    def submit(self, pipeline, job_name: str,
               quota: Optional[TenantQuota] = None,
               weight: float = 1.0) -> TenantJob:
        """Add a job (a built StreamExecutionEnvironment, or a raw
        (graph, Configuration) via an object exposing
        ``get_stream_graph``/``config``) and prime it: sources open,
        operators open (engines build — cache-attributed to this job),
        pumps start. It runs when :meth:`run` / :meth:`step_round`
        drives the loop."""
        if job_name in self.jobs:
            raise ValueError(f"job name {job_name!r} already submitted")
        graph = pipeline.get_stream_graph()
        if hasattr(pipeline, "_sinks"):
            pipeline._sinks = []
        from flink_tpu.core.config import StateOptions

        config = pipeline.config.copy()
        ckpt = config.get(StateOptions.CHECKPOINT_DIR)
        if ckpt:
            # per-job checkpoint tree, same argument as the spill dirs
            # below: chk-N ids are per-storage sequences, so two jobs
            # sharing one configured dir would overwrite each other's
            # checkpoints — and a restart would restore whichever job
            # wrote last (cross-tenant state). _on_failure reads the
            # re-rooted dir from job.config, so restores stay private.
            config.set(StateOptions.CHECKPOINT_DIR,
                       os.path.join(ckpt, f"job-{job_name}"))
        # COPY the quota (as the config above): submit re-roots
        # quota.spill_dir per job, so a caller reusing one TenantQuota
        # for two jobs would otherwise hand job B job A's private tree
        # — exactly the cross-tenant page overwrite this isolates.
        import dataclasses

        quota = (dataclasses.replace(quota) if quota is not None
                 else TenantQuota())
        if quota.spill_dir is None:
            base = config.get(StateOptions.SPILL_DIR)
            if base:
                # per-job page directory: jobs never share a spill tree
                # (SpillTier page filenames are per-tier sequences —
                # two jobs writing one tree would overwrite each
                # other's pages)
                quota.spill_dir = os.path.join(base, f"job-{job_name}")
        job = TenantJob(job_name, graph, config, quota)
        self._isolate_spill_dirs(job)
        self._start(job, restore_from=None)
        self.jobs[job_name] = job
        self.drr.add(job_name, weight)
        self.serving.bind_job(job_name, job.control)
        self._register_job_gauges(job)
        return job

    def _start(self, job: TenantJob, restore_from: Optional[str]) -> None:
        from flink_tpu.cluster.local_executor import LocalExecutor

        with PROGRAM_CACHE.job_scope(job.name):
            job.gen = LocalExecutor(job.config).run_stepwise(
                job.graph, job.name, restore_from=restore_from,
                control_queue=job.control, cooperative=True)
            job.handle = next(job.gen)
            self._arm_replicas(job)
        job.ledger.engines.clear()
        job.ledger.bind(job.handle.stateful_operators())

    def _arm_replicas(self, job: TenantJob) -> None:
        """Arm every replica-capable operator's read replica and bind
        its adapter to the serving plane (serving.replica; re-run on
        restart — the fresh engines get fresh planes, and rebinding
        atomically retargets lookups so clients that kept serving the
        pre-crash sealed generation move to the restored job's first
        republish). Runs inside the job's program-cache scope: the
        replica program families are charged like any other."""
        from flink_tpu.core.config import ServingOptions

        if not job.config.get(ServingOptions.REPLICA):
            return
        interval = job.config.get(ServingOptions.PUBLISH_INTERVAL_MS)
        for node in job.handle.nodes.values():
            op = node.operator
            if op is None or not hasattr(op, "arm_serving_replica"):
                continue
            adapter = op.arm_serving_replica(
                publish_interval_ms=interval)
            if adapter is not None:
                self.serving.bind_replica(
                    job.name, node.transformation.name, adapter)

    @staticmethod
    def _isolate_spill_dirs(job: TenantJob) -> None:
        """Per-job page directories, made real: wrap the graph's
        operator factories so every stateful operator is constructed
        with its spill dir re-rooted under the job's PRIVATE tree
        (``<spill_root>/job-<name>``). Without this, two jobs
        configured with one ``state.spill.dir`` interleave page files
        in one tree — SpillTier filenames are per-tier sequences, so
        overlapping namespace ids would overwrite (and ``pop`` would
        delete) the OTHER job's pages. Factory wrapping (rather than
        re-initializing tiers post-open) applies before operator open
        AND before restore, so restarts keep the isolation and restored
        spilled state lands in the job's own tree. The cluster owns the
        submitted graph (as MiniCluster.submit does), so mutating its
        factories is contained."""
        spill_dir = job.quota.spill_dir
        if not spill_dir:
            return
        for t in job.graph.nodes:
            orig = t.operator_factory
            if orig is None:
                continue

            def factory(_orig=orig, _dir=spill_dir):
                op = _orig()
                spill = getattr(op, "spill", None)
                if spill and spill.get("spill_dir"):
                    op.spill = {**spill, "spill_dir": _dir}
                return op

            t.operator_factory = factory

    # --------------------------------------------------------------- serving

    def lookup(self, job_name: str, operator: str, key, namespace=None):
        """Point lookup against a running job (client threads; rides the
        coalescer's current batch — one gather + one device read per
        request batch)."""
        return self.serving.lookup(job_name, operator, key, namespace)

    def lookup_batch(self, job_name: str, operator: str, keys,
                     namespace=None) -> List[Any]:
        return self.serving.lookup_batch(job_name, operator, keys,
                                         namespace)

    def lookup_batch_packed(self, job_name: str, operator: str, keys):
        """The native serving fast path: the whole key batch probes the
        GIL-free hot-row table in ONE call and hit results stay packed
        until (unless) the caller reads them — see
        :meth:`ServingPlane.lookup_batch_packed`. Bit-identical to
        :meth:`lookup_batch` when materialized."""
        return self.serving.lookup_batch_packed(job_name, operator,
                                                keys)

    # ------------------------------------------------------------ scheduling

    def step_round(self) -> bool:
        """One DRR round over every live job. Returns True while any
        job remains live."""
        live = False
        progressed = False
        for name in self.drr.begin_round():
            job = self.jobs.get(name)
            if job is None or job.finished:
                continue
            live = True
            t0 = time.perf_counter()
            # flight attribution follows the scheduler: every span the
            # quantum records (engine ingest, fires, harvests) carries
            # THIS tenant's name — one Perfetto pid per job
            from flink_tpu.observe import flight_recorder as flight

            flight.set_job(name)
            with PROGRAM_CACHE.job_scope(name):
                while self.drr.can_run(name) and not job.finished:
                    try:
                        n = next(job.gen)
                    except StopIteration as done:
                        self._finish(job, done.value)
                        break
                    except BaseException as e:  # noqa: BLE001
                        self._on_failure(job, e)
                        break
                    job.records_total += n
                    self.drr.charge(name, n)
                    if n > 0:
                        progressed = True
                    else:
                        # nothing ready: forfeit the rest of the quantum
                        # (DRR empty-queue rule)
                        self.drr.reset_idle(name)
                        break
            job.sched_s += time.perf_counter() - t0
            if not job.finished and job.quota.max_resident_rows:
                job.ledger.enforce()
        if self.arbiter is not None and live and \
                self.arbitrate_every_s > 0:
            now = time.monotonic()
            if now - self._last_arbitration >= self.arbitrate_every_s:
                self._last_arbitration = now
                self._arbitrate()
        if live and not progressed:
            time.sleep(0.0005)  # all jobs idle: don't spin the core
        return live

    def run(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Drive every job to completion; {job -> JobExecutionResult}.
        Failed jobs past their restart budget surface their error in
        the mapping value instead."""
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        while self.step_round():
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"session cluster did not finish within {timeout_s}s "
                    f"(live: {[j.name for j in self.jobs.values() if not j.finished]})")
        # every job finished: stop the serving workers (a later submit
        # re-binds replicas and restarts the pool); riders still queued
        # fail fast instead of timing out against dead queues
        self.serving.shutdown_workers()
        return {name: (job.result if job.error is None else job.error)
                for name, job in self.jobs.items()}

    def _finish(self, job: TenantJob, result) -> None:
        job.result = result
        job.finished = True
        self.serving.unbind_job(job.name)
        self._fail_stranded_lookups(job)
        self.drr.remove(job.name)
        self._release(job)

    def _release(self, job: TenantJob) -> None:
        """Drop a terminal job's execution resources. The handle keeps
        the whole operator graph alive — engines' [P,cap] device planes,
        host indexes, pumps — so a long-lived cluster churning short
        jobs would otherwise hold one dead job's working set per
        HISTORICAL job. Cheap counters (busy_ms, records_total,
        restarts, ledger violation totals) stay on the TenantJob for
        the results mapping; the per-job gauge subtree is unregistered
        so scrapes stop reading dead engines."""
        job.gen = None
        job.handle = None
        job.ledger.engines.clear()
        self.registry.unregister_scope_prefix(
            self._tenancy_group.scope + (job.name,))

    @staticmethod
    def _fail_stranded_lookups(job: TenantJob) -> None:
        """Fail control requests that raced past the executor's own
        terminal drain: a serving client can pass the plane's bound-queue
        check just as the run finishes and enqueue AFTER
        ``_fail_pending_controls`` ran — with the queue unbound, nothing
        would ever serve it and the rider blocks out its full timeout.
        Draining again after unbind closes the window from this side;
        ``ServingPlane._flush`` closes it from the client side."""
        from flink_tpu.cluster.local_executor import LocalExecutor

        LocalExecutor._fail_pending_controls(
            job.control, f"job {job.name!r} is not serving (not running, "
            "or finished)")

    def _on_failure(self, job: TenantJob, exc: BaseException) -> None:
        """Contain one job's crash: restart it from its latest COMPLETE
        checkpoint (cold from scratch when none exists) while its
        siblings keep running; past the restart budget, the job is
        failed and the error recorded — never propagated into the
        scheduler loop."""
        from flink_tpu.core.config import StateOptions

        job.gen = None
        ckpt_dir = job.config.get(StateOptions.CHECKPOINT_DIR)
        if job.restarts >= self.max_restarts:
            job.error = exc
            job.finished = True
            self.serving.unbind_job(job.name)
            self._fail_stranded_lookups(job)
            self.drr.remove(job.name)
            self._release(job)
            return
        job.restarts += 1
        try:
            restore = None
            if ckpt_dir and os.path.isdir(ckpt_dir):
                from flink_tpu.checkpoint.storage import CheckpointStorage

                cid = CheckpointStorage(ckpt_dir).latest_checkpoint_id(
                    verify=True)
                if cid is not None:
                    restore = os.path.join(ckpt_dir, f"chk-{cid}")
            # drain stale control requests: their servers died with the
            # run
            while True:
                try:
                    job.control.get_nowait().finish(None, RuntimeError(
                        f"job {job.name!r} restarting after: {exc!r}"))
                except _q.Empty:
                    break
            self._start(job, restore_from=restore)
        except BaseException as restart_exc:  # noqa: BLE001
            # the RESTART itself failed (unreadable checkpoint tree,
            # operator open error): charge it against the same budget —
            # letting it escape would unwind step_round and kill every
            # sibling, the exact propagation this method exists to stop
            self._on_failure(job, restart_exc)

    # ---------------------------------------------------------- arbitration

    def _arbitrate(self) -> None:
        """One arbitration tick: demands -> allocations -> LIVE rescale
        requests on the affected jobs' control queues (served at their
        next batch boundary; pending fires drained by the server)."""
        import jax

        from flink_tpu.cluster.local_executor import RescaleRequest
        from flink_tpu.tenancy.arbiter import JobDemand

        demands = []
        targets = {}
        for job in self.jobs.values():
            if job.finished or job.handle is None:
                continue
            pending = job._pending_rescale
            if pending is not None and pending._done.is_set():
                # harvest the finished request: the executor reports a
                # failed reshard via finish(None, e) — dropping it would
                # retry forever with no signal to the operator
                job._pending_rescale = None
                if pending.error is not None:
                    job.rescale_errors += 1
                    job.last_rescale_error = pending.error
            op = next((o for o in job.handle.stateful_operators()
                       if getattr(o, "supports_live_rescale", False)),
                      None)
            if op is None:
                continue
            eng = op.windower
            hi = job.quota.max_shards or len(jax.devices())
            hi = min(hi, len(jax.devices()), int(eng.max_parallelism))
            kgr = getattr(eng, "key_group_range", None)
            if kgr is not None:
                hi = min(hi, int(kgr[1]) - int(kgr[0]) + 1)
            targets[job.name] = (job, op, hi)
            demands.append(JobDemand(
                job=job.name, current_shards=int(eng.P),
                backlog=float(job.handle.backlog_records()),
                quota_pressure=job.ledger.pressure(),
                min_shards=job.quota.min_shards, max_shards=hi))
        if not demands:
            return
        # a watchdog-quarantined device changes the budget: the arbiter
        # divides what actually answers, not the nameplate mesh size.
        # Jobs SHARE the physical mesh, so dead devices dedupe by
        # device id (summing per-job quarantine counts would charge one
        # dead device once per tenant); shard indices without a known
        # device mapping fall back to the per-job max, never the sum
        dead_devices: set = set()
        dead_unmapped = 0
        for j in self.jobs.values():
            if j.finished or j.handle is None:
                continue
            wd = getattr(j.handle, "watchdog", None)
            if wd is None:
                continue
            if wd.quarantined_devices:
                dead_devices |= wd.quarantined_devices
            else:
                dead_unmapped = max(dead_unmapped,
                                    len(wd.quarantined))
        alloc = self.arbiter.decide(
            demands,
            dead_shards=max(len(dead_devices), dead_unmapped))
        for name, shards in alloc.items():
            job, op, hi = targets[name]
            shards = min(int(shards), hi)
            if shards == int(op.windower.P):
                continue
            if job._pending_rescale is not None:
                continue  # one in-flight rescale per job
            req = RescaleRequest(shards)
            job._pending_rescale = req
            job.control.put(req)

    # -------------------------------------------------------------- metrics

    def _register_cluster_gauges(self) -> None:
        g = self._tenancy_group
        g.gauge("jobs_live",
                lambda: sum(1 for j in self.jobs.values()
                            if not j.finished))
        # per-field accessors, not stats()/lookup_counts(): a scrape of
        # every gauge through the dict forms would recompute all fields
        # per gauge (same rule as the per-job quota gauges below)
        g.gauge("program_cache_programs",
                lambda: PROGRAM_CACHE.stat("programs"))
        g.gauge("program_cache_hits",
                lambda: PROGRAM_CACHE.stat("hits"))
        g.gauge("program_cache_misses",
                lambda: PROGRAM_CACHE.stat("misses"))
        g.gauge("queryable_lookups_total",
                lambda: self.serving.lookups_total())
        g.gauge("queryable_lookup_batches_total",
                lambda: self.serving.lookup_batches_total())
        # only the p99 gauge pays the latency-reservoir sort
        g.gauge("queryable_lookup_p99_ms",
                lambda: self.serving.lookup_p99_ms())
        # serving SLO gauges (the read-replica plane): lookup p99,
        # worst-case sealed-generation age, hot-row cache hit rate
        g.gauge("serving.lookupP99Ms",
                lambda: self.serving.lookup_p99_ms())
        g.gauge("serving.replicaStalenessMs",
                lambda: self.serving.replica_staleness_ms())
        g.gauge("serving.hotRowHitRate",
                lambda: self.serving.hot_row_hit_rate())
        if self.serving.shm_dir is not None:
            # the multi-process tier's shm-header counters (live reads
            # off the shared arenas — frontends write them lock-free)
            for name in ("probes", "hits", "torn_retries",
                         "miss_crossings"):
                g.gauge(f"serving.frontend.{name}",
                        (lambda n=name: self.serving.frontend_stats()
                         .get(f"frontend_{n}", 0.0)))

    def _register_job_gauges(self, job: TenantJob) -> None:
        g = self._tenancy_group.add_group(job.name)
        g.gauge("busyTimeMsTotal", lambda j=job: j.busy_ms)
        g.gauge("records_total", lambda j=job: j.records_total)
        g.gauge("restarts", lambda j=job: j.restarts)
        g.gauge("deficit",
                lambda j=job: self.drr.deficit(j.name) or 0.0)
        g.gauge("backlog_records",
                lambda j=job: (j.handle.backlog_records()
                               if j.handle is not None and not j.finished
                               else 0))
        g.gauge("program_cache_misses",
                lambda j=job: PROGRAM_CACHE.stats_for(j.name)["misses"])
        g.gauge("program_cache_hits",
                lambda j=job: PROGRAM_CACHE.stats_for(j.name)["hits"])
        g.gauge("rescale_errors", lambda j=job: j.rescale_errors)
        # individual accessors, not ledger.metrics(): a scrape of all
        # five gauges through metrics() would walk every engine's
        # resident-row indexes ~10 times (metrics() computes
        # resident_rows twice, once directly and once via pressure())
        g.gauge("resident_rows",
                lambda j=job: j.ledger.resident_rows())
        g.gauge("quota_rows",
                lambda j=job: j.ledger.quota.max_resident_rows)
        g.gauge("quota_pressure", lambda j=job: j.ledger.pressure())
        g.gauge("quota_violations",
                lambda j=job: j.ledger.quota_violations)
        g.gauge("rows_shed", lambda j=job: j.ledger.rows_shed)
