"""Deficit-round-robin over per-job ready queues.

The session cluster's scheduling law: every round, each live job's
deficit counter grows by its quantum (records); a job may run scheduling
steps while its deficit is positive, paying the records it actually
processed. A hot job that burns its quantum yields to the next job — it
cannot starve the rest — while an idle job's unused credit is CAPPED
(classic DRR: deficit resets when the queue is empty), so a quiet job
cannot hoard credit and then monopolize the loop in a burst.

reference: network-scheduler DRR (Shreedhar & Varghese) as used by the
reference's mailbox-fairness discussions; here the "packet cost" is
source records per step and the per-job ``busyTimeMsTotal`` gauge makes
the achieved shares observable.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class DeficitRoundRobin:
    """Deficit scheduler over named queues (jobs).

    ``quantum`` — credit (records) added per job per round; a job whose
    weight differs scales its quantum (weight 2.0 = twice the share).
    """

    def __init__(self, quantum: int = 8192):
        self.quantum = int(quantum)
        self._deficit: Dict[str, float] = {}
        self._weight: Dict[str, float] = {}
        self._order: List[str] = []

    def add(self, name: str, weight: float = 1.0) -> None:
        if name not in self._deficit:
            self._order.append(name)
        self._deficit[name] = 0.0
        self._weight[name] = float(weight)

    def remove(self, name: str) -> None:
        self._deficit.pop(name, None)
        self._weight.pop(name, None)
        if name in self._order:
            self._order.remove(name)

    def begin_round(self) -> List[str]:
        """Credit every job its (weighted) quantum; returns the service
        order for this round."""
        for name in self._order:
            self._deficit[name] += self.quantum * self._weight[name]
        return list(self._order)

    def can_run(self, name: str) -> bool:
        return self._deficit.get(name, 0.0) > 0.0

    def charge(self, name: str, records: int) -> None:
        """Pay for work actually done. A zero-record step charges a
        token cost of 1 so a spinning-but-idle job still cycles out."""
        if name in self._deficit:
            self._deficit[name] -= max(int(records), 1)

    def reset_idle(self, name: str) -> None:
        """DRR empty-queue rule: a job with nothing ready forfeits its
        accumulated credit (no hoard-then-burst)."""
        if name in self._deficit:
            self._deficit[name] = 0.0

    def deficit(self, name: str) -> Optional[float]:
        return self._deficit.get(name)
