"""High-QPS queryable-state serving: coalesce lookups into device batches.

The cost model of a point lookup against device-resident state is fixed:
one gather program dispatch + ONE ``jax.device_get`` round trip (the
flint TRC01 discipline). At serving QPS the only lever is AMORTIZATION:
concurrent lookups for the same (job, operator) coalesce into one
request batch, so a burst of N lookups pays one device round trip, not
N. That is this module:

- :class:`LookupCoalescer` — the generic client-side combiner: callers
  from any thread enqueue ``(key, namespace)`` and block on their slice
  of the batch result; the first enqueuer becomes the flusher after a
  short window (or when the batch is full) and issues ONE batched call.
- :class:`ServingPlane` — the cluster-side plane the tenancy session
  cluster owns: per-(job, operator) coalescers whose flush posts a
  :class:`~flink_tpu.cluster.local_executor.StateQueryBatchRequest` to
  the job's control queue (served on the task loop at a batch boundary,
  race-free), plus the serving metrics (lookups/s, batch sizes, p99).

reference: flink-queryable-state's KvStateClientProxy pipelines requests
per TM connection; here the pipeline depth becomes an explicit device
batch, which is what the accelerator link rewards.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple


def reservoir_p99_ms(latencies) -> float:
    """p99 of a latency reservoir (ms); 0.0 when empty. Pays the one
    sort, then reads through ``metrics.core.quantile_sorted`` — the
    shared percentile-index formula (also the fire-latency p99's)."""
    from flink_tpu.metrics.core import quantile_sorted

    return quantile_sorted(sorted(latencies), 0.99)


def lookup_stats_dict(lookups: int, batches: int,
                      latencies) -> Dict[str, float]:
    """The canonical serving-stats dict shape, built in ONE place (pays
    the one p99 sort) — every aggregation path returns through here so
    field names and avg_batch_size semantics cannot drift."""
    return {
        "lookups_total": lookups,
        "lookup_batches_total": batches,
        "avg_batch_size": lookups / batches if batches else 0.0,
        "lookup_p99_ms": reservoir_p99_ms(latencies),
    }


def aggregate_lookup_stats(coalescers) -> Dict[str, float]:
    """Merge coalescer counters + latency reservoirs into the canonical
    serving-stats dict (one sort, for the p99). Reads go through each
    coalescer's locked snapshot — client threads append concurrently,
    and iterating a deque mid-append raises."""
    lookups = 0
    batches = 0
    lat: List[float] = []
    for c in coalescers:
        n, b, ms = c.stats_snapshot()
        lookups += n
        batches += b
        lat.extend(ms)
    return lookup_stats_dict(lookups, batches, lat)


class _Pending:
    __slots__ = ("key", "namespace", "result", "error", "done")

    def __init__(self, key, namespace):
        self.key = key
        self.namespace = namespace
        self.result = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()


class LookupCoalescer:
    """Combine concurrent point lookups into batched flushes.

    ``flush_fn(keys, namespace) -> list_of_results`` executes one device
    batch. Entries sharing a namespace filter batch together; distinct
    namespaces flush as separate batches within one drain (rare — the
    common serving path passes ``namespace=None``).

    ``window_ms`` — how long the first enqueuer waits for riders before
    flushing (0 = flush immediately, still coalescing whatever arrived
    concurrently); ``max_batch`` — flush early when full.
    """

    def __init__(self, flush_fn: Callable[[List[Any], Any], List[Any]],
                 max_batch: int = 512, window_ms: float = 1.0):
        self._flush_fn = flush_fn
        self.max_batch = int(max_batch)
        self.window_s = float(window_ms) / 1000.0
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._flusher_active = False
        #: served lookups / flush batches (the amortization evidence)
        self.lookups_total = 0
        self.batches_total = 0
        #: bounded reservoir of per-lookup latencies (ms)
        self.latencies_ms: deque = deque(maxlen=8192)
        #: set by CoalescerPool.retire: post-retirement counts redirect
        #: into the pool's retained totals, so a lookup racing a
        #: retire (forget_job / unbind_job) is never silently dropped
        #: from cumulative stats
        self._fold_into = None

    def _record(self, n_lookups: int = 0, batches: int = 0,
                lat=()) -> None:
        with self._lock:
            sink = self._fold_into
            if sink is None:
                self.lookups_total += n_lookups
                self.batches_total += batches
                self.latencies_ms.extend(lat)
                return
        # release our lock before _absorb takes the pool's: no path
        # ever holds both locks at once (retire also staggers them)
        sink._absorb(n_lookups, batches, lat)

    def lookup(self, key, namespace=None, timeout_s: float = 30.0):
        """Enqueue one lookup and block until its batch lands."""
        t0 = time.perf_counter()
        entry = _Pending(key, namespace)
        flush_now = False
        with self._lock:
            self._queue.append(entry)
            if not self._flusher_active:
                # first in line becomes the flusher for this window
                self._flusher_active = True
                flush_now = True
        if flush_now:
            if self.window_s > 0:
                # ride-collection window: let concurrent callers pile on
                deadline = time.monotonic() + self.window_s
                while time.monotonic() < deadline:
                    with self._lock:
                        if len(self._queue) >= self.max_batch:
                            break
                    time.sleep(self.window_s / 4)
            self._drain()
        if not entry.done.wait(timeout_s):
            raise TimeoutError("queryable-state lookup not served")
        self._record(lat=((time.perf_counter() - t0) * 1e3,))
        if entry.error is not None:
            raise entry.error
        return entry.result

    def _drain(self) -> None:
        """Flush everything queued, in (at most max_batch)-sized device
        batches, grouped by namespace filter. Runs on the flusher's
        thread; errors fan out to every rider of the failed batch."""
        while True:
            try:
                while True:
                    with self._lock:
                        if not self._queue:
                            break
                        batch = [self._queue.popleft()
                                 for _ in range(min(len(self._queue),
                                                    self.max_batch))]
                    by_ns: Dict[Any, List[_Pending]] = {}
                    for e in batch:
                        by_ns.setdefault(e.namespace, []).append(e)
                    for ns, entries in by_ns.items():
                        try:
                            results = self._flush_fn(
                                [e.key for e in entries], ns)
                            if len(results) != len(entries):
                                # a short reply must be an ERROR for
                                # every rider — zip-truncating would
                                # hand the tail result=None, which
                                # reads as "key has no state"
                                raise RuntimeError(
                                    f"lookup flush returned "
                                    f"{len(results)} results for "
                                    f"{len(entries)} keys")
                            for e, r in zip(entries, results):
                                e.result = r
                        except BaseException as err:  # noqa: BLE001
                            for e in entries:
                                e.error = err
                        finally:
                            self._record(n_lookups=len(entries),
                                         batches=1)
                            for e in entries:
                                e.done.set()
            except BaseException:
                # release flusher duty before propagating: the next
                # lookup() claims it and drains whatever is queued
                with self._lock:
                    self._flusher_active = False
                raise
            with self._lock:
                if not self._queue:
                    self._flusher_active = False
                    return
                # entries raced in after our last empty check: keep
                # flusher duty and loop — a loop, not tail-recursion, so
                # a one-rider-per-round arrival pattern cannot grow the
                # stack

    def stats_snapshot(self) -> Tuple[int, int, List[float]]:
        """(lookups_total, batches_total, latencies) under the lock —
        the only safe way to read the counters and the reservoir while
        client threads serve."""
        with self._lock:
            return (self.lookups_total, self.batches_total,
                    list(self.latencies_ms))

    def note_batch(self, n_lookups: int, elapsed_ms: float) -> None:
        """Record an externally-flushed batch (ServingPlane's explicit
        ``lookup_batch`` path) against this coalescer's counters."""
        self._record(n_lookups=n_lookups, batches=1, lat=(elapsed_ms,))

    def p99_ms(self) -> float:
        with self._lock:
            lat = list(self.latencies_ms)
        return reservoir_p99_ms(lat)


class CoalescerPool:
    """Per-key pool of :class:`LookupCoalescer`\\ s: double-checked
    creation, retirement, cumulative stats. The ONE copy of the
    coalescer lifecycle — the serving plane (keys = (job, operator))
    and the queryable-state client share it, so the creation race,
    retirement accounting, and stats shape can't drift between them.
    Retired members fold their counters (and bounded latency
    reservoirs) into retained totals, so cumulative stats survive
    member churn (jobs finishing, clients forgetting)."""

    def __init__(self, make_flush: Callable[[Any], Callable],
                 max_batch: int = 512, window_ms: float = 1.0):
        self._make_flush = make_flush
        self._max_batch = int(max_batch)
        self._window_ms = float(window_ms)
        self._members: Dict[Any, LookupCoalescer] = {}
        self._lock = threading.Lock()
        self._retired_lookups = 0
        self._retired_batches = 0
        self._retired_lat: deque = deque(maxlen=8192)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def get(self, key) -> LookupCoalescer:
        # fully locked (construction is cheap): an unlocked fast path
        # would let get and retire interleave mid-read
        with self._lock:
            co = self._members.get(key)
            if co is None:
                co = self._members[key] = LookupCoalescer(
                    self._make_flush(key),
                    max_batch=self._max_batch,
                    window_ms=self._window_ms)
            return co

    def retire(self, match: Callable[[Any], bool]) -> None:
        with self._lock:
            popped = [self._members.pop(k)
                      for k in [k for k in self._members if match(k)]]
        for co in popped:
            # fold the counters AND flag the coalescer: a lookup that
            # already holds a reference (raced the pop) records its
            # counts into our retained totals via _record/_absorb —
            # nothing is silently dropped from cumulative stats. Locks
            # are taken one at a time (pool, then co, then pool again),
            # never nested.
            with co._lock:
                n, b = co.lookups_total, co.batches_total
                ms = list(co.latencies_ms)
                co.lookups_total = 0
                co.batches_total = 0
                co.latencies_ms.clear()
                co._fold_into = self
            self._absorb(n, b, ms)

    def _absorb(self, n_lookups: int, batches: int, lat) -> None:
        with self._lock:
            self._retired_lookups += n_lookups
            self._retired_batches += batches
            self._retired_lat.extend(lat)

    def snapshot(self) -> List[LookupCoalescer]:
        # under the lock: client threads insert concurrently, and dict
        # iteration during an insert raises
        with self._lock:
            return list(self._members.values())

    def lookups_total(self) -> int:
        """One counter, one walk — what a per-scrape gauge reads."""
        with self._lock:
            n = self._retired_lookups
        for c in self.snapshot():
            with c._lock:
                n += c.lookups_total
        return n

    def batches_total(self) -> int:
        with self._lock:
            n = self._retired_batches
        for c in self.snapshot():
            with c._lock:
                n += c.batches_total
        return n

    def latencies(self) -> List[float]:
        with self._lock:
            lat: List[float] = list(self._retired_lat)
        for c in self.snapshot():
            lat.extend(c.stats_snapshot()[2])
        return lat

    def stats(self) -> Dict[str, float]:
        """The canonical serving-stats dict, retained totals included
        (pays the one p99 sort)."""
        with self._lock:
            lookups = self._retired_lookups
            batches = self._retired_batches
            lat = list(self._retired_lat)
        for c in self.snapshot():
            n, b, ms = c.stats_snapshot()
            lookups += n
            batches += b
            lat.extend(ms)
        return lookup_stats_dict(lookups, batches, lat)


class ServingPlane:
    """The session cluster's lookup surface: per-(job, operator)
    coalescers flushing batched StateQueryBatchRequests onto the owning
    job's control queue."""

    def __init__(self, max_batch: int = 512, window_ms: float = 1.0,
                 timeout_s: float = 30.0):
        self.max_batch = int(max_batch)
        self.window_ms = float(window_ms)
        self.timeout_s = float(timeout_s)

        def make_flush(key):
            def flush(keys, namespace, _job=key[0], _op=key[1]):
                return self._flush(_job, _op, keys, namespace)

            return flush

        self._pool = CoalescerPool(make_flush, max_batch=self.max_batch,
                                   window_ms=self.window_ms)
        #: job name -> control queue (bound by the session cluster)
        self._queues: Dict[str, Any] = {}

    def bind_job(self, job_name: str, control_queue) -> None:
        self._queues[job_name] = control_queue

    def unbind_job(self, job_name: str) -> None:
        self._queues.pop(job_name, None)
        # retire the job's coalescers: a cluster churning many short
        # jobs would otherwise grow the pool (and its latency
        # reservoirs, and every scrape's walk) per HISTORICAL job
        self._pool.retire(lambda k: k[0] == job_name)

    def _coalescer(self, job_name: str, operator: str) -> LookupCoalescer:
        # bound-check BEFORE pool.get: a client still polling a finished
        # job would otherwise re-create the retired coalescer (plus its
        # latency reservoir) on every lookup, with no future unbind to
        # retire it — the per-historical-job leak, deterministically
        if job_name not in self._queues:
            raise RuntimeError(
                f"job {job_name!r} is not serving (not running, or "
                "finished)")
        co = self._pool.get((job_name, operator))
        if job_name not in self._queues:
            # unbind raced our get: retire what we may have re-created
            self._pool.retire(lambda k: k == (job_name, operator))
            raise RuntimeError(
                f"job {job_name!r} is not serving (not running, or "
                "finished)")
        return co

    def _flush(self, job_name: str, operator: str, keys, namespace):
        from flink_tpu.observe import flight_recorder as flight

        with flight.span("serving.lookup", job=job_name):
            return self._flush_inner(job_name, operator, keys,
                                     namespace)

    def _flush_inner(self, job_name: str, operator: str, keys,
                     namespace):
        from flink_tpu.cluster.local_executor import (
            StateQueryBatchRequest,
        )

        q = self._queues.get(job_name)
        if q is None:
            raise RuntimeError(
                f"job {job_name!r} is not serving (not running, or "
                "finished)")
        req = StateQueryBatchRequest(operator, keys, namespace)
        q.put(req)
        if self._queues.get(job_name) is not q:
            # the job terminated between our bound-queue check and the
            # put: the executor's terminal drain (and the cluster's
            # post-unbind drain) may both have missed this request, and
            # nothing will ever serve the dead queue — fail whatever is
            # still on it (every entry is equally stranded) so riders
            # get the prompt not-serving error instead of a timeout
            import queue as _queue

            while True:
                try:
                    stranded = q.get_nowait()
                except _queue.Empty:
                    break
                stranded.finish(None, RuntimeError(
                    f"job {job_name!r} is not serving (not running, or "
                    "finished)"))
        return req.wait(self.timeout_s)

    def lookup(self, job_name: str, operator: str, key,
               namespace=None):
        """One point lookup; rides whatever batch is forming."""
        return self._coalescer(job_name, operator).lookup(
            key, namespace, timeout_s=self.timeout_s)

    def lookup_batch(self, job_name: str, operator: str, keys,
                     namespace=None) -> List[Any]:
        """An explicit batch: bypasses the window, one request batch."""
        co = self._coalescer(job_name, operator)
        t0 = time.perf_counter()
        out = self._flush(job_name, operator, list(keys), namespace)
        co.note_batch(len(out), (time.perf_counter() - t0) * 1e3)
        return out

    # ---------------------------------------------------------------- metrics

    def lookups_total(self) -> int:
        """One counter, one walk — what the per-scrape gauge reads."""
        return self._pool.lookups_total()

    def lookup_batches_total(self) -> int:
        return self._pool.batches_total()

    def lookup_p99_ms(self) -> float:
        """p99 over every coalescer's latency reservoir (pays one sort)."""
        return reservoir_p99_ms(self._pool.latencies())

    def metrics(self) -> Dict[str, float]:
        return self._pool.stats()
