"""High-QPS queryable-state serving: coalesce lookups into device batches.

The cost model of a point lookup against device-resident state is fixed:
one gather program dispatch + ONE ``jax.device_get`` round trip (the
flint TRC01 discipline). At serving QPS the only lever is AMORTIZATION:
concurrent lookups for the same (job, operator) coalesce into one
request batch, so a burst of N lookups pays one device round trip, not
N. That is this module:

- :class:`LookupCoalescer` — the generic client-side combiner: callers
  from any thread enqueue ``(key, namespace)`` and block on their slice
  of the batch result; the first enqueuer becomes the flusher after a
  short window (or when the batch is full) and issues ONE batched call.
- :class:`ServingPlane` — the cluster-side plane the tenancy session
  cluster owns: per-(job, operator) coalescers whose flush posts a
  :class:`~flink_tpu.cluster.local_executor.StateQueryBatchRequest` to
  the job's control queue (served on the task loop at a batch boundary,
  race-free), plus the serving metrics (lookups/s, batch sizes, p99).

reference: flink-queryable-state's KvStateClientProxy pipelines requests
per TM connection; here the pipeline depth becomes an explicit device
batch, which is what the accelerator link rewards.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.observe.lock_sentinel import named_lock


def reservoir_p99_ms(latencies) -> float:
    """p99 of a latency reservoir (ms); 0.0 when empty. Pays the one
    sort, then reads through ``metrics.core.quantile_sorted`` — the
    shared percentile-index formula (also the fire-latency p99's)."""
    from flink_tpu.metrics.core import quantile_sorted

    return quantile_sorted(sorted(latencies), 0.99)


def lookup_stats_dict(lookups: int, batches: int,
                      latencies) -> Dict[str, float]:
    """The canonical serving-stats dict shape, built in ONE place (pays
    the one p99 sort) — every aggregation path returns through here so
    field names and avg_batch_size semantics cannot drift."""
    return {
        "lookups_total": lookups,
        "lookup_batches_total": batches,
        "avg_batch_size": lookups / batches if batches else 0.0,
        "lookup_p99_ms": reservoir_p99_ms(latencies),
    }


def aggregate_lookup_stats(coalescers,
                           frontend_stats=None) -> Dict[str, float]:
    """Merge coalescer counters + latency reservoirs into the canonical
    serving-stats dict (one sort, for the p99). Reads go through each
    coalescer's locked snapshot — client threads append concurrently,
    and iterating a deque mid-append raises.

    ``frontend_stats`` (optional): per-frontend counter rows as
    ``NativeHotRowCache.fe_stats`` returns them — the multi-process
    tier's shm-header counters. Frontend-served probes fold into
    ``lookups_total`` (a frontend hit IS a served lookup that never
    reached a coalescer) and the per-counter sums ride along under
    ``frontend_*``, so the bench breakdown derives from the real
    counters, not wall-clock division."""
    lookups = 0
    batches = 0
    lat: List[float] = []
    for c in coalescers:
        n, b, ms = c.stats_snapshot()
        lookups += n
        batches += b
        lat.extend(ms)
    out = lookup_stats_dict(lookups, batches, lat)
    if frontend_stats:
        for k in frontend_stats[0].keys():
            out[f"frontend_{k}"] = float(
                sum(r[k] for r in frontend_stats))
        # hits answered inside a frontend never cross to a coalescer;
        # miss crossings DO reach one (counted there already)
        out["lookups_total"] += out.get("frontend_hits", 0.0)
    return out


class _Pending:
    __slots__ = ("key", "namespace", "result", "error", "done")

    def __init__(self, key, namespace):
        self.key = key
        self.namespace = namespace
        self.result = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()


class LookupCoalescer:
    """Combine concurrent point lookups into batched flushes.

    ``flush_fn(keys, namespace) -> list_of_results`` executes one device
    batch. Entries sharing a namespace filter batch together; distinct
    namespaces flush as separate batches within one drain (rare — the
    common serving path passes ``namespace=None``).

    ``window_ms`` — how long the first enqueuer waits for riders before
    flushing (0 = flush immediately, still coalescing whatever arrived
    concurrently); ``max_batch`` — flush early when full.
    """

    def __init__(self, flush_fn: Callable[[List[Any], Any], List[Any]],
                 max_batch: int = 512, window_ms: float = 1.0):
        self._flush_fn = flush_fn
        self.max_batch = int(max_batch)
        self.window_s = float(window_ms) / 1000.0
        self._lock = named_lock("serving.coalescer")
        self._queue: deque = deque()
        self._flusher_active = False
        #: served lookups / flush batches (the amortization evidence)
        self.lookups_total = 0
        self.batches_total = 0
        #: bounded reservoir of per-lookup latencies (ms)
        self.latencies_ms: deque = deque(maxlen=8192)
        #: set by CoalescerPool.retire: post-retirement counts redirect
        #: into the pool's retained totals, so a lookup racing a
        #: retire (forget_job / unbind_job) is never silently dropped
        #: from cumulative stats
        self._fold_into = None

    def _record(self, n_lookups: int = 0, batches: int = 0,
                lat=()) -> None:
        with self._lock:
            sink = self._fold_into
            if sink is None:
                self.lookups_total += n_lookups
                self.batches_total += batches
                self.latencies_ms.extend(lat)
                return
        # release our lock before _absorb takes the pool's: no path
        # ever holds both locks at once (retire also staggers them)
        sink._absorb(n_lookups, batches, lat)

    def lookup(self, key, namespace=None, timeout_s: float = 30.0):
        """Enqueue one lookup and block until its batch lands."""
        t0 = time.perf_counter()
        entry = _Pending(key, namespace)
        flush_now = False
        with self._lock:
            self._queue.append(entry)
            if not self._flusher_active:
                # first in line becomes the flusher for this window
                self._flusher_active = True
                flush_now = True
        if flush_now:
            if self.window_s > 0:
                # ride-collection window: let concurrent callers pile on
                deadline = time.monotonic() + self.window_s
                while time.monotonic() < deadline:
                    with self._lock:
                        if len(self._queue) >= self.max_batch:
                            break
                    time.sleep(self.window_s / 4)
            # flint: disable=LCK03 -- flusher-duty handoff: exactly one
            # thread set _flusher_active under the first hold and owns
            # the duty until _drain clears it under its own hold; late
            # enqueuers see the flag and ride instead of flushing
            self._drain()
        if not entry.done.wait(timeout_s):
            raise TimeoutError("queryable-state lookup not served")
        self._record(lat=((time.perf_counter() - t0) * 1e3,))
        if entry.error is not None:
            raise entry.error
        return entry.result

    def _drain(self) -> None:
        """Flush everything queued, in (at most max_batch)-sized device
        batches, grouped by namespace filter. Runs on the flusher's
        thread; errors fan out to every rider of the failed batch."""
        while True:
            try:
                while True:
                    with self._lock:
                        if not self._queue:
                            break
                        batch = [self._queue.popleft()
                                 for _ in range(min(len(self._queue),
                                                    self.max_batch))]
                    by_ns: Dict[Any, List[_Pending]] = {}
                    for e in batch:
                        by_ns.setdefault(e.namespace, []).append(e)
                    for ns, entries in by_ns.items():
                        try:
                            results = self._flush_fn(
                                [e.key for e in entries], ns)
                            if len(results) != len(entries):
                                # a short reply must be an ERROR for
                                # every rider — zip-truncating would
                                # hand the tail result=None, which
                                # reads as "key has no state"
                                raise RuntimeError(
                                    f"lookup flush returned "
                                    f"{len(results)} results for "
                                    f"{len(entries)} keys")
                            for e, r in zip(entries, results):
                                e.result = r
                        except BaseException as err:  # noqa: BLE001
                            for e in entries:
                                e.error = err
                        finally:
                            self._record(n_lookups=len(entries),
                                         batches=1)
                            for e in entries:
                                e.done.set()
            except BaseException:
                # release flusher duty before propagating: the next
                # lookup() claims it and drains whatever is queued
                with self._lock:
                    self._flusher_active = False
                raise
            with self._lock:
                if not self._queue:
                    self._flusher_active = False
                    return
                # entries raced in after our last empty check: keep
                # flusher duty and loop — a loop, not tail-recursion, so
                # a one-rider-per-round arrival pattern cannot grow the
                # stack

    def stats_snapshot(self) -> Tuple[int, int, List[float]]:
        """(lookups_total, batches_total, latencies) under the lock —
        the only safe way to read the counters and the reservoir while
        client threads serve."""
        with self._lock:
            return (self.lookups_total, self.batches_total,
                    list(self.latencies_ms))

    def note_batch(self, n_lookups: int, elapsed_ms: float) -> None:
        """Record an externally-flushed batch (ServingPlane's explicit
        ``lookup_batch`` path) against this coalescer's counters."""
        self._record(n_lookups=n_lookups, batches=1, lat=(elapsed_ms,))

    def p99_ms(self) -> float:
        with self._lock:
            lat = list(self.latencies_ms)
        return reservoir_p99_ms(lat)


class CoalescerPool:
    """Per-key pool of :class:`LookupCoalescer`\\ s: double-checked
    creation, retirement, cumulative stats. The ONE copy of the
    coalescer lifecycle — the serving plane (keys = (job, operator))
    and the queryable-state client share it, so the creation race,
    retirement accounting, and stats shape can't drift between them.
    Retired members fold their counters (and bounded latency
    reservoirs) into retained totals, so cumulative stats survive
    member churn (jobs finishing, clients forgetting)."""

    def __init__(self, make_flush: Callable[[Any], Callable],
                 max_batch: int = 512, window_ms: float = 1.0):
        self._make_flush = make_flush
        self._max_batch = int(max_batch)
        self._window_ms = float(window_ms)
        self._members: Dict[Any, LookupCoalescer] = {}
        self._lock = named_lock("serving.pool")
        self._retired_lookups = 0
        self._retired_batches = 0
        self._retired_lat: deque = deque(maxlen=8192)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def get(self, key) -> LookupCoalescer:
        # fully locked (construction is cheap): an unlocked fast path
        # would let get and retire interleave mid-read
        with self._lock:
            co = self._members.get(key)
            if co is None:
                co = self._members[key] = LookupCoalescer(
                    self._make_flush(key),
                    max_batch=self._max_batch,
                    window_ms=self._window_ms)
            return co

    def retire(self, match: Callable[[Any], bool]) -> None:
        with self._lock:
            popped = [self._members.pop(k)
                      for k in [k for k in self._members if match(k)]]
        for co in popped:
            # fold the counters AND flag the coalescer: a lookup that
            # already holds a reference (raced the pop) records its
            # counts into our retained totals via _record/_absorb —
            # nothing is silently dropped from cumulative stats. Locks
            # are taken one at a time (pool, then co, then pool again),
            # never nested.
            with co._lock:
                n, b = co.lookups_total, co.batches_total
                ms = list(co.latencies_ms)
                co.lookups_total = 0
                co.batches_total = 0
                co.latencies_ms.clear()
                co._fold_into = self
            self._absorb(n, b, ms)

    def _absorb(self, n_lookups: int, batches: int, lat) -> None:
        with self._lock:
            self._retired_lookups += n_lookups
            self._retired_batches += batches
            self._retired_lat.extend(lat)

    def snapshot(self) -> List[LookupCoalescer]:
        # under the lock: client threads insert concurrently, and dict
        # iteration during an insert raises
        with self._lock:
            return list(self._members.values())

    def lookups_total(self) -> int:
        """One counter, one walk — what a per-scrape gauge reads."""
        with self._lock:
            n = self._retired_lookups
        for c in self.snapshot():
            with c._lock:
                n += c.lookups_total
        return n

    def batches_total(self) -> int:
        with self._lock:
            n = self._retired_batches
        for c in self.snapshot():
            with c._lock:
                n += c.batches_total
        return n

    def latencies(self) -> List[float]:
        with self._lock:
            lat: List[float] = list(self._retired_lat)
        for c in self.snapshot():
            lat.extend(c.stats_snapshot()[2])
        return lat

    def stats(self) -> Dict[str, float]:
        """The canonical serving-stats dict, retained totals included
        (pays the one p99 sort)."""
        with self._lock:
            lookups = self._retired_lookups
            batches = self._retired_batches
            lat = list(self._retired_lat)
        for c in self.snapshot():
            n, b, ms = c.stats_snapshot()
            lookups += n
            batches += b
            lat.extend(ms)
        return lookup_stats_dict(lookups, batches, lat)


class PackedLookupResult:
    """A batch lookup's results, materialized LAZILY: hit keys live in
    the native probe's packed buffers (:class:`PackedProbe` — raw
    int64/float64 bit patterns, zero copies, zero dicts built); only a
    key somebody actually reads pays dict construction, and it is
    cached per index. Misses (and Python-plane fallbacks) are
    pre-materialized ``overrides``. Sequence-compatible: ``len``,
    indexing, iteration, ``==`` against a plain list — and
    :meth:`to_dicts` for the full eager form (bit-identical to
    ``lookup_batch``, test-pinned)."""

    __slots__ = ("_n", "_probe", "_overrides", "_cache")

    def __init__(self, n: int, probe, overrides: Dict[int, Any]) -> None:
        self._n = int(n)
        self._probe = probe
        self._overrides = overrides
        self._cache: Dict[int, Any] = {}

    @classmethod
    def from_dicts(cls, results) -> "PackedLookupResult":
        return cls(len(results), None, dict(enumerate(results)))

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        if i in self._overrides:
            return self._overrides[i]
        v = self._cache.get(i)
        if v is None:
            v = self._probe.materialize(i)
            self._cache[i] = v
        return v

    def __iter__(self):
        for i in range(self._n):
            yield self[i]

    def to_dicts(self) -> List[Any]:
        return [self[i] for i in range(self._n)]

    def __eq__(self, other):
        if isinstance(other, PackedLookupResult):
            return self.to_dicts() == other.to_dicts()
        if isinstance(other, list):
            return self.to_dicts() == other
        return NotImplemented

    def __repr__(self) -> str:
        return f"PackedLookupResult(n={self._n})"


class _RepPending:
    """One rider of the replica serving path (shard-queue entry)."""

    __slots__ = ("key", "key_id", "namespace", "result", "error", "done")

    def __init__(self, key, key_id: int, namespace):
        self.key = key
        self.key_id = key_id
        self.namespace = namespace
        self.result = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()


class _ReplicaWorker(threading.Thread):
    """One serving worker: the single owner of its set of per-(job,
    operator, shard) lookup queues. Riders enqueue misses; the worker
    drains every owned queue each round, batches the entries per (job,
    operator) against ONE sealed replica generation, and completes the
    riders — multiple workers drain disjoint shard sets concurrently,
    so one tenant's burst never serializes every tenant's traffic
    behind a single drain loop (the pre-replica bottleneck)."""

    def __init__(self, plane: "ServingPlane", idx: int) -> None:
        super().__init__(name=f"serving-worker-{idx}", daemon=True)
        self._plane = plane
        self._lock = named_lock("serving.worker")
        self._queues: Dict[tuple, deque] = {}
        self._event = threading.Event()
        self._stopped = False

    def enqueue(self, qkey: tuple, entry: _RepPending) -> None:
        with self._lock:
            self._queues.setdefault(qkey, deque()).append(entry)
        self._event.set()

    def stop(self) -> None:
        self._stopped = True
        self._event.set()

    def fail_pending(self, reason: str) -> None:
        """Complete any still-queued riders with an error (shutdown —
        nothing will drain the queues again)."""
        with self._lock:
            leftovers = [e for q in self._queues.values() for e in q]
            self._queues.clear()
        for e in leftovers:
            e.error = RuntimeError(reason)
            e.done.set()

    def run(self) -> None:
        while not self._stopped:
            self._event.wait(timeout=0.1)
            self._event.clear()
            while self._drain_round():
                pass

    def _drain_round(self) -> bool:
        # pop everything queued this round (bounded: later arrivals
        # land in the next round), grouped per (job, operator) — one
        # replica batch per group per round
        groups: Dict[tuple, List[_RepPending]] = {}
        with self._lock:
            for (job, op, _shard), q in self._queues.items():
                if q:
                    groups.setdefault((job, op), []).extend(q)
                    q.clear()
        if not groups:
            return False
        for (job, op), entries in groups.items():
            self._plane._flush_replica(job, op, entries)
        return True


class ServingPlane:
    """The session cluster's lookup surface. Two read paths:

    - **Replica path** (an adapter is bound for the (job, operator)):
      probe the host hot-row cache; misses go to per-shard lookup
      queues drained by the worker pool, which resolves them against
      the SEALED replica generation — one gather + one device read per
      miss batch, zero contention with ingest, results cached under
      the generation tag. Cold rows detour through the legacy path
      below (page tiers are single-owner host state).
    - **Legacy path** (no replica — single-device engines, pre-publish
      warmup): per-(job, operator) coalescers flushing batched
      StateQueryBatchRequests onto the owning job's control queue,
      served by the task loop at a batch boundary."""

    def __init__(self, max_batch: int = 512, window_ms: float = 1.0,
                 timeout_s: float = 30.0, workers: int = 2,
                 cache_entries: int = 1 << 18,
                 shm_dir: Optional[str] = None):
        self.max_batch = int(max_batch)
        self.window_ms = float(window_ms)
        self.timeout_s = float(timeout_s)
        self.n_workers = max(int(workers), 1)
        #: when set, the hot cache allocates MAP_SHARED arenas under
        #: this directory and frontend processes may attach (the
        #: multi-process serving tier — flink_tpu.tenancy.frontend)
        self.shm_dir = shm_dir

        def make_flush(key):
            def flush(keys, namespace, _job=key[0], _op=key[1]):
                return self._flush(_job, _op, keys, namespace)

            return flush

        self._pool = CoalescerPool(make_flush, max_batch=self.max_batch,
                                   window_ms=self.window_ms)
        #: job name -> control queue (bound by the session cluster)
        self._queues: Dict[str, Any] = {}
        #: (job, operator) -> ReplicaAdapter (bound by the cluster)
        self._replicas: Dict[tuple, Any] = {}
        from flink_tpu.tenancy.hot_cache import make_hot_row_cache

        #: the native GIL-free probe table when available, else the
        #: bit-identical Python LRU (FLINK_TPU_NATIVE_HOTCACHE=0 A/B)
        self.hot_cache = make_hot_row_cache(cache_entries,
                                            shm_dir=shm_dir)
        self._workers: List[_ReplicaWorker] = []
        self._workers_lock = named_lock("serving.workers")
        #: sampled serving.cache_hit instants (1-in-N — a per-hit ring
        #: write at cache-hit QPS would itself cost a core fraction)
        self._hit_sample = 0

    # ------------------------------------------------------------- binding

    def bind_job(self, job_name: str, control_queue) -> None:
        self._queues[job_name] = control_queue

    def bind_replica(self, job_name: str, operator: str,
                     adapter) -> None:
        """Register a replica adapter for (job, operator) lookups; the
        cold-row detour rides the legacy control-queue flush."""
        adapter.cold_fetch = (
            lambda keys, _j=job_name, _o=operator:
            self._flush(_j, _o, list(keys), None))
        adapter.attach_cache(self.hot_cache, job_name, operator)
        self._replicas[(job_name, operator)] = adapter
        self._ensure_workers()

    def unbind_job(self, job_name: str) -> None:
        self._queues.pop(job_name, None)
        for k in [k for k in self._replicas if k[0] == job_name]:
            del self._replicas[k]
        self.hot_cache.invalidate_job(job_name)
        # retire the job's coalescers: a cluster churning many short
        # jobs would otherwise grow the pool (and its latency
        # reservoirs, and every scrape's walk) per HISTORICAL job
        self._pool.retire(lambda k: k[0] == job_name)

    def _ensure_workers(self) -> None:
        self._pick_worker(("", "", 0))  # starts the pool if stopped

    def shutdown_workers(self) -> None:
        """Stop the worker pool (cluster run finished). A later
        bind_replica restarts it; riders still queued fail fast."""
        with self._workers_lock:
            workers, self._workers = self._workers, []
        for w in workers:
            w.stop()
        for w in workers:
            w.join(timeout=2)
            w.fail_pending("serving workers shut down (cluster run "
                           "finished)")

    def _pick_worker(self, qkey: tuple) -> _ReplicaWorker:
        with self._workers_lock:
            while len(self._workers) < self.n_workers:
                w = _ReplicaWorker(self, len(self._workers))
                self._workers.append(w)
                w.start()
            return self._workers[hash(qkey) % len(self._workers)]

    def _coalescer(self, job_name: str, operator: str) -> LookupCoalescer:
        # bound-check BEFORE pool.get: a client still polling a finished
        # job would otherwise re-create the retired coalescer (plus its
        # latency reservoir) on every lookup, with no future unbind to
        # retire it — the per-historical-job leak, deterministically
        if job_name not in self._queues:
            raise RuntimeError(
                f"job {job_name!r} is not serving (not running, or "
                "finished)")
        co = self._pool.get((job_name, operator))
        if job_name not in self._queues:
            # unbind raced our get: retire what we may have re-created
            self._pool.retire(lambda k: k == (job_name, operator))
            raise RuntimeError(
                f"job {job_name!r} is not serving (not running, or "
                "finished)")
        return co

    def _flush(self, job_name: str, operator: str, keys, namespace):
        from flink_tpu.observe import flight_recorder as flight

        with flight.span("serving.lookup", job=job_name):
            return self._flush_inner(job_name, operator, keys,
                                     namespace)

    def _flush_inner(self, job_name: str, operator: str, keys,
                     namespace):
        from flink_tpu.cluster.local_executor import (
            StateQueryBatchRequest,
        )

        q = self._queues.get(job_name)
        if q is None:
            raise RuntimeError(
                f"job {job_name!r} is not serving (not running, or "
                "finished)")
        req = StateQueryBatchRequest(operator, keys, namespace)
        q.put(req)
        if self._queues.get(job_name) is not q:
            # the job terminated between our bound-queue check and the
            # put: the executor's terminal drain (and the cluster's
            # post-unbind drain) may both have missed this request, and
            # nothing will ever serve the dead queue — fail whatever is
            # still on it (every entry is equally stranded) so riders
            # get the prompt not-serving error instead of a timeout
            import queue as _queue

            while True:
                try:
                    stranded = q.get_nowait()
                except _queue.Empty:
                    break
                stranded.finish(None, RuntimeError(
                    f"job {job_name!r} is not serving (not running, or "
                    "finished)"))
        return req.wait(self.timeout_s)

    # ---------------------------------------------------------- replica path

    def _adapter(self, job_name: str, operator: str):
        ad = self._replicas.get((job_name, operator))
        if ad is None or not ad.ready():
            return None
        return ad

    @staticmethod
    def _filter_ns(result, namespace):
        if namespace is None:
            return result
        ns = int(namespace)
        return {ns: result[ns]} if ns in result else {}

    @staticmethod
    def _probe_faulted(job_name: str, operator: str) -> bool:
        """The ``serving.cache_probe`` chaos point: raise/delay kinds
        apply in place; a ``drop`` kind makes the probe fall to the
        MISS path for this request (the system-level shape of a torn
        native read — the entry is skipped, never served mixed).
        One module-global None check while disarmed."""
        from flink_tpu.chaos import injection as chaos

        rule = chaos.payload_action(
            "serving.cache_probe", kinds=("raise", "delay", "drop"),
            job=job_name, operator=operator)
        return rule is not None and rule.kind == "drop"

    def _cache_probe(self, job_name: str, operator: str, ad, key,
                     co) -> Tuple[bool, int, int, Any]:
        """(hit, key_id, generation, value) — one batched native probe
        (or one locked dict access on the Python fallback); a hit
        records its (sub-ms) latency against the coalescer's reservoir
        and a SAMPLED serving.cache_hit instant."""
        from flink_tpu.observe import flight_recorder as flight

        kid = ad.key_id(key)
        gen = ad.generation()
        if self._probe_faulted(job_name, operator):
            return False, kid, gen, None
        # exact=False: bound adapters re-prime/drop every entry a
        # publish changes, so presence implies validity (see HotRowCache)
        hit, val = self.hot_cache.get(job_name, operator, kid, gen,
                                      exact=False)
        if hit:
            co._record(n_lookups=1)
            self._hit_sample += 1
            if self._hit_sample % 256 == 1:
                flight.instant("serving.cache_hit", job=job_name,
                               batch=gen)
        return hit, kid, gen, val

    def _enqueue_miss(self, job_name: str, operator: str, ad, key,
                      kid: int, namespace) -> _RepPending:
        entry = _RepPending(key, kid, namespace)
        shard = ad.shard_of(kid)
        qkey = (job_name, operator, shard)
        # shard -> worker is a stable partition: exactly one worker
        # ever drains one shard queue (single-owner discipline)
        self._pick_worker(qkey).enqueue(qkey, entry)
        return entry

    def _flush_replica(self, job_name: str, operator: str,
                       entries: List[_RepPending]) -> None:
        """Worker-side: resolve one miss batch against ONE sealed
        generation, fill the hot-row cache, complete the riders. The
        PR 6 coalescer guarantees carry over: a short result raises to
        EVERY rider (zip-truncation would read as 'key has no state'),
        and counters/latencies are recorded under the coalescer lock
        (through _record, which also folds into retained totals when a
        retire raced — nothing drops from cumulative stats)."""
        from flink_tpu.observe import flight_recorder as flight

        t0 = time.perf_counter()
        try:
            # the bound-check/retire dance of the legacy path: a job
            # unbound mid-flight must not re-create a retired coalescer
            # (the per-historical-job leak) — and its riders get the
            # prompt not-serving error
            co = self._coalescer(job_name, operator)
        except RuntimeError as err:
            for e in entries:
                e.error = err
                e.done.set()
            self._pool._absorb(len(entries), 1, ())
            return
        ad = self._replicas.get((job_name, operator))
        # chunk at max_batch: bounds one device batch's gather tier and
        # keeps a burst from stretching every rider's latency behind
        # one giant flush (the legacy coalescer's exact discipline)
        for i in range(0, len(entries), self.max_batch):
            chunk = entries[i:i + self.max_batch]
            try:
                if ad is None:
                    raise RuntimeError(
                        f"job {job_name!r} is not serving (not running, "
                        "or finished)")
                gen = ad.generation()
                with flight.span("serving.lookup", job=job_name,
                                 batch=gen):
                    results, gen = ad.lookup_batch(
                        [e.key for e in chunk])
                if len(results) != len(chunk):
                    raise RuntimeError(
                        f"replica lookup returned {len(results)} "
                        f"results for {len(chunk)} keys")
            except BaseException as err:  # noqa: BLE001
                for e in chunk:
                    e.error = err
                    e.done.set()
                co._record(n_lookups=len(chunk), batches=1)
                continue
            # fill the cache only when the plane has not sealed a newer
            # generation since this chunk resolved: put() guards
            # downgrades of EXISTING entries, but an ABSENT key would
            # insert the stale value — and with presence-implies-
            # validity probes, a key that then stops changing (so no
            # future prime touches it) would serve it forever
            if ad.generation() == gen:
                # ONE batched fill (a single GIL-released C call on the
                # native plane) instead of a locked put per key
                self.hot_cache.put_many(
                    job_name, operator, [e.key_id for e in chunk],
                    gen, results)
            for e, r in zip(chunk, results):
                e.result = r
                e.done.set()
            co._record(n_lookups=len(chunk), batches=1,
                       lat=((time.perf_counter() - t0) * 1e3,))

    # ------------------------------------------------------------- lookups

    def lookup(self, job_name: str, operator: str, key,
               namespace=None):
        """One point lookup. Replica-armed operators probe the hot-row
        cache, then ride the shard-queue worker path; others ride the
        legacy coalescer's forming batch."""
        ad = self._adapter(job_name, operator)
        if ad is None:
            return self._coalescer(job_name, operator).lookup(
                key, namespace, timeout_s=self.timeout_s)
        t0 = time.perf_counter()
        co = self._coalescer(job_name, operator)
        hit, kid, gen, val = self._cache_probe(job_name, operator, ad,
                                               key, co)
        if hit:
            co._record(lat=((time.perf_counter() - t0) * 1e3,))
            return self._filter_ns(val, namespace)
        entry = self._enqueue_miss(job_name, operator, ad, key, kid,
                                   namespace)
        if not entry.done.wait(self.timeout_s):
            raise TimeoutError("queryable-state lookup not served")
        co._record(lat=((time.perf_counter() - t0) * 1e3,))
        if entry.error is not None:
            raise entry.error
        return self._filter_ns(entry.result, namespace)

    def lookup_batch(self, job_name: str, operator: str, keys,
                     namespace=None) -> List[Any]:
        """An explicit batch. Replica path: per-key cache probes, the
        misses coalesce onto the shard queues (riding other clients'
        batches); legacy path: one request batch on the control queue."""
        ad = self._adapter(job_name, operator)
        if ad is None:
            co = self._coalescer(job_name, operator)
            t0 = time.perf_counter()
            out = self._flush(job_name, operator, list(keys), namespace)
            co.note_batch(len(out), (time.perf_counter() - t0) * 1e3)
            return out
        from flink_tpu.observe import flight_recorder as flight
        from flink_tpu.state.keygroups import hash_keys_to_i64

        t0 = time.perf_counter()
        co = self._coalescer(job_name, operator)
        keys = list(keys)
        # BATCH-FIRST: one vectorized hash, then ONE probe call for
        # the whole key batch — a single GIL-released C call on the
        # native plane (one locked pass on the Python fallback) —
        # before ANY per-key Python work; only misses compose below
        kids = hash_keys_to_i64(np.asarray(keys))
        out: List[Any] = [None] * len(keys)
        miss_idx: List[Tuple[int, int]] = []
        gen = ad.generation()
        if self._probe_faulted(job_name, operator):
            miss_idx = [(i, int(k)) for i, k in enumerate(kids)]
            hits = 0
        else:
            hits = self.hot_cache.get_many(job_name, operator, kids,
                                           gen, out, miss_idx,
                                           exact=False)
        if namespace is not None:
            for i in range(len(out)):
                if out[i] is not None:
                    out[i] = self._filter_ns(out[i], namespace)
        pending = [(i, self._enqueue_miss(job_name, operator, ad,
                                          keys[i], kid, namespace))
                   for i, kid in miss_idx]
        if hits:
            # one locked record + one sampled instant for the whole
            # batch's hits — per-key lock traffic at cache-hit QPS
            # would itself be the bottleneck
            co._record(n_lookups=hits)
            self._hit_sample += hits
            if self._hit_sample % 256 < hits:
                flight.instant("serving.cache_hit", job=job_name,
                               batch=gen)
        err: Optional[BaseException] = None
        # ONE deadline for the whole request (the legacy batch path's
        # bound): a fresh full timeout per rider would let a degraded
        # worker stretch one call to n_misses x timeout_s
        deadline = t0 + self.timeout_s
        for i, entry in pending:
            if not entry.done.wait(
                    max(deadline - time.perf_counter(), 0.0)):
                raise TimeoutError("queryable-state lookup not served")
            if entry.error is not None:
                err = entry.error
            else:
                out[i] = self._filter_ns(entry.result, namespace)
        co._record(lat=((time.perf_counter() - t0) * 1e3,))
        if err is not None:
            raise err
        return out

    def lookup_batch_packed(self, job_name: str, operator: str,
                            keys) -> PackedLookupResult:
        """The NATIVE SERVING FAST PATH: one vectorized key hash, ONE
        GIL-released C probe for the whole batch, and the hits never
        leave the packed buffers — :class:`PackedLookupResult`
        materializes a dict only for keys the caller actually reads
        (a frontend serializing from the packed form pays the
        interpreter nothing per hit). Misses coalesce onto the shard
        worker queues exactly like :meth:`lookup_batch`. Falls back to
        the (bit-identical) dict path when the operator has no replica
        adapter or no native table yet."""
        ad = self._adapter(job_name, operator)
        get_packed = getattr(self.hot_cache, "get_many_packed", None)
        if ad is None or get_packed is None:
            return PackedLookupResult.from_dicts(
                self.lookup_batch(job_name, operator, keys))
        from flink_tpu.observe import flight_recorder as flight
        from flink_tpu.state.keygroups import hash_keys_to_i64

        t0 = time.perf_counter()
        co = self._coalescer(job_name, operator)
        keys = list(keys)
        n = len(keys)
        kids = hash_keys_to_i64(np.asarray(keys))
        out: List[Any] = [None] * n
        miss_idx: List[Tuple[int, int]] = []
        gen = ad.generation()
        if self._probe_faulted(job_name, operator):
            probe = None
            hits = 0
            miss_idx = [(i, int(k)) for i, k in enumerate(kids)]
        else:
            hits, probe = get_packed(job_name, operator, kids, gen,
                                     out, miss_idx, exact=False)
            if probe is None and not miss_idx:
                # no native table for the op yet (first touches, or a
                # non-packable shape): the dict path IS the fast path
                return PackedLookupResult.from_dicts(
                    self.lookup_batch(job_name, operator, keys))
        # overflow-store hits (rare: non-packable ops) were
        # materialized into `out` by the probe — carry them as
        # overrides (their packed hit flag is 0)
        overrides: Dict[int, Any] = {
            i: v for i, v in enumerate(out) if v is not None}
        pending = [(i, self._enqueue_miss(job_name, operator, ad,
                                          keys[i], kid, None))
                   for i, kid in miss_idx]
        if hits:
            co._record(n_lookups=hits)
            self._hit_sample += hits
            if self._hit_sample % 256 < hits:
                flight.instant("serving.cache_hit", job=job_name,
                               batch=gen)
        err: Optional[BaseException] = None
        deadline = t0 + self.timeout_s
        for i, entry in pending:
            if not entry.done.wait(
                    max(deadline - time.perf_counter(), 0.0)):
                raise TimeoutError("queryable-state lookup not served")
            if entry.error is not None:
                err = entry.error
            else:
                overrides[i] = entry.result
        co._record(lat=((time.perf_counter() - t0) * 1e3,))
        if err is not None:
            raise err
        return PackedLookupResult(n, probe, overrides)

    # ---------------------------------------------------------------- metrics

    def lookups_total(self) -> int:
        """One counter, one walk — what the per-scrape gauge reads."""
        return self._pool.lookups_total()

    def lookup_batches_total(self) -> int:
        return self._pool.batches_total()

    def lookup_p99_ms(self) -> float:
        """p99 over every coalescer's latency reservoir (pays one sort)."""
        return reservoir_p99_ms(self._pool.latencies())

    def replica_staleness_ms(self) -> float:
        """Worst-case age of any bound replica's sealed generation (ms
        since its boundary publish) — the serving SLO's staleness arm.
        Snapshots the adapter list first: sampler/scrape threads read
        while bind/unbind mutate the dict (iterating the live dict
        raises mid-mutation and would kill the sampler silently)."""
        return max((ad.plane.staleness_ms()
                    for ad in list(self._replicas.values())),
                   default=0.0)

    def hot_row_hit_rate(self) -> float:
        return self.hot_cache.hit_rate()

    def replica_generations(self) -> int:
        """Total sealed generations across bound replicas (the smoke's
        publish-vacuity gate reads this; snapshot — see staleness)."""
        return sum(ad.plane.generation()
                   for ad in list(self._replicas.values()))

    def replica_counters(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for ad in list(self._replicas.values()):
            for k, v in ad.plane.counters().items():
                out[k] = out.get(k, 0) + v
        return out

    def frontend_stats(self) -> Dict[str, float]:
        """Aggregate per-frontend shm counters (probes / hits / torn
        retries / miss crossings), summed across frontend slots and
        tables straight off the shared arena headers — the frontends
        write them lock-free in their own processes; the owner reads
        them here with no IPC. Empty when the multi-process tier is
        not armed (no ``shm_dir``)."""
        if self.shm_dir is None:
            return {}
        fe_stats = getattr(self.hot_cache, "fe_stats", None)
        if fe_stats is None:
            return {}
        rows = fe_stats()
        return {f"frontend_{k}": float(sum(r[k] for r in rows))
                for k in (rows[0].keys() if rows else ())}

    def metrics(self) -> Dict[str, float]:
        out = self._pool.stats()
        out.update(self.hot_cache.stats())
        out.update(self.frontend_stats())
        out["replica_staleness_ms"] = self.replica_staleness_ms()
        out["replica_generations"] = float(self.replica_generations())
        return out
