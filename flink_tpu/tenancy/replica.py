"""Read-replica serving plane: double-buffered boundary-published state.

The queryable-state cost model before this module: every lookup batch
paid a fresh gather + ``device_get`` against the LIVE state plane,
serialized behind the owning job's batch boundaries (the control-queue
detour — reads had to wait for the single-owner task loop because the
live plane mutates under them). At serving QPS that serialization IS
the latency: BENCHMARKS.md recorded p99 153 ms.

This module decouples readers from ingest with a device-resident READ
REPLICA of the hot slot rows:

- **Publish at boundaries.** At every fire/watermark boundary the
  owning engine publishes a bounded DELTA of rows changed since the
  last publish into the replica plane — one compiled device-to-device
  copy program (no D2H), riding the same sticky-bucket shape
  discipline as the engines' own steps and cached in the shared
  :data:`~flink_tpu.tenancy.program_cache.PROGRAM_CACHE` (family
  ``replica-pub``), so multi-tenant zero-recompile holds.
- **Double buffering / snapshot isolation.** A publish builds the next
  generation FUNCTIONALLY from the sealed one (``rep.at[slots].set``
  without donation — the sealed arrays are never written) and seals it
  with one atomic reference swap. Readers always resolve against the
  generation they grabbed: they see exactly the state at that
  generation's boundary, never a torn mid-batch view, and never
  contend with ingest.
- **Index without copies in steady state.** Each generation carries a
  host index ``key_id -> {namespace -> (shard, slot, extra)}``. Value
  -only publishes (the steady state of a stable key set) reuse the
  sealed index object untouched; structural publishes (new rows,
  frees, residency flips) copy the outer dict and copy-on-write only
  the touched keys' inner dicts.
- **Cold rows stay serveable.** A row the engine evicted serves from
  the page tier *through the replica path*: its index entry flips to
  ``slot == -1`` at the next publish and lookups detour those keys to
  the owning task loop (pages are single-owner host state), counted in
  ``cold_rows_served``. A row's page value cannot change while it is
  cold, so the detour still answers with boundary state.

The engines drive this through ``MeshSpillSupport.arm_replica`` /
``_publish_replica`` (parallel/sharded_windower.py): the publish delta
is derived by comparing the engine's per-shard slot metadata against
the replica's shadow of it (``rep_key/rep_ns/rep_used``), plus a
``rep_dirty`` bitmap set at the scatter sites — eviction, reload,
fires and slot reuse all surface as metadata differences, so the
delta needs no per-site bookkeeping beyond the scatters.

reference: the L6/L4 queryable-state survey (PAPER.md) — serve reads
off the keyed backend, decoupled from the task thread; the shape is
the read-replica + staleness-bounded cache every feature store builds.
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.chaos import injection as chaos
from flink_tpu.observe import flight_recorder as flight
from flink_tpu.ops.segment_ops import sticky_bucket
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE

#: index entry slot value for a row serving from the page tier
COLD_SLOT = -1


def build_replica_steps(mesh, dtypes: Tuple[str, ...]):
    """(publish_step, gather_step) for a replica plane of per-leaf
    ``[P, capacity]`` arrays with the given dtype layout. Cached in the
    shared PROGRAM_CACHE per (device ids, dtypes) — keyed on WHAT they
    compute, never on which job's replica runs them (the tenancy
    zero-recompile contract, same as build_mesh_steps)."""
    cache_key = (tuple(d.id for d in mesh.devices.flat), tuple(dtypes))
    return PROGRAM_CACHE.get_or_build(
        "replica-pub", cache_key, lambda: _build_replica_steps(mesh))


def _build_replica_steps(mesh):
    import jax
    import jax.numpy as jnp

    from flink_tpu.parallel.mesh import KEY_AXIS, shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    def publish_step(rep, live, slots):
        # rep/live: per-leaf [P, cap] sharded; slots: [P, D]. NO
        # donation: the input rep arrays ARE the sealed generation
        # readers are resolving against — the output is a fresh buffer
        # set (the double buffer). Padded lanes carry slot 0: copying
        # live slot 0 over rep slot 0 is safe because any slot whose
        # value changed since the last publish is in the delta — an
        # unchanged slot's copy is a no-op by value.
        n = len(rep)

        def local(*args):
            rep_l = args[:n]
            live_l = args[n:2 * n]
            sl = args[2 * n][0]
            return tuple(r.at[0, sl].set(a[0][sl])
                         for r, a in zip(rep_l, live_l))

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (2 * n + 1),
            out_specs=(P(KEY_AXIS),) * n,
        )(*rep, *live, slots)

    @jax.jit
    def gather_step(rep, slots):
        # slots: [P, G] -> per-leaf [P, G] replica values (the serving
        # read program — identical shape contract to the engines'
        # gather_step, over the sealed plane instead of the live one)
        n = len(rep)

        def local(*args):
            rep_l = args[:n]
            sl = args[n][0]
            return tuple(r[0][sl][None] for r in rep_l)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n + 1),
            out_specs=(P(KEY_AXIS),) * n,
        )(*rep, slots)

    return publish_step, gather_step


class ReplicaGeneration:
    """One sealed, immutable snapshot view. ``accs`` are the replica's
    device arrays (never written after seal), ``index`` maps
    ``key_id -> {namespace -> (shard, slot, extra)}`` (``slot ==
    COLD_SLOT`` serves from the page tier), ``extra`` is the owning
    adapter's per-row payload (session end; join ``(ts, host cols)``)."""

    __slots__ = ("gen", "boundary_wm", "published_at", "accs", "index",
                 "num_shards")

    def __init__(self, gen: int, boundary_wm: int, published_at: float,
                 accs, index: Dict[int, Dict[int, tuple]],
                 num_shards: int) -> None:
        self.gen = gen
        self.boundary_wm = boundary_wm
        self.published_at = published_at
        self.accs = accs
        self.index = index
        self.num_shards = num_shards


class ReplicaPlane:
    """The double-buffered replica one engine publishes into.

    Single-writer: every mutating method runs on the engine's task
    thread (single-owner discipline). Readers (serving worker threads)
    only ever touch :attr:`sealed` — an atomic reference to an
    immutable :class:`ReplicaGeneration` — and the compiled gather
    program, both safe concurrently with a publish in progress."""

    def __init__(self, mesh, leaves, capacity: int) -> None:
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from flink_tpu.parallel.mesh import KEY_AXIS

        self.mesh = mesh
        self.P = int(mesh.devices.size)
        self.capacity = int(capacity)
        self.leaves = tuple(leaves)
        self._dtypes = tuple(np.dtype(l.dtype).name for l in self.leaves)
        self._sharding = NamedSharding(mesh, P(KEY_AXIS))
        self._publish_step, self._gather_step = build_replica_steps(
            mesh, self._dtypes)
        # the engine-metadata shadow the publish delta diffs against
        self.rep_key = np.zeros((self.P, self.capacity), dtype=np.int64)
        self.rep_ns = np.zeros((self.P, self.capacity), dtype=np.int64)
        self.rep_used = np.zeros((self.P, self.capacity), dtype=bool)
        #: rows whose VALUE changed since the last publish (set by the
        #: engines' scatter sites; residency/identity changes are
        #: derived from the metadata diff instead)
        self.rep_dirty = np.zeros((self.P, self.capacity), dtype=bool)
        self._accs = self._identity_accs()
        self._pub_bucket = 0
        self._gather_bucket = 0
        self._gen = 0
        #: the sealed generation readers resolve against (atomic swap)
        self.sealed: Optional[ReplicaGeneration] = None
        #: set by rebuild(): the next publish must not carry the sealed
        #: index forward (and must seal even if the state is empty)
        self._index_reset = False
        #: minimum seconds between publishes (0 = every boundary).
        #: Batching boundaries under one publish bounds BOTH the
        #: per-boundary metadata-diff cost and the hot-row cache's
        #: invalidation rate — staleness stays bounded by the interval.
        self.min_interval_s = 0.0
        #: set by the serving adapter (attach_cache): called on the
        #: TASK thread after each seal with (generation, per_shard,
        #: host_leaves) — the publish HARVEST: the delta rows' values
        #: come host-side in ONE batched device_get so the hot-row
        #: cache re-primes without any lookup ever touching the device
        self.on_publish = None
        #: called when the plane rebuilds (restore/reshard/loss): the
        #: cache must drop this operator's entries — a rolled-back
        #: value would otherwise serve stale forever
        self.on_rebuild = None
        # ---- counters (read by serving gauges / the smoke's gates)
        self.publishes = 0
        self.rows_published = 0
        self.rows_freed = 0
        self.cold_flips = 0
        self.lookups_served = 0
        self.cold_rows_served = 0

    def _identity_accs(self):
        import jax
        import jax.numpy as jnp

        return tuple(
            jax.device_put(
                jnp.full((self.P, self.capacity), l.identity,
                         dtype=l.dtype),
                self._sharding)
            for l in self.leaves)

    def warm_tiers(self) -> None:
        """Compile the publish/gather programs at EVERY pow2 block tier
        up to the plane capacity — deterministic zero-recompile under
        the sentinel: the tiers a live run's deltas/miss batches walk
        are data-dependent, so a measured phase could otherwise hit a
        tier the warm phase never saw. Shapes compiled here are cached
        per (program fn, shape) by jax itself, and the fns are shared
        through the PROGRAM_CACHE, so a SECOND plane on the same mesh/
        dtype layout pays nothing (multi-tenant zero-recompile)."""
        import jax

        from flink_tpu.ops.segment_ops import pad_bucket_size

        top = pad_bucket_size(self.capacity, minimum=64)
        D = 64
        while True:
            block = jax.device_put(
                np.zeros((self.P, D), dtype=np.int32), self._sharding)
            # discard outputs: this is shape warmup, not a publish
            self._publish_step(self._accs, self._accs, block)
            self._gather_step(self._accs, block)
            if D >= top:
                break
            D <<= 1

    # ----------------------------------------------------------- publishing

    def needs_rebuild(self, P: int, capacity: int) -> bool:
        return P != self.P or capacity != self.capacity

    def rebuild(self, mesh, capacity: int) -> None:
        """Reset the plane over a (possibly) new mesh/capacity — after
        restore, reshard, shard loss or index growth. The next publish
        diffs against an empty shadow, i.e. republishes every resident
        row (the bounded-full publish); the generation counter keeps
        advancing so caches tagged with older generations invalidate."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from flink_tpu.parallel.mesh import KEY_AXIS

        self.mesh = mesh
        self.P = int(mesh.devices.size)
        self.capacity = int(capacity)
        self._sharding = NamedSharding(mesh, P(KEY_AXIS))
        self._publish_step, self._gather_step = build_replica_steps(
            mesh, self._dtypes)
        self.rep_key = np.zeros((self.P, self.capacity), dtype=np.int64)
        self.rep_ns = np.zeros((self.P, self.capacity), dtype=np.int64)
        self.rep_used = np.zeros((self.P, self.capacity), dtype=bool)
        self.rep_dirty = np.zeros((self.P, self.capacity), dtype=bool)
        self._accs = self._identity_accs()
        self._pub_bucket = 0
        self._gather_bucket = 0
        # readers keep serving the last sealed generation of the OLD
        # plane until the first publish on the new one seals; that
        # publish must build its index FROM SCRATCH — carrying the
        # sealed index forward would keep entries for keys that do not
        # exist in the rebuilt (restored) state, and their stale slots
        # could address OTHER keys' rows after the republish
        self._index_reset = True
        if self.on_rebuild is not None:
            self.on_rebuild()

    def mark_dirty(self, p: int, slots) -> None:
        self.rep_dirty[p, slots] = True

    def publish(self, live_accs, per_shard: Dict[int, dict],
                boundary_wm: int) -> bool:
        """Build + seal the next generation. ``per_shard[p]`` carries::

            up_slots   int32 slots to (re)publish on shard p
            up_keys    their key ids
            up_ns      their namespaces
            up_extra   per-row adapter payloads (or None)
            cold       [(key, ns, extra)] flipped to (or inserted as)
                       page-tier serving; extra None keeps the
                       existing entry's payload
            freed      [(key, ns)] dropped from the index

        Returns True when a new generation was sealed (False = no
        changes; the sealed boundary watermark still advances, so
        staleness gauges and caches read "up to date")."""
        import jax

        structural = self._index_reset
        d_max = 0
        for p, d in per_shard.items():
            d_max = max(d_max, len(d["up_slots"]))
            if d["cold"] or d["freed"] or d.get("fresh"):
                structural = True
            elif d["up_extra"] is not None and len(d["up_slots"]):
                # extras travel WITH values (a session's END extends as
                # it absorbs) — a value-only publish must still rewrite
                # those entries, which needs the COW index
                structural = True
        if d_max == 0 and not structural:
            s = self.sealed
            if s is not None:
                # metadata-only advance: same state, newer boundary —
                # mutating these two scalars on the sealed object is
                # benign (readers never derive row addressing from them)
                s.boundary_wm = boundary_wm
                s.published_at = time.monotonic()
            return False
        chaos.fault_point("serving.replica_publish", generation=self._gen + 1)
        # ---- device delta: one program, no D2H on the publish itself
        harvest = None
        if d_max:
            D = sticky_bucket(d_max, self._pub_bucket, minimum=64)
            self._pub_bucket = D
            block = np.zeros((self.P, D), dtype=np.int32)
            for p, d in per_shard.items():
                n = len(d["up_slots"])
                if n:
                    block[p, :n] = d["up_slots"]
            dev_block = jax.device_put(block, self._sharding)
            self._accs = self._publish_step(self._accs, live_accs,
                                            dev_block)
            if self.on_publish is not None:
                # the publish HARVEST: the delta rows' values, ONE
                # gather + ONE device_get (the delta-checkpoint cost
                # model) — the cache prime below is what lets hot-key
                # lookups skip the device entirely
                harvest = jax.device_get(
                    list(self._gather_step(self._accs, dev_block)))
        # ---- host index (COW: outer copy only on structural change;
        # a rebuild starts from {} — see rebuild())
        sealed = self.sealed
        index = ({} if self._index_reset
                 else sealed.index if sealed is not None else {})
        new_index = dict(index) if structural or sealed is None else index
        touched: Dict[int, Dict[int, tuple]] = {}

        def inner(key: int) -> Dict[int, tuple]:
            d = touched.get(key)
            if d is None:
                d = dict(new_index.get(key, ()))
                touched[key] = d
                new_index[key] = d
            return d

        rows = 0
        for p, d in per_shard.items():
            keys, nss = d["up_keys"], d["up_ns"]
            extra = d["up_extra"]
            slots = d["up_slots"]
            n = len(slots)
            rows += n
            if structural or sealed is None:
                for j in range(n):
                    inner(int(keys[j]))[int(nss[j])] = (
                        p, int(slots[j]),
                        extra[j] if extra is not None else None)
            else:
                # value-only publish: every pair already has an entry at
                # the same (shard, slot) — the index object is reused
                # untouched and readers of the new generation see the
                # same addressing over the new arrays
                pass
            for key, ns, c_extra in d["cold"]:
                ki = inner(int(key))
                ent = ki.get(int(ns))
                if ent is not None:
                    ki[int(ns)] = (
                        ent[0], COLD_SLOT,
                        ent[2] if c_extra is None else c_extra)
                else:
                    # a row created AND evicted within one publish
                    # interval was never resident at a boundary — it
                    # enters the index cold directly (its page value
                    # IS its boundary value)
                    ki[int(ns)] = (p, COLD_SLOT, c_extra)
                self.cold_flips += 1
            for key, ns in d["freed"]:
                ki = inner(int(key))
                ki.pop(int(ns), None)
                if not ki:
                    new_index.pop(int(key), None)
                self.rows_freed += 1
        self._gen += 1
        self.rows_published += rows
        self.publishes += 1
        # the pre-publish index: the adapters' prime needs the OLD
        # result addressing (a session's end MOVES as it absorbs — the
        # stale entry is found here). A rebuild publish starts from
        # nothing: its caches were invalidated, nothing maps back.
        prev_index = {} if self._index_reset else index
        self._index_reset = False
        self.sealed = ReplicaGeneration(
            self._gen, boundary_wm, time.monotonic(), self._accs,
            new_index, self.P)
        if self.on_publish is not None:
            # AFTER the seal: a prime tags entries with the new
            # generation, so it must not run while probes still
            # resolve the old one (they would read fresh tags as
            # future and miss)
            self.on_publish(self._gen, per_shard, harvest, prev_index)
        return True

    # -------------------------------------------------------------- reading

    def generation(self) -> int:
        s = self.sealed
        return s.gen if s is not None else 0

    def staleness_ms(self) -> float:
        s = self.sealed
        if s is None:
            return 0.0
        return (time.monotonic() - s.published_at) * 1e3

    def gather_rows(self, gen: ReplicaGeneration,
                    rows: List[Tuple[int, int]]) -> List[tuple]:
        """Read resident replica rows ``(shard, slot)`` back as per-row
        leaf tuples: ONE gather program + ONE ``jax.device_get`` for the
        whole batch (the serving cost model), against the sealed
        generation's immutable arrays — safe from any thread."""
        import jax

        if not rows:
            return []
        g_max = 0
        lanes: Dict[int, List[int]] = {}
        order: List[Tuple[int, int]] = []  # (shard, lane)
        for p, s in rows:
            lane = lanes.setdefault(p, [])
            order.append((p, len(lane)))
            lane.append(s)
            g_max = max(g_max, len(lane))
        G = sticky_bucket(g_max, self._gather_bucket, minimum=64)
        self._gather_bucket = G
        block = np.zeros((self.P, G), dtype=np.int32)
        for p, lane in lanes.items():
            block[p, :len(lane)] = lane
        gathered = self._gather_step(
            gen.accs, jax.device_put(block, self._sharding))
        host = jax.device_get(list(gathered))  # ONE batched D2H
        return [tuple(h[p][j] for h in host) for p, j in order]

    def counters(self) -> Dict[str, int]:
        return {
            "publishes": int(self.publishes),
            "rows_published": int(self.rows_published),
            "rows_freed": int(self.rows_freed),
            "cold_flips": int(self.cold_flips),
            "lookups_served": int(self.lookups_served),
            "cold_rows_served": int(self.cold_rows_served),
        }


# --------------------------------------------------------------- adapters


class ReplicaAdapter:
    """The serving plane's view of one operator's replica: everything a
    worker thread may touch without the task loop. Subclasses compose
    engine-specific results (windows/sessions) from gathered rows.

    ``cold_fetch(key_ids)`` — posts ONE live query batch for keys whose
    entries are cold (page-tier state is single-owner host state, so
    the read detours through the owning job's control queue exactly
    like the legacy path; bound by the session cluster)."""

    def __init__(self, plane: ReplicaPlane, agg) -> None:
        self.plane = plane
        self.agg = agg
        self.cold_fetch = None  # bound by ServingPlane.bind_replica
        self._cache = None
        self._cache_job = None
        self._cache_op = None

    # -- publish-harvest cache feed

    def attach_cache(self, cache, job: str, operator: str) -> None:
        """Wire the hot-row cache into the publish harvest: every
        boundary publish folds its delta into the cached entries it
        touches (on the task thread — ONE batched D2H per publish), so
        a hot key's lookups never touch the device between misses."""
        self._cache = cache
        self._cache_job = job
        self._cache_op = operator
        self.plane.on_publish = self._on_publish
        self.plane.on_rebuild = self._on_rebuild

    def _on_rebuild(self) -> None:
        if self._cache is not None:
            self._cache.invalidate_op(self._cache_job, self._cache_op)

    def prime_value_ns(self, ns: int, extra):
        """Result-dict key for an upserted row, or None when the
        cached composition cannot be updated incrementally (the key's
        entry is dropped and the next lookup re-resolves)."""
        return None

    def prime_free_ns(self, ns: int, extra):
        """Result-dict key removed by a freed row (``extra`` is the
        row's payload in the PRE-publish index, when it had one), or
        None to drop the key's entry instead."""
        return None

    def _prime_rows(self, keys_f: np.ndarray, ns_f: np.ndarray,
                    extra_f: np.ndarray, prev_index):
        """Map the delta rows to cache updates: ``(rns, valid,
        removals, kill)`` where ``rns[j]`` is row j's RESULT namespace
        (valid[j] False = no incremental update), ``removals`` is a
        list of ``(kid, result_ns)`` stale entries to delete in the
        SAME batched prime, and ``kill`` is kids whose cached entry
        drops outright. Default: per-row :meth:`prime_value_ns`."""
        n = len(keys_f)
        rns = np.zeros(n, dtype=np.int64)
        valid = np.zeros(n, dtype=bool)
        kill: set = set()
        for j in range(n):
            r = self.prime_value_ns(int(ns_f[j]), extra_f[j])
            if r is None:
                kill.add(int(keys_f[j]))
            else:
                rns[j] = int(r)
                valid[j] = True
        return rns, valid, [], kill

    def _on_publish(self, gen: int, per_shard: Dict[int, dict],
                    harvest, prev_index) -> None:
        """The publish-harvest cache feed, batch-first: flatten the
        delta rows once, finish the value columns ONCE, map rows to
        result namespaces, and fold the whole boundary into the cache
        as ONE :class:`~flink_tpu.tenancy.hot_cache.PrimeDelta` (one
        GIL-released C call on the native plane; one locked pass on
        the Python fallback) — the publish used to pay one ``put()``
        per touched key on the task thread, inside the fire-deadline
        budget."""
        cache = self._cache
        if cache is None:
            return
        from flink_tpu.tenancy.hot_cache import PrimeDelta

        job, op = self._cache_job, self._cache_op
        leaves = self.agg.leaves
        # flatten the delta rows across shards, finish ONCE
        keys_l, ns_l, extra_l, val_cols = [], [], [], None
        if harvest is not None:
            chunks: List[List[np.ndarray]] = [[] for _ in leaves]
            for p, d in per_shard.items():
                n = len(d["up_slots"])
                if not n:
                    continue
                keys_l.append(d["up_keys"])
                ns_l.append(d["up_ns"])
                extra_l.append(
                    d["up_extra"] if d["up_extra"] is not None
                    else np.zeros(n, dtype=np.int64))
                for i in range(len(leaves)):
                    chunks[i].append(harvest[i][p][:n])
            if keys_l:
                finished = self.agg.finish(tuple(
                    np.concatenate(chunks[i]).astype(l.dtype,
                                                     copy=False)
                    for i, l in enumerate(leaves)))
                val_cols = [(name, np.asarray(col))
                            for name, col in finished.items()]
        if keys_l:
            keys_f = np.concatenate(keys_l)
            ns_f = np.concatenate(ns_l)
            extra_f = np.concatenate(extra_l)
        else:
            keys_f = ns_f = extra_f = np.zeros(0, dtype=np.int64)
        removals: List[Tuple[int, int]] = []
        kill: set = set()
        if val_cols is not None:
            rns, valid, removals, kill = self._prime_rows(
                keys_f, ns_f, extra_f, prev_index)
        else:
            rns = np.zeros(0, dtype=np.int64)
            valid = np.zeros(len(keys_f), dtype=bool)
        for d in per_shard.values():
            for key, ns in d["freed"]:
                prev = prev_index.get(int(key), {}).get(int(ns))
                r = self.prime_free_ns(
                    int(ns), prev[2] if prev is not None else None)
                if r is None:
                    kill.add(int(key))
                else:
                    removals.append((int(key), int(r)))
        # ---- group per kid into the flat delta
        if valid.any():
            u_kids = keys_f[valid]
            u_rns = rns[valid] if len(rns) == len(keys_f) else rns
            order = np.argsort(u_kids, kind="stable")
            u_kids = u_kids[order]
            u_rns = u_rns[order]
            u_cols = [(name, col[valid][order])
                      for name, col in val_cols]
            uniq, starts = np.unique(u_kids, return_index=True)
            ends = np.append(starts[1:], len(u_kids))
        else:
            u_rns = np.zeros(0, dtype=np.int64)
            u_cols = [(name, col[:0]) for name, col in (val_cols or [])]
            uniq = np.zeros(0, dtype=np.int64)
            starts = ends = np.zeros(0, dtype=np.int64)
        upd_of = {int(uniq[i]): (int(starts[i]), int(ends[i]))
                  for i in range(len(uniq))}
        rem_of: Dict[int, List[int]] = {}
        for kid, r in removals:
            if kid not in kill:
                rem_of.setdefault(kid, []).append(r)
        all_kids = sorted(set(upd_of) - kill | set(rem_of) | kill)
        if not all_kids:
            return
        index = self.plane.sealed.index if self.plane.sealed else {}
        keys_a = np.asarray(all_kids, dtype=np.int64)
        uoff = np.zeros(len(all_kids) + 1, dtype=np.int64)
        u_take: List[int] = []
        roff = np.zeros(len(all_kids) + 1, dtype=np.int64)
        r_ns: List[int] = []
        flags = np.zeros(len(all_kids), dtype=np.uint8)
        for i, kid in enumerate(all_kids):
            if kid in kill:
                flags[i] = 2
                uoff[i + 1] = uoff[i]
                roff[i + 1] = roff[i]
                continue
            lo_hi = upd_of.get(kid)
            if lo_hi is not None:
                u_take.extend(range(lo_hi[0], lo_hi[1]))
                # the delta covered EVERY published row of the key ->
                # the update IS its complete composed state, safe to
                # INSERT: first-touch lookups of hot keys never touch
                # the device
                if lo_hi[1] - lo_hi[0] == len(index.get(kid, ())):
                    flags[i] |= 1
            uoff[i + 1] = uoff[i] + (
                lo_hi[1] - lo_hi[0] if lo_hi is not None else 0)
            rem = rem_of.get(kid, ())
            r_ns.extend(rem)
            roff[i + 1] = roff[i] + len(rem)
        take = np.asarray(u_take, dtype=np.int64)
        cache.prime_batch(job, op, gen, PrimeDelta(
            keys=keys_a, uoff=uoff,
            u_ns=u_rns[take] if len(take) else u_rns[:0],
            u_cols=[(name, col[take] if len(take) else col[:0])
                    for name, col in u_cols],
            roff=roff,
            r_ns=np.asarray(r_ns, dtype=np.int64),
            flags=flags))

    # -- key plumbing (worker threads)

    def key_id(self, key) -> int:
        if isinstance(key, (int, np.integer)):
            return int(key)  # integer keys ARE their identity
        from flink_tpu.state.keygroups import hash_keys_to_i64

        return int(hash_keys_to_i64(np.asarray([key]))[0])

    def shard_of(self, key_id: int) -> int:
        gen = self.plane.sealed
        n = gen.num_shards if gen is not None else self.plane.P
        return key_id % n if n else 0

    def generation(self) -> int:
        return self.plane.generation()

    def ready(self) -> bool:
        return self.plane.sealed is not None

    # -- the lookup itself

    def lookup_batch(self, keys: List[Any]) -> Tuple[List[dict], int]:
        """One result dict per key (the operator's query_state_batch
        shape), resolved against ONE sealed generation; returns
        ``(results, generation)`` so the hot-row cache can tag them."""
        from flink_tpu.state.keygroups import hash_keys_to_i64

        gen = self.plane.sealed
        if gen is None:
            raise RuntimeError("replica not published yet")
        key_ids = hash_keys_to_i64(np.asarray(keys))
        n = len(key_ids)
        rows: List[Tuple[int, int]] = []
        row_of: List[List[Tuple[int, int, Any]]] = [[] for _ in range(n)]
        cold_of: List[List[Tuple[int, Any]]] = [[] for _ in range(n)]
        for r in range(n):
            entries = gen.index.get(int(key_ids[r]))
            if not entries:
                continue
            for ns, (p, slot, extra) in entries.items():
                if slot == COLD_SLOT:
                    cold_of[r].append((int(ns), extra))
                else:
                    row_of[r].append((int(ns), len(rows), extra))
                    rows.append((p, slot))
        vals = self.plane.gather_rows(gen, rows)
        cold_vals: Dict[int, dict] = {}
        cold_rows = [r for r in range(n) if cold_of[r]]
        if cold_rows:
            if self.cold_fetch is None:
                raise RuntimeError(
                    "replica has cold rows but no cold_fetch is bound")
            fetched = self.cold_fetch([keys[r] for r in cold_rows])
            for r, res in zip(cold_rows, fetched):
                cold_vals[r] = res
                self.plane.cold_rows_served += len(cold_of[r])
        out = self.compose_all(row_of, vals, cold_of, cold_vals)
        self.plane.lookups_served += n
        return out, gen.gen

    def compose_all(self, row_of, vals, cold_of,
                    cold_vals: Dict[int, dict]) -> List[dict]:
        """Compose every requested key's result. Default: one
        :meth:`compose` call per key; adapters override with a
        vectorized pass where the window/namespace mapping allows."""
        return [self.compose(row_of[r], vals, cold_of[r],
                             cold_vals.get(r))
                for r in range(len(row_of))]

    def compose(self, entries, vals, cold_entries, cold_result) -> dict:
        raise NotImplementedError


class SessionReplicaAdapter(ReplicaAdapter):
    """Session engine: an index entry's ``extra`` is the session END;
    a key's result is ``{session_end -> finished columns}``.

    Sessions PRIME instead of invalidating: a session's result key —
    its END — moves as the session absorbs, so each publish upserts
    the row under the NEW end and deletes the stale-end entry in the
    SAME batched prime (the old end read from the PRE-publish index,
    where the (key, sid) row still carries it). The hottest workload
    class — a session absorbing across many boundaries — stays on the
    hit path instead of structurally missing at every boundary."""

    def _prime_rows(self, keys_f, ns_f, extra_f, prev_index):
        n = len(keys_f)
        rns = np.asarray(extra_f, dtype=np.int64)  # the NEW ends
        valid = np.ones(n, dtype=bool)
        removals: List[Tuple[int, int]] = []
        # one prev-index probe per KEY (rows grouped), not per row
        by_key: Dict[int, List[int]] = {}
        for j in range(n):
            by_key.setdefault(int(keys_f[j]), []).append(j)
        for kid, idxs in by_key.items():
            prev = prev_index.get(kid)
            if not prev:
                continue
            for j in idxs:
                ent = prev.get(int(ns_f[j]))
                if ent is not None and ent[2] is not None \
                        and int(ent[2]) != int(rns[j]):
                    # the session's end MOVED: the entry cached under
                    # the old end is stale — delete it in this prime
                    removals.append((kid, int(ent[2])))
        return rns, valid, removals, set()

    def prime_free_ns(self, ns: int, extra):
        # a freed (fired/merged-away) session removes its END entry;
        # ``extra`` is the pre-publish index payload = the old end.
        # A freed row with no recorded end cannot be mapped — drop the
        # key's entry (the safe fallback the old invalidate path took).
        return int(extra) if extra is not None else None

    def compose(self, entries, vals, cold_entries, cold_result) -> dict:
        out: Dict[int, Dict[str, float]] = {}
        if entries:
            leaves = [np.asarray([vals[j][i] for _, j, _ in entries],
                                 dtype=l.dtype)
                      for i, l in enumerate(self.agg.leaves)]
            finished = self.agg.finish(tuple(leaves))
            cols = {name: np.asarray(col)
                    for name, col in finished.items()}
            for r, (_ns, _j, end) in enumerate(entries):
                out[int(end)] = {name: col[r].item()
                                 for name, col in cols.items()}
        if cold_result is not None:
            # take ONLY the sessions the sealed index flagged cold out
            # of the live detour's full map (their entry extra is the
            # session end) — a cold row cannot change while cold, so
            # its live value IS its boundary value; sessions born after
            # the boundary are not in the sealed index and stay out
            for _sid, end in cold_entries:
                colsd = cold_result.get(int(end))
                if colsd is not None:
                    out[int(end)] = colsd
        return out


class JoinSideReplicaAdapter(ReplicaAdapter):
    """One join side table's replica view: rows are immutable, the
    index maps ``key -> {rid -> (shard, slot, (ts, host_col_values))}``
    and a key's result is the live ``query_side_batch`` shape — a list
    of ``{"ts", "rid", <col>: v}`` dicts sorted by (ts, rid). Device
    columns gather from the sealed plane; device-ineligible columns
    ride the published ``extra`` payload; cold rows detour through
    ``cold_fetch`` (their page value IS their boundary value — join
    rows never change after insert)."""

    def __init__(self, plane: ReplicaPlane, side) -> None:
        super().__init__(plane, agg=None)
        self.schema = list(side.schema)
        self.device_cols = list(side.device_cols)
        self.host_cols = list(side.host_cols)

    def compose(self, entries, vals, cold_entries, cold_result) -> list:
        rows: List[dict] = []
        names = [nm for nm, _ in self.schema]
        for rid, j, extra in entries:
            ts, host_vals = extra
            row = {"ts": int(ts), "rid": int(rid)}
            for gi, i in enumerate(self.device_cols):
                row[names[i]] = np.asarray(vals[j][gi]).item()
            for hi, i in enumerate(self.host_cols):
                v = host_vals[hi]
                row[names[i]] = v.item() if hasattr(v, "item") else v
            rows.append(row)
        if cold_result is not None:
            want = {int(rid) for rid, _ in cold_entries}
            for row in cold_result:
                if int(row["rid"]) in want:
                    rows.append(dict(row))
        rows.sort(key=lambda d: (d["ts"], d["rid"]))
        return rows


class WindowReplicaAdapter(ReplicaAdapter):
    """Window engine: entries are per-SLICE accumulator rows
    (namespace == slice end); results compose host-side through the
    same ``compose_windows`` the live query path uses. A window with at
    least one COLD slice answers from the live detour (raw slice values
    are not recoverable from a composed window result) — those slices
    are boundary-stable by definition of cold, and the detour is the
    exact legacy read path."""

    def __init__(self, plane: ReplicaPlane, agg, assigner) -> None:
        super().__init__(plane, agg)
        self.assigner = assigner
        #: None = unknown, probed on first lookup: does every slice map
        #: to exactly ONE window that is exactly that slice (tumbling)?
        #: Then composition is a single vectorized finish over all rows
        #: instead of a per-key per-window host merge loop.
        self._one_to_one: Optional[bool] = None

    def _probe_one_to_one(self, ns: int) -> bool:
        if self._one_to_one is None:
            a = self.assigner
            self._one_to_one = (
                [int(w) for w in a.window_ends_for_slice(int(ns))]
                == [int(ns)]
                and [int(s) for s in a.slice_ends_for_window(int(ns))]
                == [int(ns)])
        return self._one_to_one

    def prime_value_ns(self, ns: int, extra):
        # tumbling-style: the slice end IS the window end, a stable
        # result key — the cached entry updates in place. Sliding/
        # cumulative shapes fall back to drop-and-re-resolve (a slice
        # feeds k windows; incremental re-compose isn't worth it).
        return ns if self._probe_one_to_one(ns) else None

    def prime_free_ns(self, ns: int, extra):
        return ns if self._probe_one_to_one(ns) else None

    def _prime_rows(self, keys_f, ns_f, extra_f, prev_index):
        # vectorized: ONE assigner probe decides the whole batch —
        # tumbling-style rows prime under their own namespace, other
        # shapes drop every touched key (the base class would have
        # made the same per-row decision n times)
        n = len(keys_f)
        if n and self._probe_one_to_one(int(ns_f[0])):
            return (np.asarray(ns_f, dtype=np.int64),
                    np.ones(n, dtype=bool), [], set())
        return (np.zeros(n, dtype=np.int64), np.zeros(n, dtype=bool),
                [], {int(k) for k in keys_f})

    def compose_all(self, row_of, vals, cold_of, cold_vals):
        # vectorized fast path (the serving hot loop): tumbling-style
        # assigners finish EVERY gathered row in one pass — the per-key
        # compose_windows loop is only needed for sliding/cumulative
        # shapes (slice sharing) and for keys with cold slices
        some = next((row_of[r][0][0] for r in range(len(row_of))
                     if row_of[r]), None)
        if some is None or not self._probe_one_to_one(some):
            return super().compose_all(row_of, vals, cold_of, cold_vals)
        leaves = [np.asarray([v[i] for v in vals], dtype=l.dtype)
                  for i, l in enumerate(self.agg.leaves)]
        finished = self.agg.finish(tuple(leaves))
        cols = [(name, np.asarray(col)) for name, col in
                finished.items()]
        out: List[dict] = []
        for r in range(len(row_of)):
            if cold_of[r]:
                out.append(self.compose(row_of[r], vals, cold_of[r],
                                        cold_vals.get(r)))
                continue
            res: Dict[int, Dict[str, float]] = {}
            for ns, j, _extra in row_of[r]:
                res[ns] = {name: col[j].item() for name, col in cols}
            out.append(res)
        return out

    def compose(self, entries, vals, cold_entries, cold_result) -> dict:
        from flink_tpu.windowing.windower import compose_windows

        slice_vals: Dict[int, tuple] = {}
        for ns, j, _extra in entries:
            slice_vals[int(ns)] = tuple(
                np.asarray([v], dtype=l.dtype)
                for v, l in zip(vals[j], self.agg.leaves))
        out = compose_windows(self.assigner, self.agg, slice_vals) \
            if slice_vals else {}
        if cold_result is not None:
            cold_windows = sorted({
                int(w) for ns, _ in cold_entries
                for w in self.assigner.window_ends_for_slice(int(ns))})
            for w in cold_windows:
                colsd = cold_result.get(w)
                if colsd is not None:
                    out[w] = colsd
                else:
                    out.pop(w, None)
        return out
