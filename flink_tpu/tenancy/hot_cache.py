"""Host-side hot-row cache over the replica serving plane.

Fires and lookup harvests materialize exactly the HOT rows host-side;
this cache retains those composed per-key results keyed ``(job,
operator, key_id)`` and tagged with the replica GENERATION that
produced them. Invalidation is the generation tag itself: a publish
advances the generation, so the next probe of a stale entry misses
(and drops it) — no flush pass, no timer. Between publishes, repeat
lookups of hot keys never touch the device at all: the probe is one
dict access under one lock.

Capacity is bounded LRU (an ``OrderedDict``): a churning key space
evicts the coldest entries instead of growing per historical key.
The cached value is the composed result dict the operator's
``query_state_batch`` would return — callers treat it as immutable
(the serving plane hands the same object to concurrent riders).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.observe.lock_sentinel import named_lock


class PrimeDelta:
    """One publish boundary's cache delta, FLAT: the shape both cache
    planes consume (``prime_batch``), built once by the replica adapter
    from the publish harvest — the native plane packs it into a single
    GIL-released C call, the Python plane folds it under one lock.

    ``keys[i]``'s updates are rows ``uoff[i]:uoff[i+1]`` of ``u_ns`` /
    the ``u_cols`` value columns; its removals are ``roff[i]:roff[i+1]``
    of ``r_ns``. ``flags`` bit0 = insert_ok (the updates are the key's
    COMPLETE composed state — an absent entry may be created), bit1 =
    drop (the key's entry is removed outright — the invalidate-on-change
    path for compositions that cannot update incrementally)."""

    __slots__ = ("keys", "uoff", "u_ns", "u_cols", "roff", "r_ns",
                 "flags")

    def __init__(self, keys, uoff, u_ns, u_cols, roff, r_ns, flags):
        self.keys = keys
        self.uoff = uoff
        self.u_ns = u_ns
        #: [(column name, value array aligned with u_ns)]
        self.u_cols = u_cols
        self.roff = roff
        self.r_ns = r_ns
        self.flags = flags

    def __len__(self) -> int:
        return len(self.keys)


def make_hot_row_cache(max_entries: int = 1 << 18,
                       shm_dir: Optional[str] = None):
    """The native (C++) hot-row probe table when available, else this
    module's :class:`HotRowCache` — selected exactly the way
    ``make_session_meta`` picks the session-metadata plane. Lookup
    results are bit-identical across planes (test-pinned); the native
    plane probes/primes a whole key batch in ONE GIL-released C call.

    ``shm_dir`` arms the multi-process serving tier: the native tables
    allocate as MAP_SHARED file arenas under it (plus an attach
    manifest), so frontend processes probe the SAME table over shared
    memory (``flink_tpu.tenancy.frontend``). The Python plane cannot
    shm-map — requesting ``shm_dir`` without the native plane raises
    rather than silently serving a frontendless cache.

    ``FLINK_TPU_NATIVE_HOTCACHE=0`` forces the Python plane while other
    native components stay on — the A/B knob the serving bench and the
    NOTES_r19 walk use (the blanket ``FLINK_TPU_NO_NATIVE=1`` disables
    everything native). Unavailability (no toolchain, build failure)
    degrades LOUDLY via ``flink_tpu.native.note_fallback``."""
    import os

    from flink_tpu.native import (
        hotcache_available,
        native_disabled,
        note_fallback,
    )

    if (os.environ.get("FLINK_TPU_NATIVE_HOTCACHE") != "0"
            and not native_disabled()):
        if hotcache_available():
            try:
                from flink_tpu.tenancy.hot_cache_native import (
                    NativeHotRowCache,
                )

                return NativeHotRowCache(max_entries=max_entries,
                                         shm_dir=shm_dir)
            except Exception as e:  # noqa: BLE001 — degrade, loudly
                if shm_dir is not None:
                    raise
                note_fallback(
                    "native hot-row cache failed to initialize: "
                    f"{type(e).__name__}: {e}")
        else:
            note_fallback(
                "native hotcache library unavailable (build failed or "
                "no toolchain) — using the bit-identical Python cache")
    if shm_dir is not None:
        raise RuntimeError(
            "shm_dir (the multi-process serving tier) requires the "
            "native hotcache plane — it is disabled or unavailable "
            "here, and the Python cache cannot be shared-memory "
            "mapped by frontend processes")
    return HotRowCache(max_entries=max_entries)


class HotRowCache:
    """Generation-tagged LRU of composed lookup results."""

    def __init__(self, max_entries: int = 1 << 18) -> None:
        self.max_entries = int(max_entries)
        self._lock = named_lock("tenancy.hot_rows")
        self._entries: "OrderedDict[tuple, Tuple[int, Any]]" = \
            OrderedDict()
        #: counters read (under the lock) by the serving gauges and the
        #: smoke's vacuity gate
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.primes = 0

    def get(self, job: str, operator: str, key_id: int, gen: int,
            exact: bool = True) -> Tuple[bool, Any]:
        """(hit, value). ``exact=True``: only an entry tagged with the
        CURRENT generation hits; older tags are dropped (pure
        tag-invalidation — the mode when nothing re-primes entries).
        ``exact=False`` (the primed serving path): ANY entry hits —
        the publish harvest re-primes or drops every cached entry a
        boundary changed, so an entry's presence IS its validity (an
        unchanged key's value is by definition still its boundary
        value)."""
        k = (job, operator, key_id)
        with self._lock:
            ent = self._entries.get(k)
            if ent is not None and (not exact or ent[0] == gen):
                self._entries.move_to_end(k)
                self.hits += 1
                return True, ent[1]
            if ent is not None:
                del self._entries[k]
            self.misses += 1
            return False, None

    def get_many(self, job: str, operator: str, key_ids, gen: int,
                 out: list, misses: list, exact: bool = True) -> int:
        """Batched probe under ONE lock acquisition: fills ``out[i]``
        for hits, appends ``(i, key_id)`` to ``misses`` otherwise;
        returns the hit count. The per-key locked ``get`` would spend
        more time on lock traffic than on the probes at cache-hit QPS
        (the serving hot loop). ``exact`` as in :meth:`get`."""
        if hasattr(key_ids, "tolist"):  # ndarray: bulk-convert once
            key_ids = key_ids.tolist()
        hits = 0
        with self._lock:
            entries = self._entries
            for i, kid in enumerate(key_ids):
                k = (job, operator, kid)
                ent = entries.get(k)
                if ent is not None and (not exact or ent[0] == gen):
                    entries.move_to_end(k)
                    out[i] = ent[1]
                    hits += 1
                    continue
                if ent is not None:
                    del entries[k]
                misses.append((i, kid))
            self.hits += hits
            self.misses += len(misses)
        return hits

    def put(self, job: str, operator: str, key_id: int, gen: int,
            value: Any) -> None:
        k = (job, operator, key_id)
        with self._lock:
            ent = self._entries.get(k)
            if ent is not None and ent[0] > gen:
                # no downgrade: a worker that resolved against an older
                # sealed generation must not overwrite a fresher prime
                # (the stale value would then be served "forever" — no
                # future prime touches a key that stops changing)
                return
            self._entries[k] = (gen, value)
            self._entries.move_to_end(k)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def prime(self, job: str, operator: str, key_id: int, gen: int,
              updates: Optional[dict] = None, remove=(),
              insert_ok: bool = False) -> None:
        """Publish-harvest feed: fold a boundary's changes into an
        EXISTING entry (copy-on-write — readers hold references to the
        old value dict) and retag it with the publishing generation.
        ``insert_ok=True`` means ``updates`` is the key's COMPLETE
        composed state (the adapter checked the delta covers every
        published row of the key), so an absent entry may be created —
        first-touch lookups of hot keys then hit without ever paying a
        device round trip. Otherwise keys nobody cached are skipped."""
        k = (job, operator, key_id)
        with self._lock:
            ent = self._entries.get(k)
            if ent is None and not insert_ok:
                return
            if ent is not None and ent[0] > gen:
                return
            val = dict(ent[1]) if ent is not None else {}
            for ns in remove:
                val.pop(ns, None)
            if updates:
                val.update(updates)
            self._entries[k] = (gen, val)
            self._entries.move_to_end(k)
            self.primes += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def put_many(self, job: str, operator: str, key_ids, gen: int,
                 values) -> None:
        """Worker miss-resolution feed: one :meth:`put` per key (the
        native plane replaces this with one packed C call; here the
        loop is the bit-identical reference)."""
        for kid, val in zip(key_ids, values):
            self.put(job, operator, int(kid), gen, val)

    def prime_batch(self, job: str, operator: str, gen: int,
                    delta: "PrimeDelta") -> None:
        """Fold one publish boundary's flat delta (:class:`PrimeDelta`)
        into the cache: per key, drops apply first, then updates/
        removals through :meth:`prime` — semantically the per-key feed
        the adapters used to drive, now built once as arrays so the
        native plane can consume the SAME delta in one C call."""
        uoff = delta.uoff
        roff = delta.roff
        u_ns = delta.u_ns
        r_ns = delta.r_ns
        cols = delta.u_cols or []
        for i, kid in enumerate(delta.keys):
            kid = int(kid)
            fl = int(delta.flags[i])
            if fl & 2:
                self.drop(job, operator, kid)
                continue
            ups: Optional[Dict[int, dict]] = None
            lo, hi = int(uoff[i]), int(uoff[i + 1])
            if hi > lo:
                ups = {int(u_ns[j]): {name: col[j].item()
                                      for name, col in cols}
                       for j in range(lo, hi)}
            rem: List[int] = [int(r_ns[j])
                              for j in range(int(roff[i]),
                                             int(roff[i + 1]))]
            self.prime(job, operator, kid, gen, ups, rem,
                       insert_ok=bool(fl & 1))

    def drop(self, job: str, operator: str, key_id: int) -> None:
        with self._lock:
            self._entries.pop((job, operator, key_id), None)

    def invalidate_job(self, job: str) -> None:
        """Drop a finished/unbound job's entries (the per-historical-job
        leak rule the coalescer pool already follows)."""
        with self._lock:
            for k in [k for k in self._entries if k[0] == job]:
                del self._entries[k]

    def invalidate_op(self, job: str, operator: str) -> None:
        """Drop one operator's entries — a replica REBUILD (restore/
        reshard/shard loss) may roll values back, and the rebuild's
        full republish only re-primes keys still present: entries for
        keys that vanished across the restore would otherwise serve
        stale forever."""
        with self._lock:
            for k in [k for k in self._entries
                      if k[0] == job and k[1] == operator]:
                del self._entries[k]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def hit_rate(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "hot_row_hits": float(self.hits),
                "hot_row_misses": float(self.misses),
                "hot_row_evictions": float(self.evictions),
                "hot_row_entries": float(len(self._entries)),
                "hot_row_hit_rate": (self.hits / total) if total else 0.0,
            }
