"""Host-side hot-row cache over the replica serving plane.

Fires and lookup harvests materialize exactly the HOT rows host-side;
this cache retains those composed per-key results keyed ``(job,
operator, key_id)`` and tagged with the replica GENERATION that
produced them. Invalidation is the generation tag itself: a publish
advances the generation, so the next probe of a stale entry misses
(and drops it) — no flush pass, no timer. Between publishes, repeat
lookups of hot keys never touch the device at all: the probe is one
dict access under one lock.

Capacity is bounded LRU (an ``OrderedDict``): a churning key space
evicts the coldest entries instead of growing per historical key.
The cached value is the composed result dict the operator's
``query_state_batch`` would return — callers treat it as immutable
(the serving plane hands the same object to concurrent riders).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple


class HotRowCache:
    """Generation-tagged LRU of composed lookup results."""

    def __init__(self, max_entries: int = 1 << 18) -> None:
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Tuple[int, Any]]" = \
            OrderedDict()
        #: counters read (under the lock) by the serving gauges and the
        #: smoke's vacuity gate
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.primes = 0

    def get(self, job: str, operator: str, key_id: int, gen: int,
            exact: bool = True) -> Tuple[bool, Any]:
        """(hit, value). ``exact=True``: only an entry tagged with the
        CURRENT generation hits; older tags are dropped (pure
        tag-invalidation — the mode when nothing re-primes entries).
        ``exact=False`` (the primed serving path): ANY entry hits —
        the publish harvest re-primes or drops every cached entry a
        boundary changed, so an entry's presence IS its validity (an
        unchanged key's value is by definition still its boundary
        value)."""
        k = (job, operator, key_id)
        with self._lock:
            ent = self._entries.get(k)
            if ent is not None and (not exact or ent[0] == gen):
                self._entries.move_to_end(k)
                self.hits += 1
                return True, ent[1]
            if ent is not None:
                del self._entries[k]
            self.misses += 1
            return False, None

    def get_many(self, job: str, operator: str, key_ids, gen: int,
                 out: list, misses: list, exact: bool = True) -> int:
        """Batched probe under ONE lock acquisition: fills ``out[i]``
        for hits, appends ``(i, key_id)`` to ``misses`` otherwise;
        returns the hit count. The per-key locked ``get`` would spend
        more time on lock traffic than on the probes at cache-hit QPS
        (the serving hot loop). ``exact`` as in :meth:`get`."""
        hits = 0
        entries = self._entries
        with self._lock:
            for i, kid in enumerate(key_ids):
                k = (job, operator, kid)
                ent = entries.get(k)
                if ent is not None and (not exact or ent[0] == gen):
                    entries.move_to_end(k)
                    out[i] = ent[1]
                    hits += 1
                    continue
                if ent is not None:
                    del entries[k]
                misses.append((i, kid))
            self.hits += hits
            self.misses += len(misses)
        return hits

    def put(self, job: str, operator: str, key_id: int, gen: int,
            value: Any) -> None:
        k = (job, operator, key_id)
        with self._lock:
            ent = self._entries.get(k)
            if ent is not None and ent[0] > gen:
                # no downgrade: a worker that resolved against an older
                # sealed generation must not overwrite a fresher prime
                # (the stale value would then be served "forever" — no
                # future prime touches a key that stops changing)
                return
            self._entries[k] = (gen, value)
            self._entries.move_to_end(k)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def prime(self, job: str, operator: str, key_id: int, gen: int,
              updates: Optional[dict] = None, remove=(),
              insert_ok: bool = False) -> None:
        """Publish-harvest feed: fold a boundary's changes into an
        EXISTING entry (copy-on-write — readers hold references to the
        old value dict) and retag it with the publishing generation.
        ``insert_ok=True`` means ``updates`` is the key's COMPLETE
        composed state (the adapter checked the delta covers every
        published row of the key), so an absent entry may be created —
        first-touch lookups of hot keys then hit without ever paying a
        device round trip. Otherwise keys nobody cached are skipped."""
        k = (job, operator, key_id)
        with self._lock:
            ent = self._entries.get(k)
            if ent is None and not insert_ok:
                return
            if ent is not None and ent[0] > gen:
                return
            val = dict(ent[1]) if ent is not None else {}
            for ns in remove:
                val.pop(ns, None)
            if updates:
                val.update(updates)
            self._entries[k] = (gen, val)
            self._entries.move_to_end(k)
            self.primes += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def drop(self, job: str, operator: str, key_id: int) -> None:
        with self._lock:
            self._entries.pop((job, operator, key_id), None)

    def invalidate_job(self, job: str) -> None:
        """Drop a finished/unbound job's entries (the per-historical-job
        leak rule the coalescer pool already follows)."""
        with self._lock:
            for k in [k for k in self._entries if k[0] == job]:
                del self._entries[k]

    def invalidate_op(self, job: str, operator: str) -> None:
        """Drop one operator's entries — a replica REBUILD (restore/
        reshard/shard loss) may roll values back, and the rebuild's
        full republish only re-primes keys still present: entries for
        keys that vanished across the restore would otherwise serve
        stale forever."""
        with self._lock:
            for k in [k for k in self._entries
                      if k[0] == job and k[1] == operator]:
                del self._entries[k]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def hit_rate(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "hot_row_hits": float(self.hits),
                "hot_row_misses": float(self.misses),
                "hot_row_evictions": float(self.evictions),
                "hot_row_entries": float(len(self._entries)),
                "hot_row_hit_rate": (self.hits / total) if total else 0.0,
            }
