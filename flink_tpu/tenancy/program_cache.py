"""Shared compiled-program cache: one XLA program family for N jobs.

The multi-tenant claim that "job K+1 pays zero steady-state compiles on
a warm cluster" rests on one property: the compiled step programs
(scatter / fire / reset / gather / put / merge, the serving-plane query
gathers, and the fused exchange+scatter family of the device data plane
— ``parallel/shuffle.py build_exchange_scatter``, keyed ``(device ids,
aggregate layout, valued)``) are keyed on WHAT they compute — ``(program
kind, device ids, aggregate layout)`` — never on WHO runs them. Shapes are handled
one level down by jax's own jit cache together with the engines'
sticky-bucket padding discipline, so the full effective key is
``(kind, layout, bucketed shapes, device ids)``; an engine identity, a
job id, or a per-instance lambda in the key would compile the whole
family once per job and erase the tenancy win.

This module is that cache's single home. It wraps the raw program dict
(previously ``sharded_windower._STEP_CACHE``) with per-job hit/miss
attribution so the tenancy layer can PROVE sharing: after job A warms
the cluster, job B's stats must show ``misses == 0`` (the serving smoke
and the recompile smoke both gate on the stronger runtime signal — the
recompile sentinel — and read these stats for the diagnosis when it
trips).

No engine imports here: the cache must be importable from the lowest
layers (parallel/, state/) without cycles.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

from flink_tpu.observe.lock_sentinel import named_lock


class SharedProgramCache:
    """Process-global registry of compiled program families.

    ``get_or_build(kind, key, builder)`` returns the cached program for
    ``(kind, key)`` or builds, stores and returns it. ``key`` must be
    hashable and must identify everything the compiled program closes
    over (device ids, aggregate layout) — and nothing else.

    Job attribution is cooperative: the tenancy session cluster brackets
    each job's scheduling quantum with :meth:`job_scope`, so any program
    built (or hit) inside it is charged to that job. Outside a scope,
    traffic lands on the ``None`` job (single-job runs). The scope is
    PER THREAD (a MiniCluster runs each job's executor on its own
    thread), and the at-most-once build contract holds across threads:
    two jobs racing to the same key cost one XLA compile, not two — the
    loser waits on the winner's per-key latch (the stall is exactly the
    compile the cache saved it), while traffic for other keys proceeds
    unstalled.
    """

    def __init__(self) -> None:
        #: the raw storage — exposed for compatibility shims only
        self.programs: Dict[Tuple[str, Any], Any] = {}
        self._tls = threading.local()
        #: one lock for storage + stats: hits hold it for a dict probe;
        #: BUILDS run outside it behind a per-key once-latch (an XLA
        #: compile takes seconds — holding the cache lock across it
        #: would stall every other thread's unrelated cache hits)
        self._lock = named_lock("tenancy.program_cache", reentrant=True)
        #: key -> Event for builds in flight (see get_or_build)
        self._building: Dict[Tuple[str, Any], threading.Event] = {}
        #: job -> {"hits": n, "misses": n}
        self._job_stats: Dict[Optional[str], Dict[str, int]] = {}

    # ------------------------------------------------------------ attribution

    @property
    def _job(self) -> Optional[str]:
        return getattr(self._tls, "job", None)

    def set_current_job(self, job: Optional[str]) -> Optional[str]:
        """Set the job charged for subsequent cache traffic ON THIS
        THREAD; returns the previous value (for restore)."""
        prev = getattr(self._tls, "job", None)
        self._tls.job = job
        return prev

    def job_scope(self, job: Optional[str]):
        """Context manager form of :meth:`set_current_job`."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            prev = self.set_current_job(job)
            try:
                yield self
            finally:
                self.set_current_job(prev)

        return _scope()

    def _charge(self, field: str) -> None:
        st = self._job_stats.setdefault(self._job,
                                        {"hits": 0, "misses": 0})
        st[field] += 1

    # ----------------------------------------------------------------- lookup

    def get_or_build(self, kind: str, key: Any,
                     builder: Callable[[], Any]) -> Any:
        """The cached program family for ``(kind, key)``, building it on
        first use. The builder runs at most once per key for the process
        lifetime — restarted jobs, rescaled engines, NEW JOBS, and
        concurrent executor threads all hit. Two threads racing the SAME
        key cost one compile (the loser waits on the winner's latch and
        takes the cached result); a thread hitting a DIFFERENT key is
        never stalled by an in-flight build — the builder runs outside
        the cache lock. A failed build releases its latch so the next
        caller retries."""
        full = (kind, key)
        while True:
            with self._lock:
                cached = self.programs.get(full)
                if cached is not None:
                    self._charge("hits")
                    return cached
                latch = self._building.get(full)
                if latch is None:
                    self._building[full] = latch = threading.Event()
                    self._charge("misses")
                    break
            # another thread is compiling this key: wait, then re-probe
            # (on its failure we become the next builder)
            latch.wait()
        try:
            built = builder()
        except BaseException:
            # flint: disable=LCK03 -- latch protocol: the thread that
            # installed the latch above is its sole owner; no other
            # thread deletes this key's latch, so the boundary is safe
            with self._lock:
                del self._building[full]
            latch.set()
            raise
        # flint: disable=LCK03 -- latch protocol: this thread won the
        # builder election under the first hold and is the only writer
        # of this key until it sets the latch; waiters re-probe in the
        # while-loop, so the release boundary cannot lose an update
        with self._lock:
            self.programs[full] = built
            del self._building[full]
        latch.set()
        return built

    # ------------------------------------------------------------------ stats

    def stats(self) -> Dict[str, int]:
        with self._lock:
            hits = sum(s["hits"] for s in self._job_stats.values())
            misses = sum(s["misses"] for s in self._job_stats.values())
            return {"programs": len(self.programs),
                    "hits": hits, "misses": misses}

    def stat(self, field: str) -> int:
        """One stats() field without computing the others — what the
        per-scrape gauges read."""
        with self._lock:
            if field == "programs":
                return len(self.programs)
            return sum(s[field] for s in self._job_stats.values())

    def stats_for(self, job: Optional[str]) -> Dict[str, int]:
        """Per-job cache traffic ({"hits": n, "misses": n}); zeros for a
        job that never touched the cache."""
        with self._lock:
            return dict(self._job_stats.get(job,
                                            {"hits": 0, "misses": 0}))

    def reset_stats(self) -> None:
        """Clear attribution counters (NOT the programs — compiled
        executables stay shared; tests reset between phases)."""
        with self._lock:
            self._job_stats.clear()


#: THE process-global instance every engine routes through.
PROGRAM_CACHE = SharedProgramCache()
