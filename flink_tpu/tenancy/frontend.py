"""Multi-process serving tier: frontend processes over the shm hot cache.

NOTES_r19's ceiling analysis said it plainly: at 1.14M lookups/s the
native probe is ~3% of one core — past ~1.3M/s the serving CLIENTS
starve the publish loop, so the next factor needs more cores, not a
faster probe. This module is that factor, split by role:

- the OWNER process keeps ingest + publish/prime exactly as today
  (``ServingPlane`` with a shm-backed ``NativeHotRowCache``,
  ``shm_dir`` armed), and stays the table's ONLY writer;
- N FRONTEND processes (:class:`FrontendPool`) attach the same arenas
  over shared memory (``FrontendCacheClient``) and serve the hit path
  entirely in their own process: shm probe → packed zero-copy reply,
  no lock, no GIL shared with the owner, no IPC per hit. This is also
  the serving-side hot-row REPLICATION story (ROADMAP item 4's
  remainder): every frontend serves every hot row out of one physical
  copy — the mapping is the replica;
- cold misses CROSS to the owner on a bounded per-frontend request
  pipe and resolve through the existing sharded-coalescer / replica
  worker path (``ServingPlane.lookup_batch``) — exactly today's miss
  semantics, so the staleness SLO story is unchanged: frontends serve
  the same sealed generations the owner primes.

The frontends need zero locks because the seqlock probe protocol is
address-free (native/hotcache.cpp): a torn read retries then falls to
the miss path, in another process exactly as in another thread. Owner
restart is detected by the arena header's epoch word against the
manifest (see ``FrontendCacheClient.refresh``).

Failure domain: a frontend process dying mid-burst must not hurt the
owner or its siblings. ``lookup_batch`` detects the dead pipe and
RETRIES the request on a live sibling (in-flight requests fail over;
with no sibling left it fails fast with a clear error). The
``serving.frontend`` chaos point injects exactly that death at the
dispatch site — its ``drop`` kind kills the chosen frontend process
for real, mid-burst.

DCN-aware routing (:class:`LookupRouter`) composes this with the pod
plane: each key batch splits by the HOST owning its key-group range
(``host_of_key_group`` under the live ``KeyGroupAssignment``), so a
multi-host deployment probes locally instead of crossing DCN per key —
the reference's queryable-state shape (state served by the task
executor that owns the key-group range, not by one process).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.observe.lock_sentinel import named_lock

#: default seconds a dispatched request may wait before the frontend is
#: declared dead and the request retries on a sibling
REQUEST_TIMEOUT_S = 30.0


# --------------------------------------------------------------- worker

def _frontend_main(fe_id: int, shm_dir: str, req_conn,
                   miss_conn) -> None:  # pragma: no cover - subprocess
    """Frontend process body (spawn target; this import path must stay
    light — no serving plane, no cluster). Single-threaded loop:
    requests arrive on ``req_conn``, the hit path is one shm probe +
    a reply built straight off the packed buffers, misses cross to the
    owner over ``miss_conn`` and merge into the reply."""
    from flink_tpu.tenancy.hot_cache_native import FrontendCacheClient

    client = FrontendCacheClient(shm_dir, frontend_id=fe_id)
    try:
        while True:
            try:
                msg = req_conn.recv()
            except (EOFError, OSError):
                break
            if msg is None or msg[0] == "stop":
                break
            kind, req_id = msg[0], msg[1]
            try:
                if kind == "ping":
                    req_conn.send(("ok", req_id, "pong"))
                elif kind == "lookup":
                    _job, _op, keys = msg[2], msg[3], msg[4]
                    req_conn.send(_serve_lookup(
                        client, miss_conn, req_id, _job, _op, keys))
                elif kind == "drive":
                    _job, _op, keys, batch, batches = (
                        msg[2], msg[3], msg[4], msg[5], msg[6])
                    req_conn.send(_serve_drive(
                        client, req_id, _job, _op, keys, batch,
                        batches))
                else:
                    req_conn.send(("err", req_id,
                                   f"unknown request {kind!r}"))
            except (EOFError, OSError, BrokenPipeError):
                break
            except Exception as e:  # noqa: BLE001 — reply, don't die
                try:
                    req_conn.send(("err", req_id,
                                   f"{type(e).__name__}: {e}"))
                except (OSError, BrokenPipeError):
                    break
    finally:
        client.close()


def _serve_lookup(client, miss_conn, req_id, job, op, keys):
    """One request: probe the shm table, cross ONLY the misses to the
    owner, reply the merged results in input order. Keys hash through
    the SAME ``hash_keys_to_i64`` the owner's probe path uses — the
    shm table is keyed by key id, and a divergent hash would read as
    systematic misses, not wrong answers (still: hash once, same fn)."""
    from flink_tpu.state.keygroups import hash_keys_to_i64

    kids = hash_keys_to_i64(np.asarray(keys))
    hits, probe, misses = client.probe(job, op, kids, exact=False)
    out: List[Any] = [None] * len(keys)
    if probe is not None:
        for i in range(len(keys)):
            if probe.hit[i]:
                out[i] = probe.materialize(i)
    if misses:
        client.note_miss_crossings(job, op, len(misses))
        miss_conn.send((req_id, job, op, [keys[i] for i in misses]))
        rep = miss_conn.recv()
        if rep[1] != "ok":
            return ("err", req_id, rep[2])
        for i, val in zip(misses, rep[2]):
            out[i] = val
    return ("ok", req_id, out, {"hits": int(hits),
                                "misses": len(misses)})


def _serve_drive(client, req_id, job, op, keys, batch, batches):
    """Self-driving measurement loop (the multi-process bench): probe
    ``batches`` rotating windows of ``batch`` keys against the shm
    table IN this process — the shape a network frontend serves, where
    replies serialize straight from the packed buffers and never cross
    back through the owner. Misses are counted, not crossed (the bench
    pre-primes; a miss there is signal, not work to route)."""
    from flink_tpu.state.keygroups import hash_keys_to_i64

    keys = hash_keys_to_i64(np.asarray(keys, dtype=np.int64))
    n = len(keys)
    probes = hits = 0
    t0 = time.perf_counter()
    for b in range(batches):
        lo = (b * batch) % max(n - batch + 1, 1)
        got, probe, _misses = client.probe(
            job, op, keys[lo:lo + batch], exact=False)
        probes += batch
        hits += got
    wall = time.perf_counter() - t0
    return ("ok", req_id, {"probes": probes, "hits": hits,
                           "wall_s": wall, "batches": batches})


# ----------------------------------------------------------------- pool

class _Frontend:
    __slots__ = ("idx", "proc", "req", "miss", "lock", "alive",
                 "miss_thread")

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.proc = None
        self.req = None
        self.miss = None
        #: one in-flight request per frontend (the bounded pipe): the
        #: lock serializes owner-side dispatchers onto it
        self.lock = named_lock("frontend.pipe")
        self.alive = False
        self.miss_thread = None


class FrontendPool:
    """Owner-side handle on N frontend processes (see module doc).

    The pool owns: the spawn lifecycle, one MISS-SERVER thread per
    frontend (draining its bounded request pipe into
    ``plane.lookup_batch`` — the replica path, exactly today's miss
    semantics), failover dispatch, and the per-frontend counters
    (read off the shared arena headers owner-side, no IPC —
    :meth:`metrics`). The serving plane must have been built with
    ``shm_dir`` armed (``ServingPlane(shm_dir=...)``)."""

    def __init__(self, plane, n_frontends: int = 2,
                 request_timeout_s: float = REQUEST_TIMEOUT_S,
                 start: bool = True) -> None:
        shm_dir = getattr(plane.hot_cache, "shm_dir", None)
        if shm_dir is None:
            raise RuntimeError(
                "FrontendPool needs a shm-backed serving cache — "
                "build the plane with ServingPlane(shm_dir=...) "
                "(native hotcache required)")
        import multiprocessing as mp

        # spawn, never fork: the owner runs serving worker threads and
        # device runtimes a forked child must not inherit mid-state
        self._ctx = mp.get_context("spawn")
        self.plane = plane
        self.shm_dir = shm_dir
        self.n_frontends = int(n_frontends)
        self.request_timeout_s = float(request_timeout_s)
        self._frontends: List[_Frontend] = [
            _Frontend(i) for i in range(self.n_frontends)]
        self._rr = itertools.count()
        self._req_ids = itertools.count(1)
        self._closed = False
        #: retries that failed over to a sibling after a dead frontend
        self.failovers = 0
        self._fe_group = None
        if start:
            self.start()

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        for fe in self._frontends:
            if not fe.alive:
                self._start_frontend(fe)

    def _start_frontend(self, fe: _Frontend) -> None:
        req_owner, req_child = self._ctx.Pipe()
        miss_owner, miss_child = self._ctx.Pipe()
        fe.req = req_owner
        fe.miss = miss_owner
        fe.proc = self._ctx.Process(
            target=_frontend_main,
            args=(fe.idx, self.shm_dir, req_child, miss_child),
            name=f"hc-frontend-{fe.idx}", daemon=True)
        fe.proc.start()
        req_child.close()
        miss_child.close()
        fe.alive = True
        fe.miss_thread = threading.Thread(
            target=self._miss_server, args=(fe,),
            name=f"hc-miss-server-{fe.idx}", daemon=True)
        fe.miss_thread.start()

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        """Block until every live frontend answers a ping — a spawned
        child pays its interpreter+import boot before its first recv,
        and a bench (or a deploy's readiness gate) must not count that
        against the serving path."""
        deadline = time.monotonic() + timeout_s
        for fe in self._frontends:
            if not fe.alive:
                continue
            remaining = max(deadline - time.monotonic(), 0.1)
            saved = self.request_timeout_s
            self.request_timeout_s = remaining
            try:
                self._dispatch(fe, ("ping", next(self._req_ids)))
            except _FrontendDead:
                raise RuntimeError(
                    f"frontend {fe.idx} did not become ready within "
                    f"{timeout_s:.0f}s") from None
            finally:
                self.request_timeout_s = saved

    def _miss_server(self, fe: _Frontend) -> None:
        """Drain one frontend's miss pipe into the replica path. The
        thread dies with its frontend's pipe; errors reply as errors —
        a miss-resolution failure must surface at the CLIENT, not kill
        the server thread."""
        while True:
            try:
                req_id, job, op, keys = fe.miss.recv()
            except (EOFError, OSError):
                return
            try:
                results = self.plane.lookup_batch(job, op, keys)
                fe.miss.send((req_id, "ok", results))
            except Exception as e:  # noqa: BLE001
                try:
                    fe.miss.send((req_id, "err",
                                  f"{type(e).__name__}: {e}"))
                except (OSError, BrokenPipeError):
                    return

    def _kill(self, fe: _Frontend) -> None:
        """Hard-kill one frontend (the chaos ``drop`` kind and dead-
        pipe cleanup): owner and siblings are untouched by design —
        the process shares nothing but the read-mapped arenas."""
        fe.alive = False
        try:
            if fe.proc is not None and fe.proc.is_alive():
                fe.proc.terminate()
        except Exception:  # noqa: BLE001
            pass
        for conn in (fe.req, fe.miss):
            try:
                if conn is not None:
                    conn.close()
            except Exception:  # noqa: BLE001
                pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for fe in self._frontends:
            if fe.alive:
                try:
                    fe.req.send(("stop", 0))
                except (OSError, BrokenPipeError):
                    pass
        deadline = time.monotonic() + 5.0
        for fe in self._frontends:
            if fe.proc is not None:
                fe.proc.join(max(0.0, deadline - time.monotonic()))
            self._kill(fe)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -------------------------------------------------------- dispatch

    def live_frontends(self) -> List[int]:
        return [fe.idx for fe in self._frontends
                if fe.alive and fe.proc is not None
                and fe.proc.is_alive()]

    def _dispatch(self, fe: _Frontend, msg) -> Any:
        """One request/reply on a frontend's pipe, or raise
        ``_FrontendDead``. The per-frontend lock keeps the pipe
        bounded: one in-flight request per frontend."""
        with fe.lock:
            if not (fe.alive and fe.proc is not None
                    and fe.proc.is_alive()):
                raise _FrontendDead(fe.idx)
            try:
                fe.req.send(msg)
                if not fe.req.poll(self.request_timeout_s):
                    raise _FrontendDead(fe.idx)
                rep = fe.req.recv()
            except (OSError, BrokenPipeError, EOFError):
                raise _FrontendDead(fe.idx) from None
        if rep[0] == "err":
            raise RuntimeError(
                f"frontend {fe.idx} request failed: {rep[2]}")
        return rep

    def _faulted(self, job: str, operator: str, fe: _Frontend) -> None:
        """The ``serving.frontend`` chaos point at its real site — the
        owner-side dispatch. ``drop`` kills the CHOSEN frontend process
        for real (death mid-burst; the dispatch below then fails over
        to a sibling), ``raise``/``delay`` apply in place. One
        module-global None check while disarmed."""
        from flink_tpu.chaos import injection as chaos

        rule = chaos.payload_action(
            "serving.frontend", kinds=("raise", "delay", "drop"),
            job=job, operator=operator, frontend=fe.idx)
        if rule is not None and rule.kind == "drop":
            self._kill(fe)

    def lookup_batch(self, job: str, operator: str,
                     keys: Sequence[Any],
                     frontend: Optional[int] = None) -> List[Any]:
        """Route one key batch through a frontend process (round-robin
        unless pinned): shm hits answer in the frontend, misses cross
        to the owner's replica path. A dead frontend fails over to a
        live sibling; with none left this fails fast. Results are
        bit-identical to ``plane.lookup_batch`` (same tables, same
        miss path)."""
        if self._closed:
            raise RuntimeError("FrontendPool is closed")
        order: List[_Frontend]
        if frontend is not None:
            order = [self._frontends[frontend]]
            order += [fe for fe in self._frontends
                      if fe.idx != frontend]
        else:
            start = next(self._rr) % self.n_frontends
            order = [self._frontends[(start + i) % self.n_frontends]
                     for i in range(self.n_frontends)]
        keys = list(keys)
        last_dead: Optional[int] = None
        for attempt, fe in enumerate(order):
            self._faulted(job, operator, fe)
            try:
                rep = self._dispatch(
                    fe, ("lookup", next(self._req_ids), job, operator,
                         keys))
            except _FrontendDead as e:
                last_dead = e.idx
                if attempt + 1 < len(order):
                    self.failovers += 1
                continue
            return rep[2]
        raise RuntimeError(
            f"no live frontend to serve lookup (last dead: "
            f"{last_dead}; {len(self._frontends)} configured)")

    def drive(self, job: str, operator: str, keys,
              batch: int = 256, batches: int = 100,
              frontends: Optional[List[int]] = None
              ) -> List[Dict[str, float]]:
        """Run the self-driving probe loop CONCURRENTLY on the chosen
        frontends (the multi-process bench body) and return each one's
        {probes, hits, wall_s}. Keys are pre-primed by the caller."""
        targets = [self._frontends[i] for i in
                   (frontends if frontends is not None
                    else self.live_frontends())]
        keys = np.asarray(keys, dtype=np.int64).tolist()
        results: List[Optional[Dict[str, float]]] = \
            [None] * len(targets)

        def run(slot: int, fe: _Frontend) -> None:
            rep = self._dispatch(
                fe, ("drive", next(self._req_ids), job, operator,
                     keys, int(batch), int(batches)))
            results[slot] = rep[2]

        threads = [threading.Thread(target=run, args=(s, fe))
                   for s, fe in enumerate(targets)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return [r for r in results if r is not None]

    # --------------------------------------------------------- metrics

    def fe_stats(self) -> List[Dict[str, int]]:
        """Per-frontend counters (probes / hits / torn_retries /
        miss_crossings), read owner-side off the shared arena headers."""
        return self.plane.hot_cache.fe_stats(self.n_frontends)

    def metrics(self) -> Dict[str, float]:
        rows = self.fe_stats()
        agg = {f"frontend_{k}": float(sum(r[k] for r in rows))
               for k in (rows[0].keys() if rows else ())}
        agg["frontends_configured"] = float(self.n_frontends)
        agg["frontends_live"] = float(len(self.live_frontends()))
        agg["frontend_failovers"] = float(self.failovers)
        return agg

    def register_metrics(self, group) -> None:
        """Fold the pool into a tenancy/serving metric group as live
        gauges (the discipline every plane here follows: gauges read
        the real counters, dashboards never see a second bookkeeping)."""
        if self._fe_group is not None:
            return
        self._fe_group = group.add_group("frontends")
        for name in ("frontends_configured", "frontends_live",
                     "frontend_failovers", "frontend_probes",
                     "frontend_hits", "frontend_torn_retries",
                     "frontend_miss_crossings"):
            self._fe_group.gauge(
                name, (lambda n=name: self.metrics().get(n, 0.0)))


class _FrontendDead(Exception):
    def __init__(self, idx: int) -> None:
        super().__init__(f"frontend {idx} is dead")
        self.idx = idx


# --------------------------------------------------------------- router

class LookupRouter:
    """DCN-aware lookup routing over the pod plane: send each key to
    the HOST owning its key-group range, so a multi-host serving
    deployment probes locally (its own shm frontends) instead of
    crossing DCN per key.

    ``lookup_fns[host]`` is that host's serving entry point — locally
    the :class:`FrontendPool` (or the plane itself), remotely whatever
    transport reaches that host's owner (the pod plane's DCN axis; in
    tests, an in-process stand-in). Ownership follows the LIVE
    ``KeyGroupAssignment`` when the skew responder has rebalanced
    (``set_assignment``) — the same source of truth the data plane
    routes by, so serving locality tracks rebalances instead of
    fighting them."""

    def __init__(self, num_hosts: int, local_devices: int,
                 max_parallelism: int, local_host: int,
                 lookup_fns: Dict[int, Callable],
                 assignment=None,
                 key_id_fn: Optional[Callable] = None) -> None:
        self.num_hosts = int(num_hosts)
        self.local_devices = int(local_devices)
        self.max_parallelism = int(max_parallelism)
        self.local_host = int(local_host)
        self.lookup_fns = dict(lookup_fns)
        self.assignment = assignment
        self.key_id_fn = key_id_fn
        self.local_keys = 0
        self.remote_keys = 0
        self.remote_batches = 0

    def set_assignment(self, assignment) -> None:
        """Follow a live key-group rebalance (PR 16): ownership moves
        with the groups, so the router keeps probing locally for keys
        whose group now lives here."""
        self.assignment = assignment

    def plan(self, keys) -> np.ndarray:
        """The owning host per key (the routing decision, testable on
        its own)."""
        from flink_tpu.state.keygroups import (
            assign_key_groups,
            hash_keys_to_i64,
        )

        arr = np.asarray(keys)
        kids = (self.key_id_fn(arr) if self.key_id_fn is not None
                else hash_keys_to_i64(arr))
        groups = assign_key_groups(np.asarray(kids, dtype=np.int64),
                                   self.max_parallelism)
        from flink_tpu.state.keygroups import host_of_key_group

        return host_of_key_group(
            groups, self.num_hosts, self.local_devices,
            self.max_parallelism, assignment=self.assignment)

    def lookup_batch(self, job: str, operator: str,
                     keys: Sequence[Any]) -> List[Any]:
        """Split the batch by owning host, dispatch each sub-batch to
        that host's entry point, compose results back in input order."""
        keys = list(keys)
        hosts = self.plan(keys)
        out: List[Any] = [None] * len(keys)
        for host in np.unique(hosts).tolist():
            idx = np.nonzero(hosts == host)[0].tolist()
            fn = self.lookup_fns.get(int(host))
            if fn is None:
                raise KeyError(
                    f"no serving endpoint for host {host} "
                    f"({len(idx)} keys routed there)")
            sub = [keys[i] for i in idx]
            res = fn(job, operator, sub)
            for i, val in zip(idx, res):
                out[i] = val
            if int(host) == self.local_host:
                self.local_keys += len(idx)
            else:
                self.remote_keys += len(idx)
                self.remote_batches += 1
        return out

    def metrics(self) -> Dict[str, float]:
        total = self.local_keys + self.remote_keys
        return {
            "router_local_keys": float(self.local_keys),
            "router_remote_keys": float(self.remote_keys),
            "router_remote_batches": float(self.remote_batches),
            "router_local_fraction": (
                self.local_keys / total if total else 0.0),
        }


def default_shm_dir(tag: str = "serving") -> str:
    """A /dev/shm-backed (when present) per-process default for the
    arena files — RAM-backed pages, no disk writeback on the hit path."""
    base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    if base is None:
        import tempfile

        base = tempfile.gettempdir()
    return os.path.join(base, f"flink_tpu_hc_{tag}_{os.getpid()}")
