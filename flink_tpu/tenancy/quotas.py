"""Per-job state-plane quotas: a bounded slice of resident rows per job.

Tenant isolation on the state plane has two halves:

1. **Structural** — each job's engines own their OWN [P, capacity]
   device arrays, host indexes, and spill tiers (per-job page
   directories under ``<spill_root>/job-<name>/``). Eviction machinery
   only ever walks the engine it runs on, so a job spilling under
   pressure can only evict its *own* cold rows — cross-job reclaim has
   no code path (pinned by tests/test_tenancy.py).
2. **Budgeted** — the quota bounds how many of a job's rows may stay
   device-resident. Size the env's ``state.slot-table.max-device-slots``
   with :meth:`TenantQuota.per_shard_slots` so steady-state eviction
   keeps each shard under its slice, and :class:`QuotaLedger.enforce`
   is the backstop at every scheduling quantum: an engine found over
   budget sheds its own cold rows through
   ``MeshSpillSupport.enforce_resident_budget``; a job STILL over
   budget after shedding (no spill tier, tier full, every row pinned)
   counts a ``quota_violations`` — the serving smoke fails on any.

reference: fine-grained resource management (slot sharing groups with
explicit resource profiles) — here the scarce resource is HBM-resident
state rows, and "preemption" is spilling to the job's own tier.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class TenantQuota:
    """A job's state-plane budget.

    ``max_resident_rows`` bounds device-resident state rows summed over
    the job's engines and shards (0 = unbounded). ``spill_dir`` is the
    job-private spill directory root — pages of different jobs never
    share a directory, so a corrupt / reclaimed tier is contained."""

    max_resident_rows: int = 0
    spill_dir: Optional[str] = None
    #: arbitration bounds for the cross-job shard arbiter
    min_shards: int = 1
    max_shards: int = 0  # 0 = devices

    def per_shard_slots(self, shards: int) -> int:
        """The per-shard ``max_device_slots`` this quota implies (the
        engine floor of 1024 is applied by the engine itself)."""
        if not self.max_resident_rows:
            return 0
        return max(self.max_resident_rows // max(int(shards), 1), 1024)


@dataclass
class QuotaLedger:
    """Runtime accounting of one job against its quota."""

    job: str
    quota: TenantQuota
    #: engines (windowers) bound at job open
    engines: List[object] = field(default_factory=list)
    #: times an over-budget engine could not shed (no spill tier)
    quota_violations: int = 0
    #: rows shed by enforce() (the backstop path, not steady-state
    #: eviction — steady state stays under budget via max_device_slots)
    rows_shed: int = 0

    def bind(self, operators) -> None:
        """Attach the job's stateful operators' engines (mesh engines
        expose ``shard_resident_rows``; others are counted read-only)."""
        for op in operators:
            eng = getattr(op, "windower", op)
            if not hasattr(eng, "shard_resident_rows"):
                # single-device layouts count through the OPERATOR's
                # shard_resident_rows fallback (slot-table index walk)
                # — unwrapping to the bare engine would silently make
                # the quota a no-op: resident 0 forever, never
                # enforced, never reported violated
                eng = op
            if eng not in self.engines:
                self.engines.append(eng)

    def resident_rows(self) -> int:
        total = 0
        for eng in self.engines:
            fn = getattr(eng, "shard_resident_rows", None)
            if fn is not None:
                total += int(sum(fn()))
        return total

    def pressure(self) -> float:
        """resident / budget in [0, ...]; 0.0 when unbounded — the
        arbiter's quota-pressure demand term."""
        if not self.quota.max_resident_rows:
            return 0.0
        return self.resident_rows() / float(self.quota.max_resident_rows)

    def enforce(self) -> int:
        """Backstop: shed the job's own cold rows until the job is back
        under budget. Returns rows shed; counts a violation per engine
        that cannot shed. Never touches another job's engines — the
        ledger only holds this job's."""
        budget = self.quota.max_resident_rows
        if not budget:
            return 0
        over = self.resident_rows() - budget
        if over <= 0:
            return 0
        shed = 0
        for eng in self.engines:
            if shed >= over:
                break
            shrink = getattr(eng, "enforce_resident_budget", None)
            rows = getattr(eng, "shard_resident_rows", None)
            if shrink is None or rows is None:
                continue
            if not getattr(eng, "_spill_active", False):
                # nowhere to shed to: the re-check below records the
                # violation. Pre-checking (rather than catching the
                # engine's RuntimeError) keeps genuine eviction
                # failures loud instead of silently swallowed
                continue
            total = int(sum(rows()))
            want = max(total - (over - shed), 0)
            shed += shrink(want)
        if self.resident_rows() > budget:
            # STILL over after shedding — whether because an engine has
            # no tier, its tier is full, or every row is pinned: the
            # budget is being violated and the gauge (and the serving
            # smoke's gate) must say so
            self.quota_violations += 1
        self.rows_shed += shed
        return shed

    def metrics(self) -> Dict[str, float]:
        return {
            "resident_rows": self.resident_rows(),
            "quota_rows": self.quota.max_resident_rows,
            "quota_pressure": self.pressure(),
            "quota_violations": self.quota_violations,
            "rows_shed": self.rows_shed,
        }
