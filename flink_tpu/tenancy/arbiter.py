"""Cross-job shard arbitration: the autoscaler lifted one level.

PR 4's controller scales ONE job's mesh to its own load. With N jobs on
one device pool the question changes: given a fixed shard budget, who
deserves how many? The arbiter answers with weighted proportional
shares: each job's demand is its backlog (records queued upstream,
normalized) plus its quota pressure (resident rows / quota — a job
pushing against its state budget wants more shards so each shard's
slice of the budget grows), shares are clamped to per-job [min, max]
bounds and the engine's key-group span, and largest-remainder rounding
keeps the total at the budget. Allocation changes drive each job's
existing LIVE ``reshard()`` (key-group migration, no stop-redeploy) —
the arbiter only decides WHO gets shards; HOW state moves is PR 4's
proven machinery, so outputs stay oracle-identical under arbitration.

A hysteresis band suppresses one-shard flapping, and a cooldown bounds
migration churn — the same guards the single-job policy uses
(flink_tpu.autoscale.policy), applied to the vector of jobs.

reference: the dispatcher's slot-sharing + fine-grained resource
profiles decide cluster-level placement; DS2-style demand estimation
per job feeds it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class JobDemand:
    """One job's arbitration inputs for a tick."""

    job: str
    current_shards: int
    #: records queued upstream (sourceBacklogRecordsEstimate)
    backlog: float = 0.0
    #: resident rows / quota rows (0 when unbounded)
    quota_pressure: float = 0.0
    min_shards: int = 1
    #: 0 = bounded only by the budget / key-group span
    max_shards: int = 0


class ShardArbiter:
    """Weighted proportional-share allocator over a shard budget."""

    def __init__(self, total_shards: int, hysteresis: int = 0,
                 cooldown_ticks: int = 2,
                 backlog_norm: float = 65536.0):
        #: total shards the cluster hands out per tick (each job's mesh
        #: is its own [n_j, cap] plane; the budget bounds the SUM so the
        #: per-chip working sets of co-resident jobs stay bounded)
        self.total_shards = int(total_shards)
        #: suppress reallocations smaller than this many shards
        self.hysteresis = int(hysteresis)
        self.cooldown_ticks = int(cooldown_ticks)
        self.backlog_norm = float(backlog_norm)
        self._since_change = self.cooldown_ticks  # first tick may act

    def decide(self, demands: List[JobDemand],
               dead_shards: int = 0) -> Dict[str, int]:
        """Per-job shard allocation for this tick (== current when the
        tick should not act). Deterministic in its inputs.

        ``dead_shards``: devices the watchdog has quarantined — a dead
        shard changes the budget, so the arbiter divides what actually
        answers, not the nameplate mesh size."""
        if not demands:
            return {}
        current = {d.job: int(d.current_shards) for d in demands}
        if self._since_change < self.cooldown_ticks:
            # still cooling down: cooldown_ticks=N suppresses exactly N
            # ticks after a reallocation (increment AFTER the compare —
            # before it, N suppressed only N-1 and 1 suppressed none)
            self._since_change += 1
            return current
        budget = max(self.total_shards - max(int(dead_shards), 0), 1)
        floor_sum = sum(max(d.min_shards, 1) for d in demands)
        if floor_sum > budget:
            # over-subscribed floors: everyone gets their floor (the
            # budget is advisory; correctness never depends on it)
            return {d.job: max(d.min_shards, 1) for d in demands}
        weights = {
            d.job: 1.0 + d.backlog / self.backlog_norm
            + max(d.quota_pressure, 0.0)
            for d in demands
        }
        total_w = sum(weights.values())
        # ideal shares, then clamp to [min, max]; redistribute the slack
        # by largest remainder among unclamped jobs
        alloc: Dict[str, int] = {}
        remainders: List = []
        spent = 0
        for d in demands:
            ideal = budget * weights[d.job] / total_w
            lo = max(d.min_shards, 1)
            hi = d.max_shards or budget
            share = min(max(int(math.floor(ideal)), lo), hi)
            alloc[d.job] = share
            spent += share
            if share < hi:
                remainders.append((ideal - math.floor(ideal), d.job, hi))
        remainders.sort(reverse=True)
        for _, job, hi in remainders:
            if spent >= budget:
                break
            if alloc[job] < hi:
                alloc[job] += 1
                spent += 1

        def shed_excess() -> None:
            # lo clamps (and the hysteresis re-pin below) can push the
            # sum past the budget. Shed one shard at a time from the
            # job whose allocation most exceeds its ideal share and is
            # still above its floor; floor_sum <= budget (the
            # over-subscribed case returned earlier) guarantees
            # termination.
            spent = sum(alloc.values())
            while spent > budget:
                cand = max(
                    (d for d in demands
                     if alloc[d.job] > max(d.min_shards, 1)),
                    key=lambda d: (
                        alloc[d.job] - budget * weights[d.job] / total_w,
                        d.job),
                    default=None)
                if cand is None:  # pragma: no cover - floors <= budget
                    break
                alloc[cand.job] -= 1
                spent -= 1

        shed_excess()
        # hysteresis: ignore sub-band moves (migration is not free)
        for d in demands:
            if abs(alloc[d.job] - current[d.job]) <= self.hysteresis:
                alloc[d.job] = current[d.job]
        # the re-pin hands pinned jobs back the shards the shed pass
        # (or the remainder pass) took from them, so the sum can climb
        # over the budget again — shed once more; the budget invariant
        # beats the flap band
        shed_excess()
        if any(alloc[d.job] != current[d.job] for d in demands):
            self._since_change = 0
        return alloc
