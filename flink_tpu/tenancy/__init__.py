"""Multi-tenant session cluster: N jobs multiplexed over ONE device mesh.

The production gap this closes (ROADMAP item 3): the cluster so far ran
one bench job, while the reference's dispatcher / slot-sharing /
fine-grained-resource layers exist precisely to run MANY jobs on shared
hardware. Four pillars:

- **Shared compiled-program cache** (:mod:`program_cache`): step/fire/
  evict/harvest XLA programs keyed on (kind, layout, device ids) —
  job K+1 reuses job K's executables, zero per-job steady-state
  compiles (sentinel-gated in ``tools/serving_smoke.py``).
- **Per-job state-plane quotas** (:mod:`quotas`): each job's engines get
  a bounded slice of resident [P, cap] rows with per-job spill
  directories; over-quota jobs spill their OWN cold rows — never
  another job's (no cross-job reclaim, by construction and by test).
- **Fair batch interleaving** (:mod:`fairness` + :mod:`session_cluster`):
  deficit-round-robin over per-job ready queues with per-job
  ``busyTimeMsTotal``, so one hot job cannot starve the rest.
- **Read-replica serving plane** (:mod:`serving` + :mod:`replica` +
  :mod:`hot_cache`, r17): engines publish a bounded delta into a
  double-buffered device-resident replica at fire/watermark
  boundaries (snapshot isolation, zero contention with ingest); the
  publish harvest primes a host hot-row cache so hot-key lookups
  never touch the device, and cache misses batch per sealed
  generation on sharded worker queues — one gather program + ONE
  ``jax.device_get`` per miss batch (the flint TRC01 discipline),
  measured as the ``queryable_lookups_per_s`` bench row. The legacy
  control-queue coalescers remain for single-device engines and the
  cold-row (page tier) detour. Since r19 the hit path is NATIVE
  (:mod:`hot_cache_native` over ``native/hotcache.cpp``): a whole key
  batch probes a GIL-free seqlock-stamped table of packed composed
  results in ONE C call, results stay packed until a consumer reads
  them (``lookup_batch_packed``), publishes prime via one packed
  buffer, and sessions PRIME under their moving end instead of
  invalidating — measured 1.14M lookups/s vs the 477k same-box dict
  control.

The autoscaler composes one level up (:mod:`arbiter`): shard budgets
are arbitrated BETWEEN jobs (weighted by backlog + quota pressure),
driving each job's existing live ``reshard()``.

This ``__init__`` stays import-light (``program_cache`` is imported by
the lowest engine layers); the cluster-facing classes load lazily.
"""

from flink_tpu.tenancy.program_cache import (  # noqa: F401
    PROGRAM_CACHE,
    SharedProgramCache,
)

_LAZY = {
    "TenantQuota": "flink_tpu.tenancy.quotas",
    "QuotaLedger": "flink_tpu.tenancy.quotas",
    "DeficitRoundRobin": "flink_tpu.tenancy.fairness",
    "ServingPlane": "flink_tpu.tenancy.serving",
    "LookupCoalescer": "flink_tpu.tenancy.serving",
    "ReplicaPlane": "flink_tpu.tenancy.replica",
    "SessionReplicaAdapter": "flink_tpu.tenancy.replica",
    "WindowReplicaAdapter": "flink_tpu.tenancy.replica",
    "JoinSideReplicaAdapter": "flink_tpu.tenancy.replica",
    "HotRowCache": "flink_tpu.tenancy.hot_cache",
    "PrimeDelta": "flink_tpu.tenancy.hot_cache",
    "make_hot_row_cache": "flink_tpu.tenancy.hot_cache",
    "NativeHotRowCache": "flink_tpu.tenancy.hot_cache_native",
    "PackedLookupResult": "flink_tpu.tenancy.serving",
    "ShardArbiter": "flink_tpu.tenancy.arbiter",
    "JobDemand": "flink_tpu.tenancy.arbiter",
    "SessionCluster": "flink_tpu.tenancy.session_cluster",
    "TenantJob": "flink_tpu.tenancy.session_cluster",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
