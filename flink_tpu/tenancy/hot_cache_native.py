"""Native hot-row probe table: the GIL-free serving cache wrapper.

The ctypes face of ``native/hotcache.cpp`` with the exact interface of
:class:`flink_tpu.tenancy.hot_cache.HotRowCache` (its bit-identical
Python fallback — selected by ``make_hot_row_cache`` the way
``make_session_meta`` picks the session-metadata plane). The cost model
it changes: a batched probe is ONE C call that releases the GIL — an
open-addressing probe plus a memcpy per hit — instead of N locked
Python dict accesses, so concurrent serving clients stop serializing
on the interpreter lock at cache-hit QPS, and the publish harvest
primes a whole boundary delta in ONE call instead of N ``put()``\\ s.

Layout: one native table per (job, operator). Entries hold PACKED
composed results — per namespace, the operator's finished value
columns as raw int64 bit patterns with a per-entry dtype tag bitmask,
so ``int64`` and ``float64`` round-trip EXACTLY. Results whose shape
cannot pack (join row lists, object columns, oversize compositions)
ride a Python :class:`HotRowCache` overflow store with identical
semantics; the batched probe falls through to it only for keys the
native table missed, and only when the (job, operator) ever routed a
value there.

Seqlock discipline (the C side): writers flip an entry's stamp odd,
write, flip it even; readers re-check the stamp around the copy and a
torn read RETRIES then falls to the miss path — a probe can never
return a mixed-generation row, and readers never block behind the
publish writer.

Tables start small and GROW (a fresh, larger table swapped in; the old
one parks in a graveyard so in-flight readers stay safe) when the live
count presses the capacity — growth loses the cached entries, which
re-prime within a publish interval.
"""

from __future__ import annotations

import ctypes
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.native import (
    HC_FE_STAT_NAMES,
    HC_MAX_FRONTENDS,
    HC_STAT_EVICTIONS,
    HC_STAT_HITS,
    HC_STAT_MISSES,
    HC_STAT_OVERSIZE_DROPS,
    HC_STAT_PRIMES,
    HC_STAT_PUTS,
    HC_STAT_TORN_MISSES,
    HC_STAT_TORN_RETRIES,
    load_hotcache,
)
from flink_tpu.tenancy.hot_cache import HotRowCache, PrimeDelta

#: the owner's table registry inside ``shm_dir`` — frontends read it to
#: know which arena file serves which (job, operator), with the epoch
#: each arena was created under (owner-restart detector)
MANIFEST_NAME = "hotcache_manifest.json"

_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)
_u64p = ctypes.POINTER(ctypes.c_uint64)

#: namespaces a packed entry can hold; compositions wider than this
#: stay uncached (plain misses) or ride the Python overflow store
ENTRY_CAP = 8
#: first allocation per (job, operator) table; grows x4 toward the
#: cache bound under live-count pressure
MIN_TABLE_ENTRIES = 1 << 12

_EMPTY_I64 = np.zeros(0, dtype=np.int64)
_EMPTY_U64 = np.zeros(0, dtype=np.uint64)


def _ptr_i64(a: np.ndarray):
    return a.ctypes.data_as(_i64p)


class PackedProbe:
    """One batched probe's raw result: the packed entry buffers as the
    C call filled them — NO per-key Python materialization happened.
    ``hit[i]``/``counts[i]`` describe key i; hit entries sit compactly
    in ``ns``/``tags`` (and ``counts[i] * n_cols`` words in ``vals``).
    ``materialize(i)`` builds one key's composed dict on demand; the
    serving fast path hands these buffers to the client wrapper and
    dicts are only ever built for keys somebody actually reads —
    a frontend that serializes straight from the packed form never
    pays the interpreter for the hits at all."""

    __slots__ = ("hit", "counts", "ns", "vals", "tags", "cols",
                 "_offs")

    def __init__(self, hit, counts, ns, vals, tags,
                 cols: Tuple[str, ...]) -> None:
        self.hit = hit
        self.counts = counts
        self.ns = ns
        self.vals = vals
        self.tags = tags
        self.cols = cols
        self._offs = None

    def materialize(self, i: int):
        """Key i's composed result dict (None when counts say miss —
        callers consult ``hit`` first; a hit with 0 entries is ``{}``)."""
        if self._offs is None:
            self._offs = np.concatenate(
                ([0], np.cumsum(self.counts, dtype=np.int64)))
        lo = int(self._offs[i])
        hi = int(self._offs[i + 1])
        ncol = len(self.cols)
        res: Dict[int, dict] = {}
        fv = self.vals.view(np.float64)
        for e in range(lo, hi):
            tag = int(self.tags[e])
            base = e * ncol
            res[int(self.ns[e])] = {
                nm: (float(fv[base + ci]) if (tag >> ci) & 1
                     else int(self.vals[base + ci]))
                for ci, nm in enumerate(self.cols)}
        return res


class _Scratch:
    """Per-thread probe buffers with PREBUILT ctypes pointers — each
    ``.ctypes.data_as()`` conversion costs ~3 µs (it builds a fresh
    ctypeslib interface object), which at 10 pointers per probe dwarfed
    the ~5 µs C call itself. The scratch is reused across calls on one
    thread; the compact results are COPIED out (they are small — the
    hit entries only) so a lazily-consumed :class:`PackedProbe` never
    aliases buffers a later probe overwrites."""

    __slots__ = ("n", "ncol", "keys", "hit", "cnt", "ogen", "ons",
                 "ovals", "otags", "p_keys", "p_hit", "p_cnt",
                 "p_ogen", "p_ons", "p_ovals", "p_otags")

    def __init__(self) -> None:
        self.n = 0
        self.ncol = 0

    def ensure(self, n: int, ncol: int) -> None:
        if n <= self.n and ncol == self.ncol:
            return
        n = max(n, self.n, 256)
        self.n = n
        self.ncol = ncol
        self.keys = np.empty(n, dtype=np.int64)
        self.hit = np.empty(n, dtype=np.uint8)
        self.cnt = np.empty(n, dtype=np.int32)
        self.ogen = np.empty(n, dtype=np.int64)
        self.ons = np.empty(n * ENTRY_CAP, dtype=np.int64)
        self.ovals = np.empty(n * ENTRY_CAP * ncol, dtype=np.int64)
        self.otags = np.empty(n * ENTRY_CAP, dtype=np.uint64)
        self.p_keys = _ptr_i64(self.keys)
        self.p_hit = self.hit.ctypes.data_as(_u8p)
        self.p_cnt = self.cnt.ctypes.data_as(_i32p)
        self.p_ogen = _ptr_i64(self.ogen)
        self.p_ons = _ptr_i64(self.ons)
        self.p_ovals = _ptr_i64(self.ovals)
        self.p_otags = self.otags.ctypes.data_as(_u64p)


class _Table:
    """One (job, operator) native table + its packing schema. With a
    ``shm_path`` the arena is a MAP_SHARED file frontends hc_attach;
    without, it is the private heap arena (the single-process path,
    byte-for-byte today's behavior)."""

    __slots__ = ("ptr", "cols", "n_cols", "entries", "graveyard",
                 "shm_path", "epoch")

    def __init__(self, lib, cols: Tuple[str, ...], entries: int,
                 shm_path: Optional[str] = None) -> None:
        self.cols = cols
        self.n_cols = len(cols)
        self.entries = int(entries)
        self.shm_path = shm_path
        if shm_path is None:
            self.ptr = lib.hc_create(self.entries, self.n_cols,
                                     ENTRY_CAP)
        else:
            self.ptr = lib.hc_create_shared(
                shm_path.encode(), self.entries, self.n_cols,
                ENTRY_CAP)
        if not self.ptr:
            raise MemoryError("hc_create failed")
        self.epoch = int(lib.hc_epoch(self.ptr))
        #: old table pointers kept alive across growth swaps: a reader
        #: that grabbed the previous pointer must stay safe (freed on
        #: cache close)
        self.graveyard: List[int] = []


class NativeHotRowCache:
    """Drop-in :class:`HotRowCache` with the native probe table under
    it. See the module doc for the packing/overflow split."""

    def __init__(self, max_entries: int = 1 << 18,
                 shm_dir: Optional[str] = None) -> None:
        self._lib = load_hotcache()
        if self._lib is None:
            raise RuntimeError("native hotcache library unavailable")
        self.max_entries = int(max_entries)
        #: shared-memory mode: every table is a MAP_SHARED file arena
        #: under this directory (ideally /dev/shm-backed) plus a JSON
        #: manifest frontends poll to attach — None keeps the private
        #: heap arenas (zero frontends = exactly the one-process path)
        self.shm_dir = shm_dir
        self._shm_seq = 0
        if shm_dir is not None:
            os.makedirs(shm_dir, exist_ok=True)
        #: (job, operator) -> _Table (created on first packable value)
        self._tables: Dict[tuple, _Table] = {}
        #: (job, operator) whose values fundamentally cannot pack
        #: (non-dict results, object columns) — Python store only
        self._py_only: set = set()
        #: (job, operator) that ever routed a value to the overflow
        #: store (the probe falls through to it only for these)
        self._py_ops: set = set()
        #: overflow store: identical semantics, shared LRU bound
        self._py = HotRowCache(max_entries=max_entries)
        #: guards structural mutation AND every native WRITE path
        #: (prime/put/drop/clear): a writer that read a table pointer
        #: just before a growth migrate+swap would otherwise land its
        #: write in the retired graveyard table — a whole publish
        #: prime silently lost, and with presence-implies-validity
        #: probes that is stale-serving forever. Probes never take it
        #: (a probe against the just-retired pointer reads migrated,
        #: still-alive data — bounded to one race window). RLock:
        #: _maybe_grow runs inside locked writer sections.
        # function-level import: keep the frontend child's spawn
        # closure (FrontendCacheClient only) free of the observe plane
        from flink_tpu.observe.lock_sentinel import named_lock
        self._lock = named_lock("tenancy.native_cache", reentrant=True)
        self._closed = False
        #: per-thread probe scratch, one per column count (a thread
        #: alternating operators with different n_cols must not
        #: realloc + rebuild pointers every probe)
        self._tls = threading.local()

    def _scratch(self, n: int, ncol: int) -> _Scratch:
        pool = getattr(self._tls, "sc", None)
        if pool is None:
            pool = self._tls.sc = {}
        sc = pool.get(ncol)
        if sc is None:
            sc = pool[ncol] = _Scratch()
        sc.ensure(n, ncol)
        return sc

    def _probe_raw(self, tbl: _Table, key_ids, gen: int,
                   exact: bool) -> Tuple[int, "_Scratch", int]:
        """(hits, scratch, n): ONE GIL-released C call through the
        thread's prebuilt-pointer scratch."""
        keys = np.asarray(key_ids, dtype=np.int64)
        n = len(keys)
        sc = self._scratch(n, tbl.n_cols)
        np.copyto(sc.keys[:n], keys)
        hits = self._lib.hc_get_batch(
            tbl.ptr, n, sc.p_keys, int(gen) if exact else -1,
            sc.p_hit, sc.p_cnt, sc.p_ogen, sc.p_ons, sc.p_ovals,
            sc.p_otags)
        return hits, sc, n

    # ------------------------------------------------------------- tables

    def _next_shm_path(self) -> Optional[str]:
        """A FRESH arena filename per create (also per growth swap):
        re-using a path would mean truncating a file a live frontend
        has mapped — a fault, not a race. Old files unlink immediately
        after the swap; existing mappings keep their pages (POSIX), and
        frontends re-attach off the rewritten manifest."""
        if self.shm_dir is None:
            return None
        self._shm_seq += 1
        return os.path.join(self.shm_dir,
                            f"hc_{os.getpid()}_{self._shm_seq:05d}.arena")

    def _write_manifest(self) -> None:
        """Rewrite the frontend attach manifest (atomic rename). Called
        under ``self._lock`` after any structural change (new table,
        growth swap) so frontends always see a consistent registry:
        every listed path exists and its arena's epoch matches."""
        if self.shm_dir is None:
            return
        doc = {
            "version": 1,
            "seq": self._shm_seq,
            "tables": [
                {"job": j, "operator": op, "path": t.shm_path,
                 "cols": list(t.cols), "epoch": t.epoch,
                 "entries": t.entries}
                for (j, op), t in self._tables.items()
                if t.shm_path is not None],
        }
        path = os.path.join(self.shm_dir, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def _table_for(self, job: str, operator: str,
                   cols: Tuple[str, ...]) -> Optional[_Table]:
        key = (job, operator)
        tbl = self._tables.get(key)
        if tbl is not None:
            return tbl if tbl.cols == cols else None
        with self._lock:
            tbl = self._tables.get(key)
            if tbl is None:
                # shm tables allocate at the FULL cache bound up front:
                # growth would swap arena files under attached
                # frontends every x4 step — one fixed file per
                # (job, operator) keeps attachments stable for the
                # table's whole life (memory is the configured bound
                # either way; private tables keep the lazy ramp)
                entries = (self.max_entries if self.shm_dir is not None
                           else min(self.max_entries,
                                    MIN_TABLE_ENTRIES))
                tbl = _Table(self._lib, cols, entries,
                             shm_path=self._next_shm_path())
                self._tables[key] = tbl
                self._write_manifest()
            return tbl if tbl.cols == cols else None

    def _maybe_grow(self, tbl: _Table) -> None:
        """Grow a pressured table toward the cache bound (writer paths
        only). The swap is atomic at the Python attribute level; the
        outgoing pointer parks in the graveyard for reader safety."""
        if tbl.entries >= self.max_entries:
            return
        if self._lib.hc_len(tbl.ptr) * 2 < tbl.entries:
            return
        with self._lock:
            if self._lib.hc_len(tbl.ptr) * 2 < tbl.entries:
                return
            new_entries = min(tbl.entries * 4, self.max_entries)
            new_ptr = self._lib.hc_create(new_entries, tbl.n_cols,
                                          ENTRY_CAP)
            if not new_ptr:
                return
            # entries MIGRATE (one C sweep) and the retiring table's
            # counters fold forward, so growth loses nothing and stats
            # stay cumulative
            self._lib.hc_migrate(new_ptr, tbl.ptr)
            for which in range(8):
                self._lib.hc_add_stat(
                    new_ptr, which, self._lib.hc_stat(tbl.ptr, which))
            tbl.graveyard.append(tbl.ptr)
            tbl.ptr = new_ptr
            tbl.entries = new_entries

    def close(self) -> None:
        """Free the native tables (tests / explicit shutdown). Not safe
        concurrently with probes — callers quiesce first."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for tbl in self._tables.values():
                for p in tbl.graveyard:
                    self._lib.hc_destroy(p)
                self._lib.hc_destroy(tbl.ptr)
                if tbl.shm_path is not None:
                    try:
                        os.unlink(tbl.shm_path)
                    except OSError:
                        pass
            self._tables.clear()
            if self.shm_dir is not None:
                try:
                    os.unlink(os.path.join(self.shm_dir,
                                           MANIFEST_NAME))
                except OSError:
                    pass

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------------- packing

    @staticmethod
    def _pack_value(value, cols: Optional[Tuple[str, ...]]):
        """(cols, ns_list, vals_i64, tags) for a packable composed
        result, or None. Packable = dict of int namespace -> dict of
        numeric scalars with one consistent column set; int64 and
        float64 pack as exact bit patterns."""
        if not isinstance(value, dict):
            return None
        if not value:
            return (cols, [], _EMPTY_I64, _EMPTY_U64) \
                if cols is not None else None
        ns_list: List[int] = []
        rows: List[list] = []
        tags: List[int] = []
        for ns, row in value.items():
            if not isinstance(row, dict):
                return None
            try:
                ns_list.append(int(ns))
            except (TypeError, ValueError):
                return None
            if cols is None:
                cols = tuple(row.keys())
            elif len(row) != len(cols):
                return None
            tag = 0
            packed = []
            for ci, name in enumerate(cols):
                try:
                    v = row[name]
                except KeyError:
                    return None
                if isinstance(v, (bool, int, np.integer)):
                    packed.append(int(v))
                elif isinstance(v, (float, np.floating)):
                    packed.append(
                        np.float64(v).view(np.int64).item())
                    tag |= 1 << ci
                else:
                    return None
            rows.append(packed)
            tags.append(tag)
        if len(ns_list) > ENTRY_CAP:
            return None  # oversize: rides the overflow store
        vals = np.asarray(rows, dtype=np.int64).ravel()
        return (cols, ns_list,
                np.ascontiguousarray(vals),
                np.asarray(tags, dtype=np.uint64))

    @staticmethod
    def _pack_cols(u_cols) -> Optional[Tuple[np.ndarray, int]]:
        """(vals_i64 [U, n_cols] raveled, tag bitmask) for the delta's
        value columns, or None when a column cannot pack (object
        dtype). One tag for every entry — columns are dtype-uniform."""
        mats = []
        tag = 0
        for ci, (_name, col) in enumerate(u_cols):
            col = np.asarray(col)
            if np.issubdtype(col.dtype, np.floating):
                mats.append(col.astype(np.float64).view(np.int64))
                tag |= 1 << ci
            elif (np.issubdtype(col.dtype, np.integer)
                  or col.dtype == bool):
                mats.append(col.astype(np.int64))
            else:
                return None
        return np.ascontiguousarray(
            np.stack(mats, axis=1).ravel() if mats else _EMPTY_I64), tag

    # -------------------------------------------------------------- probes

    def get_many(self, job: str, operator: str, key_ids, gen: int,
                 out: list, misses: list, exact: bool = True) -> int:
        """Batched probe: ONE GIL-released C call for the whole batch,
        falling through to the overflow store only for keys the native
        table missed (and only when the op ever routed values there).
        Interface and results identical to ``HotRowCache.get_many``."""
        opkey = (job, operator)
        # flint: disable=LCK01 -- probes are deliberately lock-free
        # (see the _lock docstring): a pointer read racing a growth
        # swap probes the retired-but-alive table — bounded staleness,
        # never corruption, and the publish path holds the lock
        tbl = self._tables.get(opkey)
        if tbl is None:
            return self._py.get_many(job, operator, key_ids, gen, out,
                                     misses, exact=exact)
        hits, sc, n = self._probe_raw(tbl, key_ids, gen, exact)
        names = tbl.cols
        ncol = tbl.n_cols
        hit_l = sc.hit[:n].tolist()
        if hits:
            cnt_l = sc.cnt[:n].tolist()
            tot = int(sum(cnt_l))
            ns_l = sc.ons[:tot].tolist()
            tags_l = sc.otags[:tot].tolist()
            iv = sc.ovals[:tot * ncol]
            il = iv.tolist()
            fl = iv.view(np.float64).tolist()
            pos = 0
            vpos = 0
            if ncol == 1:  # the common agg shape: one output column
                nm0 = names[0]
                for i in range(n):
                    if not hit_l[i]:
                        misses.append((i, key_ids[i]))
                        continue
                    res: Dict[int, dict] = {}
                    for _e in range(cnt_l[i]):
                        res[ns_l[pos]] = {
                            nm0: fl[pos] if tags_l[pos] & 1
                            else il[pos]}
                        pos += 1
                    out[i] = res
                # flint: disable=LCK01 -- _py_ops only ever grows; a
                # stale negative read skips one overflow probe that
                # could not have entries yet (lock-free probe path)
                return hits if opkey not in self._py_ops else \
                    self._py_fallthrough(job, operator, gen, out,
                                         misses, exact, tbl, hits)
            for i in range(n):
                if not hit_l[i]:
                    misses.append((i, key_ids[i]))
                    continue
                res = {}
                for _e in range(cnt_l[i]):
                    tag = tags_l[pos]
                    res[ns_l[pos]] = {
                        nm: (fl[vpos + ci] if (tag >> ci) & 1
                             else il[vpos + ci])
                        for ci, nm in enumerate(names)}
                    pos += 1
                    vpos += ncol
                out[i] = res
        else:
            for i in range(n):
                misses.append((i, key_ids[i]))
        # flint: disable=LCK01 -- _py_ops only ever grows; stale
        # negative read is a skipped probe of a still-empty overflow
        if opkey in self._py_ops:
            return self._py_fallthrough(job, operator, gen, out,
                                        misses, exact, tbl, hits)
        return hits

    def _py_fallthrough(self, job: str, operator: str, gen: int,
                        out: list, misses: list, exact: bool, tbl,
                        hits: int) -> int:
        """Overflow fall-through: probe the Python store for the
        native misses; its counters absorb those keys' outcomes (the
        native table's miss count is rolled back so totals stay
        one-per-probe)."""
        if not misses:
            return hits
        still: list = []
        for i, kid in misses:
            h2, val = self._py.get(job, operator, int(kid), gen,
                                   exact=exact)
            if h2:
                out[i] = val
                hits += 1
            else:
                still.append((i, kid))
        self._lib.hc_add_stat(tbl.ptr, HC_STAT_MISSES, -len(misses))
        misses[:] = still
        return hits

    def get_many_packed(self, job: str, operator: str, key_ids,
                        gen: int, out: list, misses: list,
                        exact: bool = True):
        """The ZERO-COPY batched probe: one GIL-released C call, hits
        stay in the packed buffers (:class:`PackedProbe`) — no dict is
        built here. Overflow-store hits (rare: non-packable ops) land
        in ``out`` as materialized overrides. Returns ``(hits, probe)``
        — probe None when the op has no native table (caller takes the
        dict path)."""
        opkey = (job, operator)
        # flint: disable=LCK01 -- lock-free probe path (see the _lock
        # docstring): racing a growth swap reads the retired-but-alive
        # table, bounded staleness only
        tbl = self._tables.get(opkey)
        if tbl is None:
            return 0, None
        hits, sc, n = self._probe_raw(tbl, key_ids, gen, exact)
        if hits < n:
            hit_l = sc.hit[:n].tolist()
            for i in range(n):
                if not hit_l[i]:
                    misses.append((i, key_ids[i]))
            # flint: disable=LCK01 -- _py_ops only ever grows; stale
            # negative read skips a probe of a still-empty overflow
            if opkey in self._py_ops:
                hits = self._py_fallthrough(job, operator, gen, out,
                                            misses, exact, tbl, hits)
        # COPY the compact results out of the scratch: they are small
        # (hit entries only) and the probe object must stay valid past
        # this thread's next probe
        tot = int(sc.cnt[:n].sum())
        probe = PackedProbe(sc.hit[:n].copy(), sc.cnt[:n].copy(),
                            sc.ons[:tot].copy(),
                            sc.ovals[:tot * tbl.n_cols].copy(),
                            sc.otags[:tot].copy(), tbl.cols)
        return hits, probe

    def get(self, job: str, operator: str, key_id: int, gen: int,
            exact: bool = True) -> Tuple[bool, Any]:
        out: List[Any] = [None]
        misses: list = []
        hits = self.get_many(job, operator, [int(key_id)], gen, out,
                             misses, exact=exact)
        return (hits > 0), out[0]

    # -------------------------------------------------------------- writes

    def put(self, job: str, operator: str, key_id: int, gen: int,
            value: Any) -> None:
        self.put_many(job, operator, [key_id], gen, [value])

    def put_many(self, job: str, operator: str, key_ids, gen: int,
                 values) -> None:
        """Worker miss-resolution feed: pack every packable result into
        ONE C call (no-downgrade enforced per entry in the table);
        non-packable results route to the overflow store (and evict any
        stale native entry for the key, so exactly one store answers)."""
        with self._lock:  # writer: see _lock docstring (growth race)
            self._put_many_locked(job, operator, key_ids, gen, values)

    def _put_many_locked(self, job: str, operator: str, key_ids,
                         gen: int, values) -> None:
        opkey = (job, operator)
        py_only = opkey in self._py_only
        n_keys: List[int] = []
        n_off: List[int] = [0]
        n_ns: List[int] = []
        n_vals: List[np.ndarray] = []
        n_tags: List[np.ndarray] = []
        cols = None
        tbl = self._tables.get(opkey)
        if tbl is not None:
            cols = tbl.cols
        for kid, value in zip(key_ids, values):
            packed = None if py_only else self._pack_value(value, cols)
            if packed is None:
                self._py_ops.add(opkey)
                if not isinstance(value, dict):
                    self._py_only.add(opkey)
                    py_only = True
                self._py.put(job, operator, int(kid), gen, value)
                if tbl is not None:
                    self._lib.hc_drop(tbl.ptr, int(kid))
                continue
            cols, ns_list, vals, tags = packed
            if tbl is None:
                tbl = self._table_for(job, operator, cols)
                if tbl is None:  # schema clash: overflow route
                    self._py_ops.add(opkey)
                    self._py.put(job, operator, int(kid), gen, value)
                    continue
            n_keys.append(int(kid))
            n_off.append(n_off[-1] + len(ns_list))
            n_ns.extend(ns_list)
            n_vals.append(vals)
            n_tags.append(tags)
        if not n_keys:
            return
        keys_a = np.asarray(n_keys, dtype=np.int64)
        gens_a = np.full(len(n_keys), int(gen), dtype=np.int64)
        off_a = np.asarray(n_off, dtype=np.int64)
        ns_a = np.asarray(n_ns, dtype=np.int64) if n_ns else _EMPTY_I64
        vals_a = (np.concatenate(n_vals) if n_ns else _EMPTY_I64)
        tags_a = (np.concatenate(n_tags) if n_ns else _EMPTY_U64)
        self._lib.hc_put_batch(
            tbl.ptr, len(n_keys), _ptr_i64(keys_a), _ptr_i64(gens_a),
            _ptr_i64(off_a), _ptr_i64(ns_a), _ptr_i64(vals_a),
            tags_a.ctypes.data_as(_u64p))
        self._maybe_grow(tbl)
        if opkey in self._py_ops:
            # the key may have a stale overflow copy from before its
            # values became packable — exactly one store may answer
            for kid in n_keys:
                self._py.drop(job, operator, kid)

    def prime(self, job: str, operator: str, key_id: int, gen: int,
              updates: Optional[dict] = None, remove=(),
              insert_ok: bool = False) -> None:
        """Scalar prime (interface parity; the adapters feed
        :meth:`prime_batch`). Folds through the same packed path."""
        u_ns = []
        u_cols: List[Tuple[str, list]] = []
        if updates:
            cols = None
            for ns, row in updates.items():
                u_ns.append(int(ns))
                if cols is None:
                    cols = tuple(row.keys())
                    u_cols = [(nm, []) for nm in cols]
                for (nm, acc) in u_cols:
                    acc.append(row[nm])
        cols_np = [(nm, np.asarray(acc)) for nm, acc in u_cols]
        delta = PrimeDelta(
            keys=np.asarray([int(key_id)], dtype=np.int64),
            uoff=np.asarray([0, len(u_ns)], dtype=np.int64),
            u_ns=np.asarray(u_ns, dtype=np.int64),
            u_cols=cols_np,
            roff=np.asarray([0, len(tuple(remove))], dtype=np.int64),
            r_ns=np.asarray([int(r) for r in remove], dtype=np.int64),
            flags=np.asarray([1 if insert_ok else 0], dtype=np.uint8))
        self.prime_batch(job, operator, gen, delta)

    def prime_batch(self, job: str, operator: str, gen: int,
                    delta: PrimeDelta) -> None:
        """Publish-harvest feed: fold one boundary's flat delta in ONE
        GIL-released C call. Overflow-store entries for the same op get
        the identical fold (insert_ok stripped — inserts are the native
        table's job), so presence-implies-validity holds across both."""
        with self._lock:  # writer: see _lock docstring (growth race)
            self._prime_batch_locked(job, operator, gen, delta)

    def _prime_batch_locked(self, job: str, operator: str, gen: int,
                            delta: PrimeDelta) -> None:
        opkey = (job, operator)
        cols = tuple(nm for nm, _ in (delta.u_cols or []))
        packed = (None if opkey in self._py_only
                  else self._pack_cols(delta.u_cols or []))
        tbl = None
        if packed is not None:
            tbl = self._tables.get(opkey)
            if tbl is None and len(delta.u_ns):
                tbl = self._table_for(job, operator, cols)
            elif tbl is not None and len(delta.u_ns) \
                    and tbl.cols != cols:
                tbl = None  # schema clash
        if packed is None or (tbl is None and len(delta.u_ns)):
            # cannot pack: the overflow store takes the whole delta
            self._py_ops.add(opkey)
            self._py.prime_batch(job, operator, gen, delta)
            t = self._tables.get(opkey)
            if t is not None:
                for kid in delta.keys:
                    self._lib.hc_drop(t.ptr, int(kid))
            return
        if tbl is not None:
            vals_a, tag = packed
            keys_a = np.ascontiguousarray(
                np.asarray(delta.keys, dtype=np.int64))
            uoff_a = np.ascontiguousarray(
                np.asarray(delta.uoff, dtype=np.int64))
            u_ns_a = np.ascontiguousarray(
                np.asarray(delta.u_ns, dtype=np.int64))
            u_tags = np.full(len(u_ns_a), tag, dtype=np.uint64)
            roff_a = np.ascontiguousarray(
                np.asarray(delta.roff, dtype=np.int64))
            r_ns_a = np.ascontiguousarray(
                np.asarray(delta.r_ns, dtype=np.int64))
            flags_a = np.ascontiguousarray(
                np.asarray(delta.flags, dtype=np.uint8))
            self._lib.hc_prime_batch(
                tbl.ptr, len(keys_a), _ptr_i64(keys_a), int(gen),
                _ptr_i64(uoff_a), _ptr_i64(u_ns_a), _ptr_i64(vals_a),
                u_tags.ctypes.data_as(_u64p), _ptr_i64(roff_a),
                _ptr_i64(r_ns_a), flags_a.ctypes.data_as(_u8p))
            self._maybe_grow(tbl)
        if opkey in self._py_ops:
            strip = np.asarray(delta.flags, dtype=np.uint8) & 0xFE
            self._py.prime_batch(job, operator, gen, PrimeDelta(
                delta.keys, delta.uoff, delta.u_ns, delta.u_cols,
                delta.roff, delta.r_ns, strip))

    # ---------------------------------------------------------- lifecycle

    def drop(self, job: str, operator: str, key_id: int) -> None:
        with self._lock:  # writer: see _lock docstring (growth race)
            tbl = self._tables.get((job, operator))
            if tbl is not None:
                self._lib.hc_drop(tbl.ptr, int(key_id))
            py = (job, operator) in self._py_ops
        if py:
            self._py.drop(job, operator, key_id)

    def invalidate_op(self, job: str, operator: str) -> None:
        with self._lock:  # writer: see _lock docstring (growth race)
            tbl = self._tables.get((job, operator))
            if tbl is not None:
                self._lib.hc_clear(tbl.ptr)
        self._py.invalidate_op(job, operator)

    def invalidate_job(self, job: str) -> None:
        with self._lock:  # writer: see _lock docstring (growth race)
            for (j, _op), tbl in list(self._tables.items()):
                if j == job:
                    self._lib.hc_clear(tbl.ptr)
        self._py.invalidate_job(job)

    # ------------------------------------------------------------- metrics

    def _tables_snapshot(self) -> List["_Table"]:
        """Consistent list of live tables for metric scans: ``_tables``
        mutates under ``_lock`` (bind/grow), so an unlocked iteration
        can see the dict resize mid-walk. Counters stay monotonic
        either side of a swap — only the LIST copy needs the lock."""
        with self._lock:
            return list(self._tables.values())

    def _sum_stat(self, which: int) -> int:
        return sum(int(self._lib.hc_stat(t.ptr, which))
                   for t in self._tables_snapshot())

    @property
    def hits(self) -> int:
        return self._sum_stat(HC_STAT_HITS) + self._py.hits

    @property
    def misses(self) -> int:
        return self._sum_stat(HC_STAT_MISSES) + self._py.misses

    @property
    def evictions(self) -> int:
        return self._sum_stat(HC_STAT_EVICTIONS) + self._py.evictions

    @property
    def primes(self) -> int:
        return self._sum_stat(HC_STAT_PRIMES) + self._py.primes

    @property
    def torn_retries(self) -> int:
        return self._sum_stat(HC_STAT_TORN_RETRIES)

    @property
    def torn_misses(self) -> int:
        return self._sum_stat(HC_STAT_TORN_MISSES)

    def __len__(self) -> int:
        return (sum(int(self._lib.hc_len(t.ptr))
                    for t in self._tables_snapshot()) + len(self._py))

    def hit_rate(self) -> float:
        h, m = self.hits, self.misses
        total = h + m
        return h / total if total else 0.0

    def stats(self) -> Dict[str, float]:
        h, m = self.hits, self.misses
        total = h + m
        return {
            "hot_row_hits": float(h),
            "hot_row_misses": float(m),
            "hot_row_evictions": float(self.evictions),
            "hot_row_entries": float(len(self)),
            "hot_row_hit_rate": (h / total) if total else 0.0,
            "hot_row_native_tables": float(len(self._tables_snapshot())),
            "hot_row_torn_retries": float(self.torn_retries),
            "hot_row_torn_misses": float(self.torn_misses),
            "hot_row_oversize_drops": float(
                self._sum_stat(HC_STAT_OVERSIZE_DROPS)),
            "hot_row_native_puts": float(self._sum_stat(HC_STAT_PUTS)),
        }

    def fe_stats(self, n_frontends: int = HC_MAX_FRONTENDS
                 ) -> List[Dict[str, int]]:
        """Per-frontend counters read OWNER-SIDE off the shared arena
        headers (no IPC — the frontends accumulated them there via
        ``hc_fe_note`` / ``hc_get_batch_fe``), summed across this
        cache's tables: one dict per frontend slot with the
        ``HC_FE_STAT_NAMES`` keys. All-zero rows for unused slots."""
        rows = [dict.fromkeys(HC_FE_STAT_NAMES, 0)
                for _ in range(int(n_frontends))]
        for tbl in self._tables_snapshot():
            for fe in range(len(rows)):
                for which, name in enumerate(HC_FE_STAT_NAMES):
                    v = int(self._lib.hc_fe_stat(tbl.ptr, fe, which))
                    if v > 0:
                        rows[fe][name] += v
        return rows


class FrontendCacheClient:
    """The FRONTEND-process face of the shared hot cache: attach every
    arena the owner's manifest lists and probe them lock-free (the
    seqlock read protocol is address-free — an attached mapper is
    exactly as safe as an in-process reader thread). The hit path is
    shm-probe → :class:`PackedProbe`; nothing here ever takes a lock,
    touches the owner process, or imports the serving plane.

    Owner-restart discipline: each attachment remembers the epoch the
    manifest promised; ``refresh()`` re-reads the manifest when its
    ``seq`` moved or a probe-time ``hc_epoch`` check disagrees, then
    re-attaches the changed tables. A table the manifest no longer
    lists detaches (its unlinked file's pages stay valid while mapped,
    so in-flight probes on the OLD attachment were never at risk)."""

    def __init__(self, shm_dir: str, frontend_id: int = 0) -> None:
        self._lib = load_hotcache()
        if self._lib is None:
            raise RuntimeError("native hotcache library unavailable")
        if not (0 <= int(frontend_id) < HC_MAX_FRONTENDS):
            raise ValueError(
                f"frontend_id must be in [0, {HC_MAX_FRONTENDS})")
        self.shm_dir = shm_dir
        self.frontend_id = int(frontend_id)
        self._manifest_path = os.path.join(shm_dir, MANIFEST_NAME)
        self._manifest_seq = -1
        self._manifest_mtime = -1
        #: (job, operator) -> (ptr, cols, epoch, path)
        self._attached: Dict[tuple, tuple] = {}
        self._tls = threading.local()
        self.refresh()

    # ---------------------------------------------------------- attach

    def refresh(self) -> bool:
        """Re-read the manifest and (re-)attach changed tables.
        Returns True when the attachment set changed. Missing manifest
        (owner not up yet / shut down) detaches everything."""
        try:
            with open(self._manifest_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            changed = bool(self._attached)
            self._detach_all()
            self._manifest_seq = -1
            self._manifest_mtime = -1
            return changed
        try:
            self._manifest_mtime = os.stat(
                self._manifest_path).st_mtime_ns
        except OSError:
            self._manifest_mtime = -1
        changed = False
        want = {}
        for row in doc.get("tables", ()):
            want[(row["job"], row["operator"])] = row
        for key in list(self._attached):
            if key not in want:
                self._detach(key)
                changed = True
        for key, row in want.items():
            cur = self._attached.get(key)
            if cur is not None and cur[2] == row["epoch"]:
                continue  # same owner session: attachment still valid
            if cur is not None:
                self._detach(key)
            ptr = self._lib.hc_attach(row["path"].encode())
            if ptr and int(self._lib.hc_epoch(ptr)) == row["epoch"]:
                self._attached[key] = (ptr, tuple(row["cols"]),
                                       int(row["epoch"]), row["path"])
                changed = True
            elif ptr:
                # arena newer than the manifest copy we read — a
                # re-read next refresh picks the matching pair up
                self._lib.hc_destroy(ptr)
        self._manifest_seq = int(doc.get("seq", 0))
        return changed

    def _detach(self, key) -> None:
        ptr, _cols, _epoch, _path = self._attached.pop(key)
        self._lib.hc_destroy(ptr)  # attached mode: munmap only

    def _detach_all(self) -> None:
        for key in list(self._attached):
            self._detach(key)

    def close(self) -> None:
        self._detach_all()

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self._detach_all()
        except Exception:
            pass

    def tables(self) -> List[tuple]:
        return sorted(self._attached)

    # ----------------------------------------------------------- probes

    def _scratch(self, n: int, ncol: int) -> _Scratch:
        pool = getattr(self._tls, "sc", None)
        if pool is None:
            pool = self._tls.sc = {}
        sc = pool.get(ncol)
        if sc is None:
            sc = pool[ncol] = _Scratch()
        sc.ensure(n, ncol)
        return sc

    def probe(self, job: str, operator: str, key_ids,
              gen: int = -1, exact: bool = False):
        """One shm probe for the whole batch: ``(hits, probe, misses)``
        with ``probe`` a :class:`PackedProbe` (None when the table is
        not attached — every key is then a miss) and ``misses`` the
        indices to cross to the owner. Stale-attachment detection rides
        the probe: an epoch mismatch (the GRACEFUL owner-restart path —
        the retiring owner zeroes the arena's epoch word) triggers one
        refresh + re-probe, and a manifest mtime change (the CRASHED-
        owner path, where nobody retired the old arena) does the same
        at the cost of one stat per batch."""
        try:
            mt = os.stat(self._manifest_path).st_mtime_ns
        except OSError:
            mt = -1
        if mt != self._manifest_mtime:
            self.refresh()
        for _attempt in range(2):
            entry = self._attached.get((job, operator))
            if entry is None:
                self.refresh()
                entry = self._attached.get((job, operator))
                if entry is None:
                    return 0, None, list(range(len(key_ids)))
            ptr, cols, epoch, _path = entry
            if int(self._lib.hc_epoch(ptr)) != epoch:
                self.refresh()  # owner restarted: re-attach and retry
                continue
            keys = np.ascontiguousarray(
                np.asarray(key_ids, dtype=np.int64))
            n = len(keys)
            ncol = len(cols)
            sc = self._scratch(n, ncol)
            np.copyto(sc.keys[:n], keys)
            hits = self._lib.hc_get_batch_fe(
                ptr, self.frontend_id, n, sc.p_keys,
                int(gen) if exact else -1, sc.p_hit, sc.p_cnt,
                sc.p_ogen, sc.p_ons, sc.p_ovals, sc.p_otags)
            misses = ([] if hits == n else
                      [i for i, h in enumerate(sc.hit[:n].tolist())
                       if not h])
            tot = int(sc.cnt[:n].sum())
            probe = PackedProbe(sc.hit[:n].copy(), sc.cnt[:n].copy(),
                                sc.ons[:tot].copy(),
                                sc.ovals[:tot * ncol].copy(),
                                sc.otags[:tot].copy(), cols)
            return hits, probe, misses
        return 0, None, list(range(len(key_ids)))

    def note_miss_crossings(self, job: str, operator: str,
                            n: int) -> None:
        """Attribute ``n`` cold misses this frontend CROSSED to the
        owner for (the request-pipe trips) in the shared header."""
        entry = self._attached.get((job, operator))
        if entry is not None and n:
            self._lib.hc_fe_note(entry[0], self.frontend_id,
                                 0, 0, 0, int(n))
