"""Columnar record batches — the unit of data flow.

Where the reference moves one ``StreamRecord`` at a time through operator
``processElement`` calls (reference:
flink-runtime/src/main/java/org/apache/flink/streaming/runtime/io/AbstractStreamTaskNetworkInput.java:145,203),
this framework moves **columnar micro-batches**: a dict of NumPy arrays plus a
timestamp column. Vectorization is what lets one ``jax.jit``-ed kernel replace
millions of per-record virtual calls; it is the single most important design
departure from the reference.

A RecordBatch is immutable by convention (all transforms return new batches).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

import numpy as np

TIMESTAMP_FIELD = "__ts__"  # event-time, int64 epoch millis
KEY_ID_FIELD = "__key_id__"  # int64 key identity (set by key_by)

#: Changelog row kind (reference: flink-table-common RowKind.java / the
#: UPDATE_BEFORE/UPDATE_AFTER retraction pairs of GroupAggFunction.java:85).
#: Absent column == append-only stream (every row an INSERT).
ROWKIND_FIELD = "__rowkind__"
ROWKIND_INSERT = 0
ROWKIND_UPDATE_BEFORE = 1
ROWKIND_UPDATE_AFTER = 2
ROWKIND_DELETE = 3


from flink_tpu.core.annotations import public

def rowkind_signs(kinds: "np.ndarray") -> "np.ndarray":
    """+1 for accumulate rows (INSERT/UPDATE_AFTER), -1 for retraction rows
    (UPDATE_BEFORE/DELETE) — the changelog fold direction."""
    return np.where(
        (kinds == ROWKIND_UPDATE_BEFORE) | (kinds == ROWKIND_DELETE),
        np.int8(-1), np.int8(1))


@public
@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: np.dtype

    def __post_init__(self):
        object.__setattr__(self, "dtype", np.dtype(self.dtype))


@public
@dataclasses.dataclass(frozen=True)
class Schema:
    fields: Sequence[Field]

    @staticmethod
    def of(**name_to_dtype) -> "Schema":
        return Schema(tuple(Field(n, d) for n, d in name_to_dtype.items()))

    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)


def _as_array(v) -> np.ndarray:
    a = np.asarray(v)
    if a.dtype == object:
        # Strings and other non-numeric payloads stay as object arrays on the
        # host; they never reach the device (keys are hashed to int64 first).
        return a
    return a


@public
class RecordBatch:
    """An immutable columnar batch of records.

    columns: name -> np.ndarray, all of equal length. The reserved column
    ``__ts__`` holds event-time timestamps (int64 ms); ``__key_id__`` holds
    the int64 key identity once the stream is keyed.
    """

    __slots__ = ("columns", "_n")

    def __init__(self, columns: Mapping[str, np.ndarray]):
        cols = {k: _as_array(v) for k, v in columns.items()}
        n = None
        for k, v in cols.items():
            if v.ndim < 1:
                raise ValueError(f"column {k!r} must be at least 1-D")
            if n is None:
                n = v.shape[0]
            elif v.shape[0] != n:
                raise ValueError(
                    f"column {k!r} length {v.shape[0]} != batch length {n}")
        self.columns: Dict[str, np.ndarray] = cols
        self._n = 0 if n is None else int(n)

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_pydict(data: Mapping[str, Any], timestamps=None) -> "RecordBatch":
        cols = {k: _as_array(v) for k, v in data.items()}
        if timestamps is not None:
            cols[TIMESTAMP_FIELD] = np.asarray(timestamps, dtype=np.int64)
        return RecordBatch(cols)

    @staticmethod
    def from_rows(rows: Iterable[Mapping[str, Any]]) -> "RecordBatch":
        rows = list(rows)
        if not rows:
            return RecordBatch({})
        names = rows[0].keys()
        return RecordBatch({n: _as_array([r[n] for r in rows]) for n in names})

    @staticmethod
    def empty_like(other: "RecordBatch") -> "RecordBatch":
        return RecordBatch({k: v[:0] for k, v in other.columns.items()})

    # -- basic accessors ----------------------------------------------------

    def __len__(self) -> int:
        return self._n

    @property
    def num_records(self) -> int:
        return self._n

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def __getitem__(self, name: str) -> np.ndarray:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def names(self) -> List[str]:
        return list(self.columns.keys())

    @property
    def timestamps(self) -> np.ndarray:
        return self.columns[TIMESTAMP_FIELD]

    @property
    def has_timestamps(self) -> bool:
        return TIMESTAMP_FIELD in self.columns

    @property
    def key_ids(self) -> np.ndarray:
        return self.columns[KEY_ID_FIELD]

    @property
    def is_keyed(self) -> bool:
        return KEY_ID_FIELD in self.columns

    @property
    def row_kinds(self) -> Optional[np.ndarray]:
        """Changelog kinds column, or None for an append-only batch."""
        return self.columns.get(ROWKIND_FIELD)

    # -- transforms (all return new batches) --------------------------------

    def with_column(self, name: str, values) -> "RecordBatch":
        cols = dict(self.columns)
        cols[name] = _as_array(values)
        return RecordBatch(cols)

    def with_timestamps(self, ts) -> "RecordBatch":
        return self.with_column(TIMESTAMP_FIELD, np.asarray(ts, dtype=np.int64))

    def drop(self, *names: str) -> "RecordBatch":
        return RecordBatch({k: v for k, v in self.columns.items() if k not in names})

    def select(self, *names: str) -> "RecordBatch":
        return RecordBatch({k: self.columns[k] for k in names})

    def rename(self, mapping: Mapping[str, str]) -> "RecordBatch":
        return RecordBatch({mapping.get(k, k): v for k, v in self.columns.items()})

    def filter(self, mask: np.ndarray) -> "RecordBatch":
        mask = np.asarray(mask, dtype=bool)
        return RecordBatch({k: v[mask] for k, v in self.columns.items()})

    def take(self, indices: np.ndarray) -> "RecordBatch":
        return RecordBatch({k: v[indices] for k, v in self.columns.items()})

    def slice(self, start: int, stop: int) -> "RecordBatch":
        return RecordBatch({k: v[start:stop] for k, v in self.columns.items()})

    @staticmethod
    def concat(batches: Sequence["RecordBatch"]) -> "RecordBatch":
        batches = [b for b in batches if len(b) > 0]
        if not batches:
            return RecordBatch({})
        if len(batches) == 1:
            return batches[0]
        names = batches[0].names()
        return RecordBatch(
            {n: np.concatenate([b.columns[n] for b in batches]) for n in names})

    # -- interop ------------------------------------------------------------

    def to_pydict(self) -> Dict[str, list]:
        return {k: v.tolist() for k, v in self.columns.items()}

    def to_rows(self) -> List[Dict[str, Any]]:
        names = self.names()
        cols = [self.columns[n] for n in names]
        return [
            {n: c[i].item() if hasattr(c[i], "item") else c[i] for n, c in zip(names, cols)}
            for i in range(self._n)
        ]

    def schema(self) -> Schema:
        return Schema(tuple(Field(k, v.dtype) for k, v in self.columns.items()
                            if v.dtype != object))

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        cols = ", ".join(f"{k}:{v.dtype}" for k, v in self.columns.items())
        return f"RecordBatch(n={self._n}, {cols})"
