"""Serializers with versioned snapshots and compatibility resolution.

reference: flink-core/.../api/common/typeutils/TypeSerializer.java,
TypeSerializerSnapshot.java, TypeSerializerSchemaCompatibility.java. The
reference's contract — a serializer can snapshot its configuration into
state, and on restore the OLD snapshot is asked whether the NEW serializer
is compatible as-is / after migration / incompatible — is kept verbatim,
because it is what makes long-lived state survive job upgrades.

Re-design: serializers act on whole *columns* (NumPy arrays), not single
objects, and the wire format is a columnar block format (little-endian,
length-prefixed) rather than per-record tags. The same format is the
network/shuffle byte format (the Cython fast-coder analog — reference:
flink-python/pyflink/fn_execution/coder_impl_fast.pyx — gets a C++
implementation in native/, task of the record codec).

Wire format of one serialized batch (RowBatchSerializer):

    magic  'FTB1'
    u32    ncols
    per column:
        u16 name_len | name utf-8
        u8  kind     (0=numeric, 1=string, 2=pickle)
        u64 payload_len | payload

numeric payload:  u8 dtype_len | dtype str | raw little-endian array bytes
string payload:   u32 n | u32[n+1] byte offsets | utf-8 bytes
pickle payload:   pickle bytes (host-only columns; never on the device path)
"""

from __future__ import annotations

import dataclasses
import enum
import pickle
import struct
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

from flink_tpu.core.records import RecordBatch

MAGIC = b"FTB1"


class Compatibility(enum.Enum):
    """reference: TypeSerializerSchemaCompatibility."""

    COMPATIBLE_AS_IS = "as_is"
    COMPATIBLE_AFTER_MIGRATION = "after_migration"
    INCOMPATIBLE = "incompatible"


@dataclasses.dataclass(frozen=True)
class SerializerSnapshot:
    """Persisted serializer configuration (reference:
    TypeSerializerSnapshot — written into checkpoint metadata so restores
    can reason about format changes without the old code)."""

    serializer: str  # registry key
    version: int
    config: Mapping[str, Any]

    def restore_serializer(self) -> "TypeSerializer":
        cls = _REGISTRY[self.serializer]
        return cls.from_config(self.config)

    def resolve_compatibility(self, new: "TypeSerializer") -> Compatibility:
        if self.serializer != new.registry_key():
            return Compatibility.INCOMPATIBLE
        return new.compatibility_from(self)

    def to_json(self) -> Dict[str, Any]:
        return {"serializer": self.serializer, "version": self.version,
                "config": dict(self.config)}

    @staticmethod
    def from_json(d: Mapping[str, Any]) -> "SerializerSnapshot":
        return SerializerSnapshot(d["serializer"], d["version"], d["config"])


class TypeSerializer:
    """Column serializer. Subclasses set VERSION and implement the codec."""

    VERSION = 1

    # -- codec ---------------------------------------------------------------

    def serialize(self, values: np.ndarray) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes) -> np.ndarray:
        raise NotImplementedError

    # -- snapshot / compatibility -------------------------------------------

    @classmethod
    def registry_key(cls) -> str:
        return cls.__name__

    def config(self) -> Dict[str, Any]:
        return {}

    @classmethod
    def from_config(cls, config: Mapping[str, Any]) -> "TypeSerializer":
        return cls(**config)

    def snapshot(self) -> SerializerSnapshot:
        return SerializerSnapshot(self.registry_key(), self.VERSION,
                                  self.config())

    def compatibility_from(self, old: SerializerSnapshot) -> Compatibility:
        """Can THIS serializer read state written under ``old``?"""
        if old.config == self.config() and old.version == self.VERSION:
            return Compatibility.COMPATIBLE_AS_IS
        return Compatibility.INCOMPATIBLE

    def migrate(self, data: bytes, old: SerializerSnapshot) -> np.ndarray:
        """Read bytes written by the OLD serializer into the NEW format's
        values (reference: restore-with-migration path in
        StateSerializerProvider)."""
        return old.restore_serializer().deserialize(data)


class NumericArraySerializer(TypeSerializer):
    def __init__(self, dtype):
        self.dtype = np.dtype(dtype)

    def serialize(self, values: np.ndarray) -> bytes:
        arr = np.ascontiguousarray(values, dtype=self.dtype)
        ds = self.dtype.str.encode()
        return struct.pack("<B", len(ds)) + ds + arr.tobytes()

    def deserialize(self, data: bytes) -> np.ndarray:
        (n,) = struct.unpack_from("<B", data, 0)
        dt = np.dtype(data[1:1 + n].decode())
        return np.frombuffer(data, dtype=dt, offset=1 + n).copy()

    def config(self):
        return {"dtype": self.dtype.str}

    def compatibility_from(self, old: SerializerSnapshot) -> Compatibility:
        old_dt = np.dtype(old.config["dtype"])
        if old_dt == self.dtype:
            return Compatibility.COMPATIBLE_AS_IS
        # widening (int32->int64, float32->float64, int->float) is a safe
        # cast: readable after migration; narrowing is data loss -> refuse
        if np.can_cast(old_dt, self.dtype, casting="safe"):
            return Compatibility.COMPATIBLE_AFTER_MIGRATION
        return Compatibility.INCOMPATIBLE

    def migrate(self, data: bytes, old: SerializerSnapshot) -> np.ndarray:
        return old.restore_serializer().deserialize(data).astype(self.dtype)


class StringArraySerializer(TypeSerializer):
    def serialize(self, values: np.ndarray) -> bytes:
        encoded = [str(v).encode() for v in values.tolist()]
        offsets = np.zeros(len(encoded) + 1, dtype=np.uint32)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        return (struct.pack("<I", len(encoded)) + offsets.tobytes()
                + b"".join(encoded))

    def deserialize(self, data: bytes) -> np.ndarray:
        (n,) = struct.unpack_from("<I", data, 0)
        offsets = np.frombuffer(data, dtype=np.uint32, count=n + 1, offset=4)
        base = 4 + offsets.nbytes
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = data[base + offsets[i]:base + offsets[i + 1]].decode()
        return out

    def compatibility_from(self, old):
        return Compatibility.COMPATIBLE_AS_IS


class PickleArraySerializer(TypeSerializer):
    """Fallback for arbitrary host objects (the reference's KryoSerializer
    role). Never used on the device path."""

    def serialize(self, values: np.ndarray) -> bytes:
        return pickle.dumps(list(values.tolist()
                                 if isinstance(values, np.ndarray)
                                 else values))

    def deserialize(self, data: bytes) -> np.ndarray:
        out = np.empty(len(obj := pickle.loads(data)), dtype=object)
        out[:] = obj
        return out

    def compatibility_from(self, old):
        return Compatibility.COMPATIBLE_AS_IS


_KIND_CODE = {"numeric": 0, "string": 1, "object": 2}
_CODE_SER = {0: NumericArraySerializer, 1: StringArraySerializer,
             2: PickleArraySerializer}


class RowBatchSerializer(TypeSerializer):
    """Whole-RecordBatch codec over the columnar wire format above.

    Compatibility rules (reference: row/POJO serializer evolution —
    PojoSerializerSnapshot: new fields get defaults, removed fields are
    dropped, both = COMPATIBLE_AFTER_MIGRATION; per-field type changes
    resolve recursively):
    """

    def __init__(self, row_type):
        from flink_tpu.core.types import RowTypeInfo

        self.row_type: RowTypeInfo = row_type
        self._sers = {n: t.create_serializer()
                      for n, t in zip(row_type.names, row_type.types)}

    # -- codec ---------------------------------------------------------------

    def serialize(self, batch: RecordBatch) -> bytes:
        parts = [MAGIC, struct.pack("<I", len(self._sers))]
        for name, ser in self._sers.items():
            payload = ser.serialize(batch[name])
            nb = name.encode()
            kind = _KIND_CODE[self.row_type.field_type(name).kind]
            parts.append(struct.pack("<H", len(nb)) + nb
                         + struct.pack("<B", kind)
                         + struct.pack("<Q", len(payload)))
            parts.append(payload)
        return b"".join(parts)

    def deserialize(self, data: bytes) -> RecordBatch:
        if data[:4] != MAGIC:
            raise ValueError("bad magic — not a serialized batch")
        (ncols,) = struct.unpack_from("<I", data, 4)
        pos = 8
        cols: Dict[str, np.ndarray] = {}
        for _ in range(ncols):
            (nlen,) = struct.unpack_from("<H", data, pos)
            pos += 2
            name = data[pos:pos + nlen].decode()
            pos += nlen
            kind = data[pos]
            pos += 1
            (plen,) = struct.unpack_from("<Q", data, pos)
            pos += 8
            payload = data[pos:pos + plen]
            pos += plen
            ser = self._sers.get(name)
            if ser is None or _KIND_CODE[
                    self.row_type.field_type(name).kind] != kind:
                ser = _CODE_SER[kind]() if kind != 0 else None
                if ser is None:
                    ser = NumericArraySerializer(np.int64)  # dtype in payload
            cols[name] = ser.deserialize(payload)
        return RecordBatch(cols)

    # -- snapshot / compatibility -------------------------------------------

    def config(self):
        return self.row_type.to_config()

    @classmethod
    def from_config(cls, config):
        from flink_tpu.core.types import RowTypeInfo

        return cls(RowTypeInfo.from_config(config))

    def compatibility_from(self, old: SerializerSnapshot) -> Compatibility:
        from flink_tpu.core.types import RowTypeInfo

        old_rt = RowTypeInfo.from_config(old.config)
        if (old_rt.names == self.row_type.names
                and old_rt.types == self.row_type.types):
            return Compatibility.COMPATIBLE_AS_IS
        result = Compatibility.COMPATIBLE_AFTER_MIGRATION
        for name, t in zip(self.row_type.names, self.row_type.types):
            if name not in old_rt.names:
                continue  # new field: filled with defaults on migrate
            old_t = old_rt.field_type(name)
            c = t.create_serializer().compatibility_from(
                SerializerSnapshot(
                    t.create_serializer().registry_key(), 1,
                    old_t.create_serializer().config())
            ) if old_t.kind == t.kind else (
                Compatibility.INCOMPATIBLE)
            if c is Compatibility.INCOMPATIBLE:
                return Compatibility.INCOMPATIBLE
        return result

    def migrate(self, data: bytes, old: SerializerSnapshot) -> RecordBatch:
        """Read an old-format batch into the new row type: removed fields
        dropped, new fields default-filled (zeros / empty strings / None),
        changed dtypes safe-cast."""
        old_batch = old.restore_serializer().deserialize(data)
        n = len(old_batch)
        cols: Dict[str, np.ndarray] = {}
        for name, t in zip(self.row_type.names, self.row_type.types):
            if name in old_batch.columns:
                col = old_batch[name]
                if t.kind == "numeric":
                    col = col.astype(np.dtype(t.dtype))
                cols[name] = col
            elif t.kind == "numeric":
                cols[name] = np.zeros(n, dtype=np.dtype(t.dtype))
            else:
                fill = np.empty(n, dtype=object)
                fill[:] = "" if t.kind == "string" else None
                cols[name] = fill
        return RecordBatch(cols)


_REGISTRY: Dict[str, type] = {
    c.__name__: c for c in (
        NumericArraySerializer, StringArraySerializer, PickleArraySerializer,
        RowBatchSerializer)
}


def register_serializer(cls: type) -> type:
    """Extension point for user serializers (reference: custom
    TypeSerializer registration)."""
    _REGISTRY[cls.__name__] = cls
    return cls
