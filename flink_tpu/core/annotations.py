"""API stability annotations + enforcement.

reference: flink-annotations (@Public, @PublicEvolving, @Internal,
@Experimental) with ArchUnit rules asserting every class reachable from the
public API surface carries a stability marker. Here the decorators stamp
``__api_stability__`` and the enforcement lives in
tests/test_annotations_flamegraph.py (the ArchUnit role): everything
exported from ``flink_tpu``'s top level must be @public or
@public_evolving.
"""

from __future__ import annotations

PUBLIC = "public"
PUBLIC_EVOLVING = "public-evolving"
EXPERIMENTAL = "experimental"
INTERNAL = "internal"


def _stamp(level: str):
    def decorate(obj):
        obj.__api_stability__ = level
        return obj

    return decorate


#: stable API — breaking changes only at major versions
public = _stamp(PUBLIC)
#: public but may evolve between minor versions
public_evolving = _stamp(PUBLIC_EVOLVING)
#: may change or vanish at any time
experimental = _stamp(EXPERIMENTAL)
#: implementation detail, no compatibility promise
internal = _stamp(INTERNAL)


def stability_of(obj) -> str | None:
    return getattr(obj, "__api_stability__", None)
