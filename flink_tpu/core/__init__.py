from flink_tpu.core.config import ConfigOption, Configuration
from flink_tpu.core.records import RecordBatch, Schema, Field

__all__ = ["ConfigOption", "Configuration", "RecordBatch", "Schema", "Field"]
