"""Managed device-memory accounting across operators.

reference: flink-runtime/src/main/java/org/apache/flink/runtime/memory/
MemoryManager.java — the per-slot managed-memory pool batch/streaming
operators reserve pages from (RocksDB blocks, sort buffers, hash tables),
sized by ``taskmanager.memory.managed.size``; exhaustion fails the
reservation with the pool breakdown rather than OOM-killing the process.

Re-design: the unit is BYTES of device (HBM) accumulator state, not
32 KiB host segments — slot tables and pane rings reserve their array
footprint at creation and each growth, and release on dispose. One pool
per executor run covers every operator in the job, so a second windowed
aggregation can no longer silently push the first one's growth into an
opaque XLA allocation failure: the reservation error names every owner
and its bytes, and points at the spill tier as the pressure valve.
"""

from __future__ import annotations

import threading
from typing import Dict


class MemoryReservationError(RuntimeError):
    """A reservation would exceed the managed device budget."""


class MemoryManager:
    """Thread-safe byte-granular reservation pool (0 = unlimited)."""

    def __init__(self, budget_bytes: int = 0):
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._owners: Dict[str, int] = {}

    @property
    def reserved_bytes(self) -> int:
        with self._lock:
            return sum(self._owners.values())

    def usage(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._owners)

    def reserve(self, owner: str, nbytes: int) -> None:
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._lock:
            total = sum(self._owners.values())
            if self.budget_bytes and total + nbytes > self.budget_bytes:
                breakdown = ", ".join(
                    f"{o}={b:,}B" for o, b in sorted(
                        self._owners.items(), key=lambda kv: -kv[1]))
                raise MemoryReservationError(
                    f"managed device memory exhausted: {owner!r} asked "
                    f"for {nbytes:,}B but only "
                    f"{self.budget_bytes - total:,}B of the "
                    f"{self.budget_bytes:,}B budget "
                    f"(memory.device.size) remain. Reserved: "
                    f"[{breakdown or 'none'}]. Lower "
                    "state.slot-table.capacity, enable the spill tier "
                    "(state.slot-table.max-device-slots), or raise the "
                    "budget")
            self._owners[owner] = self._owners.get(owner, 0) + nbytes

    def release(self, owner: str, nbytes: int) -> None:
        with self._lock:
            cur = self._owners.get(owner, 0)
            left = cur - int(nbytes)
            if left > 0:
                self._owners[owner] = left
            else:
                self._owners.pop(owner, None)

    def release_all(self, owner: str) -> int:
        with self._lock:
            return self._owners.pop(owner, 0)
