"""Pluggable FileSystem abstraction.

reference: flink-core/.../core/fs/FileSystem.java (scheme-dispatched
pluggable filesystems: local, HDFS, S3, GCS... via flink-filesystems/*).
Re-design: a small SPI with two built-ins — local disk and an in-process
memory FS (tests, zero-egress environments). Cloud/DFS schemes register
through ``register_filesystem`` exactly like the reference's service
loader; in this container no cloud SDKs exist, so none are bundled.

Paths carry their scheme: ``file:///tmp/x``, ``mem://bucket/x``; bare
paths are local.
"""

from __future__ import annotations

import io
import os
import posixpath
import shutil
import threading
from typing import Dict, List, Tuple

_registry: Dict[str, "FileSystem"] = {}
_lock = threading.Lock()


class FileSystem:
    """SPI: byte-stream IO + the small directory surface snapshots need."""

    def open(self, path: str, mode: str = "rb"):
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def delete(self, path: str, recursive: bool = False) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Atomic within one filesystem (the snapshot-commit primitive)."""
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    def open(self, path: str, mode: str = "rb"):
        if "w" in mode or "a" in mode:
            os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                        exist_ok=True)
        return open(path, mode)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def mkdirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def listdir(self, path: str) -> List[str]:
        return os.listdir(path)

    def delete(self, path: str, recursive: bool = False) -> None:
        if os.path.isdir(path):
            if recursive:
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.rmdir(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)


class _MemFile(io.BytesIO):
    def __init__(self, fs: "InMemoryFileSystem", path: str, data: bytes,
                 writable: bool):
        super().__init__(data)
        if not writable:
            self.seek(0)
        else:
            self.seek(len(data))
        self._fs = fs
        self._path = path
        self._writable = writable

    def flush(self) -> None:
        # mirror local-FS visibility: a write-then-flush is observable by
        # readers even if close() is never reached
        super().flush()
        if self._writable:
            self._fs._store[self._path] = self.getvalue()

    def close(self) -> None:
        if self._writable:
            self._fs._store[self._path] = self.getvalue()
        super().close()


class InMemoryFileSystem(FileSystem):
    """Process-local FS (``mem://``): tests and scratch artifacts.

    Directory semantics are prefix-based like object stores.
    """

    def __init__(self):
        self._store: Dict[str, bytes] = {}
        self._dirs: set = set()

    def _norm(self, path: str) -> str:
        return posixpath.normpath(path).lstrip("/")

    def open(self, path: str, mode: str = "rb"):
        p = self._norm(path)
        if "r" in mode and "w" not in mode and "+" not in mode:
            if p not in self._store:
                raise FileNotFoundError(path)
            return _MemFile(self, p, self._store[p], writable=False)
        base = self._store.get(p, b"") if "a" in mode else b""
        return _MemFile(self, p, base, writable=True)

    def exists(self, path: str) -> bool:
        p = self._norm(path)
        return (p in self._store or p in self._dirs
                or any(k.startswith(p + "/") for k in self._store))

    def mkdirs(self, path: str) -> None:
        self._dirs.add(self._norm(path))

    def listdir(self, path: str) -> List[str]:
        p = self._norm(path)
        prefix = "" if p in (".", "") else p + "/"
        out = set()
        for k in list(self._store) + list(self._dirs):
            if k != p and k.startswith(prefix):
                out.add(k[len(prefix):].split("/")[0])
        return sorted(out)

    def delete(self, path: str, recursive: bool = False) -> None:
        p = self._norm(path)
        self._store.pop(p, None)
        self._dirs.discard(p)
        if recursive:
            for k in [k for k in self._store if k.startswith(p + "/")]:
                del self._store[k]
            self._dirs = {d for d in self._dirs
                          if not d.startswith(p + "/")}

    def rename(self, src: str, dst: str) -> None:
        s, d = self._norm(src), self._norm(dst)
        if s in self._store:
            self._store[d] = self._store.pop(s)
            return
        moved = False
        for k in [k for k in self._store if k.startswith(s + "/")]:
            self._store[d + k[len(s):]] = self._store.pop(k)
            moved = True
        if s in self._dirs or moved:
            self._dirs.discard(s)
            self._dirs.add(d)
        elif not moved:
            raise FileNotFoundError(src)


def register_filesystem(scheme: str, fs: FileSystem) -> None:
    """Plug a filesystem for a scheme (reference: FileSystemFactory SPI)."""
    with _lock:
        _registry[scheme] = fs


def get_filesystem(path: str) -> Tuple[FileSystem, str]:
    """Resolve ``path`` to (filesystem, scheme-local path)."""
    if "://" in path:
        scheme, rest = path.split("://", 1)
        with _lock:
            fs = _registry.get(scheme)
            known = sorted(_registry)
        if fs is None:
            raise ValueError(
                f"no filesystem registered for scheme {scheme!r} "
                f"(registered: {known})")
        return fs, rest
    with _lock:
        return _registry["file"], path


# built-ins
register_filesystem("file", LocalFileSystem())
register_filesystem("mem", InMemoryFileSystem())
