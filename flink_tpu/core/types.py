"""Type information — the framework's type system.

reference: flink-core/.../api/common/typeinfo/TypeInformation.java,
BasicTypeInfo.java, typeutils/RowTypeInfo; extraction in
api/java/typeutils/TypeExtractor.java.

Re-design: types describe *columns*, not scalar objects — the unit of data
is a columnar RecordBatch, so a type is (logical kind, numpy dtype) and a
row type is an ordered mapping of field name -> column type. Extraction is
trivial compared to the reference's 4k-LoC bytecode-level TypeExtractor:
NumPy dtypes carry the information already.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

from flink_tpu.core.records import RecordBatch, Schema


@dataclasses.dataclass(frozen=True)
class TypeInformation:
    """A column type: logical kind + physical dtype."""

    kind: str  # 'numeric' | 'string' | 'object'
    dtype: Optional[str] = None  # numpy dtype str for 'numeric'

    def create_serializer(self):
        from flink_tpu.core import serializers as ser

        if self.kind == "numeric":
            return ser.NumericArraySerializer(np.dtype(self.dtype))
        if self.kind == "string":
            return ser.StringArraySerializer()
        return ser.PickleArraySerializer()

    # -- extraction ----------------------------------------------------------

    @staticmethod
    def of(value: Any) -> "TypeInformation":
        """Extract from a dtype, numpy array, python scalar, or python type."""
        if isinstance(value, TypeInformation):
            return value
        if isinstance(value, np.ndarray):
            return TypeInformation._of_dtype(value.dtype)
        if isinstance(value, (np.dtype, type)) or isinstance(value, str):
            try:
                return TypeInformation._of_dtype(np.dtype(value))
            except TypeError:
                pass
        if isinstance(value, (bool, int, float, np.generic)):
            return TypeInformation._of_dtype(np.asarray(value).dtype)
        if isinstance(value, (str, bytes)):
            return STRING_TYPE_INFO
        return OBJECT_TYPE_INFO

    @staticmethod
    def _of_dtype(dt: np.dtype) -> "TypeInformation":
        if dt == object:
            return OBJECT_TYPE_INFO
        if dt.kind in "US":
            return STRING_TYPE_INFO
        return TypeInformation("numeric", dt.str)


STRING_TYPE_INFO = TypeInformation("string")
OBJECT_TYPE_INFO = TypeInformation("object")
LONG_TYPE_INFO = TypeInformation("numeric", np.dtype(np.int64).str)
INT_TYPE_INFO = TypeInformation("numeric", np.dtype(np.int32).str)
DOUBLE_TYPE_INFO = TypeInformation("numeric", np.dtype(np.float64).str)
FLOAT_TYPE_INFO = TypeInformation("numeric", np.dtype(np.float32).str)
BOOL_TYPE_INFO = TypeInformation("numeric", np.dtype(np.bool_).str)


@dataclasses.dataclass(frozen=True)
class RowTypeInfo:
    """Ordered field name -> column type (reference: RowTypeInfo /
    the Table layer's RowType)."""

    names: Sequence[str]
    types: Sequence[TypeInformation]

    def __post_init__(self):
        object.__setattr__(self, "names", tuple(self.names))
        object.__setattr__(self, "types", tuple(self.types))
        if len(self.names) != len(self.types):
            raise ValueError("names/types length mismatch")

    @staticmethod
    def of(**name_to_type) -> "RowTypeInfo":
        names, types = [], []
        for n, t in name_to_type.items():
            names.append(n)
            types.append(TypeInformation.of(t))
        return RowTypeInfo(names, types)

    @staticmethod
    def from_batch(batch: RecordBatch) -> "RowTypeInfo":
        names, types = [], []
        for n, col in batch.columns.items():
            names.append(n)
            types.append(TypeInformation.of(col))
        return RowTypeInfo(names, types)

    @staticmethod
    def from_schema(schema: Schema) -> "RowTypeInfo":
        return RowTypeInfo([f.name for f in schema.fields],
                           [TypeInformation._of_dtype(f.dtype)
                            for f in schema.fields])

    def field_type(self, name: str) -> TypeInformation:
        return self.types[self.names.index(name)]

    def create_serializer(self):
        from flink_tpu.core.serializers import RowBatchSerializer

        return RowBatchSerializer(self)

    def to_config(self) -> Dict[str, Any]:
        return {"names": list(self.names),
                "types": [dataclasses.asdict(t) for t in self.types]}

    @staticmethod
    def from_config(cfg: Mapping[str, Any]) -> "RowTypeInfo":
        return RowTypeInfo(cfg["names"],
                           [TypeInformation(**t) for t in cfg["types"]])
