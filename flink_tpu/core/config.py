"""Typed, layered configuration.

Semantic equivalent of the reference's ``ConfigOption``/``Configuration``
(reference: flink-core/src/main/java/org/apache/flink/configuration/ConfigOption.java:41,
Configuration.java): typed keys with defaults, deprecated-key fallbacks and
layered override (cluster config < per-job config < dynamic overrides).

Idiomatic-Python re-design: a ``ConfigOption`` is a small frozen descriptor;
``Configuration`` is a dict-backed store with typed access and layering via
``with_fallback``. No reflection, no YAML coupling (a YAML front-end can load
into a plain dict).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Generic, Iterator, List, Optional, Sequence, TypeVar

T = TypeVar("T")


from flink_tpu.core.annotations import public

@public
@dataclasses.dataclass(frozen=True)
class ConfigOption(Generic[T]):
    """A typed configuration key with a default.

    Mirrors the builder contract of the reference ConfigOption (key, type,
    default, description, deprecated/fallback keys) without the builder
    ceremony.
    """

    key: str
    default: Optional[T] = None
    type: type = str
    description: str = ""
    fallback_keys: Sequence[str] = ()

    def with_default(self, default: T) -> "ConfigOption[T]":
        return dataclasses.replace(self, default=default)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"ConfigOption({self.key!r}, default={self.default!r})"


def _coerce(value: Any, typ: type) -> Any:
    if value is None or typ is None:
        return value
    if isinstance(value, typ):
        return value
    if typ is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes", "on")
        return bool(value)
    if typ in (int, float, str):
        return typ(value)
    if typ is list and isinstance(value, str):
        return [v.strip() for v in value.split(";") if v.strip()]
    return value


@public
class Configuration:
    """Layered key/value store with typed access through ConfigOptions."""

    def __init__(self, data: Optional[Dict[str, Any]] = None) -> None:
        self._data: Dict[str, Any] = dict(data or {})
        self._fallback: Optional[Configuration] = None

    # -- typed access -------------------------------------------------------

    def get(self, option: ConfigOption[T]) -> Optional[T]:
        for key in (option.key, *option.fallback_keys):
            found, value = self._lookup(key)
            if found:
                return _coerce(value, option.type)
        return option.default

    def set(self, option: "ConfigOption[T] | str", value: T) -> "Configuration":
        key = option.key if isinstance(option, ConfigOption) else option
        self._data[key] = value
        return self

    def contains(self, option: "ConfigOption | str") -> bool:
        key = option.key if isinstance(option, ConfigOption) else option
        return self._lookup(key)[0]

    # -- raw access ---------------------------------------------------------

    def keys(self):
        """Every key visible through this configuration — own layer
        plus the fallback chain, own layer first on duplicates. The
        scan surface for prefix-keyed option namespaces (e.g.
        ``stateplane.backend.<family>``): a consumer that only probes
        the names it knows would silently ignore a typo'd key."""
        out = dict.fromkeys(self._data)
        fb = self._fallback
        while fb is not None:
            for k in fb._data:
                out.setdefault(k)
            fb = fb._fallback
        return list(out)

    def get_raw(self, key: str, default: Any = None) -> Any:
        found, value = self._lookup(key)
        return value if found else default

    def _lookup(self, key: str):
        if key in self._data:
            return True, self._data[key]
        if self._fallback is not None:
            return self._fallback._lookup(key)
        return False, None

    # -- layering -----------------------------------------------------------

    def with_fallback(self, other: "Configuration") -> "Configuration":
        """Return a new Configuration: self's entries override ``other``'s."""
        merged = Configuration(self._data)
        merged._fallback = other
        return merged

    def to_dict(self) -> Dict[str, Any]:
        base = self._fallback.to_dict() if self._fallback else {}
        base.update(self._data)
        return base

    def copy(self) -> "Configuration":
        c = Configuration(dict(self._data))
        c._fallback = self._fallback
        return c

    def keys(self) -> List[str]:
        return list(self.to_dict().keys())

    def __iter__(self) -> Iterator[str]:
        return iter(self.to_dict())

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Configuration({self.to_dict()!r})"


# ---------------------------------------------------------------------------
# Core options (colocated here; subsystem options live with their subsystem,
# mirroring the reference's option placement convention).
# ---------------------------------------------------------------------------

class CoreOptions:
    DEFAULT_PARALLELISM = ConfigOption(
        "parallelism.default", default=1, type=int,
        description="Default operator parallelism (number of key-group shards "
        "processed concurrently; on TPU this is the mesh size of the keyed axis).")
    MAX_PARALLELISM = ConfigOption(
        "pipeline.max-parallelism", default=128, type=int,
        description="Number of key groups (rescale granularity). Mirrors the "
        "reference default lower bound of 1<<7 "
        "(reference: KeyGroupRangeAssignment.java:32).")
    AUTO_WATERMARK_INTERVAL = ConfigOption(
        "pipeline.auto-watermark-interval-ms", default=200, type=int,
        description="Periodic watermark emission interval.")
    OBJECT_REUSE = ConfigOption(
        "pipeline.object-reuse", default=True, type=bool,
        description="Batches are immutable columnar arrays; reuse is always safe.")


class BatchOptions:
    """Micro-batching knobs — the analog of the reference's async state
    batching (reference: runtime/asyncprocessing/AsyncExecutionController.java:67
    batchSize / bufferTimeout)."""

    BATCH_SIZE = ConfigOption(
        "execution.micro-batch.size", default=8192, type=int,
        description="Max records per micro-batch handed to the device.")
    BATCH_TIMEOUT_MS = ConfigOption(
        "execution.micro-batch.timeout-ms", default=10, type=int,
        description="Max time to wait filling a micro-batch before flushing.")
    LATENCY_TARGET_MS = ConfigOption(
        "execution.micro-batch.latency-target-ms", default=0, type=int,
        description="Adaptive batch sizing: hold the per-batch processing "
        "time to a fraction of this latency budget by resizing the "
        "micro-batch online from an EMA of observed throughput "
        "(reference: BufferDebloater). 0 = fixed batch size.")
    MIN_BATCH_SIZE = ConfigOption(
        "execution.micro-batch.min-size", default=256, type=int,
        description="Lower bound for adaptive batch sizing.")
    MAX_DISPATCH_AHEAD = ConfigOption(
        "execution.pipeline.max-dispatch-batches", default=4, type=int,
        description="How many batches of device work the task loop may "
        "dispatch ahead of completion (per-batch fences). Smaller = "
        "tighter fire latency (a fire kernel queues behind at most this "
        "many batches); larger = more overlap headroom on "
        "high-latency device links.")
    ASYNC_FIRES = ConfigOption(
        "execution.window.async-fires", default=True, type=bool,
        description="Dispatch window fires asynchronously: the fire kernel "
        "and its device->host copies run while the loop keeps ingesting; "
        "the executor forwards results (and the covering watermark) once "
        "they land. Hides the device-link round-trip latency behind "
        "useful work (reference: AsyncExecutionController overlap).")
    IN_FLIGHT_BATCHES = ConfigOption(
        "execution.pipeline.in-flight-batches", default=2, type=int,
        description="Bounded prefetch depth per source: a pump thread "
        "polls/timestamps the next batches while the task loop drives the "
        "device (credit-style backpressure — the pump blocks when the loop "
        "falls behind; reference: RemoteInputChannel credit flow control). "
        "0 = poll sources inline on the task loop.")


class LatencyOptions:
    """The fire-latency tier: a watermark fire must cost a bounded delta,
    not a full-window harvest, and must never queue behind a
    multi-hundred-ms ingest dispatch (the Drizzle/Spark-Streaming
    micro-batch latency/throughput trade, applied to the device state
    plane — see README "Latency tier")."""

    FIRE_DEADLINE_MS = ConfigOption(
        "latency.fire-deadline-ms", default=0, type=int,
        description="Fire-latency budget in wall-clock ms. When > 0 the "
        "task loop splits each ingest micro-batch against this budget "
        "using the measured per-record step rate, harvesting landed "
        "async fires between the splits — a due fire is never stuck "
        "behind a full batch dispatch. Also the deadline the autoscale "
        "fire-latency signal judges p99 against. 0 (default) = whole "
        "batches, fires harvested at batch boundaries only.")
    PANE_PREAGG = ConfigOption(
        "latency.pane-preagg", default=True, type=bool,
        description="Incremental pane pre-aggregation for the panes "
        "window layout (state.window-layout=panes): maintain per-window "
        "running partials combined AT ABSORB, so a watermark fire "
        "gathers ONE partial ring row (the pane that closes) instead of "
        "merging the window's k slice rows (the full-window harvest). "
        "The full-harvest path remains as the fallback for windows "
        "without a maintained partial (and for this option = false). "
        "Float sums fold in record order rather than per-slice order, "
        "so f32 results can differ from the full harvest in the last "
        "ulp (exact for count/min/max and integer-valued sums).")


class ServingOptions:
    """The queryable-state read path (tenancy serving plane). The read
    replica decouples lookups from ingest: engines publish a bounded
    delta at fire/watermark boundaries into a double-buffered
    device-resident replica, serving workers resolve misses against
    the SEALED generation off the task loop, and the host hot-row
    cache (generation-invalidated) absorbs repeat traffic without
    touching the device at all. See README "Multi-tenant serving"."""

    REPLICA = ConfigOption(
        "serving.replica", default=True, type=bool,
        description="Arm the read-replica serving plane for jobs "
        "submitted to a tenancy session cluster: mesh engines publish "
        "a boundary delta per watermark (one device-to-device copy "
        "program, no D2H) and lookups resolve against the sealed "
        "generation — snapshot isolation, zero contention with "
        "ingest. false = every lookup takes the legacy control-queue "
        "path, serialized behind the owning job's batch boundaries "
        "(the pre-replica behavior; also the A/B lever the NOTES_r17 "
        "measurements use). Plain LocalExecutor runs never arm a "
        "replica regardless — publishing costs a per-boundary "
        "metadata diff that only pays off when something reads it.")
    PUBLISH_INTERVAL_MS = ConfigOption(
        "serving.replica.publish-interval-ms", default=0, type=int,
        description="Minimum milliseconds between replica publishes. "
        "0 (default) publishes at every fire/watermark boundary — the "
        "tightest staleness. > 0 batches boundaries under one publish: "
        "the per-boundary metadata diff is paid once per interval and "
        "the hot-row cache invalidates at a bounded rate (lookup "
        "staleness stays <= the interval + one boundary). The serving "
        "bench runs 25 ms; per-boundary costs only matter when "
        "boundaries are much more frequent than readers need.")
    # NOTE: the worker-pool size and hot-row cache capacity are
    # CLUSTER-scoped (one serving plane serves every tenant), so they
    # are constructor parameters of ServingPlane / SessionCluster, not
    # per-job config options.


class ExecutionModeOptions:
    """Bounded/batch execution (reference: RuntimeExecutionMode.BATCH,
    the adaptive batch scheduler deciding parallelism from data volume —
    scheduler/adaptivebatch/AdaptiveBatchScheduler.java — and bulk batch
    shuffle — SortMergeResultPartition.java)."""

    RUNTIME_MODE = ConfigOption(
        "execution.runtime-mode", default="streaming", type=str,
        description="'streaming' (default) or 'batch'. Batch mode requires "
        "bounded sources, suppresses intermediate watermarks (every "
        "window/aggregate fires exactly once at end-of-input), and ships "
        "coalesced bulk blocks through the shuffle instead of "
        "latency-sized micro-batches.")
    TARGET_RECORDS_PER_SUBTASK = ConfigOption(
        "execution.batch.target-records-per-subtask", default=1_000_000,
        type=int,
        description="Adaptive batch parallelism: with "
        "execution.stage-parallelism=-1 in batch mode, the keyed stage "
        "parallelism is ceil(estimated source records / this target), "
        "like the reference's adaptive batch scheduler deciding "
        "parallelism from produced data volume.")


class DeploymentOptions:
    """Subtask-expansion execution (reference: ExecutionGraph parallel
    expansion — DefaultExecutionGraph / Execution.deploy — where every
    JobVertex runs `parallelism` subtasks connected by the shuffle)."""

    STAGE_PARALLELISM = ConfigOption(
        "execution.stage-parallelism", default=0, type=int,
        description="Subtask count for the keyed stage. 0 (default) runs "
        "the whole pipeline in one task; N > 0 expands the job into "
        "source subtasks + N keyed subtasks connected through the shuffle "
        "service with key-group routing and aligned checkpoint barriers "
        "(reference: ExecutionJobVertex parallel expansion + "
        "KeyGroupStreamPartitioner).")
    STAGE_FALLBACK = ConfigOption(
        "execution.stage-fallback", default=False, type=bool,
        description="When execution.stage-parallelism is set but the "
        "graph shape is not stage-expandable, fall back to single-slot "
        "execution instead of failing the submission. Off by default: a "
        "user who asked for parallelism N should not silently get 1.")
    SOURCE_PARALLELISM = ConfigOption(
        "execution.source-parallelism", default=1, type=int,
        description="Subtask count for the source stage in multi-slot "
        "mode. Each source subtask receives open(subtask_index, "
        "parallelism) and must split its input accordingly.")
    STAGE_MESH_DEVICES = ConfigOption(
        "execution.stage-mesh-devices", default=0, type=int,
        description="Mesh x stage composition: devices each KEYED subtask "
        "opens its window engine over (a private sub-mesh sharded within "
        "the subtask's key-group range). 0 (default) = one device per "
        "subtask. Subtask expansion distributes across slots/hosts (the "
        "reference's distribution model); the sub-mesh distributes across "
        "chips within one subtask's jitted program (the SPMD model).")
    SHUFFLE_MODE = ConfigOption(
        "shuffle.mode", default="device", type=str,
        description="keyBy data plane for the mesh engines: 'device' "
        "(default) computes shard routing, segment sort and the record "
        "exchange INSIDE the compiled program (one flat device_put + "
        "all_to_all over the mesh axis, fused with the aggregate "
        "scatter — keyBy -> window -> aggregate is one XLA program); "
        "'host' keeps the explicit fallback: [shards, B] bucketing in "
        "host numpy + a sharded device_put per block. See "
        "flink_tpu/parallel/shuffle.py.")
    SHUFFLE_HOSTS = ConfigOption(
        "shuffle.hosts", default=0, type=int,
        description="Number of HOSTS the key-group mesh spans (the "
        "(hosts, local) factorization of the device axis). 0/1 (the "
        "default) keeps the flat single-axis exchange; >1 routes "
        "device-mode keyBy through the two-level ICI/DCN exchange "
        "(parallel/exchange2.py): stage 1 all_to_all over the "
        "intra-host axis, stage 2 batches only the cross-host residue "
        "over the hosts axis — on a multi-process pod mesh the hosts "
        "axis IS the process boundary; on one process it is a virtual "
        "factorization (tests/CI). Engines whose mesh size the count "
        "does not divide keep the flat exchange.")
    JOIN_MODE = ConfigOption(
        "join.mode", default="host", type=str,
        description="Execution plane for the DataStream interval join "
        "(KeyedStream.interval_join().between() — INNER): 'host' "
        "(default) buffers sides as columnar batches in host numpy "
        "(runtime/join_operators.py — also the semantics oracle); "
        "'device' runs the join over dual keyed slot tables on the "
        "mesh: both inputs ride the keyBy data plane co-partitioned "
        "by key group, and a banded segment-intersection program "
        "gathers/intersects/emits each batch's candidates "
        "(flink_tpu/joins/). Outer joins and the SQL planner's join "
        "operators stay on the host path regardless of this option.")
    CEP_MODE = ConfigOption(
        "cep.mode", default="host", type=str,
        description="Execution plane for CEP pattern matching "
        "(CEP.pattern() and SQL MATCH_RECOGNIZE): 'host' (default) "
        "threads each key's NFA through the Python per-event loop "
        "(cep/operator.py — also the semantics oracle); 'device' keeps "
        "per-key computation states as [P, capacity] bitmask columns "
        "on the key-group mesh and advances ALL keys' NFAs with one "
        "compiled gather/scan/scatter program per fire "
        "(flink_tpu/cep/mesh_engine.py), with completed matches "
        "queryable through the replica plane. Only bounded-partial "
        "patterns (fixed-length sequences, consecutive times(), "
        "SKIP_PAST_LAST_EVENT or NO_SKIP) compile to the device; "
        "anything else falls back LOUDLY to the host operator "
        "(cep.host_fallbacks metric).")
    SHUFFLE_SERVICE = ConfigOption(
        "shuffle.service", default="local", type=str,
        description="Registered ShuffleService transport connecting "
        "subtasks: 'local' (in-process bounded queues, credit-based) or "
        "'grpc' (cross-process batches over gRPC). Reference: "
        "ShuffleServiceFactory SPI.")
    SHUFFLE_CREDITS = ConfigOption(
        "shuffle.credits-per-channel", default=2, type=int,
        description="In-flight batches allowed per (producer, consumer) "
        "channel before the producer blocks — the credit-based flow "
        "control bound (reference: RemoteInputChannel.unannouncedCredit).")
    LOCAL_AGG = ConfigOption(
        "execution.local-agg", default=True, type=bool,
        description="Two-phase aggregation: pre-aggregate window "
        "contributions on the source stage before the keyed shuffle "
        "(at most one row per (key, slice) per batch), shrinking shuffle "
        "volume and defusing key skew (reference: "
        "MiniBatchLocalGroupAggFunction / agg-phase-strategy TWO_PHASE). "
        "Applies when the keyed stage is an aligned window aggregation.")


class StateOptions:
    TABLE_EXEC_OVER_ENGINE = ConfigOption(
        "table.exec.over.engine", default="auto", type=str,
        description="Compute engine for OVER windowed aggregations: "
        "'device' = one fused jitted kernel computes every frame of "
        "every key per fire (segmented scans + monotonicized "
        "searchsorted, runtime/over_device.py); 'host' = per-key-segment "
        "NumPy prefix scans (runtime/over_agg.py); 'auto' (default) = "
        "device when the frame family supports it (bounded RANGE "
        "MIN/MAX stays host). Reference operators: "
        "flink-table-runtime/.../over/RowTimeRowsBoundedPrecedingFunction.java:1.")
    TABLE_EXEC_STATE_TTL = ConfigOption(
        "table.exec.state.ttl", default=0, type=int,
        description="Idle-state retention for SQL operators, in ms: a "
        "GROUP BY accumulator or upsert-materializer key untouched this "
        "long is dropped (slot freed, snapshots shrink); a later arrival "
        "re-INSERTs. 0 (default) = keep state forever. The reference's "
        "table.exec.state.ttl / StateTtlConfig semantics (reference: "
        "flink-core/.../api/common/state/StateTtlConfig.java:1, "
        "flink-runtime/.../runtime/state/ttl/TtlStateFactory.java:1).")
    DEVICE_MEMORY_BUDGET = ConfigOption(
        "memory.device.size", default=0, type=int,
        description="Managed device (HBM) memory budget in BYTES shared "
        "by every stateful operator of a job — the "
        "taskmanager.memory.managed.size role (reference: "
        "MemoryManager.java). Slot tables and pane rings reserve their "
        "accumulator footprint from this pool at creation and each "
        "growth; an over-budget reservation fails with a per-operator "
        "breakdown instead of an opaque device OOM. 0 (default) = "
        "unlimited.")
    BACKEND = ConfigOption(
        "state.backend", default="tpu-slot-table", type=str,
        description="Keyed-state backend (flink_tpu.state.backends SPI): "
        "'tpu-slot-table' commits accumulators to the accelerator (HBM, "
        "with the spill tier beyond it); 'host-heap' commits them to the "
        "host CPU device — no accelerator traffic at all, the "
        "HashMapStateBackend role for small-state jobs. Third-party "
        "placements register via register_state_backend().")
    SLOT_CAPACITY = ConfigOption(
        "state.slot-table.capacity", default=1 << 20, type=int,
        description="Fixed slot capacity per keyed window state (XLA static shape).")
    CHECKPOINT_DIR = ConfigOption(
        "state.checkpoints.dir", default=None, type=str,
        description="Directory for checkpoint snapshots.")
    NUM_RETAINED = ConfigOption(
        "state.checkpoints.num-retained", default=3, type=int,
        description="Completed checkpoints to keep on disk (reference: "
        "state.checkpoints.num-retained). GC anchors on the newest "
        "checkpoints that PASS CRC verification: a torn/corrupt newest "
        "can never strand the job by deleting its fallback chain. "
        "Overrides execution.checkpointing.retained when both are set.")
    MAX_DEVICE_SLOTS = ConfigOption(
        "state.slot-table.max-device-slots", default=0, type=int,
        description="Device-resident slot budget per keyed state (HBM "
        "bound). 0 = unbounded (grow by doubling). When the budget is "
        "reached, cold namespaces spill to host memory and reload "
        "transparently on access (the RocksDB/ForSt beyond-memory role). "
        "At parallelism > 1 the budget applies PER DEVICE (each mesh "
        "shard owns one device's HBM), so total capacity scales with the "
        "mesh while each chip stays bounded.")
    WINDOW_LAYOUT = ConfigOption(
        "state.window-layout", default="auto", type=str,
        description="Keyed window state layout: 'slots' ((key, slice) "
        "slot table — the general engine: sessions, spill, mesh), "
        "'panes' (ring-of-slices x key-rows — fires are pure device "
        "reductions with no per-fire host->device transfer; aligned "
        "windows on one device only), or 'auto' (currently resolves to "
        "'slots'; flips to panes once hardware measurements land — "
        "bench.py measures both).")
    SPILL_DIR = ConfigOption(
        "state.spill.dir", default=None, type=str,
        description="Filesystem tier for spilled state (any core.fs "
        "scheme). None = spill stays in host memory.")
    SPILL_HOST_MAX_BYTES = ConfigOption(
        "state.spill.host-max-bytes", default=0, type=int,
        description="Host-memory budget for spilled namespaces before they "
        "overflow to state.spill.dir. 0 = unbounded host tier.")


class AutoscaleOptions:
    """Elastic rescaling of the keyed mesh (flink_tpu/autoscale/): a
    DS2-style policy reads the job metric tree and live-migrates key
    groups between mesh shards (reference: the reactive/adaptive
    scheduler pair + the k8s autoscaler's ScalingMetricEvaluator)."""

    ENABLED = ConfigOption(
        "autoscale.enabled", default=False, type=bool,
        description="Tick a scaling policy inside the task loop and "
        "LIVE-rescale mesh-sharded keyed operators (no stop-redeploy). "
        "Requires an operator running a mesh engine (parallelism > 1).")
    INTERVAL_MS = ConfigOption(
        "autoscale.interval-ms", default=1000, type=int,
        description="Policy sampling/decision interval.")
    UTILIZATION_TARGET = ConfigOption(
        "autoscale.utilization-target", default=0.7, type=float,
        description="Size the operator so busy fraction lands here; the "
        "headroom absorbs bursts without rescaling (DS2 utilization).")
    MIN_SHARDS = ConfigOption(
        "autoscale.min-shards", default=1, type=int,
        description="Lower bound on the mesh size.")
    MAX_SHARDS = ConfigOption(
        "autoscale.max-shards", default=0, type=int,
        description="Upper bound on the mesh size; 0 = the number of "
        "visible devices.")
    COOLDOWN_MS = ConfigOption(
        "autoscale.cooldown-ms", default=30_000, type=int,
        description="Minimum time between rescales.")
    HYSTERESIS = ConfigOption(
        "autoscale.hysteresis", default=0.25, type=float,
        description="Relative dead band: targets within this fraction of "
        "the current size are noise and ignored.")
    IMBALANCE_LIMIT = ConfigOption(
        "autoscale.imbalance-limit", default=2.0, type=float,
        description="Refuse to scale DOWN while max/mean resident rows "
        "per shard exceeds this — a hot shard under key skew is not "
        "spare capacity.")
    FIRE_BREACH_TICKS = ConfigOption(
        "autoscale.fire-breach-ticks", default=3, type=int,
        description="Consecutive policy ticks the fire-latency p99 must "
        "exceed latency.fire-deadline-ms before the fire-latency signal "
        "triggers a scale-up — a single slow harvest is noise, a "
        "sustained deadline miss is a capacity problem even when "
        "throughput keeps up.")


class CheckpointOptions:
    INTERVAL_MS = ConfigOption(
        "execution.checkpointing.interval-ms", default=0, type=int,
        description="Checkpoint interval; 0 disables periodic checkpoints.")
    EVERY_N_BATCHES = ConfigOption(
        "execution.checkpointing.every-n-source-batches", default=0, type=int,
        description="Deterministic trigger: checkpoint every N source "
        "batches (tests/benchmarks; 0 = use the time interval).")
    RETAINED = ConfigOption(
        "execution.checkpointing.retained", default=3, type=int,
        description="How many completed checkpoints to keep.")
    COMPRESSION = ConfigOption(
        "execution.checkpointing.compression", default=True, type=bool,
        description="Compress snapshot arrays (zlib inside .npz; the "
        "reference uses lz4/snappy for state artifacts).")
    INCREMENTAL = ConfigOption(
        "execution.checkpointing.incremental", default=False, type=bool,
        description="Write delta checkpoints (dirty rows + tombstones) "
        "between periodic full snapshots.")
    FULL_EVERY = ConfigOption(
        "execution.checkpointing.incremental.full-every", default=10,
        type=int,
        description="Consolidate: every Nth checkpoint is a full snapshot, "
        "bounding restore-chain length.")
    MODE = ConfigOption(
        "execution.checkpointing.mode", default="exactly-once", type=str)
    UNALIGNED = ConfigOption(
        "execution.checkpointing.unaligned", default=False, type=bool,
        description="Barriers overtake in-flight data; overtaken batches "
        "are persisted as channel state so a checkpoint completes in "
        "bounded time under backpressure (reference: "
        "ExecutionCheckpointingOptions.ENABLE_UNALIGNED). Savepoints "
        "remain aligned. Stage-parallel executor only.")


def retained_checkpoints(config) -> int:
    """Checkpoints to keep on disk: ``state.checkpoints.num-retained``
    (the reference's key) wins when explicitly set; the legacy
    ``execution.checkpointing.retained`` remains honored. The ONE copy
    of the precedence rule, shared by both executors."""
    if config.contains(StateOptions.NUM_RETAINED) or \
            not config.contains(CheckpointOptions.RETAINED):
        return config.get(StateOptions.NUM_RETAINED)
    return config.get(CheckpointOptions.RETAINED)


class WatchdogOptions:
    """Device watchdog (flink_tpu/runtime/watchdog.py): deadline-tracked
    device interactions on the mesh engines + shard quarantine — the
    detection half of shard-granular partial failover (the reference's
    HeartbeatManager role, scoped to one device/shard)."""

    ENABLED = ConfigOption(
        "watchdog.enabled", default=False, type=bool,
        description="Wrap mesh-engine device interactions (dispatch "
        "fences, fire harvests, device_get batches, serving lookups) in "
        "deadline-tracked watchdog sections; a shard past its miss "
        "budget is declared dead at the next batch boundary "
        "(ShardFailedError -> failover).")
    DEADLINE_MS = ConfigOption(
        "watchdog.deadline-ms", default=0, type=int,
        description="A device interaction slower than this records a "
        "deadline MISS against its shard(s); 0 tracks heartbeats only.")
    MAX_MISSES = ConfigOption(
        "watchdog.max-misses", default=3, type=int,
        description="Consecutive deadline misses a shard survives "
        "before being declared dead (timeout -> retry -> declare-dead "
        "escalation).")


class RestartOptions:
    """reference: RestartStrategyOptions (restart-strategy.* keys)."""

    STRATEGY = ConfigOption(
        "restart-strategy.type", default="fixed-delay", type=str,
        description="none | fixed-delay | exponential-delay | failure-rate.")
    MAX_ATTEMPTS = ConfigOption(
        "restart-strategy.max-attempts", default=3, type=int)
    DELAY_MS = ConfigOption(
        "restart-strategy.delay-ms", default=100, type=int)
    MAX_BACKOFF_MS = ConfigOption(
        "restart-strategy.exponential-delay.max-backoff-ms",
        default=60_000, type=int,
        description="Backoff ceiling for exponential-delay.")
    BACKOFF_MULTIPLIER = ConfigOption(
        "restart-strategy.exponential-delay.backoff-multiplier",
        default=2.0, type=float)
    JITTER_FACTOR = ConfigOption(
        "restart-strategy.exponential-delay.jitter-factor",
        default=0.0, type=float,
        description="Spread each backoff by +/- this fraction "
        "(thundering-herd protection across concurrent restarts).")
    RESET_BACKOFF_THRESHOLD_MS = ConfigOption(
        "restart-strategy.exponential-delay.reset-backoff-threshold-ms",
        default=0, type=int,
        description="After this long without failures the backoff and "
        "attempt budget reset to initial (0 = never reset; reference: "
        "ExponentialDelayRestartBackoffTimeStrategy).")
    FAILURE_RATE_INTERVAL_MS = ConfigOption(
        "restart-strategy.failure-rate.failure-rate-interval-ms",
        default=60_000, type=int,
        description="Sliding window for failure-rate counting.")


class ClusterOptions:
    NUM_TASK_EXECUTORS = ConfigOption(
        "cluster.task-executors", default=1, type=int)
    SLOTS_PER_EXECUTOR = ConfigOption(
        "taskmanager.numberOfTaskSlots", default=1, type=int)
    HEARTBEAT_INTERVAL_MS = ConfigOption(
        "heartbeat.interval-ms", default=500, type=int)
    HEARTBEAT_TIMEOUT_MS = ConfigOption(
        "heartbeat.timeout-ms", default=5000, type=int)
    REST_PORT = ConfigOption(
        "rest.port", default=0, type=int,
        description="REST status endpoint port; 0 = ephemeral, -1 = off.")
    RPC_PORT = ConfigOption(
        "rpc.port", default=0, type=int,
        description="Control-plane gRPC port (0 = ephemeral). Standalone "
        "deployments pin it so TaskExecutor processes can join "
        "(reference: jobmanager.rpc.port).")
    RPC_BIND_ADDRESS = ConfigOption(
        "rpc.bind-address", default="127.0.0.1", type=str,
        description="Address the control-plane gRPC server binds; use "
        "0.0.0.0 for cross-host standalone clusters (reference: "
        "jobmanager.rpc.address/bind-host).")
    RPC_ADVERTISED_ADDRESS = ConfigOption(
        "rpc.advertised-address", default="", type=str,
        description="Address peers use to CONNECT to this process "
        "(registered with the ResourceManager, returned in slot offers). "
        "Empty = the bind address, or the host's resolved IP when binding "
        "0.0.0.0 (reference: taskmanager.host).")


class SchedulerOptions:
    """reference: JobManagerOptions.SCHEDULER + adaptive scheduler knobs."""

    MODE = ConfigOption(
        "jobmanager.scheduler", default="default", type=str,
        description="'default' (fail fast when no resources) or 'adaptive' "
        "(wait for resources, rescale reactively on resource change — "
        "reference: scheduler/adaptive/AdaptiveScheduler.java).")
    RESOURCE_WAIT_TIMEOUT_MS = ConfigOption(
        "jobmanager.adaptive-scheduler.resource-wait-timeout-ms",
        default=30_000, type=int,
        description="How long WaitingForResources waits for a slot before "
        "the job fails.")
    RESOURCE_STABILIZATION_MS = ConfigOption(
        "jobmanager.adaptive-scheduler.resource-stabilization-timeout-ms",
        default=100, type=int,
        description="Settle time after a resource change before (re)acting "
        "on it.")


class HighAvailabilityOptions:
    """reference: HighAvailabilityOptions (high-availability.* keys)."""

    MODE = ConfigOption(
        "high-availability.type", default="none", type=str,
        description="'none' or 'filesystem' (file-lock leader election + "
        "persisted job graph store; the role ZooKeeper/K8s drivers play in "
        "the reference).")
    STORAGE_DIR = ConfigOption(
        "high-availability.storageDir", default=None, type=str,
        description="Directory for leader locks, job graph store and blobs.")
    LEASE_TIMEOUT_MS = ConfigOption(
        "high-availability.lease-timeout-ms", default=3000, type=int,
        description="Leader lease considered stale after this long without "
        "renewal.")
