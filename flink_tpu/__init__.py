"""flink_tpu — a TPU-native stream-processing framework.

A ground-up re-design of Apache Flink's semantic contracts (dataflow graph,
keyed partitioning by key-group, event-time watermarks + timers, trigger-based
windows, aligned-barrier exactly-once snapshots, pluggable state backend &
shuffle) executed as vectorized micro-batches on a TPU device mesh:

- records are columnar batches (``flink_tpu.core.records.RecordBatch``)
- per-key windowed state is a TPU-resident key->slot table
  (``flink_tpu.state.slot_table.SlotTable``)
- ``AggregateFunction.add`` over a batch is one jitted segment-reduce
  (``flink_tpu.ops.segment_ops``)
- ``keyBy`` shards the key-group axis over a ``jax.sharding.Mesh``
  (``flink_tpu.parallel``)
- window fires are masked segment-extracts triggered by watermark advance
- snapshots are async device_get of the slot arrays + host hash maps

Reference semantics: Apache Flink 2.x (see SURVEY.md). This is not a port;
the architecture is JAX/XLA-first.
"""

from flink_tpu.version import __version__

from flink_tpu.core.config import ConfigOption, Configuration
from flink_tpu.core.records import RecordBatch
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.datastream.stream import AsyncDataStream
from flink_tpu.runtime.process import (
    BroadcastProcessFunction,
    CoProcessFunction,
    KeyedProcessFunction,
    OutputTag,
    ProcessFunction,
)
from flink_tpu.state.keyed_state import (
    ListStateDescriptor,
    MapStateDescriptor,
    ReducingStateDescriptor,
    ValueStateDescriptor,
)

__all__ = [
    "__version__",
    "AsyncDataStream",
    "BroadcastProcessFunction",
    "ConfigOption",
    "Configuration",
    "CoProcessFunction",
    "KeyedProcessFunction",
    "ListStateDescriptor",
    "MapStateDescriptor",
    "OutputTag",
    "ProcessFunction",
    "RecordBatch",
    "ReducingStateDescriptor",
    "StreamExecutionEnvironment",
    "ValueStateDescriptor",
]
