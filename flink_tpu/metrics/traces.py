"""Structured trace spans for checkpoint/recovery/job-lifecycle durations.

reference: flink-metrics/flink-metrics-core/.../traces/Span.java +
SpanBuilder; reported via TraceReporter (slf4j or OpenTelemetry,
flink-metrics-otel/.../OpenTelemetryTraceReporter.java). The reference emits
spans for checkpointing and recovery durations (SURVEY.md §5).

Re-design: a thread-safe in-process collector; spans are plain records.
An OTel exporter can be attached where the package is available (not baked
into this image — gated import).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Span:
    scope: str
    name: str
    start_ts_ms: float
    end_ts_ms: float
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return self.end_ts_ms - self.start_ts_ms


class SpanBuilder:
    def __init__(self, collector: "TraceCollector", scope: str, name: str):
        self._collector = collector
        self._scope = scope
        self._name = name
        self._attributes: Dict[str, Any] = {}
        self._start: Optional[float] = None

    def set_attribute(self, key: str, value) -> "SpanBuilder":
        self._attributes[key] = value
        return self

    def __enter__(self) -> "SpanBuilder":
        self._start = time.time() * 1000
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        end = time.time() * 1000
        if exc_type is not None:
            self._attributes["error"] = repr(exc)
        self._collector.add(Span(self._scope, self._name, self._start, end,
                                 dict(self._attributes)))


#: process-default collector: control-plane paths that are not owned by
#: one job's executor (sharded checkpoint storage, the partial-failover
#: protocol) report their restore/replay durations here so they are
#: observable even when no per-job collector was threaded through
_DEFAULT: Optional["TraceCollector"] = None


def default_collector() -> "TraceCollector":
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = TraceCollector()
    return _DEFAULT


class TraceCollector:
    """Bounded in-memory span store; the REST layer and tests read it."""

    def __init__(self, capacity: int = 4096):
        self._spans: List[Span] = []
        self._capacity = capacity
        self._lock = threading.Lock()

    def span(self, scope: str, name: str) -> SpanBuilder:
        return SpanBuilder(self, scope, name)

    def add(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self._capacity:
                self._spans = self._spans[-self._capacity:]

    def spans(self, scope: Optional[str] = None) -> List[Span]:
        with self._lock:
            if scope is None:
                return list(self._spans)
            return [s for s in self._spans if s.scope == scope]
