#: Canonical cross-cutting metric-group names (the per-operator scopes
#: like ``job.<name>#<uid>`` are dynamic and not listed). Producers call
#: ``group.add_group(<one of these>)``; flint's REG02 cross-checks every
#: literal producer against this tuple and flags stale entries. Keep it
#: a plain literal tuple: flint parses it statically.
KNOWN_METRIC_GROUPS = (
    "autoscale",
    "cep",
    "chaos",
    "flight",
    "frontends",
    "latency",
    "skew",
    "state",
    "tenancy",
    "watchdog",
    "window",
)

from flink_tpu.metrics.core import (  # noqa: E402,F401
    Counter,
    Gauge,
    Histogram,
    Meter,
    MetricGroup,
    MetricRegistry,
)
from flink_tpu.metrics.reporters import (  # noqa: E402,F401
    LoggingReporter,
    PrometheusReporter,
)
from flink_tpu.metrics.traces import (  # noqa: E402,F401
    Span,
    SpanBuilder,
    TraceCollector,
    default_collector,
)
