from flink_tpu.metrics.core import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Meter,
    MetricGroup,
    MetricRegistry,
)
from flink_tpu.metrics.reporters import (  # noqa: F401
    LoggingReporter,
    PrometheusReporter,
)
from flink_tpu.metrics.traces import Span, SpanBuilder, TraceCollector  # noqa: F401
