"""Metric reporters.

reference: the 9 pluggable reporters under flink-metrics/* —
flink-metrics-prometheus/.../PrometheusReporter.java exposes an HTTP
endpoint in the Prometheus text exposition format; flink-metrics-slf4j logs
periodic dumps. Here: PrometheusReporter renders the text format and can
serve it from a background http.server; LoggingReporter prints snapshots.
"""

from __future__ import annotations

import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from flink_tpu.metrics.core import Counter, Gauge, Histogram, Meter

logger = logging.getLogger("flink_tpu.metrics")

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(parts) -> str:
    return _INVALID.sub("_", "_".join(parts))


class PrometheusReporter:
    """Render (and optionally serve) metrics in Prometheus text format."""

    def __init__(self, port: Optional[int] = None):
        self.port = port
        self._registry = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def open(self, registry) -> None:
        self._registry = registry
        if self.port is not None:
            self._start_server()

    def render(self) -> str:
        lines = []
        for (scope, name), metric in self._registry.items():
            mname = _prom_name(("flink_tpu",) + scope[-1:] + (name,))
            labels = ""
            if len(scope) > 1:
                labelstr = ",".join(
                    f'scope_{i}="{s}"' for i, s in enumerate(scope[:-1]))
                labels = "{" + labelstr + "}"
            if isinstance(metric, Counter):
                lines.append(f"# TYPE {mname} counter")
                lines.append(f"{mname}{labels} {metric.get()}")
            elif isinstance(metric, Histogram):
                snap = metric.snapshot()
                lines.append(f"# TYPE {mname} summary")
                for q in ("p50", "p95", "p99"):
                    qv = {"p50": "0.5", "p95": "0.95", "p99": "0.99"}[q]
                    ql = labels[:-1] + "," if labels else "{"
                    lines.append(
                        f'{mname}{ql}quantile="{qv}"}} {snap[q]}')
                lines.append(f"{mname}_count{labels} {snap['count']}")
            elif isinstance(metric, Meter):
                lines.append(f"# TYPE {mname} gauge")
                lines.append(f"{mname}{labels} {metric.rate}")
            elif isinstance(metric, Gauge):
                v = metric.get()
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    lines.append(f"# TYPE {mname} gauge")
                    lines.append(f"{mname}{labels} {v}")
        return "\n".join(lines) + "\n"

    def _start_server(self) -> None:
        reporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                body = reporter.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()


class LoggingReporter:
    """Periodic-dump reporter (reference: flink-metrics-slf4j)."""

    def __init__(self, level: int = logging.INFO):
        self.level = level
        self._registry = None

    def open(self, registry) -> None:
        self._registry = registry

    def report(self) -> None:
        for key, value in sorted(self._registry.snapshot().items()):
            logger.log(self.level, "metric %s = %s", key, value)

    def close(self) -> None:
        pass
