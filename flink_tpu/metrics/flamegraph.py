"""On-demand flame graphs by periodic thread sampling.

reference: flink-runtime/.../webmonitor/threadinfo/VertexFlameGraph.java +
rest/handler/job/JobVertexFlameGraphHandler.java — the Web UI requests a
flame graph for a vertex; the runtime samples the task threads' stacks for a
short window and folds them into a frame tree (the d3-flame-graph JSON
shape: {name, value, children}).

Re-design: task threads are named by role (``task-*``, ``source-subtask-*``,
``keyed-subtask-*``, ``jobmaster-*``), so a sample filters by thread-name
prefix instead of vertex ids; stacks come from ``sys._current_frames()``
(the CPython equivalent of ThreadMXBean.getThreadInfo).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional


class _Node:
    __slots__ = ("name", "value", "children")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.children: Dict[str, "_Node"] = {}

    def child(self, name: str) -> "_Node":
        node = self.children.get(name)
        if node is None:
            node = self.children[name] = _Node(name)
        return node

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "value": self.value,
            "children": [c.to_dict()
                         for c in sorted(self.children.values(),
                                         key=lambda n: -n.value)],
        }


def sample_flame_graph(duration_ms: int = 200, interval_ms: int = 10,
                       thread_name_prefixes: Optional[List[str]] = None
                       ) -> dict:
    """Sample all (or prefix-matching) threads' stacks for ``duration_ms``
    and fold them into a frame tree. Returns the d3-flame-graph JSON shape
    with an ``endTimestamp``/``samples`` header like the reference's
    VertexFlameGraph."""
    root = _Node("root")
    samples = 0
    deadline = time.monotonic() + duration_ms / 1000.0
    me = threading.get_ident()
    while True:
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            name = names.get(ident, str(ident))
            if thread_name_prefixes is not None and not any(
                    name.startswith(p) for p in thread_name_prefixes):
                continue
            # unwind to root, then fold top-down
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} "
                             f"({code.co_filename.rsplit('/', 1)[-1]}:"
                             f"{f.f_lineno})")
                f = f.f_back
            node = root.child(name)
            node.value += 1
            for entry in reversed(stack):
                node = node.child(entry)
                node.value += 1
            samples += 1
            # one unit per thread-sample at every level, so a parent's
            # value always >= the sum of its children (d3 invariant)
            root.value += 1
        if time.monotonic() >= deadline:
            break
        time.sleep(interval_ms / 1000.0)
    return {
        "endTimestamp": int(time.time() * 1000),
        "samples": samples,
        "root": root.to_dict(),
    }


#: thread-name prefixes of the task/data-plane threads (the reference
#: samples the vertex's task threads, not the control plane)
TASK_THREAD_PREFIXES = [
    "task-", "source-subtask-", "keyed-subtask-", "source-pump-",
    "async-wait",
]
