"""Metric types + hierarchical metric groups.

reference: flink-metrics/flink-metrics-core — Metric/Counter/Gauge/Histogram/
Meter interfaces, hierarchical MetricGroup scopes (job -> task -> operator),
TM-side registry runtime/metrics/MetricRegistryImpl.java (SURVEY.md §5).

Re-design: metrics are plain Python objects owned by the single-threaded
task loop (no locks on the hot path — the same single-owner discipline the
reference gets from the mailbox model); reporters snapshot on demand from
whatever thread serves them. Histogram keeps a bounded reservoir.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple


def quantile_sorted(data: List[float], q: float) -> float:
    """Quantile of an already-sorted list (shared index formula)."""
    if not data:
        return 0.0
    return data[min(len(data) - 1, int(q * len(data)))]


class Counter:
    __slots__ = ("_value",)

    def __init__(self) -> None:
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    def dec(self, n: int = 1) -> None:
        self._value -= n

    @property
    def count(self) -> int:
        return self._value

    def get(self) -> int:
        return self._value


class Gauge:
    """Wraps a supplier; value computed at report time."""

    __slots__ = ("_supplier",)

    def __init__(self, supplier: Callable[[], Any]) -> None:
        self._supplier = supplier

    def get(self):
        return self._supplier()


class SettableGauge(Gauge):
    __slots__ = ("_value",)

    def __init__(self, initial=0) -> None:
        self._value = initial
        super().__init__(lambda: self._value)

    def set(self, v) -> None:
        self._value = v


class Histogram:
    """Bounded-reservoir histogram with quantile snapshots
    (reference: DescriptiveStatisticsHistogram)."""

    __slots__ = ("_reservoir", "_count")

    def __init__(self, reservoir_size: int = 8192) -> None:
        self._reservoir: deque = deque(maxlen=reservoir_size)
        self._count = 0

    def update(self, value: float) -> None:
        self._reservoir.append(value)
        self._count += 1

    @property
    def count(self) -> int:
        return self._count

    def quantile(self, q: float) -> float:
        return quantile_sorted(sorted(self._reservoir), q)

    def snapshot(self) -> Dict[str, float]:
        if not self._reservoir:
            return {"count": self._count, "min": 0.0, "max": 0.0,
                    "mean": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        data = sorted(self._reservoir)
        n = len(data)
        return {
            "count": self._count,
            "min": data[0],
            "max": data[-1],
            "mean": sum(data) / n,
            "p50": quantile_sorted(data, 0.5),
            "p95": quantile_sorted(data, 0.95),
            "p99": quantile_sorted(data, 0.99),
        }


class Meter:
    """Events-per-second over a sliding minute (reference: MeterView)."""

    __slots__ = ("_count", "_stamps")

    def __init__(self) -> None:
        self._count = 0
        self._stamps: deque = deque(maxlen=128)

    def mark(self, n: int = 1) -> None:
        self._count += n
        self._stamps.append((time.monotonic(), self._count))

    @property
    def count(self) -> int:
        return self._count

    @property
    def rate(self) -> float:
        if len(self._stamps) < 2:
            return 0.0
        (t0, c0), (t1, c1) = self._stamps[0], self._stamps[-1]
        dt = t1 - t0
        return (c1 - c0) / dt if dt > 0 else 0.0


class MetricGroup:
    """Hierarchical scope: job -> task -> operator, like the reference's
    AbstractMetricGroup. Leaf metrics register into the shared registry with
    their full scope string."""

    def __init__(self, registry: "MetricRegistry",
                 scope: Tuple[str, ...] = ()) -> None:
        self.registry = registry
        self.scope = scope

    def add_group(self, name: str) -> "MetricGroup":
        return MetricGroup(self.registry, self.scope + (str(name),))

    def _register(self, name: str, metric) -> Any:
        self.registry.register(self.scope, name, metric)
        return metric

    def counter(self, name: str) -> Counter:
        return self._register(name, Counter())

    def gauge(self, name: str, supplier: Callable[[], Any]) -> Gauge:
        return self._register(name, Gauge(supplier))

    def settable_gauge(self, name: str, initial=0) -> SettableGauge:
        return self._register(name, SettableGauge(initial))

    def histogram(self, name: str, reservoir_size: int = 8192) -> Histogram:
        return self._register(name, Histogram(reservoir_size))

    def meter(self, name: str) -> Meter:
        return self._register(name, Meter())

    def scope_string(self, delimiter: str = ".") -> str:
        return delimiter.join(self.scope)


class MetricRegistry:
    """Flat store of (scope, name) -> metric + attached reporters
    (reference: runtime/metrics/MetricRegistryImpl.java)."""

    def __init__(self) -> None:
        self._metrics: Dict[Tuple[Tuple[str, ...], str], Any] = {}
        self._reporters: List[Any] = []

    def register(self, scope: Tuple[str, ...], name: str, metric) -> None:
        self._metrics[(scope, name)] = metric

    def unregister_scope_prefix(self, prefix: Tuple[str, ...]) -> None:
        self._metrics = {
            (s, n): m for (s, n), m in self._metrics.items()
            if s[:len(prefix)] != prefix
        }

    def add_reporter(self, reporter) -> None:
        self._reporters.append(reporter)
        reporter.open(self)

    def close(self) -> None:
        for r in self._reporters:
            r.close()

    def root_group(self, *scope: str) -> MetricGroup:
        return MetricGroup(self, tuple(scope))

    # ------------------------------------------------------------- snapshot

    def snapshot(self) -> Dict[str, Any]:
        """Flat name -> value view (gauges evaluated, histograms expanded)."""
        out: Dict[str, Any] = {}
        for (scope, name), metric in list(self._metrics.items()):
            key = ".".join(scope + (name,))
            if isinstance(metric, Histogram):
                for k, v in metric.snapshot().items():
                    out[f"{key}.{k}"] = v
            elif isinstance(metric, Meter):
                out[f"{key}.count"] = metric.count
                out[f"{key}.rate"] = metric.rate
            elif isinstance(metric, (Counter, Gauge)):
                out[key] = metric.get()
            else:
                out[key] = metric
        return out

    def items(self):
        return list(self._metrics.items())
