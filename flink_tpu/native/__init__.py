"""Native (C++) runtime components, loaded via ctypes.

Build happens on demand with g++ (no pip deps): the shared object is cached
under ``native/build/`` next to a source-hash stamp, so editing a ``.cpp``
always triggers a rebuild (mtime alone lies after checkouts/copies). Set
``FLINK_TPU_NO_NATIVE=1`` (or ``FLINK_TPU_NATIVE=0``) to force the pure
Python fallbacks (used in tests to cover both paths).

Every function fetched off a CDLL returned by :func:`load_native` must
declare ``argtypes`` AND ``restype`` before its first call — a missing
``restype`` silently truncates 64-bit returns (and pointers) to C int.
flint rule NAT01 enforces this statically against
:data:`NATIVE_SYMBOL_PREFIXES`.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Dict, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")

#: every exported symbol of every native library starts with one of
#: these — the registry flint's NAT01 cross-checks ctypes declarations
#: and call sites against (the stringly-typed-registry discipline of
#: chaos.KNOWN_FAULT_POINTS, applied to the C ABI)
NATIVE_SYMBOL_PREFIXES = ("sm_", "sx_", "codec_", "ngen_", "hc_")

#: hotcache symbols that MUTATE the arena — owner-side only. Frontends
#: attach with hc_attach and are read-only by contract (the seqlock
#: protects readers against a concurrent writer, not writer vs writer);
#: flint's SHM01 statically forbids any of these in an attach-rooted
#: scope. Keep this a plain literal tuple: flint parses it statically.
HOTCACHE_WRITER_SYMBOLS = ("hc_put_batch", "hc_prime_batch", "hc_drop",
                           "hc_clear", "hc_migrate", "hc_add_stat")

#: the libraries build_all() compiles (source basename -> .so basename)
NATIVE_LIBS = {
    "slotmap": ("slotmap.cpp", "_slotmap.so"),
    "sessions": ("sessions.cpp", "_sessions.so"),
    "codec": ("codec.cpp", "_codec.so"),
    "datagen": ("datagen.cpp", "_datagen.so"),
    "hotcache": ("hotcache.cpp", "_hotcache.so"),
}

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def native_disabled() -> bool:
    return (os.environ.get("FLINK_TPU_NO_NATIVE") == "1"
            or os.environ.get("FLINK_TPU_NATIVE") == "0")


#: count of LOUD degradations to a Python fallback plane (build
#: failure, load failure, runtime sweep error) — 0 on a healthy deploy.
#: Explicit opt-outs (FLINK_TPU_NO_NATIVE=1 etc.) do NOT count: only
#: the cases where native was wanted and silently losing it would hide
#: a throughput regression behind a green suite.
_fallbacks = 0
_fallback_reasons: set = set()


def note_fallback(reason: str) -> None:
    """Record one native->Python degradation: warn once per distinct
    reason (so a per-engine construction loop cannot spam) and bump the
    :func:`native_fallbacks` counter."""
    global _fallbacks
    _fallbacks += 1
    if reason not in _fallback_reasons:
        _fallback_reasons.add(reason)
        import warnings

        warnings.warn(
            f"flink_tpu native plane degraded to Python fallback: "
            f"{reason}", RuntimeWarning, stacklevel=3)


def native_fallbacks() -> int:
    """Total native->Python degradations this process (see
    :func:`note_fallback`)."""
    return _fallbacks


def reset_fallbacks_for_testing() -> None:
    global _fallbacks
    _fallbacks = 0
    _fallback_reasons.clear()


_build_token: Optional[str] = None


def _build_provenance() -> str:
    """Compiler + host token folded into the artifact stamp: the build
    uses ``-march=native``, so an artifact is only valid for the
    (toolchain, CPU) that produced it — a copied build/ directory from
    a newer microarchitecture would otherwise load and SIGILL
    mid-suite. Cached per process (one g++ subprocess)."""
    global _build_token
    if _build_token is None:
        try:
            gxx = subprocess.run(["g++", "-dumpfullversion"],
                                 capture_output=True, timeout=10,
                                 text=True).stdout.strip()
        except Exception:
            gxx = "unknown"
        cpu = ""
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    if line.startswith("model name"):
                        cpu = line.split(":", 1)[1].strip()
                        break
        except OSError:
            pass
        import platform

        _build_token = f"g++={gxx};arch={platform.machine()};cpu={cpu}"
    return _build_token


def _source_hash(src: str) -> str:
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    h.update(b"\x00" + _build_provenance().encode())
    return h.hexdigest()


def load_native(src_basename: str, so_basename: str) -> Optional[ctypes.CDLL]:
    """Compile-on-demand ctypes loader shared by every native component
    (slotmap, sessions, codec, datagen). Returns the CDLL, or None when
    disabled (FLINK_TPU_NO_NATIVE=1 / FLINK_TPU_NATIVE=0) or the
    toolchain/compile is unavailable.

    Staleness: the cached ``.so`` is paired with a ``.srchash`` stamp
    holding the sha256 of the source it was built from PLUS the build
    provenance (g++ version, machine, CPU model — the build uses
    ``-march=native``); a mismatch (or a missing stamp) forces a
    rebuild, so editing the ``.cpp`` can never load yesterday's binary
    and a build/ directory copied from a different host can never load
    the wrong microarchitecture's code — mtime comparison alone breaks
    under git checkouts and file copies that preserve timestamps. The
    compile is flock-guarded (concurrent processes build once) and
    writes to a temp name, os.replace()d into place — the .so first,
    the stamp after, so a crash between the two re-runs the build
    instead of trusting a half-updated pair.
    """
    if native_disabled():
        return None
    src = os.path.join(_REPO_ROOT, "native", src_basename)
    so_path = os.path.join(_BUILD_DIR, so_basename)
    if not os.path.exists(src):
        # sourceless deployment: a prebuilt artifact is all there is —
        # no staleness question to answer
        try:
            return ctypes.CDLL(so_path) if os.path.exists(so_path) else None
        except OSError:
            return None
    stamp_path = so_path + ".srchash"
    want_hash = _source_hash(src)

    def _stale() -> bool:
        if not os.path.exists(so_path):
            return True
        try:
            with open(stamp_path, "r") as f:
                return f.read().strip() != want_hash
        except OSError:
            return True  # stampless artifact: provenance unknown

    if _stale():
        os.makedirs(_BUILD_DIR, exist_ok=True)
        lock_path = so_path + ".lock"
        try:
            lock_f = open(lock_path, "w")
        except OSError:
            return None
        try:
            try:
                import fcntl

                fcntl.flock(lock_f, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # no flock (non-POSIX): fall back to tmp+rename only
            if _stale():  # a racing process may have built while we waited
                tmp = so_path + f".tmp.{os.getpid()}"
                cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                       "-std=c++17", src, "-o", tmp]
                try:
                    r = subprocess.run(cmd, capture_output=True, timeout=120)
                    if r.returncode != 0 or not os.path.exists(tmp):
                        return None
                    os.replace(tmp, so_path)
                    stamp_tmp = stamp_path + f".tmp.{os.getpid()}"
                    with open(stamp_tmp, "w") as f:
                        f.write(want_hash)
                    os.replace(stamp_tmp, stamp_path)
                except Exception:
                    return None
        finally:
            lock_f.close()
    try:
        return ctypes.CDLL(so_path)
    except OSError:
        return None


def load_slotmap() -> Optional[ctypes.CDLL]:
    """The slotmap library, or None if unavailable/disabled."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        lib = load_native("slotmap.cpp", "_slotmap.so")
        if lib is None:
            return None
        c = ctypes
        i64, i32, u8, vp = (c.c_int64, c.c_int32, c.c_uint8, c.c_void_p)
        P = c.POINTER
        lib.sm_create.restype = vp
        lib.sm_create.argtypes = [i64, i64]
        lib.sm_destroy.restype = None
        lib.sm_destroy.argtypes = [vp]
        lib.sm_capacity.restype = i64
        lib.sm_capacity.argtypes = [vp]
        lib.sm_used.restype = i64
        lib.sm_used.argtypes = [vp]
        lib.sm_slot_keys.restype = P(i64)
        lib.sm_slot_keys.argtypes = [vp]
        lib.sm_slot_namespaces.restype = P(i64)
        lib.sm_slot_namespaces.argtypes = [vp]
        lib.sm_slot_used.restype = P(u8)
        lib.sm_slot_used.argtypes = [vp]
        lib.sm_lookup_or_insert.restype = i32
        lib.sm_lookup_or_insert.argtypes = [vp, i64, P(i64), P(i64), P(i32),
                                            P(u8)]
        lib.sm_erase.restype = i64
        lib.sm_erase.argtypes = [vp, i64, P(i64), P(i64), P(i32)]
        lib.sm_lookup.restype = None
        lib.sm_lookup.argtypes = [vp, i64, P(i64), P(i64), P(i32)]
        lib.sm_verify.restype = None
        lib.sm_verify.argtypes = [vp, i64, P(i64), P(i64), P(i32), P(i32)]
        lib.sm_group_rows.restype = i64
        lib.sm_group_rows.argtypes = [P(i64), i64, P(i64), P(i32)]
        lib.sm_pane_ingest.restype = i32
        lib.sm_pane_ingest.argtypes = [vp, i64, P(i64), P(i64), i64, i64,
                                       i64, P(i32), P(u8), P(i32), P(i64),
                                       P(i64), P(i64)]
        lib.sm_flat_fuse.restype = None
        lib.sm_flat_fuse.argtypes = [i64, P(i32), P(i32), P(i64), i64,
                                     P(i32)]
        _lib = lib
        return _lib


def slotmap_available() -> bool:
    return load_slotmap() is not None


_sessions_lib: Optional[ctypes.CDLL] = None
_sessions_tried = False


def load_sessions() -> Optional[ctypes.CDLL]:
    """The native session-metadata plane (native/sessions.cpp), or None.

    One fused C sweep per batch replaces the numpy hot loop of
    ``windowing/session_meta.py``: sessionize + absorb + fire-candidate
    maintenance in one pass, with the session's device slot folded into
    the metadata row (see flink_tpu/windowing/session_native.py).
    """
    global _sessions_lib, _sessions_tried
    with _lock:
        if _sessions_tried:
            return _sessions_lib
        _sessions_tried = True
        lib = load_native("sessions.cpp", "_sessions.so")
        if lib is None:
            return None
        c = ctypes
        i64, i32, u8, vp = (c.c_int64, c.c_int32, c.c_uint8, c.c_void_p)
        P = c.POINTER
        lib.sx_create.restype = vp
        lib.sx_create.argtypes = [i64, i64]
        lib.sx_destroy.restype = None
        lib.sx_destroy.argtypes = [vp]
        lib.sx_capacity.restype = i64
        lib.sx_capacity.argtypes = [vp]
        lib.sx_used.restype = i64
        lib.sx_used.argtypes = [vp]
        lib.sx_keys.restype = P(i64)
        lib.sx_keys.argtypes = [vp]
        lib.sx_starts.restype = P(i64)
        lib.sx_starts.argtypes = [vp]
        lib.sx_ends.restype = P(i64)
        lib.sx_ends.argtypes = [vp]
        lib.sx_sids.restype = P(i64)
        lib.sx_sids.argtypes = [vp]
        lib.sx_dslots.restype = P(i32)
        lib.sx_dslots.argtypes = [vp]
        lib.sx_used_mask.restype = P(u8)
        lib.sx_used_mask.argtypes = [vp]
        lib.sx_lookup.restype = None
        lib.sx_lookup.argtypes = [vp, i64, P(i64), P(i32)]
        lib.sx_insert.restype = i32
        lib.sx_insert.argtypes = [vp, i64, P(i64), P(i32)]
        lib.sx_erase_rows.restype = None
        lib.sx_erase_rows.argtypes = [vp, i64, P(i32)]
        lib.sx_lookup1.restype = i32
        lib.sx_lookup1.argtypes = [vp, i64]
        lib.sx_insert1.restype = i32
        lib.sx_insert1.argtypes = [vp, i64]
        lib.sx_erase1.restype = None
        lib.sx_erase1.argtypes = [vp, i32]
        lib.sx_multi_add.restype = None
        lib.sx_multi_add.argtypes = [vp, i64]
        lib.sx_multi_remove.restype = None
        lib.sx_multi_remove.argtypes = [vp, i64]
        lib.sx_multi_count.restype = i64
        lib.sx_multi_count.argtypes = [vp]
        lib.sx_absorb.restype = i64
        lib.sx_absorb.argtypes = [vp, i64, P(i64), P(i64),  # n, keys, ts
                                  i64, i64, i64, i64,  # gap, late, mfw, sid
                                  P(i64), P(i64),      # order, rec_to_sess
                                  P(i64), P(i64), P(i64), P(i64),  # k/s/e/sid
                                  P(i32), P(i32), P(u8),  # slot/row/flags
                                  P(i64)]              # out n_fast
        lib.sx_fold.restype = None
        lib.sx_fold.argtypes = [vp, i64, P(i64), P(i64), P(i32)]
        lib.sx_fold_rows.restype = None
        lib.sx_fold_rows.argtypes = [vp, i64, P(i32), P(i64), P(i32)]
        lib.sx_push_chunk.restype = None
        lib.sx_push_chunk.argtypes = [vp, i64, P(i64), P(i64), P(i64)]
        lib.sx_min_pending.restype = i64
        lib.sx_min_pending.argtypes = [vp]
        lib.sx_pop.restype = i64
        lib.sx_pop.argtypes = [vp, i64, P(i64)]
        lib.sx_pop_fetch.restype = None
        lib.sx_pop_fetch.argtypes = [vp, P(i64), P(i64), P(i64), P(i64),
                                     P(i32)]
        lib.sx_pop_fetch_rest.restype = None
        lib.sx_pop_fetch_rest.argtypes = [vp, P(i64), P(i64), P(i64)]
        lib.sx_shard_group.restype = i64
        lib.sx_shard_group.argtypes = [i64, P(i64), P(i64), P(u8), P(i32),
                                       P(i32), i64, i64, i64, i64,
                                       P(i64), P(i64), P(i64),
                                       P(i64), P(i64), P(u8), P(i32),
                                       P(i32)]
        lib.sx_route.restype = None
        lib.sx_route.argtypes = [i64, i64, P(i64), P(i64), i64, P(i64),
                                 P(i32), P(i64), P(i32), P(i64)]
        lib.sx_rec_shard_max.restype = i64
        lib.sx_rec_shard_max.argtypes = [i64, P(i64), i64, i64, i64, i64]
        _sessions_lib = lib
        return _sessions_lib


def sessions_available() -> bool:
    return load_sessions() is not None


_datagen_lib: Optional[ctypes.CDLL] = None
_datagen_tried = False


def load_datagen() -> Optional[ctypes.CDLL]:
    """The native stream generator (native/datagen.cpp), or None."""
    global _datagen_lib, _datagen_tried
    with _lock:
        if _datagen_tried:
            return _datagen_lib
        _datagen_tried = True
        lib = load_native("datagen.cpp", "_datagen.so")
        if lib is None:
            return None
        c = ctypes
        i64, f32p = c.c_int64, c.POINTER(c.c_float)
        P = c.POINTER
        lib.ngen_bids.restype = None
        lib.ngen_bids.argtypes = [i64, i64, i64, i64, i64, i64, i64, i64,
                                  P(c.c_int64), P(c.c_int64), f32p,
                                  P(c.c_int64)]
        _datagen_lib = lib
        return _datagen_lib


_hotcache_lib: Optional[ctypes.CDLL] = None
_hotcache_tried = False

#: hc_stat counter indices (must match the Stat enum in hotcache.cpp)
HC_STAT_HITS = 0
HC_STAT_MISSES = 1
HC_STAT_EVICTIONS = 2
HC_STAT_PRIMES = 3
HC_STAT_PUTS = 4
HC_STAT_TORN_RETRIES = 5
HC_STAT_TORN_MISSES = 6
HC_STAT_OVERSIZE_DROPS = 7

#: per-frontend counter indices (must match the FeStat enum in
#: hotcache.cpp) — accumulated IN the shared arena header by attached
#: frontend processes (hc_fe_note), read owner-side without IPC
HC_FE_STAT_PROBES = 0
HC_FE_STAT_HITS = 1
HC_FE_STAT_TORN_RETRIES = 2
HC_FE_STAT_MISS_CROSSINGS = 3
HC_FE_STAT_NAMES = ("probes", "hits", "torn_retries", "miss_crossings")
#: fe_stats rows reserved in the arena header (kMaxFrontends)
HC_MAX_FRONTENDS = 64


def load_hotcache() -> Optional[ctypes.CDLL]:
    """The native hot-row probe table (native/hotcache.cpp), or None.

    One GIL-released C call probes/primes a whole key batch against an
    open-addressing, seqlock-stamped table of packed composed results —
    the serving hot loop of flink_tpu/tenancy/hot_cache_native.py
    (flink_tpu/tenancy/hot_cache.py stays the bit-identical Python
    fallback).
    """
    global _hotcache_lib, _hotcache_tried
    with _lock:
        if _hotcache_tried:
            return _hotcache_lib
        _hotcache_tried = True
        lib = load_native("hotcache.cpp", "_hotcache.so")
        if lib is None:
            return None
        c = ctypes
        i64, i32, u8, u64, vp = (c.c_int64, c.c_int32, c.c_uint8,
                                 c.c_uint64, c.c_void_p)
        P = c.POINTER
        lib.hc_create.restype = vp
        lib.hc_create.argtypes = [i64, i64, i64]
        # shared-memory arena family (r21): the owner creates the table
        # as a MAP_SHARED file arena; frontend processes attach the SAME
        # table and probe it lock-free (seqlock readers are address-free)
        lib.hc_create_shared.restype = vp
        lib.hc_create_shared.argtypes = [c.c_char_p, i64, i64, i64]
        lib.hc_attach.restype = vp
        lib.hc_attach.argtypes = [c.c_char_p]
        lib.hc_epoch.restype = i64
        lib.hc_epoch.argtypes = [vp]
        lib.hc_arena_bytes.restype = i64
        lib.hc_arena_bytes.argtypes = [vp]
        lib.hc_is_attached.restype = i64
        lib.hc_is_attached.argtypes = [vp]
        lib.hc_fe_note.restype = None
        lib.hc_fe_note.argtypes = [vp, i32, i64, i64, i64, i64]
        lib.hc_fe_stat.restype = i64
        lib.hc_fe_stat.argtypes = [vp, i32, i32]
        lib.hc_destroy.restype = None
        lib.hc_destroy.argtypes = [vp]
        lib.hc_len.restype = i64
        lib.hc_len.argtypes = [vp]
        lib.hc_capacity.restype = i64
        lib.hc_capacity.argtypes = [vp]
        lib.hc_stat.restype = i64
        lib.hc_stat.argtypes = [vp, i32]
        lib.hc_add_stat.restype = None
        lib.hc_add_stat.argtypes = [vp, i32, i64]
        lib.hc_clear.restype = None
        lib.hc_clear.argtypes = [vp]
        lib.hc_get_batch.restype = i64
        lib.hc_get_batch.argtypes = [vp, i64, P(i64), i64, P(u8),
                                     P(i32), P(i64), P(i64), P(i64),
                                     P(u64)]
        # the frontend variant: same probe + per-frontend attribution
        # folded in the same GIL-released call
        lib.hc_get_batch_fe.restype = i64
        lib.hc_get_batch_fe.argtypes = [vp, i32, i64, P(i64), i64,
                                        P(u8), P(i32), P(i64), P(i64),
                                        P(i64), P(u64)]
        lib.hc_put_batch.restype = i64
        lib.hc_put_batch.argtypes = [vp, i64, P(i64), P(i64), P(i64),
                                     P(i64), P(i64), P(u64)]
        lib.hc_prime_batch.restype = i64
        lib.hc_prime_batch.argtypes = [vp, i64, P(i64), i64, P(i64),
                                       P(i64), P(i64), P(u64), P(i64),
                                       P(i64), P(u8)]
        lib.hc_drop.restype = None
        lib.hc_drop.argtypes = [vp, i64]
        lib.hc_migrate.restype = i64
        lib.hc_migrate.argtypes = [vp, vp]
        # test-only: freeze/unfreeze a slot's seqlock stamp so the
        # torn-read retry path is deterministically coverable
        lib.hc_debug_lock_slot.restype = i64
        lib.hc_debug_lock_slot.argtypes = [vp, i64]
        lib.hc_debug_unlock_slot.restype = i64
        lib.hc_debug_unlock_slot.argtypes = [vp, i64]
        _hotcache_lib = lib
        return _hotcache_lib


def hotcache_available() -> bool:
    return load_hotcache() is not None


def build_all() -> Dict[str, bool]:
    """Compile every native library up front (CI calls this before the
    suite so a missing toolchain is LOUD, not a silent mid-suite
    fallback). Returns {name: available}."""
    return {name: load_native(src, so) is not None
            for name, (src, so) in NATIVE_LIBS.items()}


def build_report() -> str:
    """One status line for CI logs: ``NATIVE: built`` when every
    library compiled, else ``NATIVE: SKIPPED (...)`` naming why."""
    if native_disabled():
        return "NATIVE: SKIPPED (disabled via env)"
    built = build_all()
    if all(built.values()):
        return "NATIVE: built (" + ", ".join(sorted(built)) + ")"
    missing = sorted(n for n, ok in built.items() if not ok)
    return ("NATIVE: SKIPPED (no compiler or build failed: "
            + ", ".join(missing) + ")")


def group_matrix(keys, slots, sidx, n_slices: int):
    """(unique keys, [K, n_slices] slot matrix) grouped by key in O(n)
    via the native hash table — the window-fire matrix build (absent
    cells stay at identity slot 0). The matrix is allocated RIGHT-SIZED
    at K distinct keys (the native call only assigns row ids), so the
    memory cost matches the np.unique path it replaces. Returns None
    when the native library is unavailable (callers fall back)."""
    import numpy as np

    lib = load_slotmap()
    if lib is None:
        return None
    n = len(keys)
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    out_keys = np.empty(n, dtype=np.int64)
    row_of = np.empty(n, dtype=np.int32)
    c = ctypes
    rows = lib.sm_group_rows(
        keys.ctypes.data_as(c.POINTER(c.c_int64)), n,
        out_keys.ctypes.data_as(c.POINTER(c.c_int64)),
        row_of.ctypes.data_as(c.POINTER(c.c_int32)))
    matrix = np.zeros((rows, n_slices), dtype=np.int32)
    matrix[row_of, np.asarray(sidx)] = slots
    return out_keys[:rows], matrix
