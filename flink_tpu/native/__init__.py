"""Native (C++) runtime components, loaded via ctypes.

Build happens on demand with g++ (no pip deps): the shared object is cached
under ``native/build/``. Set ``FLINK_TPU_NO_NATIVE=1`` to force the pure
Python fallbacks (used in tests to cover both paths).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def load_native(src_basename: str, so_basename: str) -> Optional[ctypes.CDLL]:
    """Compile-on-demand ctypes loader shared by every native component
    (slotmap, codec). Returns the CDLL, or None when disabled
    (FLINK_TPU_NO_NATIVE=1) or the toolchain/compile is unavailable.
    The compile writes to a temp name and os.replace()s it into place so
    concurrent processes never load a half-written .so."""
    if os.environ.get("FLINK_TPU_NO_NATIVE") == "1":
        return None
    src = os.path.join(_REPO_ROOT, "native", src_basename)
    so_path = os.path.join(_BUILD_DIR, so_basename)
    if not os.path.exists(so_path) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(so_path)):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = so_path + f".tmp.{os.getpid()}"
        cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
               "-std=c++17", src, "-o", tmp]
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
            if r.returncode != 0 or not os.path.exists(tmp):
                return None
            os.replace(tmp, so_path)
        except Exception:
            return None
    try:
        return ctypes.CDLL(so_path)
    except OSError:
        return None


def load_slotmap() -> Optional[ctypes.CDLL]:
    """The slotmap library, or None if unavailable/disabled."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        lib = load_native("slotmap.cpp", "_slotmap.so")
        if lib is None:
            return None
        c = ctypes
        i64, i32, u8, vp = (c.c_int64, c.c_int32, c.c_uint8, c.c_void_p)
        P = c.POINTER
        lib.sm_create.restype = vp
        lib.sm_create.argtypes = [i64, i64]
        lib.sm_destroy.argtypes = [vp]
        lib.sm_capacity.restype = i64
        lib.sm_capacity.argtypes = [vp]
        lib.sm_used.restype = i64
        lib.sm_used.argtypes = [vp]
        lib.sm_slot_keys.restype = P(i64)
        lib.sm_slot_keys.argtypes = [vp]
        lib.sm_slot_namespaces.restype = P(i64)
        lib.sm_slot_namespaces.argtypes = [vp]
        lib.sm_slot_used.restype = P(u8)
        lib.sm_slot_used.argtypes = [vp]
        lib.sm_lookup_or_insert.restype = i32
        lib.sm_lookup_or_insert.argtypes = [vp, i64, P(i64), P(i64), P(i32),
                                            P(u8)]
        lib.sm_erase.restype = i64
        lib.sm_erase.argtypes = [vp, i64, P(i64), P(i64), P(i32)]
        lib.sm_lookup.restype = None
        lib.sm_lookup.argtypes = [vp, i64, P(i64), P(i64), P(i32)]
        lib.sm_group_rows.restype = i64
        lib.sm_group_rows.argtypes = [P(i64), i64, P(i64), P(i32)]
        lib.sm_pane_ingest.restype = i32
        lib.sm_pane_ingest.argtypes = [vp, i64, P(i64), P(i64), i64, i64,
                                       i64, P(i32), P(u8), P(i32), P(i64),
                                       P(i64), P(i64)]
        lib.sm_flat_fuse.restype = None
        lib.sm_flat_fuse.argtypes = [i64, P(i32), P(i32), P(i64), i64,
                                     P(i32)]
        _lib = lib
        return _lib


def slotmap_available() -> bool:
    return load_slotmap() is not None


_datagen_lib: Optional[ctypes.CDLL] = None
_datagen_tried = False


def load_datagen() -> Optional[ctypes.CDLL]:
    """The native stream generator (native/datagen.cpp), or None."""
    global _datagen_lib, _datagen_tried
    with _lock:
        if _datagen_tried:
            return _datagen_lib
        _datagen_tried = True
        lib = load_native("datagen.cpp", "_datagen.so")
        if lib is None:
            return None
        c = ctypes
        i64, f32p = c.c_int64, c.POINTER(c.c_float)
        P = c.POINTER
        lib.ngen_bids.restype = None
        lib.ngen_bids.argtypes = [i64, i64, i64, i64, i64, i64, i64, i64,
                                  P(c.c_int64), P(c.c_int64), f32p,
                                  P(c.c_int64)]
        _datagen_lib = lib
        return _datagen_lib


def group_matrix(keys, slots, sidx, n_slices: int):
    """(unique keys, [K, n_slices] slot matrix) grouped by key in O(n)
    via the native hash table — the window-fire matrix build (absent
    cells stay at identity slot 0). The matrix is allocated RIGHT-SIZED
    at K distinct keys (the native call only assigns row ids), so the
    memory cost matches the np.unique path it replaces. Returns None
    when the native library is unavailable (callers fall back)."""
    import numpy as np

    lib = load_slotmap()
    if lib is None:
        return None
    n = len(keys)
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    out_keys = np.empty(n, dtype=np.int64)
    row_of = np.empty(n, dtype=np.int32)
    c = ctypes
    rows = lib.sm_group_rows(
        keys.ctypes.data_as(c.POINTER(c.c_int64)), n,
        out_keys.ctypes.data_as(c.POINTER(c.c_int64)),
        row_of.ctypes.data_as(c.POINTER(c.c_int32)))
    matrix = np.zeros((rows, n_slices), dtype=np.int32)
    matrix[row_of, np.asarray(sidx)] = slots
    return out_keys[:rows], matrix
