"""Native (C++) runtime components, loaded via ctypes.

Build happens on demand with g++ (no pip deps): the shared object is cached
under ``native/build/``. Set ``FLINK_TPU_NO_NATIVE=1`` to force the pure
Python fallbacks (used in tests to cover both paths).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "slotmap.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_SO_PATH = os.path.join(_BUILD_DIR, "_slotmap.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           _SRC, "-o", _SO_PATH]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        return r.returncode == 0 and os.path.exists(_SO_PATH)
    except Exception:
        return False


def load_slotmap() -> Optional[ctypes.CDLL]:
    """The slotmap library, or None if unavailable/disabled."""
    global _lib, _tried
    if os.environ.get("FLINK_TPU_NO_NATIVE") == "1":
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO_PATH) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO_PATH)):
            if not _compile():
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        c = ctypes
        i64, i32, u8, vp = (c.c_int64, c.c_int32, c.c_uint8, c.c_void_p)
        P = c.POINTER
        lib.sm_create.restype = vp
        lib.sm_create.argtypes = [i64, i64]
        lib.sm_destroy.argtypes = [vp]
        lib.sm_capacity.restype = i64
        lib.sm_capacity.argtypes = [vp]
        lib.sm_used.restype = i64
        lib.sm_used.argtypes = [vp]
        lib.sm_slot_keys.restype = P(i64)
        lib.sm_slot_keys.argtypes = [vp]
        lib.sm_slot_namespaces.restype = P(i64)
        lib.sm_slot_namespaces.argtypes = [vp]
        lib.sm_slot_used.restype = P(u8)
        lib.sm_slot_used.argtypes = [vp]
        lib.sm_lookup_or_insert.restype = i32
        lib.sm_lookup_or_insert.argtypes = [vp, i64, P(i64), P(i64), P(i32),
                                            P(u8)]
        lib.sm_erase.restype = i64
        lib.sm_erase.argtypes = [vp, i64, P(i64), P(i64), P(i32)]
        lib.sm_lookup.restype = None
        lib.sm_lookup.argtypes = [vp, i64, P(i64), P(i64), P(i32)]
        _lib = lib
        return _lib


def slotmap_available() -> bool:
    return load_slotmap() is not None
