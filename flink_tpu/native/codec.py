"""Native columnar wire codec (native/codec.cpp) + the batch frame format.

The data plane's record (de)serializer: the role the reference gives its
compiled fast coders and lz4/snappy buffer compression (SURVEY.md §2.10
items 5 and 7; reference: pyflink/fn_execution/coder_impl_fast.pyx,
root pom.xml:168 lz4-java).

A RecordBatch crosses the wire as ONE C++ codec block whose raw payload is

    u32 meta_len | meta (struct-packed column table incl. shapes) | columns

— the column metadata rides INSIDE the CRC-protected (and compressed)
payload, so a bit flip in a dtype string or shape fails the CRC exactly
like one in the column bytes; nothing outside the block influences what
gets materialized. ``columns`` is every column's raw buffer concatenated,
LZ-compressed when that wins, CRC-protected. Numeric
columns are zero-copy on decode (np.frombuffer views into one contiguous
decode buffer). Object columns (e.g. original string key values) ride as
UTF-8/pickle sub-blobs inside the payload — pickle only for non-string
objects, and only there; a frame that was corrupted or truncated fails the
CRC before any column is materialized (unlike a bare-pickle transport, the
fast path executes no code on decode).

Senders fall back to cloudpickle when the native library is unavailable
(FLINK_TPU_NO_NATIVE=1 covers both paths in tests); receivers of a native
frame without the library fail with a precise error naming the fix.
"""

from __future__ import annotations

import ctypes
import struct
import threading

import numpy as np

from flink_tpu.core.records import RecordBatch

_lock = threading.Lock()
_lib = None
_tried = False


def load_codec():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        from flink_tpu.native import load_native

        lib = load_native("codec.cpp", "_codec.so")
        if lib is None:
            return None
        c = ctypes
        u8p = c.POINTER(c.c_uint8)
        lib.codec_encode.restype = c.c_int
        lib.codec_encode.argtypes = [u8p, c.c_uint64, c.c_int,
                                     c.POINTER(u8p),
                                     c.POINTER(c.c_uint64)]
        lib.codec_raw_len.restype = c.c_int64
        lib.codec_raw_len.argtypes = [u8p, c.c_uint64]
        lib.codec_decode.restype = c.c_int
        lib.codec_decode.argtypes = [u8p, c.c_uint64, u8p, c.c_uint64]
        lib.codec_free.restype = None
        lib.codec_free.argtypes = [u8p]
        _lib = lib
        return _lib


def codec_available() -> bool:
    return load_codec() is not None


def _require_codec():
    lib = load_codec()
    if lib is None:
        raise RuntimeError(
            "received a native-codec frame but the codec library is "
            "unavailable on this node (g++ missing, build failed, or "
            "FLINK_TPU_NO_NATIVE=1) — every shuffle participant needs "
            "the same transport capabilities")
    return lib


def _u8_ptr(buf) -> "ctypes.POINTER":
    """Zero-copy uint8 pointer into any buffer-protocol object."""
    arr = np.frombuffer(buf, dtype=np.uint8)
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(arr)


def _encode_block(payload: bytes, compress: bool) -> bytes:
    lib = _require_codec()
    c = ctypes
    ptr, n = _u8_ptr(payload)
    out = c.POINTER(c.c_uint8)()
    out_len = c.c_uint64()
    rc = lib.codec_encode(ptr, n, 1 if compress else 0,
                          c.byref(out), c.byref(out_len))
    if rc != 0:
        raise MemoryError("codec_encode failed")
    try:
        return bytes(c.cast(
            out, c.POINTER(c.c_uint8 * out_len.value)).contents)
    finally:
        lib.codec_free(out)


def _decode_block(block) -> np.ndarray:
    """Frame -> raw payload as a uint8 array (the decode buffer that
    numeric column views alias — one allocation, no extra copies)."""
    lib = _require_codec()
    ptr, n = _u8_ptr(block)
    raw_len = lib.codec_raw_len(ptr, n)
    if raw_len < 0:
        raise ValueError("malformed codec frame")
    out = np.empty(raw_len, dtype=np.uint8)
    rc = lib.codec_decode(ptr, n,
                          out.ctypes.data_as(
                              ctypes.POINTER(ctypes.c_uint8)),
                          raw_len)
    if rc == -3:
        raise ValueError("codec frame CRC mismatch (corrupted in transit)")
    if rc != 0:
        raise ValueError(f"malformed codec frame (rc={rc})")
    return out


# column kinds in the meta table
_K_NUMERIC = 0   # raw buffer, np.frombuffer on decode
_K_STRINGS = 1   # all-str object column as utf-8 + u32 offsets
_K_PICKLED = 2   # arbitrary objects (trusted links only)

_COL_FMT = "<HBBBQ"  # name_len, kind, dtype_len, ndim, nbytes


def encode_batch(batch: RecordBatch, compress: bool = True) -> bytes:
    """RecordBatch -> wire bytes (native framed block)."""
    import cloudpickle

    metas = []
    chunks = []
    for name, col in batch.columns.items():
        col = np.asarray(col)
        if col.dtype.kind == "O":
            if all(isinstance(v, str) for v in col):
                enc = [v.encode("utf-8") for v in col]
                offs = np.zeros(len(enc) + 1, dtype=np.uint32)
                np.cumsum([len(b) for b in enc], out=offs[1:])
                blob = offs.tobytes() + b"".join(enc)
                metas.append((name, _K_STRINGS, "", (len(col),),
                              len(blob)))
            else:
                blob = cloudpickle.dumps(col)
                metas.append((name, _K_PICKLED, "", (len(col),),
                              len(blob)))
            chunks.append(blob)
        else:
            buf = np.ascontiguousarray(col)
            blob = buf.tobytes()
            metas.append((name, _K_NUMERIC, buf.dtype.str, buf.shape,
                          len(blob)))
            chunks.append(blob)
    meta_parts = [struct.pack("<I", len(metas))]
    for name, kind, dt, shape, nbytes in metas:
        nb = name.encode("utf-8")
        db = dt.encode("ascii")
        meta_parts.append(struct.pack(_COL_FMT, len(nb), kind, len(db),
                                      len(shape), nbytes))
        meta_parts.append(nb)
        meta_parts.append(db)
        meta_parts.append(struct.pack(f"<{len(shape)}Q", *shape))
    meta = b"".join(meta_parts)
    return _encode_block(
        struct.pack("<I", len(meta)) + meta + b"".join(chunks), compress)


def decode_batch(data) -> RecordBatch:
    """Wire bytes -> RecordBatch (numeric columns zero-copy views into
    the single decode buffer)."""
    import cloudpickle

    decoded = _decode_block(data)
    (meta_len,) = struct.unpack_from("<I", decoded, 0)
    meta = decoded[4:4 + meta_len]
    payload = decoded[4 + meta_len:]
    (ncols,) = struct.unpack_from("<I", meta, 0)
    pos = 4
    cols = {}
    off = 0
    for _ in range(ncols):
        name_len, kind, dt_len, ndim, nbytes = struct.unpack_from(
            _COL_FMT, meta, pos)
        pos += struct.calcsize(_COL_FMT)
        name = bytes(meta[pos:pos + name_len]).decode("utf-8")
        pos += name_len
        dt = bytes(meta[pos:pos + dt_len]).decode("ascii")
        pos += dt_len
        shape = struct.unpack_from(f"<{ndim}Q", meta, pos)
        pos += 8 * ndim
        blob = payload[off:off + nbytes]
        off += nbytes
        if kind == _K_NUMERIC:
            cols[name] = np.frombuffer(
                blob, dtype=np.dtype(dt)).reshape(shape)
        elif kind == _K_STRINGS:
            n = shape[0]
            offs = np.frombuffer(blob[:4 * (n + 1)], dtype=np.uint32)
            body = blob[4 * (n + 1):].tobytes()
            cols[name] = np.array(
                [body[offs[i]:offs[i + 1]].decode("utf-8")
                 for i in range(n)], dtype=object)
        else:
            cols[name] = cloudpickle.loads(blob.tobytes())
    return RecordBatch(cols)
