"""flink_tpu.stateplane — the shared state-plane kernel library.

One home for the compiled device programs every engine dispatches
(ROADMAP item 5): the canonical flat program families
(:mod:`~flink_tpu.stateplane.families`), the pane-ring delta-harvest
bundle (:mod:`~flink_tpu.stateplane.pane`), the exchange-rank
combinator with its first Pallas backend
(:mod:`~flink_tpu.stateplane.rank`), and the pluggable per-family
backend hook (:mod:`~flink_tpu.stateplane.backends`). Engines —
SlotTable, PaneTable, the mesh engines, the joins — are thin policies
over these builders; flint REG04 pins every PROGRAM_CACHE kind to
:data:`KNOWN_PROGRAM_FAMILIES`.
"""

from flink_tpu.stateplane.backends import (
    backend_of,
    backend_scope,
    configure_backends,
    pallas_available,
    set_backend,
)
from flink_tpu.stateplane.families import (
    KNOWN_PROGRAM_FAMILIES,
    flat_fence,
    flat_gather,
    flat_merge_pairs,
    flat_put,
    flat_reset,
    flat_scatter_combine,
    flat_scatter_signed,
    flat_scatter_valued,
    flat_segment_fire,
    flat_segment_fire_projected,
    flat_segment_merge,
)
from flink_tpu.stateplane.pane import pane_programs
from flink_tpu.stateplane.rank import (
    build_exchange_rank,
    exchange_rank_flat,
    pallas_rank,
    xla_rank,
)

__all__ = [
    "KNOWN_PROGRAM_FAMILIES",
    "backend_of",
    "backend_scope",
    "build_exchange_rank",
    "configure_backends",
    "exchange_rank_flat",
    "flat_fence",
    "flat_gather",
    "flat_merge_pairs",
    "flat_put",
    "flat_reset",
    "flat_scatter_combine",
    "flat_scatter_signed",
    "flat_scatter_valued",
    "flat_segment_fire",
    "flat_segment_fire_projected",
    "flat_segment_merge",
    "pallas_available",
    "pallas_rank",
    "pane_programs",
    "set_backend",
    "xla_rank",
]
