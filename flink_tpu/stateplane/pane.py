"""The delta-harvest family: [R, C] pane-ring programs.

The fire-latency tier's incremental pre-aggregation keeps window state
as a ring of pane slices x key columns; a fire harvests ONE merged row
(the delta) instead of re-reducing the window. The six programs of
that discipline — flat 2-D scatter (const and valued variants), the
fire-row merge+finish (+optional projection), the row reset/put of the
evict/reload cohort path, and the window-partial fold — are one bundle
here, cached in the shared PROGRAM_CACHE under the ``delta-harvest``
kind, keyed on aggregate layout (+ projector identity) only.

The int8 presence plane rides as the trailing array of ``accs`` in
every program — it distinguishes "identity because empty" from
"identity because the values folded to it", which the fire validity
mask needs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from flink_tpu.ops.segment_ops import MERGE_FN, SCATTER_METHOD
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE


def pane_programs(agg, projector=None):
    """(scatter2d, scatter2d_valued, fire_rows, reset_row, put_row,
    fold_rows) for [R, C] pane arrays. The presence plane rides as an
    extra trailing array in ``accs``."""
    key = ("pane", agg.cache_key(),
           None if projector is None else projector.cache_key())
    return PROGRAM_CACHE.get_or_build(
        "delta-harvest", key, lambda: _build_pane_programs(agg, projector))


def _build_pane_programs(agg, projector):
    leaves = agg.leaves
    methods = tuple(SCATTER_METHOD[l.reduce] for l in leaves)
    merges = tuple(MERGE_FN[l.reduce] for l in leaves)
    idents = tuple(l.identity for l in leaves)
    finish = agg.finish
    n = len(leaves)

    @partial(jax.jit, donate_argnums=(0,))
    def scatter2d(accs, flat, values):
        # ONE flat i32 index array crosses host->device per batch (the
        # tunneled link's bandwidth is the scarce resource — rows/cols
        # are pre-fused on host; flat 1-D scatter also lowers better on
        # TPU than 2-D scatter; the reshape is a bitcast under jit)
        C = accs[0].shape[1]
        pad = (flat % C) == 0  # col 0 is the reserved identity column
        vit = iter(values)
        out = []
        for a, m, l in zip(accs[:n], methods, leaves):
            if l.const is not None:
                v = jnp.where(pad,
                              jnp.asarray(l.identity, dtype=l.dtype),
                              jnp.asarray(l.const, dtype=l.dtype))
            else:
                v = next(vit)
            shape = a.shape
            out.append(
                getattr(a.reshape(-1).at[flat], m)(v).reshape(shape))
        presence = accs[n].reshape(-1).at[flat].max(
            jnp.where(pad, 0, 1).astype(jnp.int8)
        ).reshape(accs[n].shape)
        return tuple(out) + (presence,)

    @jax.jit
    def fire_rows(accs, rows, used_n):
        merged = tuple(
            m(a[rows], axis=0) for a, m in zip(accs[:n], merges))
        present = accs[n][rows].max(axis=0)
        cols = finish(merged)
        valid = (jnp.arange(present.shape[0]) < used_n) & (present > 0)
        if projector is None:
            return cols, valid
        return projector.project(cols, valid)

    @partial(jax.jit, donate_argnums=(0,))
    def scatter2d_valued(accs, flat, values):
        # every leaf valued (locally pre-aggregated partials), each folded
        # by its own reduce; pad lanes carry leaf identities at flat 0
        C = accs[0].shape[1]
        pad = (flat % C) == 0
        out = [getattr(a.reshape(-1).at[flat], m)(v).reshape(a.shape)
               for a, m, v in zip(accs[:n], methods, values)]
        presence = accs[n].reshape(-1).at[flat].max(
            jnp.where(pad, 0, 1).astype(jnp.int8)).reshape(accs[n].shape)
        return tuple(out) + (presence,)

    @partial(jax.jit, donate_argnums=(0,))
    def reset_row(accs, row):
        out = [a.at[row].set(jnp.asarray(i, dtype=a.dtype))
               for a, i in zip(accs[:n], idents)]
        return tuple(out) + (accs[n].at[row].set(jnp.int8(0)),)

    @partial(jax.jit, donate_argnums=(0,))
    def put_row(accs, row, cols, values):
        out = [a.at[row, cols].set(v)
               for a, v in zip(accs[:n], values)]
        presence = accs[n].at[row, cols].set(
            jnp.where(cols == 0, 0, 1).astype(jnp.int8))
        return tuple(out) + (presence,)

    @partial(jax.jit, donate_argnums=(0,))
    def fold_rows(accs, dst, rows):
        # window-partial (re)build: dst row := merge of the given ring
        # rows (overwrite semantics — dst is freshly allocated or being
        # rebuilt from the authoritative panes). One dispatch per
        # window, amortized one per slide period.
        out = [a.at[dst].set(m(a[rows], axis=0))
               for a, m in zip(accs[:n], merges)]
        presence = accs[n].at[dst].set(accs[n][rows].max(axis=0))
        return tuple(out) + (presence,)

    return (scatter2d, scatter2d_valued, fire_rows, reset_row, put_row,
            fold_rows)
