"""The exchange-rank program family: rank-within-destination.

Every device exchange in the repo (the fused flat exchange+scatter, the
join ingest exchange, both stages of the two-level pod exchange) needs
the same combinator: for a staged column of destination indices ``d``,
the rank of record ``i`` within its destination — the count of PRIOR
same-destination records. Ranks flatten to per-destination bucket
offsets ``d * W + rank`` so an ``all_to_all`` block scatter preserves
stream order per destination (the property that keeps float folds
bit-identical between host bucketing and device exchange).

Two backends compute the same rank:

- ``xla``: the one-hot-cumsum idiom — ``cumsum(one_hot(d, D))`` is an
  O(n*D) matmul-shaped program standing in for a counting sort
  (ROADMAP item 3b's named worst offender).
- ``pallas``: a ``pl.pallas_call`` counting-sort kernel — one O(n)
  sequential pass over an SMEM count array. Interpret mode on CPU CI;
  real-TPU numbers belong to the item-3b revalidation round.

Both are A/B gated bit-identical for ALL int32 inputs (including
negative and out-of-range sentinel lanes): rank(i) = #{j < i :
0 <= d_j < D and d_j == clip(d_i, 0, D-1)}. The enclosing exchange
builders resolve the backend at build time via
:mod:`flink_tpu.stateplane.backends` and tag their PROGRAM_CACHE keys
with it, so an A/B swap is a new cache entry, never a silent retrace.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from flink_tpu.tenancy.program_cache import PROGRAM_CACHE

try:  # pallas ships with jax but may be absent/broken on some builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - import-time environment gate
    pl = None
    pltpu = None


def xla_rank(d, num_dests: int):
    """Rank within destination via one-hot + cumsum (the XLA idiom all
    four exchange sites hand-rolled before the stateplane extraction)."""
    oh = jax.nn.one_hot(d, num_dests, dtype=jnp.int32)
    rank = jnp.cumsum(oh, axis=0) - oh
    return jnp.take_along_axis(
        rank, jnp.clip(d, 0, num_dests - 1)[:, None], axis=1)[:, 0]


def _rank_kernel(d_ref, out_ref, counts_ref, *, num_dests: int):
    """Counting sort: one sequential pass, counts in SMEM.

    Bit-compatible with :func:`xla_rank` for every int32 input: lanes
    with ``d`` outside ``[0, num_dests)`` READ the count at the clipped
    bucket (what take_along_axis does) but never increment (their
    one-hot row is all zero)."""
    counts_ref[...] = jnp.zeros_like(counts_ref)

    def body(i, carry):
        d = d_ref[i]
        b = jnp.clip(d, 0, num_dests - 1)
        c = counts_ref[b]
        out_ref[i] = c
        counts_ref[b] = jnp.where((d >= 0) & (d < num_dests), c + 1, c)
        return carry

    jax.lax.fori_loop(0, d_ref.shape[0], body, 0)


def pallas_rank(d, num_dests: int):
    """Rank within destination as a Pallas counting-sort kernel."""
    if pl is None or pltpu is None:  # pragma: no cover
        raise RuntimeError("pallas backend requested but "
                           "jax.experimental.pallas is unavailable")
    interpret = jax.default_backend() != "tpu"
    return pl.pallas_call(
        partial(_rank_kernel, num_dests=int(num_dests)),
        out_shape=jax.ShapeDtypeStruct(d.shape, jnp.int32),
        scratch_shapes=[pltpu.SMEM((int(num_dests),), jnp.int32)],
        interpret=interpret,
    )(d.astype(jnp.int32))


_RANK_FNS = {"xla": xla_rank, "pallas": pallas_rank}


def exchange_rank_flat(d, num_dests: int, width, backend: str = "xla"):
    """Destination indices ``[C]`` -> flat bucket offsets ``[C]``.

    ``flat[i] = d[i] * width + rank(i)`` for in-range lanes whose rank
    fits the bucket; every other lane gets the out-of-range sentinel
    ``num_dests * width`` (dropped by ``.at[flat].set(mode="drop")``).
    ``width`` may be a python int or a traced scalar from a static arg.
    """
    rank_d = _RANK_FNS[backend](d, int(num_dests))
    ok = (d < num_dests) & (rank_d < width)
    return jnp.where(ok, d * width + rank_d, num_dests * width)


def build_exchange_rank(num_dests: int, backend: str = "xla"):
    """The standalone cached exchange-rank program: ``(d, width) ->
    flat``. The in-exchange sites trace :func:`exchange_rank_flat`
    inline (it fuses into their one program); this cached form is the
    unit the A/B gate, the property test and the recompile phases
    exercise directly."""
    key = (int(num_dests), str(backend))
    return PROGRAM_CACHE.get_or_build(
        "exchange-rank", key, lambda: _build_exchange_rank(
            int(num_dests), str(backend)))


def _build_exchange_rank(num_dests: int, backend: str):
    @partial(jax.jit, static_argnums=(1,))
    def rank_program(d, width):
        return exchange_rank_flat(d, num_dests, int(width), backend)

    return rank_program
