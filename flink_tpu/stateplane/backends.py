"""Pluggable program backends for the state-plane families.

Every family resolves to its XLA-idiom implementation by default; a
per-family override (``stateplane.backend.<family>=pallas|xla`` in the
job configuration, or :func:`set_backend` / :func:`backend_scope` in
process scope) swaps in an alternative kernel BEHIND the same builder
entry points. Two invariants make the swap safe:

- **Bit identity**: an alternative backend must be A/B gated
  bit-identical to the XLA program it replaces (values, emission
  order, downstream fold order) before it may ship. The gate for the
  first Pallas kernel lives in ``tools/pallas_ab_gate.py`` and
  ``tests/test_stateplane.py``.
- **Cache-key honesty**: builders resolve the backend at BUILD time
  and tag their PROGRAM_CACHE keys with it (see
  ``shuffle.build_exchange_scatter`` and friends), so a swap is a new
  cache entry — never a silent retrace of an existing key, and the
  zero-steady-state-recompile contract holds per backend.

Only ``exchange-rank`` has a non-XLA implementation today; requesting
``pallas`` for any other family raises loudly instead of silently
running XLA (a config typo must not vacuously pass an A/B experiment).
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

from flink_tpu.observe.lock_sentinel import named_lock

#: families with a real alternative implementation, by backend name
_PALLAS_CAPABLE = ("exchange-rank",)

_VALID_BACKENDS = ("xla", "pallas")

_lock = named_lock("stateplane.backends")
_overrides: Dict[str, str] = {}

_CONFIG_PREFIX = "stateplane.backend."


def pallas_available() -> bool:
    """True when the Pallas counting-sort kernel actually runs on this
    host (interpret mode counts — that is the CPU CI configuration).
    Probed once, cached; a broken pallas install degrades to False so
    callers can emit a LOUD skip instead of crashing."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            import numpy as np

            from flink_tpu.stateplane.rank import pallas_rank, xla_rank

            d = np.array([0, 1, 0, 2, 1, 0], dtype=np.int32)
            got = np.asarray(pallas_rank(d, 3))
            want = np.asarray(xla_rank(d, 3))
            _PALLAS_OK = bool((got == want).all())
        except Exception:
            _PALLAS_OK = False
    return _PALLAS_OK


_PALLAS_OK: Optional[bool] = None


def _validate(family: str, backend: str) -> str:
    from flink_tpu.stateplane.families import KNOWN_PROGRAM_FAMILIES

    if family not in KNOWN_PROGRAM_FAMILIES:
        raise ValueError(f"unknown program family {family!r}")
    if backend not in _VALID_BACKENDS:
        raise ValueError(f"unknown backend {backend!r} for family "
                         f"{family!r} (valid: {_VALID_BACKENDS})")
    if backend == "pallas" and family not in _PALLAS_CAPABLE:
        raise ValueError(
            f"family {family!r} has no pallas implementation yet "
            f"(pallas-capable: {_PALLAS_CAPABLE}) — the backend hook "
            "must not silently fall back to xla")
    return backend


def backend_of(family: str) -> str:
    """The backend the NEXT build of ``family`` resolves to."""
    with _lock:
        return _overrides.get(family, "xla")


def _set_locked(family: str, backend: str) -> None:
    """Install one override. Caller holds ``_lock``."""
    if backend == "xla":
        _overrides.pop(family, None)
    else:
        _overrides[family] = backend


def set_backend(family: str, backend: str) -> None:
    """Process-scope override (the config hook calls through here)."""
    _validate(family, backend)
    with _lock:
        _set_locked(family, backend)


@contextlib.contextmanager
def backend_scope(family: str, backend: str):
    """Scoped override — the A/B gates swap backends under this.

    Entry reads the previous value and installs the override under ONE
    lock hold; exit restores under one hold and only after re-checking
    that the override is still the one this scope installed. A
    concurrent :func:`set_backend` mid-scope therefore wins and
    survives the exit — the naive read/set/.../restore shape let the
    exit silently clobber it (the check-then-act race LCK03 flags)."""
    _validate(family, backend)
    with _lock:
        prev = _overrides.get(family, "xla")
        _set_locked(family, backend)
    try:
        yield
    finally:
        with _lock:
            if _overrides.get(family, "xla") == backend:
                _set_locked(family, prev)


def configure_backends(config) -> Dict[str, str]:
    """Apply every ``stateplane.backend.<family>`` key of a job
    configuration; returns the applied overrides. Unknown families and
    backends raise (same loudness as :func:`set_backend`) — the key
    space is SCANNED for the prefix, not probed per known family, so a
    typo'd family key fails instead of being silently ignored."""
    from flink_tpu.stateplane.families import KNOWN_PROGRAM_FAMILIES

    try:
        candidates = [k for k in config.keys()
                      if k.startswith(_CONFIG_PREFIX)]
    except AttributeError:  # duck-typed config without key iteration
        candidates = [_CONFIG_PREFIX + f for f in KNOWN_PROGRAM_FAMILIES]
    applied: Dict[str, str] = {}
    for key in candidates:
        raw = config.get_raw(key, None)
        if raw is None:
            continue
        family = key[len(_CONFIG_PREFIX):]
        set_backend(family, str(raw))
        applied[family] = str(raw)
    return applied
