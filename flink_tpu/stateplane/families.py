"""The canonical program-family library of the device state plane.

Before this module, ~eight homes (SlotTable, PaneTable, the two mesh
engines, the join side tables, the replica publisher, the two-level
exchange, CEP) each hand-rolled their own gather / scatter / evict /
snapshot program families — flint's TRC01 sweep once fixed the same
bug class in five of them (NOTES_r9). This module is the ONE home:
every compiled state-plane program the engines dispatch is built here
(or in a sibling stateplane module) and cached in the shared
:data:`~flink_tpu.tenancy.program_cache.PROGRAM_CACHE` under a family
kind drawn from :data:`KNOWN_PROGRAM_FAMILIES`.

The registry is the flint REG04 contract: a ``PROGRAM_CACHE``
``get_or_build`` call whose kind is not in this tuple is a violation,
and a registry entry with no call site is stale. The first seven kinds
are the canonical flat families (this module + ``pane.py`` +
``rank.py``); the rest are the composite per-engine programs that
FUSE canonical pieces (exchange+scatter in one XLA program, the CEP
advance, ...) — inventoried in the README's state-plane table, each
either already built from these pieces or an explicit follow-up.

Builders key programs on WHAT they compute — reduce methods, identity
constants, dtypes, aggregate layout — never on an engine, job, or
instance identity (the multi-tenant zero-recompile contract; shapes
are handled one level down by jit + the engines' sticky-bucket
padding). The bodies are the exact programs the engines compiled
before the extraction — bit-identity of every ported path is pinned
by ``tests/test_stateplane.py``.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from flink_tpu.ops.segment_ops import MERGE_FN, SCATTER_METHOD
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE

#: Every program-family kind that may appear as the first argument of a
#: ``PROGRAM_CACHE.get_or_build`` call (flint REG04). Canonical flat
#: families first, then the composite per-engine programs.
KNOWN_PROGRAM_FAMILIES = (
    # -- canonical flat families (stateplane-owned builders) --
    "gather",           # rows out of flat accumulators (spill/snapshot read)
    "scatter-combine",  # batch fold into flat accumulators (ingest write)
    "segment-reduce",   # slot-segment merge (+finish/projection) — fires
    "evict-cohort",     # cohort put/reset (spill reload, eviction clear)
    "snapshot-lift",    # snapshot ordering fence / row lift
    "delta-harvest",    # pane-ring partial scatter + fire-row harvest
    "exchange-rank",    # rank-within-destination (xla | pallas backends)
    # -- composite per-engine programs (fused from canonical pieces) --
    "mesh-steps",
    "session-merge",
    "delta-fire",
    "exchange-scatter",
    "exchange2-stage1",
    "exchange2-stage2",
    "pod-route",
    "pod-agree",
    "replica-pub",
    "join-put",
    "join-exchange-put",
    "join-gather",
    "join-banded-probe",
    "join-exchange2-stage1",
    "join-exchange2-stage2",
    "cep-advance",
    "cep-prune",
)


def _methods(leaves) -> Tuple[str, ...]:
    return tuple(SCATTER_METHOD[l.reduce] for l in leaves)


def _idents(leaves) -> tuple:
    return tuple(l.identity for l in leaves)


def _dtypes(leaves) -> Tuple[str, ...]:
    return tuple(l.dtype.str for l in leaves)


# ------------------------------------------------------------ scatter-combine


def flat_scatter_combine(leaves):
    """Batch fold into flat accumulators; const leaves broadcast their
    compile-time constant on device, identity-masked at the reserved
    slot 0 (padded lanes target it and fires read it for missing
    slices)."""
    consts = tuple(None if l.const is None else (l.const, l.dtype.str)
                   for l in leaves)
    key = ("const", _methods(leaves), consts, _dtypes(leaves))
    return PROGRAM_CACHE.get_or_build(
        "scatter-combine", key, lambda: _build_scatter_combine(leaves))


def _build_scatter_combine(leaves):
    methods = _methods(leaves)

    @partial(jax.jit, donate_argnums=(0,))
    def scatter(accs, slots, values):
        vit = iter(values)
        out = []
        for a, m, l in zip(accs, methods, leaves):
            if l.const is not None:
                # padded lanes target the reserved slot 0, which
                # must stay identity (fires read it for missing
                # slices) — mask the const there
                v = jnp.where(slots == 0,
                              jnp.asarray(l.identity, dtype=l.dtype),
                              jnp.asarray(l.const, dtype=l.dtype))
            else:
                v = next(vit)
            out.append(getattr(a.at[slots], m)(v))
        return tuple(out)

    return scatter


def flat_scatter_valued(leaves):
    """Scatter where EVERY leaf takes an explicit value array, each
    folded by its own reduce method — the merge of locally pre-
    aggregated partials (two-phase aggregation). Pad lanes must carry
    each leaf's identity at the reserved slot 0."""
    key = ("valued", _methods(leaves), _dtypes(leaves))
    return PROGRAM_CACHE.get_or_build(
        "scatter-combine", key, lambda: _build_scatter_valued(leaves))


def _build_scatter_valued(leaves):
    methods = _methods(leaves)

    @partial(jax.jit, donate_argnums=(0,))
    def scatter_valued(accs, slots, values):
        return tuple(
            getattr(a.at[slots], m)(v)
            for a, m, v in zip(accs, methods, values))

    return scatter_valued


def flat_scatter_signed(leaves):
    """Scatter of sign-applied values — the retraction fold. Only valid
    for pure-add layouts, where padding with 0 at the reserved slot is
    harmless."""
    key = ("signed", _dtypes(leaves))
    return PROGRAM_CACHE.get_or_build(
        "scatter-combine", key, lambda: _build_scatter_signed())


def _build_scatter_signed():
    @partial(jax.jit, donate_argnums=(0,))
    def scatter_signed(accs, slots, values):
        return tuple(
            a.at[slots].add(v) for a, v in zip(accs, values))

    return scatter_signed


# ------------------------------------------------------------- segment-reduce


def flat_segment_fire(agg):
    """(accs, slot_matrix [w, k]) -> result columns [w]: merge each
    window's slot segment, then ``finish``."""
    key = ("fire", agg.cache_key())
    return PROGRAM_CACHE.get_or_build(
        "segment-reduce", key, lambda: _build_segment_fire(agg))


def _build_segment_fire(agg):
    merges = tuple(MERGE_FN[l.reduce] for l in agg.leaves)
    finish = agg.finish

    @jax.jit
    def fire(accs, slot_matrix):
        merged = tuple(
            m(a[slot_matrix], axis=1) for a, m in zip(accs, merges)
        )
        return finish(merged)

    return fire


def flat_segment_fire_projected(agg, projector):
    """The fire merge+finish fused with a FireProjector so only n rows
    cross HBM->host instead of wp; validity derives on device from the
    scalar row count (see flink_tpu.windowing.fire_projectors)."""
    key = ("fire-proj", agg.cache_key(), projector.cache_key())
    return PROGRAM_CACHE.get_or_build(
        "segment-reduce", key,
        lambda: _build_segment_fire_projected(agg, projector))


def _build_segment_fire_projected(agg, projector):
    merges = tuple(MERGE_FN[l.reduce] for l in agg.leaves)
    finish = agg.finish
    project = projector.project

    @jax.jit
    def fire_proj(accs, slot_matrix, w):
        valid = jnp.arange(slot_matrix.shape[0]) < w
        merged = tuple(
            m(a[slot_matrix], axis=1) for a, m in zip(accs, merges)
        )
        return project(finish(merged), valid)

    return fire_proj


def flat_segment_merge(leaves):
    """(accs, slot_matrix [w, k]) -> merged leaves [w] WITHOUT finish —
    the hybrid-fire read path: device-resident slices merge on device,
    spilled slices merge on host, finish runs on host over the union."""
    key = ("merge", tuple(MERGE_FN[l.reduce].__name__ for l in leaves),
           _dtypes(leaves))
    return PROGRAM_CACHE.get_or_build(
        "segment-reduce", key, lambda: _build_segment_merge(leaves))


def _build_segment_merge(leaves):
    merges = tuple(MERGE_FN[l.reduce] for l in leaves)

    @jax.jit
    def merge(accs, slot_matrix):
        return tuple(
            m(a[slot_matrix], axis=1) for a, m in zip(accs, merges))

    return merge


def flat_merge_pairs(leaves):
    """acc[dst] op= acc[src] for arrays of (dst, src), then reset the
    src slots — the session-merge move (padded lanes have
    src == dst == 0, a no-op on the reserved identity slot)."""
    key = ("merge-pairs", _methods(leaves), _idents(leaves),
           _dtypes(leaves))
    return PROGRAM_CACHE.get_or_build(
        "segment-reduce", key, lambda: _build_merge_pairs(leaves))


def _build_merge_pairs(leaves):
    methods = _methods(leaves)
    idents = _idents(leaves)

    @partial(jax.jit, donate_argnums=(0,))
    def merge(accs, dst, src):
        out = []
        for a, m, i in zip(accs, methods, idents):
            moved = a[src]
            a = getattr(a.at[dst], m)(moved)
            # src != dst for real pairs; padded lanes have src == dst == 0
            a = a.at[src].set(i)
            out.append(a)
        return tuple(out)

    return merge


# --------------------------------------------------------------------- gather


def flat_gather(leaves):
    """(accs, slots) -> per-leaf gathered values — the incremental-
    snapshot / eviction read path: only the addressed slots leave the
    device instead of the whole [capacity] arrays."""
    key = (_dtypes(leaves),)
    return PROGRAM_CACHE.get_or_build(
        "gather", key, lambda: _build_gather())


def _build_gather():
    @jax.jit
    def gather(accs, slots):
        return tuple(a[slots] for a in accs)

    return gather


# --------------------------------------------------------------- evict-cohort


def flat_put(leaves):
    """(accs, slots, values) -> ``a[slots] = v`` — the spill-reload
    write path: values gathered to host at eviction time are placed
    back verbatim (identity-masked at the reserved slot 0 pad target)."""
    idents = _idents(leaves)
    key = ("put", idents, _dtypes(leaves))
    return PROGRAM_CACHE.get_or_build(
        "evict-cohort", key, lambda: _build_put(idents))


def _build_put(idents):
    @partial(jax.jit, donate_argnums=(0,))
    def put(accs, slots, values):
        out = []
        for a, v, i in zip(accs, values, idents):
            v = jnp.where(slots == 0, jnp.asarray(i, dtype=v.dtype),
                          v)
            out.append(a.at[slots].set(v))
        return tuple(out)

    return put


def flat_reset(leaves):
    """Reset an eviction cohort's slots to their identities."""
    idents = _idents(leaves)
    key = ("reset", idents, _dtypes(leaves))
    return PROGRAM_CACHE.get_or_build(
        "evict-cohort", key, lambda: _build_reset(idents))


def _build_reset(idents):
    @partial(jax.jit, donate_argnums=(0,))
    def reset(accs, slots):
        return tuple(
            a.at[slots].set(i) for a, i in zip(accs, idents)
        )

    return reset


# -------------------------------------------------------------- snapshot-lift


def flat_fence(dtype_str: str):
    """A tiny non-donated device read enqueued AFTER everything
    dispatched so far — its readiness proves the device caught up to
    this point (snapshot ordering, dispatch-depth bounding)."""
    return PROGRAM_CACHE.get_or_build(
        "snapshot-lift", ("fence", dtype_str),
        lambda: jax.jit(lambda a: a[:1]))


def pane_fence(dtype_str: str):
    """The [R, C] pane-plane fence: a [1, 1] slice of the live
    accumulator, enqueued behind all prior work."""
    return PROGRAM_CACHE.get_or_build(
        "snapshot-lift", ("pane-fence", dtype_str),
        lambda: jax.jit(lambda a: a[:1, :1]))
